"""Headline benchmark: Llama causal-LM training throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (BASELINE.md), so ``vs_baseline`` is
measured MFU / 0.40 — the north-star criterion "Llama under sharding-3
reaches >= A100-cluster MFU" with 40% as the strong-A100-baseline MFU
(BASELINE.json north_star).  On TPU the model runs bf16 through the jitted
donated train step (models/llama.py build_train_step); on CPU fallback a
tiny config keeps runtime sane (numbers then only track relative progress).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def main():
    import jax

    backend = jax.default_backend()
    on_tpu = backend not in ("cpu",)

    import paddle_tpu as paddle
    from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                   build_train_step)
    import jax.numpy as jnp

    if on_tpu:
        # 400M-param Llama (GQA, swiglu), bf16 params + fp32 master/Adam
        # state, seq 1024 — sized to one v5e chip's 16GB HBM with the FULL
        # AdamW state resident and no activation remat (a per-chip slice of
        # llama-8b sharding-3 over a v5e-16 carries a comparable ~5-7GB
        # param+optimizer budget).  Chosen from a measured config sweep:
        # h1536/L12 no-remat (0.52 MFU) beat h768/L12 (0.33), h2048/L8
        # (0.49), and every remat variant that fit.  Round-2 re-sweep
        # confirmed the optimum: b12 (0.488), b16 (0.454), s2048/b4
        # (0.445), L16 (0.502), h2048/L12 (0.450) all lose to this
        # config; component ablation puts the step within ~10% of the
        # chip's measured gemm ceiling (dense 4k-chain runs 83% peak)
        # with the AdamW update at its HBM bandwidth bound — so the final
        # lever is gradient accumulation (gradient-merge in the reference):
        # scanning accum micro-steps per AdamW update amortizes the
        # optimizer's ~15 GB read-modify-write.  Measured clean-chip:
        # accum=1 0.51, 16 0.577, 32 0.598 — accum=32's effective batch
        # (256×1024 = 262k tokens/update) is still well inside real
        # LLM-training configs (GPT-3 ran 3.2M).
        # post-accum re-sweep (accum changes the optimum: the optimizer
        # RMW no longer penalizes parameter count, so wider layers win):
        # h1536/L12/b8 0.592, h2048/L8/b8 0.611, h2048/L10/b6 0.620,
        # h2048/L12/b5 0.522 (HBM pressure), h2560/L8/b4 0.562.
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          intermediate_size=5504, num_hidden_layers=10,
                          num_attention_heads=16, num_key_value_heads=4,
                          max_position_embeddings=2048)
        batch, seq, steps, warmup = 6, 1024, 3, 2  # r3: wider measurement
        # window (r2 verdict weak#6: 2-step windows can hide variance; now
        # 3 timed windows x 3 steps each, warmup unchanged at 2)
        accum = 64  # r3 re-sweep: accum=32 0.612, 48 0.619, 64 0.622,
        # 96 0.626 (diminishing; 64 keeps the effective batch at 393k
        # tokens/update, well inside real LLM configs)
        compute_dtype = jnp.bfloat16
        param_dtype = jnp.bfloat16
    else:
        cfg = LlamaConfig.debug()
        batch, seq, steps, warmup = 4, 64, 5, 1
        accum = 1
        compute_dtype = jnp.float32
        param_dtype = jnp.float32

    if on_tpu:
        # TPU-side numeric gate (VERDICT r1 weak#9: interpret-mode tests
        # never exercise the COMPILED kernel's numerics): compiled Pallas
        # flash fwd+bwd vs the XLA softmax reference on-device.
        from paddle_tpu.ops.pallas.flash_attention import (_attn_reference,
                                                           flash_attention_raw)

        rngk = np.random.default_rng(0)
        qs = jnp.asarray(rngk.standard_normal((2, 512, 8, 64)), jnp.bfloat16)
        ks = jnp.asarray(rngk.standard_normal((2, 512, 4, 64)), jnp.bfloat16)
        vs = jnp.asarray(rngk.standard_normal((2, 512, 4, 64)), jnp.bfloat16)

        def _loss_flash(q, k, v):
            return jnp.sum(flash_attention_raw(
                q, k, v, causal=True, interpret=False).astype(jnp.float32) ** 2)

        def _loss_ref(q, k, v):
            return jnp.sum(_attn_reference(
                q, k, v, True, 64 ** -0.5).astype(jnp.float32) ** 2)

        def _rel(a, b):
            a = a.astype(jnp.float32)
            b = b.astype(jnp.float32)
            return float(jnp.max(jnp.abs(a - b)) / jnp.max(jnp.abs(b)))

        of = flash_attention_raw(qs, ks, vs, causal=True, interpret=False)
        fwd_err = _rel(of, _attn_reference(qs, ks, vs, True, 64 ** -0.5))
        gf = jax.grad(_loss_flash, argnums=(0, 1, 2))(qs, ks, vs)
        gr = jax.grad(_loss_ref, argnums=(0, 1, 2))(qs, ks, vs)
        grad_err = max(_rel(a, b) for a, b in zip(gf, gr))
        print(f"# tpu numeric gate: flash rel fwd_err={fwd_err:.4f} "
              f"grad_err={grad_err:.4f} (bf16 tol 0.02)", file=sys.stderr)
        assert fwd_err < 0.02 and grad_err < 0.02, \
            f"compiled flash kernel numerics out of tolerance: " \
            f"{fwd_err}, {grad_err}"

    from paddle_tpu.models.llama import llama_decay_mask

    model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 multi_precision=True)
    # round-7 hot path: bf16 grad-accum carry (accum_dtype default under
    # bf16 compute) + fused multi-tensor AdamW via the flat opt state
    step = build_train_step(model, opt, compute_dtype=compute_dtype,
                            accum_steps=accum)
    params = model.functional_state()
    decay_mask = llama_decay_mask(model)
    if param_dtype != jnp.float32:
        # bf16 at-rest params: halves param HBM and kills the per-step
        # fp32->bf16 cast; AdamW multi_precision keeps an fp32 master copy
        # in the flat optimizer state for update accuracy — seeded from
        # the UNROUNDED fp32 values (master_from), cast params after.
        params_f32 = params
        params = {k: (v.astype(param_dtype)
                      if jnp.issubdtype(v.dtype, jnp.floating) else v)
                  for k, v in params.items()}
        opt_state = opt.init_flat_state(params, decay_mask=decay_mask,
                                        master_from=params_f32)
    else:
        opt_state = opt.init_flat_state(params, decay_mask=decay_mask)
    bshape = (accum, batch, seq) if accum > 1 else (batch, seq)
    ids = np.random.randint(0, cfg.vocab_size, bshape, dtype=np.int32)
    labels = np.random.randint(0, cfg.vocab_size, bshape, dtype=np.int32)

    for i in range(warmup):
        loss, params, opt_state = step(params, opt_state, i, 1e-4, ids, labels)
    jax.block_until_ready((loss, params))
    float(loss)  # device-to-host sync: the tunnel's block_until_ready has
    # been observed returning early (axon platform)

    # several timed windows; report the best (the tunnel adds high-variance
    # queueing noise on top of steady-state device time)
    windows = 3 if on_tpu else 1
    best_dt = float("inf")
    sno = warmup
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss, params, opt_state = step(params, opt_state, sno, 1e-4,
                                           ids, labels)
            sno += 1
        jax.block_until_ready((loss, params))
        final_loss = float(loss)
        best_dt = min(best_dt, time.perf_counter() - t0)
    dt = best_dt

    tokens_per_sec = accum * batch * seq * steps / dt

    # params (weights only) for 6ND FLOPs estimate
    n_params = sum(int(np.prod(v.shape)) for v in params.values())
    flops_per_token = 6 * n_params
    achieved_flops = tokens_per_sec * flops_per_token
    kind = jax.devices()[0].device_kind.lower()
    if "v5 lite" in kind or "v5e" in kind:
        peak = 197e12
    elif "v5p" in kind or "v5" in kind:
        peak = 459e12
    elif "v4" in kind:
        peak = 275e12
    elif backend == "cpu":
        peak = 2e12
    else:
        peak = 459e12
    mfu = achieved_flops / peak
    vs_baseline = mfu / 0.40  # >= 1.0 beats the A100-cluster MFU north star

    # ---- supplementary diagnostics (stderr + BENCH_EXTRA.json; the
    # headline JSON line below stays the single stdout contract) ----
    extras = {}
    try:
        from paddle_tpu.ops import microbench

        extras["eager_dispatch"] = microbench.run(
            n=300 if on_tpu else 150)
        print(f"# eager dispatch: {extras['eager_dispatch']}",
              file=sys.stderr)
    except Exception as e:  # noqa: BLE001 — diagnostics must not kill bench
        print(f"# eager microbench failed: {e}", file=sys.stderr)
    if on_tpu:
        try:
            extras["varlen_vs_dense"] = _varlen_vs_dense_bench()
            print(f"# varlen flash: {extras['varlen_vs_dense']}",
                  file=sys.stderr)
        except Exception as e:  # noqa: BLE001
            print(f"# varlen bench failed: {e}", file=sys.stderr)
        try:
            extras["flashmask"] = _flashmask_bench()
            print(f"# flashmask: {extras['flashmask']}", file=sys.stderr)
        except Exception as e:  # noqa: BLE001
            print(f"# flashmask bench failed: {e}", file=sys.stderr)
        try:
            extras["flash_decoding"] = _flash_decoding_bench()
            print(f"# flash decoding: {extras['flash_decoding']}",
                  file=sys.stderr)
        except Exception as e:  # noqa: BLE001
            print(f"# flash decoding bench failed: {e}", file=sys.stderr)
        try:
            extras["decode_e2e"] = _decode_e2e_bench(params, cfg)
            print(f"# decode e2e: {extras['decode_e2e']}", file=sys.stderr)
        except Exception as e:  # noqa: BLE001
            print(f"# decode e2e bench failed: {e}", file=sys.stderr)
        try:
            extras["serving"] = _serving_bench(params, cfg)
            print(f"# serving: {extras['serving']}", file=sys.stderr)
        except Exception as e:  # noqa: BLE001
            print(f"# serving bench failed: {e}", file=sys.stderr)
    try:
        extras["serving_8b_int8"] = _serving_8b_int8_bench()
        print(f"# serving 8b int8: {extras['serving_8b_int8']}",
              file=sys.stderr)
    except Exception as e:  # noqa: BLE001
        print(f"# serving 8b int8 bench failed: {e}", file=sys.stderr)
    try:
        with open("BENCH_EXTRA.json", "w") as f:
            json.dump(extras, f, indent=1)
    except OSError:
        pass

    print(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s",
        "vs_baseline": round(vs_baseline, 4),
    }))
    print(f"# backend={backend} params={n_params/1e6:.1f}M batch={batch} "
          f"seq={seq} accum={accum} steps={steps} dt={dt:.2f}s "
          f"loss={final_loss:.3f} mfu={mfu:.3f}", file=sys.stderr)


def _chained_device_time(fn, x, n_lo=9, n_hi=73, reps=5, consts=()):
    """On-device per-iteration time of ``fn`` with the tunnel's per-call
    overhead (~60-70ms RTT, swamping ms-scale kernels) subtracted out:
    chain n_lo and n_hi dependent applications inside ONE jitted call
    each and take the slope ((t_hi - t_lo) / (n_hi - n_lo)) — both
    measurements carry one RTT, so it cancels.  Root-caused in round 4:
    the old per-call wall-clock methodology measured the link, not the
    kernel, which is why BENCH_r03's varlen leg read 1.05x.  The chain
    lengths are far apart so the device-time delta (tens of ms) clears
    the RTT jitter; min-of-reps rides the RTT floor."""
    import time

    import jax

    def chain(m):
        # large operands (KV caches) ride as jit ARGUMENTS, not closure
        # constants — embedded constants get serialized into the tunnel's
        # remote-compile request and blow its size limit
        return jax.jit(lambda q, *cs: jax.lax.fori_loop(
            0, m, lambda i, y: fn(y, *cs), q))

    lo, hi = chain(n_lo), chain(n_hi)
    lo(x, *consts).block_until_ready()
    hi(x, *consts).block_until_ready()
    deltas = []
    for _ in range(reps):
        # paired back-to-back samples see the same tunnel congestion;
        # the median of per-pair slopes rejects RTT drift between reps
        t0 = time.perf_counter()
        lo(x, *consts).block_until_ready()
        tl = time.perf_counter() - t0
        t0 = time.perf_counter()
        hi(x, *consts).block_until_ready()
        th = time.perf_counter() - t0
        deltas.append((th - tl) / (n_hi - n_lo))
    deltas.sort()
    return deltas[len(deltas) // 2]


def _varlen_vs_dense_bench():
    """Packed-varlen (ragged kernel, per-segment block skip) vs the
    dense-padded-with-masks path on identical workloads: 4 sequences
    (~32% padding when padded to max).  VERDICT r2 missing#3's win
    criterion: packed-varlen beats dense-masked at >=30% padding.
    Device time via _chained_device_time (tunnel-free)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.flash_attention import (
        flash_attention_raw, flash_attn_unpadded_raw,
        varlen_block_skip_fraction)

    seqlens = [1300, 2048, 700, 1500]   # max 2048 -> 32% padding dense
    h, d = 16, 64
    total = sum(seqlens)
    rng = np.random.default_rng(0)
    maxlen = max(seqlens)
    b = len(seqlens)

    qp = jnp.asarray(rng.standard_normal((total, h, d)), jnp.bfloat16)
    cu = jnp.asarray(np.cumsum([0] + seqlens), jnp.int32)

    qd = jnp.asarray(rng.standard_normal((b, maxlen, h, d)), jnp.bfloat16)
    seg = np.zeros((b, maxlen), np.int32)
    for i, n in enumerate(seqlens):
        seg[i, :n] = i + 1
    seg = jnp.asarray(seg)

    def packed(q):
        return flash_attn_unpadded_raw(q, q, q, cu, cu, causal=True,
                                       interpret=False)

    def dense(q):
        return flash_attention_raw(q, q, q, causal=True, interpret=False,
                                   q_segment_ids=seg, kv_segment_ids=seg)

    def grad_step(fn):
        g = jax.grad(lambda q: jnp.sum(fn(q).astype(jnp.float32)))
        return lambda q: g(q).astype(q.dtype)

    tp = _chained_device_time(packed, qp)
    td = _chained_device_time(dense, qd)
    tpg = _chained_device_time(grad_step(packed), qp, n_lo=3, n_hi=27)
    tdg = _chained_device_time(grad_step(dense), qd, n_lo=3, n_hi=27)

    # auto-dispatch path (round 6): padding-aware kernel choice over the
    # SAME padded workload — at 32% padding it must pick the dense-masked
    # kernel (trace-time choice -> identical compiled program, never
    # slower than its fallback); the packed win is captured at high
    # padding below
    from paddle_tpu.ops.pallas.flash_attention import (
        PACKED_PADDING_CROSSOVER, flash_attention_auto)

    def auto_mid(q):
        return flash_attention_auto(q, q, q, seqlens, causal=True,
                                    interpret=False)

    tag = _chained_device_time(grad_step(auto_mid), qd, n_lo=3, n_hi=27)

    # second point: HIGH padding (~64%) — the regime the varlen path
    # exists for.  Round-5's fused backward + compressed-grid dense
    # kernel moved the crossover: at 32% padding the (equally-improved)
    # dense baseline now wins outright; packed pays off once padding
    # dominates (see BASELINE.md round-5 notes).
    seqlens_hi = [2048, 450, 300, 250]
    total_hi = sum(seqlens_hi)
    qp_hi = jnp.asarray(rng.standard_normal((total_hi, h, d)), jnp.bfloat16)
    cu_hi = jnp.asarray(np.cumsum([0] + seqlens_hi), jnp.int32)
    qd_hi = jnp.asarray(rng.standard_normal((b, maxlen, h, d)), jnp.bfloat16)
    seg_hi = np.zeros((b, maxlen), np.int32)
    for i, n in enumerate(seqlens_hi):
        seg_hi[i, :n] = i + 1
    seg_hi = jnp.asarray(seg_hi)

    def packed_hi(q):
        return flash_attn_unpadded_raw(q, q, q, cu_hi, cu_hi, causal=True,
                                       interpret=False)

    def dense_hi(q):
        return flash_attention_raw(q, q, q, causal=True, interpret=False,
                                   q_segment_ids=seg_hi,
                                   kv_segment_ids=seg_hi)

    tpg_hi = _chained_device_time(grad_step(packed_hi), qp_hi,
                                  n_lo=3, n_hi=27)
    tdg_hi = _chained_device_time(grad_step(dense_hi), qd_hi,
                                  n_lo=3, n_hi=27)

    def auto_hi(q):
        return flash_attention_auto(q, q, q, seqlens_hi, causal=True,
                                    interpret=False)

    tag_hi = _chained_device_time(grad_step(auto_hi), qd_hi,
                                  n_lo=3, n_hi=27)
    return {
        "auto_fwdbwd_ms": round(tag * 1e3, 3),
        "auto_vs_dense_fwdbwd_x": round(tdg / tag, 3),
        "auto_choice_midpad": (
            "packed" if 1 - total / (b * maxlen)
            >= PACKED_PADDING_CROSSOVER else "dense"),
        "auto_hi_fwdbwd_ms": round(tag_hi * 1e3, 3),
        "auto_vs_dense_hi_fwdbwd_x": round(tdg_hi / tag_hi, 3),
        "auto_choice_hipad": (
            "packed" if 1 - total_hi / (b * maxlen)
            >= PACKED_PADDING_CROSSOVER else "dense"),
        "crossover_padding_frac": PACKED_PADDING_CROSSOVER,
        "packed_ms": round(tp * 1e3, 3),
        "dense_masked_ms": round(td * 1e3, 3),
        "speedup_x": round(td / tp, 3),
        "packed_fwdbwd_ms": round(tpg * 1e3, 3),
        "dense_fwdbwd_ms": round(tdg * 1e3, 3),
        "fwdbwd_speedup_x": round(tdg / tpg, 3),
        "padding_frac": round(1 - total / (b * maxlen), 3),
        "est_block_skip_frac": round(
            varlen_block_skip_fraction(seqlens, 512), 3),
        "hi_padding_frac": round(1 - total_hi / (b * maxlen), 3),
        "hi_fwdbwd_speedup_x": round(tdg_hi / tpg_hi, 3),
        "hi_packed_fwdbwd_ms": round(tpg_hi * 1e3, 3),
        "hi_dense_fwdbwd_ms": round(tdg_hi * 1e3, 3),
        "method": "chained-iteration device time (tunnel-free)",
    }


def _flashmask_bench():
    """FlashMask causal document mask vs plain causal flash on the same
    packed stream: mask-structure-driven block skipping should win by
    roughly the live-tile ratio (VERDICT r3 next#1's bench leg)."""
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.flash_attention import flash_attention_raw
    from paddle_tpu.ops.pallas.flashmask import (
        causal_document_row_indices, flashmask_attention_raw,
        flashmask_block_skip_fraction)

    seqlens = [700, 400, 620, 500, 356, 640, 480, 400]   # 8 docs, 4096
    s = sum(seqlens)
    h, d = 16, 64
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((1, s, h, d)), jnp.bfloat16)
    idx = causal_document_row_indices(seqlens)

    def fm(x):
        return flashmask_attention_raw(x, x, x, idx, causal=True,
                                       interpret=False)

    def causal(x):
        return flash_attention_raw(x, x, x, causal=True, interpret=False)

    def grad_step(fn):
        import jax

        g = jax.grad(lambda x: jnp.sum(fn(x).astype(jnp.float32)))
        return lambda x: g(x).astype(x.dtype)

    tm = _chained_device_time(fm, q)
    tc = _chained_device_time(causal, q)
    # round-5: the fused one-pass backward + DMA-elided dead tiles make
    # the mask-driven skip survive training (r4 was fwd-only ~1.6x,
    # fwd+bwd ~1.0x; target >= 1.4x fwd+bwd at 0.77 skip fraction)
    tmg = _chained_device_time(grad_step(fm), q, n_lo=3, n_hi=27)
    tcg = _chained_device_time(grad_step(causal), q, n_lo=3, n_hi=27)
    return {
        "flashmask_ms": round(tm * 1e3, 3),
        "causal_dense_ms": round(tc * 1e3, 3),
        "speedup_x": round(tc / tm, 3),
        "flashmask_fwdbwd_ms": round(tmg * 1e3, 3),
        "causal_fwdbwd_ms": round(tcg * 1e3, 3),
        "fwdbwd_speedup_x": round(tcg / tmg, 3),
        "skip_frac": round(flashmask_block_skip_fraction(idx, True, s,
                                                         512), 3),
        "method": "chained-iteration device time (tunnel-free)",
    }


def _flash_decoding_bench():
    """Pallas flash-decoding (DMA clamped to seq_len) vs the best-effort
    XLA decode (grouped einsum over the FULL cache, no head repeat) on a
    llama-8B-shaped KV cache at ~12% average fill: the kernel's HBM
    traffic scales with actual lengths, XLA's with cache capacity."""
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.decode_attention import flash_decode_raw

    b, h, kvh, d, t_max = 8, 32, 8, 128, 8192
    lens = np.array([1024, 512, 2048, 768, 1024, 640, 896, 1280], np.int32)
    rng = np.random.default_rng(0)
    kc = jnp.asarray(rng.standard_normal((b, kvh, t_max, d)), jnp.bfloat16)
    vc = jnp.asarray(rng.standard_normal((b, kvh, t_max, d)), jnp.bfloat16)
    lens_j = jnp.asarray(lens)
    q0 = jnp.asarray(rng.standard_normal((b, h, d)), jnp.bfloat16)
    rep = h // kvh
    import jax

    def pallas_step(q, kc, vc):
        return flash_decode_raw(q, kc, vc, lens_j, interpret=False)

    def xla_step(q, kc, vc):
        qg = q.reshape(b, kvh, rep, d)
        s = jnp.einsum("bgrd,bgtd->bgrt", qg.astype(jnp.float32),
                       kc.astype(jnp.float32)) / np.sqrt(d)
        s = jnp.where(jnp.arange(t_max)[None, None, None, :]
                      < lens_j[:, None, None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bgrt,bgtd->bgrd", p, vc.astype(jnp.float32))
        return o.reshape(b, h, d).astype(q.dtype)

    tp = _chained_device_time(pallas_step, q0, consts=(kc, vc))
    tx = _chained_device_time(xla_step, q0, consts=(kc, vc))

    # paged (vLLM-layout) variant: same workload split into 64-token
    # pages with a shuffled physical layout
    from paddle_tpu.ops.pallas.decode_attention import paged_decode_raw

    page = 64
    mp = t_max // page
    nb = b * mp
    tables = jnp.asarray(
        rng.permutation(nb).reshape(b, mp).astype(np.int32))
    kp = jnp.asarray(rng.standard_normal((nb, kvh, page, d)), jnp.bfloat16)
    vp = jnp.asarray(rng.standard_normal((nb, kvh, page, d)), jnp.bfloat16)

    def paged_step(q, kp, vp):
        return paged_decode_raw(q, kp, vp, lens_j, tables,
                                interpret=False)

    def xla_paged_step(q, kp, vp):
        ks = kp[jnp.maximum(tables, 0)]          # [b, mp, kvh, page, d]
        vs = vp[jnp.maximum(tables, 0)]
        ks = jnp.moveaxis(ks, 2, 1).reshape(b, kvh, mp * page, d)
        vs = jnp.moveaxis(vs, 2, 1).reshape(b, kvh, mp * page, d)
        return xla_step(q, ks, vs)

    tpp = _chained_device_time(paged_step, q0, consts=(kp, vp))
    txp = _chained_device_time(xla_paged_step, q0, consts=(kp, vp))

    # int8 KV cache at HIGH fill (~94%): the memory-bound regime where
    # halving the cache stream shows (round-4 verdict next#4's leg).
    # Same dense kernel, int8 blocks widened in-kernel; scales fold
    # outside so the comparison isolates the HBM traffic.
    lens_hi = jnp.full((b,), int(t_max * 0.9375), jnp.int32)
    k8 = jnp.asarray(
        rng.integers(-127, 128, (b, kvh, t_max, d)), jnp.int8)
    v8 = jnp.asarray(
        rng.integers(-127, 128, (b, kvh, t_max, d)), jnp.int8)

    def dense_hi(q, kc, vc):
        return flash_decode_raw(q, kc, vc, lens_hi, interpret=False)

    t_bf16_hi = _chained_device_time(dense_hi, q0, consts=(kc, vc))
    t_int8_hi = _chained_device_time(dense_hi, q0, consts=(k8, v8))
    return {
        "pallas_ms": round(tp * 1e3, 3),
        "xla_full_cache_ms": round(tx * 1e3, 3),
        "speedup_x": round(tx / tp, 3),
        "paged_pallas_ms": round(tpp * 1e3, 3),
        "paged_xla_gather_ms": round(txp * 1e3, 3),
        "paged_speedup_x": round(txp / tpp, 3),
        "avg_fill_frac": round(float(lens.mean()) / t_max, 3),
        "int8_hi_fill_ms": round(t_int8_hi * 1e3, 3),
        "bf16_hi_fill_ms": round(t_bf16_hi * 1e3, 3),
        "int8_hi_fill_speedup_x": round(t_bf16_hi / t_int8_hi, 3),
        "hi_fill_frac": 0.9375,
        "method": "chained-iteration device time (tunnel-free)",
    }


def _decode_e2e_bench(params, cfg, reps=3):
    """End-to-end autoregressive decode throughput on the bench model
    (574M, bf16): the full compiled generate scan — embedding, all
    layers through the Pallas flash-decoding kernel, sampling — measured
    as the slope between two generation lengths (prefill, compile, and
    tunnel RTT cancel).  The serving-side counterpart of the training
    tokens/s headline (reference analog: fused_multi_transformer +
    masked_multihead_attention decode path)."""
    import time

    import jax
    import jax.numpy as jnp

    from paddle_tpu.models import generation as G

    cfg_key = G.register_config(cfg)
    b, S = 8, 128
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (b, S)), jnp.int32)
    key = jax.random.PRNGKey(0)

    def run(n):
        out = G._generate_jit(params, ids, key, cfg_id=cfg_key,
                              max_new_tokens=n, do_sample=False,
                              temperature=1.0, top_k=0, top_p=1.0,
                              eos_id=-1)
        jax.block_until_ready(out)

    lo, hi = 16, 80
    run(lo)
    run(hi)                        # compile both variants
    tlo = thi = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        run(lo)
        tlo = min(tlo, time.perf_counter() - t0)
        t0 = time.perf_counter()
        run(hi)
        thi = min(thi, time.perf_counter() - t0)
    per_tok = (thi - tlo) / (hi - lo)
    return {
        "ms_per_decode_step": round(per_tok * 1e3, 3),
        "decode_tokens_per_sec": round(b / per_tok, 1),
        "batch": b,
        "prompt_len": S,
        "method": "two-length slope (prefill/compile/RTT cancel)",
    }


def _serving_bench(params, cfg):
    """Mixed-trace continuous-batching throughput (round-4 verdict
    next#5's bench leg): requests with varied prompt/generation lengths
    arriving over time into the paged-cache engine
    (inference/serving.py).  Through the dev tunnel every scheduler
    iteration pays a ~100ms host round trip, so wall-clock throughput
    measures the link, not the chip; the leg therefore reports BOTH the
    wall number and a device-time estimate from the per-chunk slope
    (two chunk lengths, RTT cancels — same methodology as decode_e2e)."""
    import time

    import jax
    import jax.numpy as jnp

    from paddle_tpu.inference.serving import ContinuousBatchingEngine

    rng = np.random.default_rng(0)

    def make_engine(chunk):
        return ContinuousBatchingEngine(
            cfg, params, max_slots=8, num_pages=8 * 16 + 1, page_size=128,
            max_seq_len=2048, decode_chunk_steps=chunk)

    # arrival trace: 12 requests, staggered so later ones join mid-decode
    prompts = [rng.integers(0, cfg.vocab_size,
                            (int(rng.integers(32, 160)),)).astype(np.int32)
               for _ in range(12)]
    budgets = [int(rng.integers(24, 64)) for _ in range(12)]

    def drive(chunk):
        eng = make_engine(chunk)
        t0 = time.perf_counter()
        produced = 0
        it = 0
        qi = 0
        while qi < len(prompts) or eng.queue or eng.active.any():
            # 3 new requests join every 2 iterations (mid-decode joins)
            if it % 2 == 0:
                for _ in range(3):
                    if qi < len(prompts):
                        eng.add_request(prompts[qi],
                                        max_new_tokens=budgets[qi])
                        qi += 1
            produced += eng.step()
            it += 1
        dt = time.perf_counter() - t0
        return produced, dt, it

    ntok_hi, dt_hi, iters_hi = drive(16)

    # device time per batched decode step: fill a warm engine, then time
    # the COMPILED decode-chunk program at two chunk lengths — the slope
    # cancels the tunnel RTT (and the fixed dispatch cost), same
    # methodology as decode_e2e.  time_decode_chunk syncs via a scalar
    # readback (the tunnel's block_until_ready can return early) and
    # leaves the host schedule untouched, so both lengths see the same
    # fill.
    eng = make_engine(8)
    for p, bdg in zip(prompts[:8], [512] * 8):
        eng.add_request(p, max_new_tokens=bdg)
    eng._admit()

    t_lo, t_hi = eng.time_decode_chunk(4), eng.time_decode_chunk(20)
    per_step = (t_hi - t_lo) / 16.0
    total_new = float(sum(budgets))
    out = {
        "requests": len(prompts),
        "total_new_tokens": int(total_new),
        "wall_tokens_per_sec_chunk16": round(ntok_hi / dt_hi, 1),
        "admission": "3 requests / 2 iterations (mid-decode joins)",
        "pages_per_step": eng.pages_per_step,
        "method": "warm-batch chunk-length slope (4 vs 20; RTT cancels)",
    }
    if per_step > 1e-5:
        out["device_ms_per_batched_step"] = round(per_step * 1e3, 3)
        out["device_tokens_per_sec"] = round(8 / per_step, 1)
    else:
        # a non-positive slope means the sync was defeated (tunnel
        # block_until_ready early-return class) — report the failure,
        # never a fabricated headline number
        out["device_slope_failed"] = round(per_step * 1e3, 4)
    return out


def _serving_8b_int8_bench():
    """llama-8B-shaped single-chip serving leg: weight-only int8 params
    (per-out-channel scales, dequant fused into the consumer dots — int8
    is what streams from HBM) + int8 KV cache, through the same
    continuous-batching engine.  Round-5 verdict Weak #3: every e2e
    inference number was 574M-only even though int8 weights (~8GB) +
    int8 KV fit one v5e chip.  Weights are randomly initialized on
    device (throughput is layout/dtype-faithful; token VALUES are
    meaningless and never read beyond the sync)."""
    import jax
    import jax.numpy as jnp

    if jax.default_backend() == "cpu":
        return {"skipped": "cpu fallback: the 8B-shaped leg needs a real "
                           "chip (8GB int8 weights; CPU run would measure "
                           "the host, not the serving path)"}

    from paddle_tpu.models import LlamaConfig
    from paddle_tpu.inference.serving import ContinuousBatchingEngine

    cfg = LlamaConfig(vocab_size=128256, hidden_size=4096,
                      intermediate_size=14336, num_hidden_layers=32,
                      num_attention_heads=32, num_key_value_heads=8,
                      max_position_embeddings=2048,
                      tie_word_embeddings=True)
    h, kvh, d, inter = 4096, 1024, 128, 14336
    key = jax.random.PRNGKey(0)

    def w8(key, shape):
        return jax.random.randint(key, shape, -127, 128, jnp.int8)

    def sc(shape):
        return jnp.full(shape, 0.004, jnp.float32)

    params = {
        "model.embed_tokens.weight": w8(jax.random.fold_in(key, 1),
                                        (cfg.vocab_size, h)),
        "model.embed_tokens.weight._scale": sc((cfg.vocab_size,)),
        "model.norm.weight": jnp.ones((h,), jnp.bfloat16),
    }
    shapes = {
        "self_attn.q_proj.weight": (h, h),
        "self_attn.k_proj.weight": (h, kvh),
        "self_attn.v_proj.weight": (h, kvh),
        "self_attn.o_proj.weight": (h, h),
        "mlp.gate_proj.weight": (h, inter),
        "mlp.up_proj.weight": (h, inter),
        "mlp.down_proj.weight": (inter, h),
    }
    for i in range(cfg.num_hidden_layers):
        lk = jax.random.fold_in(key, 100 + i)
        params[f"model.layers.{i}.input_layernorm.weight"] = \
            jnp.ones((h,), jnp.bfloat16)
        params[f"model.layers.{i}.post_attention_layernorm.weight"] = \
            jnp.ones((h,), jnp.bfloat16)
        for j, (name, shape) in enumerate(sorted(shapes.items())):
            params[f"model.layers.{i}.{name}"] = \
                w8(jax.random.fold_in(lk, j), shape)
            params[f"model.layers.{i}.{name}._scale"] = sc((shape[1],))
    weight_bytes = sum(int(np.prod(v.shape)) for k, v in params.items()
                       if v.dtype == jnp.int8)

    eng = ContinuousBatchingEngine(
        cfg, params, max_slots=8, num_pages=8 * 16 + 1, page_size=128,
        max_seq_len=2048, decode_chunk_steps=8, cache_dtype=jnp.int8)
    rng = np.random.default_rng(0)
    for _ in range(8):
        eng.add_request(rng.integers(0, cfg.vocab_size, (128,)).astype(
            np.int32), max_new_tokens=512)
    eng._admit()

    t_lo, t_hi = eng.time_decode_chunk(4), eng.time_decode_chunk(20)
    per_step = (t_hi - t_lo) / 16.0
    # weight-streaming floor: every decode step reads the full int8
    # weight set once (v5e ~819GB/s HBM)
    floor_ms = weight_bytes / 819e9 * 1e3
    out = {
        "model": "llama3-8b-shaped (random int8 weights, tied head)",
        "weight_gb_int8": round(weight_bytes / 1e9, 2),
        "cache_dtype": "int8",
        "slots": 8,
        "pages_per_step": eng.pages_per_step,
        "weight_stream_floor_ms": round(floor_ms, 3),
        "method": "warm-batch chunk-length slope (4 vs 20; RTT cancels)",
    }
    if per_step > 1e-5:
        out["device_ms_per_batched_step"] = round(per_step * 1e3, 3)
        out["device_tokens_per_sec"] = round(8 / per_step, 1)
        out["vs_weight_stream_floor_x"] = round(per_step * 1e3 / floor_ms,
                                                2)
    else:
        out["device_slope_failed"] = round(per_step * 1e3, 4)
    return out


def profile():
    """Per-lever step-time attribution of the TRAINING hot path (round-7
    acceptance: the overhaul win must be decomposable).  Levers measured
    as built-program deltas, so each number is attributable to exactly
    one code path:

      - ``flash``: attention fwd+bwd slice, head-batched vs per-head
        kernels (the HB lever),
      - ``grad_merge``: full accum step with the bf16 carry vs the fp32
        accumulator (the HBM-traffic lever),
      - ``optimizer``: full step with the fused flat AdamW vs the legacy
        per-param apply, plus the fused pass timed alone,
      - ``residual``: step minus attention and optimizer slices (matmul
        chain + scan glue).

    On TPU the numbers are device-scale (min-of-windows over multi-step
    loops; flash via _chained_device_time); on CPU a tiny config runs the
    SAME programs in interpret mode — relative numbers only, but every
    lever is exercised, so the leg is a structural regression gate."""
    import time

    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                   build_train_step)
    from paddle_tpu.models.llama import llama_decay_mask
    from paddle_tpu.ops.pallas.flash_attention import flash_attention_raw

    on_tpu = jax.default_backend() not in ("cpu",)
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          intermediate_size=5504, num_hidden_layers=10,
                          num_attention_heads=16, num_key_value_heads=4,
                          max_position_embeddings=2048)
        batch, seq, accum, steps = 6, 1024, 8, 2  # accum proxy: per-token
        # cost matches the accum=64 headline (r5 methodology), keeps the
        # 5-variant profile affordable through the tunnel
        compute_dtype = param_dtype = jnp.bfloat16
    else:
        cfg = LlamaConfig.debug()
        batch, seq, accum, steps = 2, 64, 4, 1
        compute_dtype = jnp.float32
        param_dtype = jnp.float32

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 multi_precision=True)
    params0 = model.functional_state()
    decay_mask = llama_decay_mask(model)
    if param_dtype != jnp.float32:
        pf32 = params0
        params0 = {k: (v.astype(param_dtype)
                       if jnp.issubdtype(v.dtype, jnp.floating) else v)
                   for k, v in params0.items()}
        flat_state = opt.init_flat_state(params0, decay_mask=decay_mask,
                                         master_from=pf32)
    else:
        flat_state = opt.init_flat_state(params0, decay_mask=decay_mask)
    legacy_state = opt.init_state(params0)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (accum, batch, seq)).astype(
        np.int32)
    labels = rng.integers(0, cfg.vocab_size, (accum, batch, seq)).astype(
        np.int32)

    def time_step(step_fn, opt_state, reps=3):
        import jax as _j

        p = _j.tree_util.tree_map(jnp.copy, params0)
        st = _j.tree_util.tree_map(jnp.copy, opt_state)
        loss, p, st = step_fn(p, st, 0, 1e-4, ids, labels)  # compile+warm
        _j.block_until_ready((loss, p))
        float(loss)
        best = float("inf")
        sno = 1
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(steps):
                loss, p, st = step_fn(p, st, sno, 1e-4, ids, labels)
                sno += 1
            _j.block_until_ready((loss, p))
            float(loss)
            best = min(best, (time.perf_counter() - t0) / steps)
        return best

    out = {"config": {"accum": accum, "batch": batch, "seq": seq,
                      "layers": cfg.num_hidden_layers,
                      "backend": jax.default_backend()}}

    # ---- headline variant: bf16 carry + fused AdamW -------------------
    def mk(**kw):
        return build_train_step(model, opt, compute_dtype=compute_dtype,
                                accum_steps=accum, **kw)
    # accum_dtype passed EXPLICITLY: the CPU leg computes in fp32, whose
    # default accumulator is also fp32 — without this the grad-merge
    # lever below would time two identical programs and the bf16-carry
    # branch would go unexercised (on TPU it matches the bf16 default)
    t_main = time_step(mk(accum_dtype=jnp.bfloat16), flat_state)
    out["step_ms"] = round(t_main * 1e3, 3)

    # ---- grad-merge lever: fp32 accumulator variant -------------------
    t_f32acc = time_step(mk(accum_dtype=jnp.float32), flat_state)
    out["step_fp32_accum_ms"] = round(t_f32acc * 1e3, 3)
    out["grad_merge_saving_ms"] = round((t_f32acc - t_main) * 1e3, 3)

    # ---- optimizer lever: legacy per-param apply variant --------------
    t_legacy = time_step(mk(accum_dtype=jnp.bfloat16), legacy_state)
    out["step_unfused_opt_ms"] = round(t_legacy * 1e3, 3)
    out["fused_optimizer_saving_ms"] = round((t_legacy - t_main) * 1e3, 3)

    # fused AdamW pass alone (grads = params-shaped ones)
    gr = {k: jnp.ones(v.shape, v.dtype) for k, v in params0.items()
          if jnp.issubdtype(v.dtype, jnp.floating)}

    opt_apply = jax.jit(lambda p, g, s: opt.apply_flat(
        p, g, s, 1e-4, 2, decay_mask=decay_mask))
    np_, ns_ = opt_apply(params0, gr, flat_state)
    jax.block_until_ready(np_)
    t_opt_pass = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        np_, ns_ = opt_apply(params0, gr, flat_state)
        jax.block_until_ready(np_)
        t_opt_pass = min(t_opt_pass, time.perf_counter() - t0)
    out["optimizer_pass_ms"] = round(t_opt_pass * 1e3, 3)

    # ---- flash lever: HB vs per-head fwd+bwd at the model shape -------
    h, kvh, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                 cfg.head_dim)
    q = jnp.asarray(rng.standard_normal((batch, seq, h, d)), compute_dtype)
    k = jnp.asarray(rng.standard_normal((batch, seq, kvh, d)),
                    compute_dtype)
    v = jnp.asarray(rng.standard_normal((batch, seq, kvh, d)),
                    compute_dtype)

    import os

    def fa_grad(q, k, v):
        g = jax.grad(lambda q: jnp.sum(flash_attention_raw(
            q, k, v, causal=True).astype(jnp.float32)))
        return g(q).astype(q.dtype)

    def time_flash():
        if on_tpu:
            # k/v ride as jit arguments (consts), not closure constants —
            # embedded constants blow the tunnel's remote-compile size
            # limit (see _chained_device_time's contract)
            return _chained_device_time(fa_grad, q, n_lo=3, n_hi=27,
                                        consts=(k, v))
        fj = jax.jit(fa_grad)
        fj(q, k, v).block_until_ready()
        t0 = time.perf_counter()
        fj(q, k, v).block_until_ready()
        return time.perf_counter() - t0

    # Honor an engaged kill switch (PADDLE_TPU_FLASH_HEAD_BATCHED=0):
    # the headline step above ran the per-head kernels, so forcing the
    # HB route here would both misattribute the step AND re-enable
    # kernels the operator disabled (possibly crashing their toolchain).
    # Otherwise force each routing explicitly so neither leg silently
    # measures the wrong kernels; restore the ambient setting after.
    hb_env = os.environ.get("PADDLE_TPU_FLASH_HEAD_BATCHED")
    hb_active = hb_env != "0"
    t_hb = None
    try:
        if hb_active:
            os.environ["PADDLE_TPU_FLASH_HEAD_BATCHED"] = "1"
            t_hb = time_flash()
        os.environ["PADDLE_TPU_FLASH_HEAD_BATCHED"] = "0"
        t_ph = time_flash()
    finally:
        if hb_env is None:
            os.environ.pop("PADDLE_TPU_FLASH_HEAD_BATCHED", None)
        else:
            os.environ["PADDLE_TPU_FLASH_HEAD_BATCHED"] = hb_env
    out["flash_fwdbwd_perhead_ms"] = round(t_ph * 1e3, 3)
    if hb_active:
        out["flash_fwdbwd_hb_ms"] = round(t_hb * 1e3, 3)
        out["flash_hb_speedup_x"] = round(t_ph / max(t_hb, 1e-9), 3)
    else:
        out["flash_hb_skipped"] = \
            "PADDLE_TPU_FLASH_HEAD_BATCHED=0 (kill switch honored)"
    # attribute with the kernel the headline step actually ran
    flash_slice = (t_hb if hb_active else t_ph) \
        * cfg.num_hidden_layers * accum
    out["flash_slice_ms"] = round(flash_slice * 1e3, 3)
    out["residual_ms"] = round(
        (t_main - flash_slice - t_opt_pass) * 1e3, 3)
    out["method"] = ("chained/device windows" if on_tpu
                     else "wall-clock tiny-config (relative only)")

    # ---- round-9: communication-overlap lever attribution -------------
    try:
        out["overlap_levers"] = _profile_overlap_levers()
    except Exception as e:  # noqa: BLE001 — the profile must not die on
        out["overlap_levers"] = {"error": repr(e)}  # a mesh-less host
    # ---- round-10: HBM memory-lever attribution (peak per lattice
    # point + the autotuned config; also written to MEMCONFIG.json) ----
    try:
        out["memory_levers"] = _profile_memory_levers()
    except Exception as e:  # noqa: BLE001
        out["memory_levers"] = {"error": repr(e)}
    return out


def _profile_memory_levers():
    """Walk the remat/offload lattice (parallel/memory.py) at the bench
    shape and record each point's compiled peak HBM plus the headroom
    against the chip budget; tune_memory_config picks the cheapest
    fitting point.  On TPU the budget is the chip's real HBM and the
    peaks are device-scale; on CPU a synthetic budget (1.5x the flat
    peak) exercises the same walk structurally — either way the record
    lands in MEMCONFIG.json so capacity planning is a repo artifact,
    not tribal knowledge."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                   build_train_step)
    from paddle_tpu.models.llama import llama_decay_mask
    from paddle_tpu.parallel.memory import (MEMORY_LATTICE,
                                            init_offloaded_state,
                                            measure_step_memory,
                                            tune_memory_config)

    on_tpu = jax.default_backend() not in ("cpu",)
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          intermediate_size=5504, num_hidden_layers=10,
                          num_attention_heads=16, num_key_value_heads=4,
                          max_position_embeddings=2048)
        batch, seq = 6, 1024
        compute_dtype = jnp.bfloat16
        kind = jax.devices()[0].device_kind.lower()
        hbm = int(16e9 if ("v5 lite" in kind or "v5e" in kind)
                  else 95e9 if "v5p" in kind else 32e9)
    else:
        cfg = LlamaConfig.debug()
        batch, seq = 4, 64
        compute_dtype = jnp.float32
        hbm = None                       # synthetic, set from flat peak

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    params = model.functional_state()
    mask = llama_decay_mask(model)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    labels = rng.integers(0, cfg.vocab_size, (batch, seq)).astype(
        np.int32)

    def builder(mc):
        step = build_train_step(model, opt, compute_dtype=compute_dtype,
                                memory=mc)
        if mc.optimizer_residency == "host":
            st = init_offloaded_state(opt, params, decay_mask=mask,
                                      bucket_bytes=mc.stream_bucket_bytes)
        else:
            st = opt.init_flat_state(params, decay_mask=mask)
        return step, (params, st, jnp.int32(0), jnp.float32(1e-4), ids,
                      labels)

    if hbm is None:
        fn0, args0 = builder(MEMORY_LATTICE[0])
        hbm = int(measure_step_memory(fn0, *args0)["peak_bytes"] * 1.5)
    chosen, records = tune_memory_config(builder, hbm)
    out = {
        "backend": jax.default_backend(),
        "hbm_budget_bytes": hbm,
        "chosen": chosen.to_json() if chosen is not None else None,
        "lattice": [
            {"label": r["label"], "peak_bytes": r["peak_bytes"],
             "host_bytes": r["host_bytes"], "fits": r["fits"],
             "headroom_bytes": hbm - r["peak_bytes"]}
            for r in records],
        "method": ("compiled memory_analysis, device-scale" if on_tpu
                   else "compiled memory_analysis, debug shape "
                        "(structural only; CPU host==device memory)"),
    }
    try:
        with open("MEMCONFIG.json", "w") as f:
            json.dump({"hbm_budget_bytes": hbm,
                       "chosen": out["chosen"],
                       "records": records}, f, indent=1)
    except OSError:
        pass
    return out


def _profile_overlap_levers():
    """Per-lever attribution of the overlap engine (round-9 acceptance:
    exposed-communication time per lever, overlap-on never numerically
    divergent).  Levers are BUILT-PROGRAM deltas on the dp2 x sharding2
    x mp2 mesh: flat GSPMD vs overlap engine, then overlap with one
    lever disabled at a time (prefetch, bucketing, collective matmul),
    plus the hierarchical pair on a sharding4 mesh with a declared fake
    2-slice map.  On TPU the numbers are device-scale exposed-comm
    deltas; on the 8-virtual-device CPU mesh they are structural only —
    but the parity assertion is exact on both, so the leg is a
    numerical-divergence gate regardless of backend."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    import paddle_tpu as paddle
    from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                   build_train_step)
    from paddle_tpu.models.llama import apply_llama_sharding
    from paddle_tpu.parallel.overlap import OverlapConfig

    devs = jax.devices()
    if len(devs) < 8:
        return {"skipped": f"needs 8 devices for the dp2 x sharding2 x "
                           f"mp2 mesh, have {len(devs)}"}
    on_tpu = jax.default_backend() not in ("cpu",)
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          intermediate_size=5504, num_hidden_layers=10,
                          num_attention_heads=16, num_key_value_heads=4,
                          max_position_embeddings=2048)
        batch, seq, steps = 8, 1024, 2
        dtype = jnp.bfloat16
    else:
        cfg = LlamaConfig.debug(vocab=128, hidden=64, layers=2, heads=4,
                                kv_heads=2, inter=128, max_pos=64)
        batch, seq, steps = 8, 16, 1
        dtype = jnp.float32

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    mesh = Mesh(np.asarray(devs[:8], dtype=object).reshape(2, 2, 2),
                ("dp", "sharding", "mp"))
    apply_llama_sharding(model, mesh)
    params0 = model.functional_state()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    labels = rng.integers(0, cfg.vocab_size, (batch, seq)).astype(
        np.int32)

    def run(step_fn, reps=3):
        p = {k: jnp.copy(v) for k, v in params0.items()}
        st = opt.init_state(p)
        loss, p, st = step_fn(p, st, 0, 1e-4, ids, labels)
        jax.block_until_ready((loss, p))
        lval = float(loss)
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for i in range(steps):
                loss, p, st = step_fn(p, st, i + 1, 1e-4, ids, labels)
            jax.block_until_ready((loss, p))
            best = min(best, (time.perf_counter() - t0) / steps)
        return lval, best

    def mk(overlap):
        return build_train_step(model, opt, mesh=mesh,
                                compute_dtype=dtype, overlap=overlap)

    # forced-on ring threshold on CPU (tiny shapes sit below the
    # production default; the lever must exercise the ring schedule)
    cm_min = 1 if not on_tpu else OverlapConfig().collective_matmul_min_out_elems
    variants = {
        "flat_gspmd": None,
        "overlap_full": OverlapConfig(collective_matmul_min_out_elems=cm_min),
        "overlap_no_prefetch": OverlapConfig(
            prefetch=False, collective_matmul_min_out_elems=cm_min),
        "overlap_unbucketed": OverlapConfig(
            bucket_bytes=0, collective_matmul_min_out_elems=cm_min),
        "overlap_no_collective_matmul": OverlapConfig(
            collective_matmul=False),
    }
    out = {"mesh": "dp2 x sharding2 x mp2",
           "backend": jax.default_backend(),
           "method": ("device windows" if on_tpu else
                      "wall-clock 8-virtual-device (structural only)")}
    losses = {}
    for name, oc in variants.items():
        lval, t = run(mk(oc))
        losses[name] = lval
        out[f"{name}_ms"] = round(t * 1e3, 3)
    ref = losses["flat_gspmd"]
    out["parity_max_loss_dev"] = round(
        max(abs(v - ref) for v in losses.values()), 8)
    out["parity_ok"] = bool(out["parity_max_loss_dev"]
                            <= (2e-2 if dtype == jnp.bfloat16 else 1e-5)
                            * max(abs(ref), 1.0))
    for name in variants:
        if name != "flat_gspmd":
            out[f"{name}_vs_flat_ms"] = round(
                out[f"{name}_ms"] - out["flat_gspmd_ms"], 3)

    # hierarchical pair: sharding4 with a declared fake 2-slice split
    mesh4 = Mesh(np.asarray(devs[:8], dtype=object).reshape(1, 4, 2),
                 ("dp", "sharding", "mp"))
    apply_llama_sharding(model, mesh4)
    params4 = model.functional_state()

    def run4(oc):
        step_fn = build_train_step(model, opt, mesh=mesh4,
                                   compute_dtype=dtype, overlap=oc)
        p = {k: jnp.copy(v) for k, v in params4.items()}
        st = opt.init_state(p)
        loss, p, st = step_fn(p, st, 0, 1e-4, ids, labels)
        jax.block_until_ready((loss, p))
        lval = float(loss)
        t0 = time.perf_counter()
        loss, p, st = step_fn(p, st, 1, 1e-4, ids, labels)
        jax.block_until_ready((loss, p))
        return lval, time.perf_counter() - t0

    lf, tf = run4(OverlapConfig(hierarchical="off"))
    lh, th = run4(OverlapConfig(hierarchical="on",
                                slice_map=(0, 0, 1, 1)))
    out["hier_flat_ms"] = round(tf * 1e3, 3)
    out["hier_two_stage_ms"] = round(th * 1e3, 3)
    out["hier_parity_ok"] = bool(
        abs(lh - lf) <= (2e-2 if dtype == jnp.bfloat16 else 1e-5)
        * max(abs(lf), 1.0))
    apply_llama_sharding(model, mesh)   # restore
    return out


def serving_trace(smoke: bool = False, seed: int = 0):
    """Open-loop serving bench over the round-11 unified plane
    (bench.py --serving-trace -> SERVING_r01.json).

    Synthetic arrival trace: Poisson arrivals, lognormal prompt
    lengths, a configurable fraction of requests sharing one system
    prompt (chat-shaped traffic — the prefix cache's beat).  The trace
    drives ``engine.step()`` open-loop (arrivals keyed to WALL time, so
    a slow engine accumulates queue depth instead of slowing the
    offered load) through the unified engine with the radix prefix
    cache and speculative decoding enabled, and reports:

    - tokens/s/chip at the achieved fill,
    - p50/p99 per-token latency (each engine step's wall time
      attributed to the tokens it emitted),
    - p50/p99 time-to-first-token from arrival,
    - mean speculative accepted length per verify window,
    - prefix-cache hit/eviction counters + prefill-token savings.

    CPU sessions run the kernels in interpret mode — absolute numbers
    are structural; the TPU confirmation ride the BASELINE.md round-11
    checklist.  The draft is the ORACLE self-draft (the target's own
    params): it pins the acceptance plumbing at its upper bound; a
    distilled drafter only changes the acceptance rate, not the
    schedule."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.inference.serving import ContinuousBatchingEngine

    rng = np.random.default_rng(seed)
    paddle.seed(29)
    cfg = LlamaConfig.debug(vocab=64, hidden=32, layers=2, heads=4,
                            kv_heads=2, inter=64, max_pos=256)
    model = LlamaForCausalLM(cfg)
    params = {k: jnp.asarray(v)
              for k, v in model.functional_state().items()}

    n_req = 6 if smoke else 24
    rate = 40.0                      # requests/s offered (open loop)
    shared_ratio = 0.5               # chat traffic: half share a system
    max_new = 4 if smoke else 8      # prompt
    sys_prompt = rng.integers(1, cfg.vocab_size, (24,)).astype(np.int32)

    arrivals = np.cumsum(rng.exponential(1.0 / rate, n_req))
    plens = np.clip(rng.lognormal(2.2, 0.6, n_req), 4,
                    96).astype(int)
    reqs = []
    for i in range(n_req):
        body = rng.integers(1, cfg.vocab_size,
                            (int(plens[i]),)).astype(np.int32)
        # deterministic round-robin shared assignment (NOT sampled):
        # the queued tail of the trace must contain shared-prefix
        # requests so the hits>0 gate is structural, not seed luck —
        # a sampled tail can be all-private and the leg would flake
        if (i * shared_ratio) % 1.0 < shared_ratio:
            body = np.concatenate([sys_prompt, body])
        reqs.append((float(arrivals[i]), body))

    eng = ContinuousBatchingEngine(
        cfg, params, max_slots=4, num_pages=65, page_size=16,
        max_seq_len=160, prefill_token_budget=16,
        enable_prefix_cache=True, draft_params=params,
        speculative_k=2)

    t0 = time.perf_counter()
    pending = list(reqs)
    arrival_of = {}
    first_tok_at = {}
    step_tok_lat = []                # per-token latency samples
    while pending or eng.queue or eng.active.any():
        now = time.perf_counter() - t0
        while pending and pending[0][0] <= now:
            arr, prompt = pending.pop(0)
            rid = eng.add_request(prompt, max_new_tokens=max_new,
                                  arrival=arr)
            arrival_of[rid] = arr
        ts = time.perf_counter()
        produced = eng.step()
        dt = time.perf_counter() - ts
        if produced:
            step_tok_lat.extend([dt / produced] * produced)
        now = time.perf_counter() - t0
        for rid in list(eng.out_tokens) + [f.rid for f in eng.finished]:
            first_tok_at.setdefault(rid, now)
        if not pending and not eng.queue and not eng.active.any():
            break
        if not produced and pending and not eng.active.any() \
                and not eng.queue:
            time.sleep(max(0.0, pending[0][0] - now))
    elapsed = time.perf_counter() - t0
    done = sorted(eng.finished, key=lambda f: f.rid)
    stats = eng.serving_stats()
    eng.shutdown()

    lat = np.asarray(step_tok_lat) if step_tok_lat else np.zeros(1)
    cache = stats.get("prefix_cache", {})
    saved = sum(v["cached_tokens"] for v in stats["prefill"].values())
    res = {
        "ok": (len(done) == n_req
               and stats.get("mean_accepted_len", 0.0) > 1.0
               and cache.get("hits", 0) > 0),
        "backend": jax.default_backend(),
        "interpret_mode": jax.default_backend() == "cpu",
        "requests": len(done),
        "generated_tokens": int(sum(len(f.tokens) for f in done)),
        "elapsed_s": elapsed,
        "tokens_per_s_per_chip": (sum(len(f.tokens) for f in done)
                                  / elapsed / max(1, len(jax.devices()))),
        "per_token_latency_p50_ms": float(np.percentile(lat, 50) * 1e3),
        "per_token_latency_p99_ms": float(np.percentile(lat, 99) * 1e3),
        "ttft_p50_s": float(np.percentile(
            [first_tok_at[r] - arrival_of[r] for r in arrival_of], 50)),
        "ttft_p99_s": float(np.percentile(
            [first_tok_at[r] - arrival_of[r] for r in arrival_of], 99)),
        "mean_accepted_len": float(stats.get("mean_accepted_len", 0.0)),
        "prefix_cache": cache,
        "prefill_tokens_saved": int(saved),
        "trace": {"n_requests": n_req, "poisson_rate": rate,
                  "prompt_lognormal": [2.2, 0.6],
                  "shared_prompt_ratio": shared_ratio,
                  "max_new_tokens": max_new, "seed": seed},
    }
    return res


def _ensure_tests_path():
    """Make tests/fault_injection.py importable (the fault-injection
    harness doubles as the bench's scripted-trace driver)."""
    import sys as _sys

    tests_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "tests")
    if tests_dir not in _sys.path:
        _sys.path.insert(0, tests_dir)


def serving_fleet_trace(smoke: bool = False, seed: int = 0):
    """Multi-replica serving-resilience bench (round-13): a scripted
    fault trace — a replica KILL mid-decode, a watchdog-flagged HANG,
    and a sustained overload burst — through the FleetRouter over
    FakeReplicas (bench.py --serving-fleet-trace ->
    SERVING_FLEET_r01.json).

    Records what the round-13 BASELINE entry predicts against:

    - recovery time per fault (ticks from death to the replacement
      SERVING, wall seconds including weight delivery through the
      cached reshard plan),
    - shed rate (stage-3 rejections / offered) during the burst, with
      the ladder-engagement order,
    - p50/p99 per-token latency UNDER FAULT,
    - the zero-loss + bit-parity gates: every ACCEPTED request
      completes with greedy tokens identical to one-shot generate().

    CPU sessions run the kernels in interpret mode — absolute latency
    is structural; recovery tick counts and the loss/parity gates are
    exact."""
    import jax

    _ensure_tests_path()
    from fault_injection import (OverloadBurst, ReplicaFaultEvent,
                                 build_serving_fleet, run_fleet_trace,
                                 toy_llama)
    from paddle_tpu.inference.fleet import RouterConfig
    from paddle_tpu.models.generation import generate

    cfg, model, params = toy_llama()
    rng = np.random.default_rng(seed)
    n_req = 5 if smoke else 12
    max_new = 4 if smoke else 6
    sysp = rng.integers(1, cfg.vocab_size, (16,)).astype(np.int32)
    requests = []
    for i in range(n_req):
        n = int(np.clip(rng.lognormal(2.0, 0.5), 4, 24))
        body = rng.integers(1, cfg.vocab_size, (n,)).astype(np.int32)
        prompt = np.concatenate([sysp, body]) if i % 2 == 0 else body
        # named requests land on ticks 0-1, BEFORE the ladder can reach
        # the reject stage — the burst is what gets shed
        requests.append((i % 2, prompt, max_new))
    # the heartbeat timeout needs real headroom over a LOADED interpret-
    # mode step (~70 ms p99 on throttled CPU): 0.5 s never false-flags,
    # the scripted 1.2 s stall always does
    scripts = {0: [ReplicaFaultEvent(step=3, kind="kill")],
               1: [ReplicaFaultEvent(step=6, kind="hang", stall_s=1.2)]}
    router, rs = build_serving_fleet(
        cfg, params, target=2, step_timeout_s=0.5, scripts=scripts,
        router_cfg=RouterConfig(admission_token_cap=48))
    bursts = [OverloadBurst(tick=2, n_requests=5,
                            duration=5 if smoke else 8,
                            prompt_len=20, max_new_tokens=4)]

    t0 = time.perf_counter()
    res = run_fleet_trace(router, requests, bursts=bursts, seed=seed)
    elapsed = time.perf_counter() - t0
    out = router.results()
    lost = [rid for rid in res["rids"] if rid not in out]
    parity = True
    for rid, prompt, mnew in res["submitted"]:
        if rid not in out:
            continue
        ref = generate(model, prompt[None], max_new_tokens=mnew,
                       do_sample=False)
        ref_new = np.asarray(ref._value if hasattr(ref, "_value")
                             else ref)[0, len(prompt):]
        parity &= (len(out[rid]) == mnew
                   and np.array_equal(out[rid], ref_new))
    stats = router.stats()
    lat = np.asarray(res["per_token_lat"]) if res["per_token_lat"] \
        else np.zeros(1)
    ladder_ups = [(ev["from"], ev["to"]) for ev in stats["ladder_log"]
                  if ev["to"] > ev["from"]]
    faults = sorted(ev["fault"] for ev in stats["recoveries"])
    # a recovery event with no replacement is a MISSED recovery, not a
    # 0-tick one — it fails the gate and is reported separately
    unrecovered = [ev for ev in stats["recoveries"]
                   if ev["replacement_id"] is None]
    recovered_ticks = [ev["recovery_ticks"] for ev in stats["recoveries"]
                       if ev["recovery_ticks"] is not None]
    delivery = rs.check_delivery_budget()
    ok = (not lost and parity
          and faults == ["ReplicaHung", "ReplicaKilled"]
          and not unrecovered
          and res["rejected"] > 0
          and ladder_ups[:3] == [(0, 1), (1, 2), (2, 3)]
          and delivery.ok)
    return {
        "ok": bool(ok),
        "backend": jax.default_backend(),
        "interpret_mode": jax.default_backend() == "cpu",
        "accepted": len(res["rids"]),
        "completed": len(out),
        "lost": len(lost),
        "bit_identical": bool(parity),
        "rejected": res["rejected"],
        "shed_rate": stats["shed_rate"],
        "ladder_ups": ladder_ups,
        "recoveries": stats["recoveries"],
        "unrecovered": len(unrecovered),
        "recovery_ticks_max": max(recovered_ticks, default=0),
        "per_token_latency_p50_ms": float(np.percentile(lat, 50) * 1e3),
        "per_token_latency_p99_ms": float(np.percentile(lat, 99) * 1e3),
        "elapsed_s": elapsed,
        "ticks": res["ticks"],
        "delivery": {"plans_built": rs.telemetry["plans_built"],
                     "deliveries": rs.telemetry["deliveries"],
                     "moved_bytes": int(rs.delivery_plan().moved_bytes),
                     "doctor_ok": bool(delivery.ok)},
        "trace": {"n_requests": n_req, "burst": "5/tick",
                  "max_new_tokens": max_new, "seed": seed},
    }


def _drive_router_trace(router, schedule):
    """Deterministic driver shared by the disagg bench runs: submit
    each (tick, prompt, max_new) at its tick, step to drain, and record
    per-token latency plus per-request TTFT (wall from submit to the
    first COMMITTED token — for the disaggregated fleet that spans
    prefill, KV handoff and the first decode harvest)."""
    from paddle_tpu.inference.fleet import OverloadRejected

    by_tick = {}
    for t, prompt, mnew in schedule:
        by_tick.setdefault(int(t), []).append((prompt, mnew))
    submitted = {}          # rid -> (prompt, mnew, t_submit)
    ttft = {}
    lat = []
    rejected = 0
    tick = 0
    while True:
        for prompt, mnew in by_tick.pop(tick, []):
            try:
                rid = router.submit(prompt, max_new_tokens=mnew)
            except OverloadRejected:     # ladder stage 3: explicit shed
                rejected += 1
                continue
            submitted[rid] = (prompt, mnew, time.perf_counter())
        t0 = time.perf_counter()
        produced = router.step()
        dt = time.perf_counter() - t0
        if produced:
            lat.extend([dt / produced] * produced)
        now = time.perf_counter()
        for rid, (_, _, ts) in submitted.items():
            if rid not in ttft:
                req = router.requests.get(rid)
                if req is not None and req.emitted:
                    ttft[rid] = now - ts
        tick += 1
        if not by_tick and not router.pending():
            break
        if tick > 3000:
            raise RuntimeError("disagg trace did not drain")
    return {"submitted": submitted, "ttft": ttft, "per_token_lat": lat,
            "rejected": rejected, "ticks": tick}


def serving_disagg_trace(smoke: bool = False, seed: int = 0):
    """Disaggregated prefill/decode bench (round-16): the SAME
    prompt-burst trace through (a) the round-13 unified fleet and
    (b) the two-pool disaggregated fleet, plus (full mode) the int8-KV
    disaggregated fleet — bench.py --serving-disagg-trace ->
    SERVING_DISAGG_r01.json.

    Records what the round-16 BASELINE entry predicts against:

    - p50/p99 per-token latency and TTFT, unified vs disaggregated
      (CPU sessions run interpret-mode kernels: the absolute numbers
      are structural, the unified-vs-disagg SHAPE is the prediction —
      decode p99 flat under the prompt burst);
    - KV-handoff bytes pre/post the int8 KV form (the quantized wire:
      int8 pages move ~1 byte/element bit-exactly; the float-cache
      handoff is the raw denominator), with the plan-once/stream-per-
      handoff telemetry and the MEM001 + wire budget doctor gates;
    - the zero-loss + bit-parity gates: disaggregated greedy streams
      identical to one-shot generate() on every completed request.

    Smoke mode runs the disaggregated float fleet only and computes
    the int8 wire ratio structurally from the same page geometry."""
    import jax
    import jax.numpy as jnp

    _ensure_tests_path()
    from fault_injection import (build_disagg_fleet, build_serving_fleet,
                                 toy_llama)
    from paddle_tpu.models.generation import generate

    cfg, model, params = toy_llama()
    rng = np.random.default_rng(seed)
    n_req = 5 if smoke else 12
    max_new = 4 if smoke else 6
    sysp = rng.integers(1, cfg.vocab_size, (16,)).astype(np.int32)
    schedule = []
    for i in range(n_req):
        n = int(np.clip(rng.lognormal(2.0, 0.5), 4, 24))
        body = rng.integers(1, cfg.vocab_size, (n,)).astype(np.int32)
        prompt = np.concatenate([sysp, body]) if i % 2 == 0 else body
        # a prompt BURST: everything lands on ticks 0-2
        schedule.append((i % 3, prompt, max_new))

    def check_parity(router, res):
        ok = True
        for rid, (prompt, mnew, _) in res["submitted"].items():
            out = router.results().get(rid)
            if out is None:
                return False, 1
            ref = generate(model, prompt[None], max_new_tokens=mnew,
                           do_sample=False)
            ref_new = np.asarray(ref._value if hasattr(ref, "_value")
                                 else ref)[0, len(prompt):]
            ok &= (len(out) == mnew and np.array_equal(out, ref_new))
        return ok, 0

    def pcts(xs):
        a = np.asarray(list(xs)) if xs else np.zeros(1)
        return {"p50_ms": float(np.percentile(a, 50) * 1e3),
                "p99_ms": float(np.percentile(a, 99) * 1e3)}

    t0 = time.perf_counter()
    runs = {}
    routers = {}
    # (a) unified fleet baseline (full mode only — the smoke leg's
    # parity bar is the disagg run against one-shot generate)
    if not smoke:
        router_u, _ = build_serving_fleet(cfg, params, target=2)
        res_u = _drive_router_trace(router_u, schedule)
        par_u, lost_u = check_parity(router_u, res_u)
        runs["unified"] = {
            "parity": par_u, "lost": lost_u, "ticks": res_u["ticks"],
            "per_token": pcts(res_u["per_token_lat"]),
            "ttft": pcts(res_u["ttft"].values())}
    # (b) disaggregated fleet, float KV (the raw-handoff denominator)
    router_d, rs_d = build_disagg_fleet(cfg, params, prefill=1,
                                        decode=2 if not smoke else 1)
    res_d = _drive_router_trace(router_d, schedule)
    par_d, lost_d = check_parity(router_d, res_d)
    hd = dict(router_d.planner.telemetry)
    runs["disagg"] = {
        "parity": par_d, "lost": lost_d, "ticks": res_d["ticks"],
        "per_token": pcts(res_d["per_token_lat"]),
        "ttft": pcts(res_d["ttft"].values()),
        "handoffs": router_d.telemetry["handoffs"],
        "handoffs_mid_decode": router_d.telemetry["handoffs_mid_decode"],
        "handoff_bytes": hd}
    routers["disagg"] = router_d
    # (c) the int8-KV wire: real fleet in full mode, structural page
    # arithmetic in smoke (same geometry, 1 byte/elem + the engine's
    # frozen scale sidecar living OUTSIDE the per-handoff wire)
    raw_bytes = hd["bytes_wire"]
    if smoke:
        itemsize = np.dtype(np.float32).itemsize
        int8_bytes = raw_bytes // itemsize
        runs["disagg_int8"] = {"structural": True,
                               "handoff_bytes_wire": int8_bytes}
        par_i = True
    else:
        router_i, _ = build_disagg_fleet(cfg, params, prefill=1,
                                         decode=2,
                                         cache_dtype=jnp.int8)
        res_i = _drive_router_trace(router_i, schedule)
        int8_bytes = router_i.planner.telemetry["bytes_wire"]
        # int8 parity is against the int8 unified ENGINE (the quantized
        # cache shifts near-ties vs the float reference by design); the
        # tier-1 test pins it bit-for-bit — here the gate is completion
        par_i = len(router_i.results()) == len(res_i["submitted"])
        runs["disagg_int8"] = {
            "completed_all": par_i, "ticks": res_i["ticks"],
            "per_token": pcts(res_i["per_token_lat"]),
            "ttft": pcts(res_i["ttft"].values()),
            "handoffs": router_i.telemetry["handoffs"],
            "handoff_bytes": dict(router_i.planner.telemetry)}
        routers["disagg_int8"] = router_i
    ratio = raw_bytes / int8_bytes if int8_bytes else 0.0

    # the doctor gates on the last real handoff payload; the wire
    # budget is PER-PAYLOAD and derived from the payload GEOMETRY (the
    # int8 page form: 1 byte/element), never from the measured plan
    # itself — so a silently-dropped int8 cache (4 bytes/element on
    # the wire) fires the gate instead of re-deriving its own budget
    doctor_router = routers.get("disagg_int8", router_d)
    tree = doctor_router.planner.last_tree
    delivery_ok = True
    if tree is not None:
        if doctor_router is router_d:
            # smoke mode has only the float fleet: gate MEM001 alone
            # (the wire gate's fire/clean behavior is pinned tier-1 in
            # tests/test_serving_disagg.py on the int8 payload)
            rep = doctor_router.planner.check_handoff_budget(tree)
        else:
            int8_form_bytes = sum(int(np.prod(np.shape(v)))
                                  for v in tree.values())
            rep = doctor_router.planner.check_handoff_budget(
                tree, wire_budget_bytes=int8_form_bytes)
        delivery_ok = rep.ok
    ok = (par_d and par_i and not lost_d
          and runs["disagg"]["handoffs"] > 0
          and ratio > 1.5 and delivery_ok
          and (smoke or (runs["unified"]["parity"]
                         and not runs["unified"]["lost"])))
    return {
        "ok": bool(ok),
        "backend": jax.default_backend(),
        "interpret_mode": jax.default_backend() == "cpu",
        "runs": runs,
        "handoff_bytes_raw": int(raw_bytes),
        "handoff_bytes_int8": int(int8_bytes),
        "handoff_wire_ratio": round(float(ratio), 3),
        "handoff_doctor_ok": bool(delivery_ok),
        "elapsed_s": time.perf_counter() - t0,
        "trace": {"n_requests": n_req, "max_new_tokens": max_new,
                  "burst_ticks": 3, "seed": seed},
    }


def health_trace(smoke: bool = False, seed: int = 0):
    """bench.py --health-trace -> HEALTH_r01.json (round-17 training
    health guardian): scripted numeric-fault traces through the armed
    ``resilient_train_loop`` on the deterministic toy problem, plus the
    SDC checksum legs.  Records what BASELINE.md round-17 predicts
    against:

    - detection latency in STEPS per fired rule (the in-step gates make
      it 0 — the faulted update never applies);
    - response-ladder stage counts (skip / lr-backoff / rollback /
      forced replay skips) per trace;
    - steps replayed by the rollback leg (bounded by
      checkpoint_every) with the skip leg's bit-identical-params gate;
    - the codec-checksum legs: a flipped coded payload raises
      ChecksumError on the host delivery path and NaN-poisons (probe
      catches) inside jit;
    - the HEALTH001/002 fixtures firing exactly."""
    import tempfile

    import jax

    _ensure_tests_path()
    from fault_injection import (FaultEvent, NumericFaultEvent, flip_bit,
                                 run_toy_health_loop, toy_init,
                                 toy_mesh_builder, toy_step_builder,
                                 toy_target)
    from paddle_tpu.distributed.health import HealthConfig

    t0 = time.perf_counter()
    steps = 12 if smoke else 24
    out = {"backend": jax.default_backend(),
           "trace": {"steps": steps, "seed": seed}}

    # leg 1 — NaN batch: in-step skip, params BIT-IDENTICAL to a clean
    # run that never saw the quarantined batch
    with tempfile.TemporaryDirectory() as d:
        res = run_toy_health_loop(
            d, num_steps=steps,
            numeric_faults=[NumericFaultEvent(offset=5, kind="nan")])[0]
    mesh, specs = toy_mesh_builder(jax.devices())
    state = toy_init(mesh, specs)
    fold = toy_step_builder(mesh, specs)
    for t in range(steps):
        if t != 5:
            state = fold(state, toy_target(t))[1]
    skip_parity = bool(
        np.array_equal(np.asarray(res.state["w"]),
                       np.asarray(state["w"]))
        and np.array_equal(np.asarray(res.state["opt"]["m"]),
                           np.asarray(state["opt"]["m"])))
    out["skip"] = {
        "parity_bit_identical": skip_parity,
        "stage_counts": res.health["stage_counts"],
        "detection_latency_steps": res.health["detection_latency_steps"],
        "quarantined": [(r["data_offset"], r["rule"])
                        for r in res.health["quarantined"]]}

    # leg 2 — loss-spike burst straddling a checkpoint window: skip ->
    # lr-backoff -> rollback, genuine replay bounded by the interval
    with tempfile.TemporaryDirectory() as d:
        res2 = run_toy_health_loop(
            d, num_steps=max(14, steps),
            numeric_faults=[NumericFaultEvent(offset=5, kind="spike"),
                            NumericFaultEvent(offset=6, kind="spike"),
                            NumericFaultEvent(offset=7, kind="spike")])[0]
    ev = res2.recoveries[0] if res2.recoveries else None
    sc2 = res2.health["stage_counts"]
    out["ladder"] = {
        "stage_counts": sc2,
        "detection_latency_steps": res2.health["detection_latency_steps"],
        "rollback_fault": ev.fault if ev else None,
        "resume_step": ev.resume_step if ev else None,
        "steps_replayed": ev.steps_replayed if ev else None,
        "checkpoint_every": 4}
    ladder_ok = (ev is not None and ev.fault == "NumericFault"
                 and 0 < ev.steps_replayed <= 4
                 and sc2["skip"] == 1 and sc2["backoff"] == 1
                 and sc2["rollback"] == 1
                 and res2.final_step == max(14, steps))

    # leg 3 — SDC spot-check: a diverging peer crc rolls back
    with tempfile.TemporaryDirectory() as d:
        res3 = run_toy_health_loop(
            d, num_steps=max(14, steps),
            health=HealthConfig(warmup_steps=3, spot_check_every=4,
                                spot_check_slices=2),
            faults=[FaultEvent(step=8, kind="sdc")])[0]
    sdc_ok = (len(res3.recoveries) == 1
              and res3.recoveries[0].fault == "SDCError"
              and res3.final_step == max(14, steps))
    out["sdc"] = {"fault": (res3.recoveries[0].fault
                            if res3.recoveries else None),
                  "steps_replayed": (res3.recoveries[0].steps_replayed
                                     if res3.recoveries else None)}

    # leg 4 — codec checksums: host path raises, jit path poisons
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from paddle_tpu.parallel.codec import (ChecksumError, CollectiveCodec,
                                           decode_rows, encode_rows)
    from paddle_tpu.parallel.reshard import execute_encoded, plan_reshard

    codec = CollectiveCodec(block=64, weight_profile="int8",
                            checksum=True)
    host = {"w": np.random.RandomState(seed).randn(64, 32).astype(
        np.float32)}
    m1 = Mesh(np.asarray(jax.devices()[:1], dtype=object), ("r",))
    plan = plan_reshard(host, m1, None)
    caught = False
    try:
        execute_encoded(plan, host, codec,
                        corrupt=lambda p, path, ci: flip_bit(p, 17))
    except ChecksumError:
        caught = True
    packed = np.asarray(encode_rows(
        jnp.asarray(host["w"].reshape(2, -1)), codec, "int8"))
    poisoned = np.asarray(decode_rows(
        jnp.asarray(flip_bit(packed, 9)), host["w"].size // 2, codec,
        "int8"))
    poison_ok = bool(np.isnan(poisoned[0]).all()
                     and np.isfinite(poisoned[1]).all())
    out["checksum"] = {"host_flip_caught": caught,
                       "jit_flip_poisons_nan": poison_ok,
                       "wire_overhead_bytes_per_row": 4}

    # leg 5 — the doctor's HEALTH fixtures fire exactly
    from paddle_tpu.analysis.fixtures import SEEDED

    fixtures = {}
    for code in ("HEALTH001", "HEALTH002"):
        try:
            rep = SEEDED[code]()
            fixtures[code] = sorted(set(rep.codes())) == [code]
        except Exception as e:  # noqa: BLE001
            fixtures[code] = False
            out.setdefault("fixture_errors", {})[code] = repr(e)
    out["fixtures"] = fixtures

    out["ok"] = bool(skip_parity and ladder_ok and sdc_ok and caught
                     and poison_ok and all(fixtures.values()))
    out["elapsed_s"] = time.perf_counter() - t0
    return out


def comm_bytes_trace(smoke=False):
    """bench.py --comm-bytes-trace — structural (CPU-runnable) pre/post-
    codec bytes-on-the-wire report for the flagship hierarchical overlap
    step on the fake-2-slice mesh (round-15 quantized DCN collectives):

    - per BUCKET of the bucketed grad reduce-scatter: the fwd
      weights-gather DCN payload and the bwd grad-reduce DCN residue,
      raw vs block-scaled packed int8 (+bf16 scale sidecar).  Raw
      bytes use the ACTUAL wire dtype: the weights-gather moves the
      bf16 compute dtype on every backend; the grad reduce-scatter
      moves bf16 on TPU but fp32 on this CPU harness (XLA:CPU's bf16
      reduction promotion, parallel/compat.py);
    - the traced per-stage (ICI/DCN) wire tables, codec off vs on
      (analysis.self_check.flagship_wire_table — what COMM004 budgets
      and DOCTOR.json carries).

    ``ok`` requires the bucketed reduce-scatter's DCN bytes to shrink
    >= 3x with the int8 codec on the fp32-wire CPU harness (the
    round-15 acceptance bar); on a bf16-wire backend the achievable
    ceiling is ~2x (1 byte vs 2 bytes per element) and the bar scales
    to >= 1.7 — same codec, honest denominator."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle  # noqa: F401 (registers ops)

    devs = jax.devices()
    if len(devs) < 8:
        return {"ok": True,
                "skipped": f"needs 8 devices (have {len(devs)}); the "
                           f"tier-1 suite runs this leg on the virtual "
                           f"CPU mesh"}
    from jax.sharding import Mesh

    from paddle_tpu.analysis.self_check import (_flagship,
                                                FLAGSHIP_SLICE_MAP,
                                                flagship_wire_table)
    from paddle_tpu.models.llama import (_filter_spec_to_mesh,
                                         apply_llama_sharding,
                                         plan_spec_for)
    from paddle_tpu.parallel import overlap as OV
    from paddle_tpu.parallel.codec import CollectiveCodec, packed_width

    cfg, model, opt, params, ids, labels = _flagship()
    mesh = Mesh(np.asarray(devs[:8], dtype=object).reshape(1, 4, 2),
                ("dp", "sharding", "mp"))
    apply_llama_sharding(model, mesh)
    codec = CollectiveCodec()
    oc = OV.OverlapConfig(hierarchical="on",
                          slice_map=FLAGSHIP_SLICE_MAP, codec=codec)
    shapes = OV.llama_layer_shapes(cfg)
    layout, buckets, _ = OV.stack_layout_plan(
        shapes, mesh,
        lambda s: _filter_spec_to_mesh(plan_spec_for(s), mesh), oc,
        compute_dtype=jnp.bfloat16)
    hier = oc.resolve_hier(mesh, "sharding")
    sh = int(mesh.shape["sharding"])
    mp = int(mesh.shape["mp"])
    S, K = hier.num_slices, hier.per_slice
    L = cfg.num_hidden_layers
    # actual wire itemsizes for the bf16-compute flagship: the
    # weights-gather is pure data movement -> bf16 everywhere; the grad
    # reduce-scatter is a REDUCTION, promoted to fp32 on XLA:CPU only
    # (parallel/compat.py) — bf16 on TPU.  The acceptance bar scales
    # with the denominator: >= 3x against fp32 wire, >= 1.7x against
    # bf16 (whose 2-bytes->1-byte ceiling is ~2x).
    gather_itemsize = 2
    reduce_itemsize = 4 if jax.default_backend() == "cpu" else 2
    reduce_bar = 3.0 if reduce_itemsize == 4 else 1.7
    rows = []
    for bi, bucket in enumerate(buckets):
        local = sum(int(np.prod(layout[s].local_shape(sh, mp)))
                    for s in bucket)
        full = local * sh
        residue = full // K          # what survives the ICI stage
        gather_raw = local * gather_itemsize
        gather_coded = packed_width(local, codec.block)
        reduce_raw = residue * reduce_itemsize
        reduce_coded = S * packed_width(residue // S, codec.block)
        rows.append({
            "bucket": bi, "suffixes": list(bucket), "layers": L,
            "elems_local": local, "elems_full": full,
            # ICI legs are full-precision on purpose (the placement
            # rule): identical pre/post codec
            "ici_gather_bytes": local * gather_itemsize * (K - 1),
            "ici_reduce_bytes": full * reduce_itemsize * (K - 1) // K,
            "gather_dcn_bytes_raw": gather_raw,
            "gather_dcn_bytes_coded": gather_coded,
            "gather_ratio": round(gather_raw / gather_coded, 3),
            "reduce_dcn_bytes_raw": reduce_raw,
            "reduce_dcn_bytes_coded": reduce_coded,
            "reduce_ratio": round(reduce_raw / reduce_coded, 3),
        })
    wire = flagship_wire_table()
    rs_ratio = wire.get("reducescatter_ratio") or 0.0
    ok = (bool(rows)
          and all(r["reduce_ratio"] >= reduce_bar for r in rows)
          and rs_ratio >= reduce_bar)
    out = {"ok": bool(ok),
           "backend": jax.default_backend(),
           "reduce_wire_itemsize": reduce_itemsize,
           "reduce_ratio_bar": reduce_bar,
           "codec": codec.to_json(),
           "slice_map": list(FLAGSHIP_SLICE_MAP),
           "num_slices": S, "per_slice": K,
           "buckets": rows,
           "traced_reducescatter_ratio": rs_ratio,
           "traced_dcn_ratio": wire.get("dcn_ratio")}
    if not smoke:
        out["wire_tables"] = {k: wire[k]
                              for k in ("codec_off", "codec_on")
                              if k in wire}
    return out


def moe_trace(smoke: bool = False):
    """bench.py --moe-trace -> MOE_r02.json (round-18 MoE expert
    parallelism + the round-20 DROPLESS engine): the capacity AND
    dropless EP train steps, side by side, on the fake-2-slice
    dp1 x sharding2 x ep4 mesh —

    - tokens/s through both coded EP steps (structural on CPU; the TPU
      confirmation rides BASELINE checklist (k)/(n));
    - dispatch bytes pre/post codec PER ENGINE: the traced per-stage
      (ICI/DCN) wire tables with the codec off vs on, and each
      engine's dispatch all-to-all DCN ratio (>= 3x is the acceptance
      bar — COMM004 pins the same contracts in self_check);
    - dropped-token rate: capacity-overflow telemetry per step for the
      capacity engine; STRUCTURALLY zero for the dropless engine
      (asserted, not observed — no [E, C, d] buffer exists);
    - load-balance entropy: normalized entropy of the global
      per-expert top-1 routing fraction (1.0 = perfectly balanced).
    """
    import time

    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle  # noqa: F401 (registers ops)

    devs = jax.devices()
    if len(devs) < 8:
        return {"ok": True,
                "skipped": f"needs 8 devices (have {len(devs)}); the "
                           f"tier-1 suite runs this leg on the virtual "
                           f"CPU mesh"}
    from paddle_tpu.analysis.passes.collective_budget import \
        collect_wire_table
    from paddle_tpu.analysis.self_check import (
        MOE_DCN_WIRE_BUDGET, MOE_DROPLESS_DCN_WIRE_BUDGET,
        MOE_SLICE_MAP, _moe_ep_flagship)
    from paddle_tpu.parallel.codec import CollectiveCodec
    from paddle_tpu.parallel.expert import (
        build_moe_ep_dropless_train_step, build_moe_ep_train_step)
    from paddle_tpu.parallel.overlap import OverlapConfig

    cfg, mesh, params0, x2d, tgt = _moe_ep_flagship()
    dcn_axes = {"ep": list(MOE_SLICE_MAP)}
    steps = 3 if smoke else 10
    g = int(x2d.shape[0])

    def run_engine(build):
        """Wire tables (codec off/on) + a timed codec-on loop for one
        EP engine; the wire loop's last iteration IS the coded step."""
        wire = {}
        for name, codec in (("codec_off", None),
                            ("codec_on", CollectiveCodec(block=64))):
            oc = OverlapConfig(hierarchical="on",
                               slice_map=MOE_SLICE_MAP, codec=codec)
            step = build(cfg, mesh, oc=oc)
            wire[name] = collect_wire_table(
                jax.make_jaxpr(step)(params0, x2d, tgt).jaxpr, dcn_axes)
        off_a2a = wire["codec_off"]["dcn"]["kinds"].get(
            "alltoall", {}).get("bytes", 0)
        on_a2a = wire["codec_on"]["dcn"]["kinds"].get(
            "alltoall", {}).get("bytes", 0)
        ratio = off_a2a / on_a2a if on_a2a else None
        # the steps donate their params arg — give each engine its own
        # placed copy so the second engine doesn't read deleted buffers
        params = jax.tree_util.tree_map(jnp.copy, params0)
        losses, drops, loads = [], [], []
        loss, aux, dropped, load, params = step(params, x2d, tgt)
        jax.block_until_ready(loss)     # compile outside the clock
        # keep the timed loop ASYNC (file convention, cf. the train
        # bench): device outputs are collected and converted to host
        # values only after the clock stops, so wall measures
        # pipelined throughput
        t0 = time.perf_counter()
        for _ in range(steps):
            loss, aux, dropped, load, params = step(params, x2d, tgt)
            losses.append(loss)
            drops.append(dropped)
            loads.append(load)
        jax.block_until_ready((losses, drops, loads))
        wall = time.perf_counter() - t0
        losses = [float(v) for v in losses]
        drops = [float(v) for v in drops]
        loads = [np.asarray(v) for v in loads]
        load_mean = np.mean(loads, axis=0)
        p = load_mean / max(load_mean.sum(), 1e-9)
        entropy = float(-(p * np.log(np.maximum(p, 1e-12))).sum()
                        / np.log(len(p)))
        return {"tokens_per_s": round(steps * g / wall, 1),
                "loss_first_last": [losses[0], losses[-1]],
                "losses_finite_decreasing":
                    bool(all(np.isfinite(losses))
                         and losses[-1] < losses[0]),
                "dispatch_dcn_bytes_raw": off_a2a,
                "dispatch_dcn_bytes_coded": on_a2a,
                "dispatch_dcn_ratio": (round(ratio, 3) if ratio
                                       else None),
                "total_dcn_bytes": {k: wire[k]["dcn"]["bytes"]
                                    for k in wire},
                "dropped_token_rate":
                    float(np.mean(drops) / (g * cfg.top_k)),
                "load_balance_entropy": entropy,
                "per_expert_load": [round(float(v), 4)
                                    for v in load_mean],
                "wire_tables": wire}

    cap = run_engine(build_moe_ep_train_step)
    drop = run_engine(build_moe_ep_dropless_train_step)
    cap_ok = (cap["dispatch_dcn_ratio"] is not None
              and cap["dispatch_dcn_ratio"] >= 3.0
              and cap["total_dcn_bytes"]["codec_on"]
              <= MOE_DCN_WIRE_BUDGET
              and cap["losses_finite_decreasing"]
              and 0.0 <= cap["dropped_token_rate"] < 1.0
              and 0.0 < cap["load_balance_entropy"] <= 1.0)
    drop_ok = (drop["dispatch_dcn_ratio"] is not None
               and drop["dispatch_dcn_ratio"] >= 3.0
               and drop["total_dcn_bytes"]["codec_on"]
               <= MOE_DROPLESS_DCN_WIRE_BUDGET
               and drop["losses_finite_decreasing"]
               and drop["dropped_token_rate"] == 0.0
               and 0.0 < drop["load_balance_entropy"] <= 1.0)
    out = {"ok": bool(cap_ok and drop_ok),
           "backend": jax.default_backend(),
           "mesh": "dp1 x sharding2 x ep4 (fake 2-slice)",
           "slice_map": list(MOE_SLICE_MAP),
           "num_experts": cfg.num_expert, "top_k": cfg.top_k,
           "capacity_factor": cfg.capacity_factor,
           "steps": steps, "tokens_per_step": g,
           "dcn_wire_budget": MOE_DCN_WIRE_BUDGET,
           "dropless_dcn_wire_budget": MOE_DROPLESS_DCN_WIRE_BUDGET,
           "tokens_per_s_capacity_vs_dropless": [
               cap["tokens_per_s"], drop["tokens_per_s"]]}
    for name, leg in (("capacity", cap), ("dropless", drop)):
        if smoke:
            leg = {k: v for k, v in leg.items() if k != "wire_tables"}
        out[name] = leg
    # back-compat flat fields (round-18 consumers read the capacity leg)
    for k in ("tokens_per_s", "loss_first_last",
              "dispatch_dcn_bytes_raw", "dispatch_dcn_bytes_coded",
              "dispatch_dcn_ratio", "total_dcn_bytes",
              "dropped_token_rate", "load_balance_entropy",
              "per_expert_load"):
        out[k] = out["capacity"][k]
    return out


def doctor():
    """bench.py --doctor — run the Graph Doctor (paddle_tpu.analysis)
    over the benched steps: every seeded-bug fixture must trigger exactly
    its finding code, the flagship entry points (build_train_step in
    both accum regimes, llama fwd/bwd, the serving decode chunk) must
    report zero findings, and every tracked exemption must still match a
    live suppressed finding.  Round-14: DOCTOR.json additionally carries
    the ``sharding`` block (per-stack reshard audits + the cross-stack
    SpecLayout agreement gate) and ``sharding_canonical_table`` — the
    flagship's canonical per-tensor spec table.  Round-19: the
    ``sharding`` block gains the SCHED001 derivation gates (the unified
    PartitionSchedule vs the hand-written tables, byte-identical) and
    DOCTOR.json carries ``unified_schedule`` — the shrunk pinned
    reshard allowances plus the joint partition x memory x overlap
    autotune's CHOSEN schedule.  Writes DOCTOR.json; exits non-zero
    from the CLI on any failure (see ANALYSIS.md for the finding
    codes)."""
    from paddle_tpu.analysis import self_check

    res = self_check()
    res["doctor"] = True
    return res


class _FastSkip(Exception):
    """Round-17 tier-1 wall management: a smoke leg skipped in fast
    mode because a DEDICATED tier-1 suite asserts the same property in
    the same run (the annotation names it).  The CLI ``--smoke`` keeps
    full mode."""

    def __init__(self, home: str):
        self.home = home


def schedule_trace(smoke: bool = False):
    """bench.py --schedule-trace -> SCHEDULE_r01.json (round-19 unified
    partitioning schedule):

    - the flagship accum-4 RESHARD BILL, schedule-derived (shard-major
      FlatUpdateLayout) vs the legacy row-major wire format — the
      SHARD001 numbers the unified schedule shrank (23 all-to-alls /
      148 collective-permutes / 75 all-gathers -> 5 / 14 / 57 on the
      container toolchain), attributed to the flat-update tactic whose
      boundary the schedule derivation removed;
    - per-TACTIC manual-collective wire bytes of the hierarchical
      overlap step (axis -> named tactic: sharding3 / tp / dp / sep /
      ep), ICI vs DCN staged — where each tactic spends its wire;
    - the joint partition x memory x overlap autotune under the pinned
      HBM + DCN budgets (memoized doctor section: the walk's records,
      the three forcing picks, the CHOSEN schedule DOCTOR.json
      carries).

    ``ok`` requires the schedule-derived bill within the pinned
    allowances, >= 3x fewer collective-permutes AND all-to-alls than
    the row-major wire format, and the joint autotune's three-way
    forcing structure to hold.  ``smoke`` skips the row-major
    comparison compile (the round-14 pinned bill is the recorded
    "before") — the tier-1 leg in tests/test_bench_smoke.py runs this
    mode; the CLI runs everything."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle  # noqa: F401 (registers ops)

    devs = jax.devices()
    if len(devs) < 8:
        return {"ok": True,
                "skipped": f"needs 8 devices (have {len(devs)}); the "
                           f"tier-1 suite runs this leg on the virtual "
                           f"CPU mesh"}
    from jax.sharding import Mesh

    from paddle_tpu.analysis.core import AnalysisContext
    from paddle_tpu.analysis.passes.collective_budget import (
        collect_wire_by_axis, scan_hlo_collectives)
    from paddle_tpu.analysis.self_check import (
        _flagship, FLAGSHIP_SLICE_MAP, SHARDING_RESHARD_ALLOWANCES,
        joint_schedule_section)
    from paddle_tpu.models import build_train_step
    from paddle_tpu.models.llama import (apply_llama_sharding,
                                         llama_decay_mask)
    from paddle_tpu.parallel.overlap import OverlapConfig
    from paddle_tpu.parallel.schedule import (PartitionSchedule,
                                              _AXIS_TO_TACTIC)

    cfg, model, opt, params0, ids, labels = _flagship()
    mesh = Mesh(np.asarray(devs[:8], dtype=object).reshape(2, 2, 2),
                ("dp", "sharding", "mp"))
    apply_llama_sharding(model, mesh)
    params = {k: jnp.asarray(v)
              for k, v in model.functional_state().items()}
    mask = llama_decay_mask(model)
    sched = PartitionSchedule.from_model(model, mesh)

    def reshard_bill(state):
        step = build_train_step(model, opt, mesh=mesh,
                                compute_dtype=jnp.bfloat16,
                                accum_steps=4, schedule=sched)
        ctx = AnalysisContext(
            step, (params, state, 0, 1e-4, ids.reshape(4, 1, 16),
                   labels.reshape(4, 1, 16)), {})
        hlo = scan_hlo_collectives(ctx.compiled_text)
        return {k: dict(v) for k, v in hlo.items() if v["count"]}

    lo = sched.flat_update_layout()
    pinned = SHARDING_RESHARD_ALLOWANCES["gspmd[accum4]"]
    if smoke:
        # tier-1 wall management: NO bill compiles in smoke mode — the
        # doctor's sharding section (same tier-1 process, memoized)
        # already compiles the schedule-derived accum-4 entry and
        # enforces the pinned allowances (SHARD001); the round-14 pin
        # is the recorded "before" and the round-19 pin the recorded
        # "after".  The CLI runs both compiles for the real artifact.
        bill_sm = {k: {"count": v} for k, v in pinned.items()}
        bill_sm["recorded"] = True
        bill_rm = {"alltoall": {"count": 23},
                   "collectivepermute": {"count": 148},
                   "allgather": {"count": 75}, "recorded": True}
    else:
        bill_sm = reshard_bill(opt.init_flat_state(
            params, decay_mask=mask, flat_layout=lo))
        bill_rm = reshard_bill(opt.init_flat_state(params,
                                                   decay_mask=mask))

    def cnt(bill, kind):
        v = bill.get(kind, {})
        return int(v.get("count", 0)) if isinstance(v, dict) else 0

    cp_ratio = cnt(bill_rm, "collectivepermute") / max(
        cnt(bill_sm, "collectivepermute"), 1)
    a2a_ratio = cnt(bill_rm, "alltoall") / max(cnt(bill_sm, "alltoall"),
                                               1)
    within_pin = all(cnt(bill_sm, k) <= pinned[k]
                     for k in ("alltoall", "collectivepermute",
                               "allgather"))

    # per-tactic wire attribution of the hierarchical overlap step:
    # every manual collective's ring-model bytes keyed by the named
    # tactic(s) of its axis tuple (a multi-axis collective is ONE
    # entry under its joint key, so the table sums to COMM004's
    # per-stage totals exactly), ICI/DCN staged per the fake-2-slice
    # map.  Tier-1 wall management: smoke mode skips the
    # whole-flagship trace — the per-stage wire CONTRACT is enforced
    # by COMM004 in the doctor leg (same process), and the attribution
    # artifact rides the CLI (SCHEDULE_r01.json).
    per_tactic = {}
    if smoke:
        per_tactic = {"smoke_skipped":
                      "traced per-tactic attribution rides the CLI "
                      "--schedule-trace (SCHEDULE_r01.json); the "
                      "ICI/DCN wire contract is COMM004-enforced in "
                      "the doctor leg"}
    else:
        hmesh = Mesh(np.asarray(devs[:8], dtype=object).reshape(1, 4, 2),
                     ("dp", "sharding", "mp"))
        apply_llama_sharding(model, hmesh)
        hparams = {k: jnp.asarray(v)
                   for k, v in model.functional_state().items()}
        hoc = OverlapConfig(hierarchical="on",
                            slice_map=FLAGSHIP_SLICE_MAP)
        hstep = build_train_step(model, opt, mesh=hmesh,
                                 compute_dtype=jnp.bfloat16, overlap=hoc)
        hctx = AnalysisContext(
            hstep, (hparams, opt.init_state(hparams), 0, 1e-4, ids,
                    labels), {})
        by_axis = collect_wire_by_axis(
            hctx.jaxpr, {"sharding": list(FLAGSHIP_SLICE_MAP)})

        def tactic_key(axes_key: str) -> str:
            names = []
            for a in axes_key.split("+"):
                t = _AXIS_TO_TACTIC.get(a)
                names.append(t.name if t is not None else a)
            return "+".join(names)

        per_tactic = {tactic_key(k): v for k, v in by_axis.items()}

    if smoke:
        # tier-1 wall: reuse the memoized section when a full CLI run
        # already paid it in this process, else skip with the paper
        # trail (the seeded forcing walk in tests/test_schedule.py is
        # the tier-1 contract; -m slow re-asserts the real walk)
        from paddle_tpu.analysis.self_check import _JOINT_MEMO

        key = (jax.default_backend(), len(jax.devices()))
        joint = _JOINT_MEMO.get(key) or {
            "ok": True,
            "smoke_skipped": "real joint walk rides the CLI "
                             "--schedule-trace / --doctor and -m slow; "
                             "tier-1 contract: tests/test_schedule.py "
                             "seeded walk"}
    else:
        joint = joint_schedule_section()
    ok = (within_pin and cp_ratio >= 3.0 and a2a_ratio >= 3.0
          and bool(joint.get("ok"))
          and (smoke or bool(per_tactic)))
    out = {"ok": bool(ok),
           "backend": jax.default_backend(),
           "schedule": {"tactics": list(sched.tactic_names()),
                        "mesh": "dp2 x sharding2 x mp2",
                        "flat_layout": lo.signature},
           "reshard_bill": {
               "row_major": bill_rm, "shard_major": bill_sm,
               "pinned_allowances": dict(pinned),
               "collectivepermute_ratio": round(cp_ratio, 2),
               "alltoall_ratio": round(a2a_ratio, 2),
               "within_pinned": bool(within_pin)},
           "per_tactic_wire": per_tactic,
           "joint_autotune": {k: joint.get(k)
                              for k in ("ok", "picked", "chosen_label",
                                        "hbm_budget",
                                        "dcn_wire_budget")}}
    if not smoke:
        out["joint_autotune"]["records"] = joint.get("records")
        out["joint_autotune"]["chosen"] = joint.get("chosen")
    return out


def roofline_trace(smoke: bool = False):
    """bench.py --roofline-trace -> ROOFLINE_r01.json (round-20 roofline
    step-time estimator + enumerated partitioning search):

    - the ENUMERATED search space: candidate tactic compositions
      (pp / dp / sharding3 / sep / tp — and ep on the MoE sheet) on a
      (2, 32)-slice v5p pod, divisibility- and HBM-pruned, ranked by
      the analytic step-time estimate — llama3-8B top-10 table plus
      the MoE sheet's ep-point counts;
    - the estimator-vs-measured DRIFT gate on the fake-2-slice joint
      lattice (analysis.self_check.roofline_drift_section): the
      predicted winner under the pinned budgets must equal the
      measured joint pick, per-record fit/no-fit frontier parity, and
      predicted DCN wire within 10% of the pins;
    - predict-mode autotune (full mode, 8 devices): the estimator
      re-ranks the flagship lattice and ``tune_schedule_config(
      predict=True, top_k=1)`` compiles ONLY the top-ranked point,
      which must pass the measured MEM001 + COMM004 budget gates and
      match the recorded joint pick — the ISSUE-17 acceptance leg
      ("top candidate verified by actual compile without compiling
      the rest").

    ``ok`` requires >= 20 feasible llama3-8B candidates, ep points on
    the MoE sheet, the drift gate green, and (full mode) the predict
    walk choosing the pinned pick with exactly one compile.  ``smoke``
    is fully compile-free: the drift gate reads the memoized joint
    section when a CLI run already paid it, else the RECORDED pins
    (tests/test_roofline.py asserts the same contract tier-1; the
    compiled walk rides this CLI and ``-m slow``)."""
    import jax

    import paddle_tpu as paddle  # noqa: F401 (registers ops)
    from paddle_tpu.analysis.self_check import (
        JOINT_DCN_WIRE_BUDGET, JOINT_FLAGSHIP_BATCH, JOINT_FLAGSHIP_SEQ,
        JOINT_HBM_BUDGET, RECORDED_JOINT_RECORDS, joint_flagship_config,
        joint_schedule_points, roofline_drift_section)
    from paddle_tpu.models import LlamaConfig
    from paddle_tpu.parallel import roofline as rf

    # --- leg 1: enumerated partitioning search (always compile-free)
    cands = rf.enumerate_partitionings((2, 32), LlamaConfig.llama3_8b(),
                                       batch=16, seq=4096, chip="v5p")
    sheet_8b = rf.llama_cost_sheet(LlamaConfig.llama3_8b())
    ranked = rf.rank_partitionings(cands, sheet_8b, batch=16, seq=4096,
                                   chip="v5p")
    top10 = [{"label": pt.label(), "estimate": est.to_json()}
             for est, pt in ranked[:10]]

    moe_sheet = rf.ModelCostSheet(
        name="moe_debug", num_layers=4, hidden=256, intermediate=512,
        num_heads=8, num_kv_heads=4, head_dim=32, vocab=1024,
        num_experts=8)
    moe_cands = rf.enumerate_partitionings((2, 32), moe_sheet, batch=16,
                                           seq=4096, chip="v5p")
    n_ep = sum(1 for pt in moe_cands
               if dict(pt.axes).get("ep", 1) > 1)

    # --- leg 2: estimator-vs-measured drift gate (compile-free; full
    # mode feeds the LIVE joint section so measured_source="compiled")
    if smoke or len(jax.devices()) < 8:
        drift = roofline_drift_section()       # memoized or recorded
    else:
        from paddle_tpu.analysis.self_check import joint_schedule_section

        drift = roofline_drift_section(joint_schedule_section())

    # --- leg 3: predict-mode autotune — compile ONLY the top-ranked
    # point, gate it on the measured budgets (full mode)
    if smoke:
        predict = {"smoke_skipped":
                   "the compiled predict-walk rides the CLI "
                   "--roofline-trace and -m slow "
                   "(tests/test_roofline.py); its walk CONTRACT "
                   "(only top_k compiled, predicted order honored) is "
                   "tier-1 via the fake-builder walk in "
                   "tests/test_roofline.py"}
        predict_ok = True
    elif len(jax.devices()) < 8:
        predict = {"skipped": f"needs 8 devices (have "
                              f"{len(jax.devices())})"}
        predict_ok = True
    else:
        import jax.numpy as jnp

        from paddle_tpu.analysis.self_check import _joint_flagship
        from paddle_tpu.models import build_train_step
        from paddle_tpu.models.llama import apply_llama_sharding
        from paddle_tpu.parallel.codec import CollectiveCodec
        from paddle_tpu.parallel.memory import MemoryConfig
        from paddle_tpu.parallel.schedule import (joint_schedule_lattice,
                                                  tune_schedule_config)

        cfg, model, ids, labels = _joint_flagship()
        lattice = joint_schedule_lattice(
            joint_schedule_points(),
            memory_lattice=(MemoryConfig(remat="none"),),
            codec_points=(None, CollectiveCodec()))
        sheet = rf.llama_cost_sheet(joint_flagship_config())
        by_label = {jc.label(): jc for jc in lattice}
        anchor = RECORDED_JOINT_RECORDS[0]
        cal = rf.calibration_offset_from(
            anchor, by_label[anchor["label"]], sheet,
            batch=JOINT_FLAGSHIP_BATCH, seq=JOINT_FLAGSHIP_SEQ)
        estimator = rf.joint_estimator(
            sheet, batch=JOINT_FLAGSHIP_BATCH, seq=JOINT_FLAGSHIP_SEQ,
            hbm_budget=JOINT_HBM_BUDGET,
            dcn_budget=JOINT_DCN_WIRE_BUDGET, calibration_offset=cal)

        def builder(jc):
            mesh = jc.partition.mesh()
            apply_llama_sharding(model, mesh)
            params = {k: jnp.asarray(v)
                      for k, v in model.functional_state().items()}
            opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                         parameters=model.parameters())
            step = build_train_step(model, opt, mesh=mesh,
                                    compute_dtype=jnp.bfloat16,
                                    overlap=jc.overlap, memory=jc.memory)
            return step, (params, opt.init_state(params), jnp.int32(0),
                          jnp.float32(1e-4), ids, labels)

        chosen, recs = tune_schedule_config(
            builder, JOINT_HBM_BUDGET, lattice,
            dcn_wire_bytes=JOINT_DCN_WIRE_BUDGET, predict=True,
            estimator=estimator, top_k=1)
        n_compiled = sum(1 for r in recs if r.get("compiled"))
        predict_ok = (chosen is not None and n_compiled == 1
                      and chosen.label() == drift.get("measured_pick"))
        predict = {"ok": bool(predict_ok),
                   "chosen_label": chosen.label() if chosen else None,
                   "n_compiled": n_compiled,
                   "n_lattice": len(lattice),
                   "records": [{"label": r["label"],
                                "predicted_rank": r["predicted_rank"],
                                "compiled": r["compiled"],
                                "peak_bytes": r.get("peak_bytes"),
                                "dcn_wire_bytes": r.get("dcn_wire_bytes"),
                                "fits": r.get("fits")} for r in recs]}

    ok = (len(cands) >= 20 and n_ep > 0 and bool(drift.get("ok"))
          and predict_ok)
    return {"ok": bool(ok),
            "backend": jax.default_backend(),
            "search": {"mesh": "(2 slices) x 32 v5p chips",
                       "model": "llama3-8B b16 s4096",
                       "n_candidates": len(cands),
                       "top10": top10,
                       "moe_n_candidates": len(moe_cands),
                       "moe_n_ep_points": n_ep},
            "drift": drift,
            "predict_autotune": predict}


def smoke(fast: bool = False):
    """CPU-safe tier-1 gate over the serving/varlen dispatch hot paths
    (round-6 satellite: dispatch-layer regressions must fail the suite,
    not surface one round later in the next BENCH json).  Tiny shapes,
    interpret-mode kernels.  Returns a dict with an overall ``ok`` plus
    one entry per leg; raises nothing (failures are reported in the
    dict so the CLI can print a useful JSON).

    ``fast=True`` (what tests/test_bench_smoke.py runs since round 17 —
    the tier-1 wall sat at the 870 s cliff again) skips the six
    round-6/7 dispatch legs whose properties are each asserted by a
    dedicated tier-1 suite in the same run (annotated per leg via
    ``fast_skipped``); every round-8+ leg — the doctor gate and the
    per-round trace gates — still runs.  The CLI ``--smoke`` mode runs
    everything."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle  # noqa: F401 (registers ops)
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.generation import (generate,
                                              quantize_params_int8)
    from paddle_tpu.inference.serving import ContinuousBatchingEngine
    from paddle_tpu.ops.pallas.flash_attention import (
        _attn_reference, flash_attention_auto)
    from paddle_tpu.ops.pallas.decode_attention import (flash_decode_raw,
                                                        paged_decode_raw)

    legs = {}
    rng = np.random.default_rng(0)
    paddle.seed(7)
    cfg = LlamaConfig.debug(vocab=64, hidden=32, layers=2, heads=4,
                            kv_heads=2, inter=64, max_pos=128)
    model = LlamaForCausalLM(cfg)
    params = {k: jnp.asarray(v)
              for k, v in model.functional_state().items()}

    prompts = [rng.integers(1, cfg.vocab_size, (n,)).astype(np.int32)
               for n in (5, 11)]

    # 1. pipelined continuous-batching engine: greedy parity vs the
    #    one-shot generate path (the whole scheduler + paged kernel)
    try:
        if fast:
            raise _FastSkip("tests/test_serving.py (one-shot parity + "
                            "scheduler suite)")
        eng = ContinuousBatchingEngine(cfg, params, max_slots=2,
                                       num_pages=17, page_size=16,
                                       max_seq_len=64,
                                       decode_chunk_steps=3)
        for p in prompts:
            eng.add_request(p, max_new_tokens=5)
        done = eng.run()
        ok = len(done) == len(prompts)
        for i, p in enumerate(prompts):
            ref = generate(model, p[None], max_new_tokens=5,
                           do_sample=False)
            ref = np.asarray(ref._value if hasattr(ref, "_value")
                             else ref)[0, len(p):]
            ok = ok and (done[i].tokens == ref[:len(done[i].tokens)]).all()
        legs["serving_pipeline_parity"] = {"ok": bool(ok)}
    except _FastSkip as s:
        legs["serving_pipeline_parity"] = {"ok": True,
                                           "fast_skipped": s.home}
    except Exception as e:  # noqa: BLE001
        legs["serving_pipeline_parity"] = {"ok": False, "error": repr(e)}

    # 2. padding-aware varlen dispatch: both branches numerically match
    #    the reference at their respective padding regimes
    try:
        if fast:
            raise _FastSkip("tests/test_attention_dispatch.py (both "
                            "branches + crossover)")
        b, s, h, d = 2, 32, 4, 16
        q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
        res = {}
        # low_pad sits below PACKED_PADDING_CROSSOVER (dense branch),
        # high_pad above it (pad 0.4375 > 0.40 -> packed branch), so the
        # smoke gate compiles and checks BOTH kernels
        for name, lens in (("low_pad", [30, 32]), ("high_pad", [4, 32])):
            got = np.asarray(flash_attention_auto(q, q, q, lens,
                                                  causal=True))
            okl = True
            for i, n in enumerate(lens):
                want = np.asarray(_attn_reference(
                    q[i:i + 1, :n], q[i:i + 1, :n], q[i:i + 1, :n],
                    True, d ** -0.5))
                okl = okl and np.abs(got[i, :n] - want[0]).max() < 2e-4
            res[name] = bool(okl)
        legs["varlen_auto_dispatch"] = {"ok": all(res.values()), **res}
    except _FastSkip as s:
        legs["varlen_auto_dispatch"] = {"ok": True, "fast_skipped": s.home}
    except Exception as e:  # noqa: BLE001
        legs["varlen_auto_dispatch"] = {"ok": False, "error": repr(e)}

    # 3. multi-page paged decode kernel == dense decode kernel on the
    #    same logical cache (shuffled physical pages)
    try:
        if fast:
            raise _FastSkip("tests/test_decode_attention.py + "
                            "tests/test_flash_decoding.py (paged == "
                            "dense decode)")
        b, h, kvh, d, page, mp = 2, 4, 2, 32, 8, 4
        lens = np.array([9, 26], np.int32)
        kc = rng.standard_normal((b, kvh, mp * page, d)).astype(np.float32)
        vc = rng.standard_normal((b, kvh, mp * page, d)).astype(np.float32)
        qd = jnp.asarray(rng.standard_normal((b, h, d)), jnp.float32)
        perm = rng.permutation(b * mp)
        tables = perm.reshape(b, mp).astype(np.int32)
        kp = np.zeros((b * mp, kvh, page, d), np.float32)
        vp = np.zeros((b * mp, kvh, page, d), np.float32)
        for bi in range(b):
            for j in range(mp):
                kp[tables[bi, j]] = kc[bi, :, j * page:(j + 1) * page]
                vp[tables[bi, j]] = vc[bi, :, j * page:(j + 1) * page]
        dense_o = np.asarray(flash_decode_raw(
            qd, jnp.asarray(kc), jnp.asarray(vc), jnp.asarray(lens)))
        paged_o = np.asarray(paged_decode_raw(
            qd, jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(lens),
            jnp.asarray(tables), pages_per_step=2))
        legs["paged_multipage_kernel"] = {
            "ok": bool(np.abs(dense_o - paged_o).max() < 2e-4)}
    except _FastSkip as s:
        legs["paged_multipage_kernel"] = {"ok": True,
                                          "fast_skipped": s.home}
    except Exception as e:  # noqa: BLE001
        legs["paged_multipage_kernel"] = {"ok": False, "error": repr(e)}

    # 5. training hot path (round-7 satellite): accum-scan micro-step
    #    with the bf16 carry + fused flat AdamW, checked against the
    #    full-batch step with the legacy per-param optimizer — one leg
    #    covers all three training levers end to end
    try:
        if fast:
            raise _FastSkip("tests/test_grad_accum_bf16_carry.py + "
                            "tests/test_fused_adamw.py (accum/fused "
                            "parity at tighter bounds)")
        from paddle_tpu.models import build_train_step
        from paddle_tpu.models.llama import llama_decay_mask

        topt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                      parameters=model.parameters())
        tparams = {k: jnp.copy(v) for k, v in params.items()}
        mask = llama_decay_mask(model)
        ids2 = rng.integers(0, cfg.vocab_size, (4, 8)).astype(np.int32)
        lab2 = rng.integers(0, cfg.vocab_size, (4, 8)).astype(np.int32)

        def deep(t):
            import jax as _j

            return _j.tree_util.tree_map(jnp.copy, t)

        full = build_train_step(model, topt, compute_dtype=jnp.float32)
        l_full, p_full, _ = full(deep(tparams),
                                 topt.init_state(deep(tparams)),
                                 0, 1e-3, ids2, lab2)
        acc = build_train_step(model, topt, compute_dtype=jnp.float32,
                               accum_steps=2, accum_dtype=jnp.bfloat16)
        l_acc, p_acc, st_acc = acc(
            deep(tparams),
            topt.init_flat_state(deep(tparams), decay_mask=mask),
            0, 1e-3, ids2.reshape(2, 2, 8), lab2.reshape(2, 2, 8))
        okl = abs(float(l_acc) - float(l_full)) \
            <= 1e-5 * max(abs(float(l_full)), 1.0)
        okp = True
        for kk in p_full:
            a = np.asarray(p_acc[kk], np.float32)
            b2_ = np.asarray(p_full[kk], np.float32)
            # bf16-carry tolerance: grads quantized to bf16 before the
            # fold; cancelling micro-grads can push single elements to
            # a lr-scale deviation, so gate at 3x lr (the tight parity
            # bound lives in tests/test_grad_accum_bf16_carry.py)
            okp = okp and np.allclose(a, b2_, atol=3e-3)
        legs["train_accum_fused_step"] = {
            "ok": bool(okl and okp and np.isfinite(float(l_acc))),
            "loss_match": bool(okl), "param_match": bool(okp)}
    except _FastSkip as s:
        legs["train_accum_fused_step"] = {"ok": True,
                                          "fast_skipped": s.home}
    except Exception as e:  # noqa: BLE001
        legs["train_accum_fused_step"] = {"ok": False, "error": repr(e)}

    # 6. flash attention fwd+bwd in interpret mode vs the XLA reference
    #    (covers the default head-batched route: b/s/h/kvh give rep=2)
    try:
        if fast:
            raise _FastSkip("tests/test_pallas_flash.py (fwd+bwd "
                            "interpret parity incl. head-batched)")
        import jax as _j

        b, s, h, d = 2, 32, 4, 16
        qf = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
        kf = jnp.asarray(rng.standard_normal((b, s, 2, d)), jnp.float32)
        vf = jnp.asarray(rng.standard_normal((b, s, 2, d)), jnp.float32)

        from paddle_tpu.ops.pallas.flash_attention import \
            flash_attention_raw

        def lf(q, k, v):
            return jnp.sum(flash_attention_raw(
                q, k, v, causal=True).astype(jnp.float32) ** 2)

        def lr_(q, k, v):
            return jnp.sum(_attn_reference(
                q, k, v, True, d ** -0.5).astype(jnp.float32) ** 2)

        gf = _j.grad(lf, argnums=(0, 1, 2))(qf, kf, vf)
        gr = _j.grad(lr_, argnums=(0, 1, 2))(qf, kf, vf)
        okg = all(np.allclose(np.asarray(a), np.asarray(b_),
                              rtol=2e-3, atol=2e-4)
                  for a, b_ in zip(gf, gr))
        legs["flash_fwdbwd_interpret"] = {"ok": bool(okg)}
    except _FastSkip as s:
        legs["flash_fwdbwd_interpret"] = {"ok": True,
                                          "fast_skipped": s.home}
    except Exception as e:  # noqa: BLE001
        legs["flash_fwdbwd_interpret"] = {"ok": False, "error": repr(e)}

    # 7. graph doctor (round-8): the static-analysis gate itself —
    #    seeded-bug fixtures all fire, flagship sweeps all clean, and
    #    the exemption table is live (ISSUE 3 acceptance: a pass that
    #    cannot detect is indistinguishable from one that never fires)
    try:
        from paddle_tpu.analysis import self_check

        # joint=False: tier-1 wall management (round-19) — the joint
        # autotune's 3 flagship compiles ride the CLI --doctor /
        # --schedule-trace (DOCTOR.json / SCHEDULE_r01.json) and the
        # tier-2 real-walk test; its forcing CONTRACT is tier-1 via
        # tests/test_schedule.py's seeded walk
        sc = self_check(joint=not fast)
        detail = {sect: {k: bool(v.get("ok"))
                         for k, v in sc.get(sect, {}).items()}
                  for sect in ("seeded", "clean", "exemptions")}
        legs["doctor_self_check"] = {"ok": bool(sc["ok"]), **detail}
    except Exception as e:  # noqa: BLE001
        legs["doctor_self_check"] = {"ok": False, "error": repr(e)}

    # 4. weight-only int8 params through the serving engine, checked
    #    against the int8-weight ONE-SHOT generate on the same params
    #    (int8 KV there vs fp cache here can flip rare near-ties only)
    try:
        if fast:
            raise _FastSkip("tests/test_int8_weights.py (int8-weight "
                            "serving/generate parity)")
        from paddle_tpu.models.generation import (_generate_jit,
                                                  register_config)

        qp = quantize_params_int8(params)
        eng = ContinuousBatchingEngine(cfg, qp, max_slots=1,
                                       num_pages=9, page_size=16,
                                       max_seq_len=64,
                                       decode_chunk_steps=3,
                                       cache_dtype=jnp.int8)
        eng.add_request(prompts[0], max_new_tokens=4)
        done = eng.run()
        toks = done[0].tokens
        ref = np.asarray(_generate_jit(
            qp, jnp.asarray(prompts[0][None]), jax.random.PRNGKey(0),
            cfg_id=register_config(cfg), max_new_tokens=4,
            do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
            eos_id=-1))[0]
        match = float((toks == ref).mean()) if len(toks) == 4 else 0.0
        legs["int8_weight_serving"] = {
            "ok": bool(len(toks) == 4 and match >= 0.75),
            "match_vs_oneshot": match}
    except _FastSkip as s:
        legs["int8_weight_serving"] = {"ok": True, "fast_skipped": s.home}
    except Exception as e:  # noqa: BLE001
        legs["int8_weight_serving"] = {"ok": False, "error": repr(e)}

    # 8. round-9 overlap engine: the full-manual overlap train step
    #    (ZeRO-3 prefetch + bucketed RS + collective matmul) must match
    #    the flat GSPMD step bit-for-tolerance on the dp2 x sharding2 x
    #    mp2 mesh — self-skips on hosts without 8 (virtual) devices
    try:
        legs["overlap_parity"] = _smoke_overlap_parity()
    except Exception as e:  # noqa: BLE001
        legs["overlap_parity"] = {"ok": False, "error": repr(e)}

    # 9. round-9 collective_budget doctor leg: the COMM fixtures fire
    #    exactly their codes and the flagship single-chip step honors a
    #    ZERO-collective budget
    try:
        legs["collective_budget_doctor"] = _smoke_collective_budget()
    except Exception as e:  # noqa: BLE001
        legs["collective_budget_doctor"] = {"ok": False, "error": repr(e)}

    # 10. round-10 HBM memory engine: named-policy remat + host-
    #     offloaded bucket-streamed AdamW must match the flat fused
    #     step bit-for-bit, and the autotuner must return a fitting
    #     config under a synthetic budget
    try:
        legs["memory_parity"] = _smoke_memory_parity()
    except Exception as e:  # noqa: BLE001
        legs["memory_parity"] = {"ok": False, "error": repr(e)}

    # 11. round-10 memory_budget doctor leg: MEM001/MEM002/HLO003
    #     fixtures fire exactly their codes and the flagship step fits
    #     its declared peak-HBM budget
    try:
        legs["memory_budget_doctor"] = _smoke_memory_budget()
    except Exception as e:  # noqa: BLE001
        legs["memory_budget_doctor"] = {"ok": False, "error": repr(e)}

    # 12. round-11 serving plane: the open-loop arrival trace through
    #     the unified engine (radix prefix cache + chunked prefill +
    #     speculative decode) — ok requires every request completed,
    #     mean accepted length > 1 AND at least one prefix-cache hit
    try:
        tr = serving_trace(smoke=True)
        legs["serving_trace"] = {
            "ok": bool(tr["ok"]),
            "mean_accepted_len": tr["mean_accepted_len"],
            "prefix_cache_hits": tr["prefix_cache"].get("hits", 0),
            "prefill_tokens_saved": tr["prefill_tokens_saved"]}
    except Exception as e:  # noqa: BLE001
        legs["serving_trace"] = {"ok": False, "error": repr(e)}

    # 13. round-12 reshard engine: A→B→A redistribution across a shrink
    #     pair must be bit-equal with bounded per-step transients, and
    #     the doctor's MEM001 budget must pass on the worst step
    try:
        legs["reshard_parity"] = _smoke_reshard_parity()
    except Exception as e:  # noqa: BLE001
        legs["reshard_parity"] = {"ok": False, "error": repr(e)}

    # 14. round-12 elastic recovery: a fault-injected worker kill mid-run
    #     must resume from the last complete checkpoint within the
    #     checkpoint_every replay budget and land loss-parity with an
    #     uninterrupted run
    try:
        legs["elastic_recovery"] = _smoke_elastic_recovery()
    except Exception as e:  # noqa: BLE001
        legs["elastic_recovery"] = {"ok": False, "error": repr(e)}

    # 15+16. round-13 serving resilience, ONE shared scripted run, two
    #     gates: a mid-decode replica kill loses zero requests with
    #     bit-identical greedy streams (router_parity), and the
    #     replacement arrives through the cached MEM001-budgeted
    #     delivery plan within one router tick (replica_recovery)
    try:
        legs["router_parity"], legs["replica_recovery"] = \
            _smoke_fleet_legs()
    except Exception as e:  # noqa: BLE001
        legs["router_parity"] = {"ok": False, "error": repr(e)}
        legs["replica_recovery"] = {"ok": False, "error": repr(e)}

    # 17. round-14 Sharding Doctor: the SHARD fixtures fire exactly
    #     their codes and the GSPMD/overlap/hybrid stacks' canonical
    #     SpecLayout tables agree on the llama flagship parameter tree
    #     (SHARD003 empty — the unified-partitioning precondition)
    try:
        legs["sharding_doctor"] = _smoke_sharding_doctor()
    except Exception as e:  # noqa: BLE001
        legs["sharding_doctor"] = {"ok": False, "error": repr(e)}

    # 19. round-16 disaggregated serving: the prompt-burst trace through
    #     the two-pool fleet — every stream bit-identical to one-shot
    #     generate(), handoffs > 0 through the MEM001-budgeted cached
    #     plan, and the int8 KV wire measurably below the raw form
    try:
        tr = serving_disagg_trace(smoke=True)
        legs["serving_disagg"] = {
            "ok": bool(tr["ok"]),
            "handoffs": tr["runs"]["disagg"]["handoffs"],
            "handoff_wire_ratio": tr["handoff_wire_ratio"],
            "handoff_doctor_ok": tr["handoff_doctor_ok"]}
    except Exception as e:  # noqa: BLE001
        legs["serving_disagg"] = {"ok": False, "error": repr(e)}

    # 20. round-17 training health guardian: the scripted numeric-fault
    #     trace — NaN skip is bit-identical to the clean run, the spike
    #     burst walks skip → backoff → rollback with bounded replay, a
    #     flipped coded payload is caught at decode, and the
    #     HEALTH001/002 fixtures fire exactly
    try:
        tr = health_trace(smoke=True)
        legs["health_trace"] = {
            "ok": bool(tr["ok"]),
            "skip_parity": tr["skip"]["parity_bit_identical"],
            "ladder_stage_counts": tr["ladder"]["stage_counts"],
            "steps_replayed": tr["ladder"]["steps_replayed"],
            "checksum_caught": tr["checksum"]["host_flip_caught"]}
    except Exception as e:  # noqa: BLE001
        legs["health_trace"] = {"ok": False, "error": repr(e)}

    # 18. round-15 quantized DCN collectives: the COMM004 fixture fires
    #     exactly, and the flagship bucketed reduce-scatter's DCN bytes
    #     shrink >= 3x with the int8 codec (structural per-bucket table
    #     + the traced wire tables; flagship_wire_table is memoized, so
    #     this shares the doctor leg's traces)
    try:
        legs["comm_bytes_trace"] = _smoke_comm_bytes()
    except Exception as e:  # noqa: BLE001
        legs["comm_bytes_trace"] = {"ok": False, "error": repr(e)}

    # 21. round-18 MoE expert parallelism: the EP train step on the
    #     fake-2-slice mesh — loss decreases through the coded
    #     dispatch, the dispatch all-to-alls' DCN bytes shrink >= 3x
    #     with the int8 codec under the pinned wire budget, overflow
    #     telemetry and balance entropy well-formed, the round-20
    #     DROPLESS engine under ITS pinned budget with a structurally
    #     zero dropped rate, and the COMM004[moe_dispatch] +
    #     COMM004[moe_dropless] fixtures fire exactly
    try:
        legs["moe_trace"] = _smoke_moe_trace()
    except Exception as e:  # noqa: BLE001
        legs["moe_trace"] = {"ok": False, "error": repr(e)}

    # 22. round-19 unified partitioning schedule: the schedule-derived
    #     flagship accum-4 step's reshard bill within the NEW pinned
    #     allowances with >= 3x fewer collective-permutes/all-to-alls
    #     than the row-major wire format, per-tactic wire attribution
    #     present, and the joint partition x memory x overlap autotune's
    #     three-way budget forcing holds (the chosen schedule is what
    #     DOCTOR.json carries)
    try:
        tr = schedule_trace(smoke=True)
        legs["schedule_trace"] = {
            "ok": bool(tr["ok"]),
            "within_pinned": tr.get("reshard_bill", {}).get(
                "within_pinned"),
            "collectivepermute_ratio": tr.get("reshard_bill", {}).get(
                "collectivepermute_ratio"),
            "joint_chosen": tr.get("joint_autotune", {}).get(
                "chosen_label"),
        } if "skipped" not in tr else {"ok": True, **tr}
    except Exception as e:  # noqa: BLE001
        legs["schedule_trace"] = {"ok": False, "error": repr(e)}

    # 23. round-20 roofline estimator + enumerated partitioning search:
    #     >= 20 feasible candidates on the (2, 32) v5p pod with ep
    #     points on the MoE sheet, and the estimator's predicted winner
    #     on the fake-2-slice joint lattice equals the measured joint
    #     pick (frontier parity, wire drift <= 10%) — compile-free
    try:
        tr = roofline_trace(smoke=True)
        legs["roofline_trace"] = {
            "ok": bool(tr["ok"]),
            "n_candidates": tr["search"]["n_candidates"],
            "moe_n_ep_points": tr["search"]["moe_n_ep_points"],
            "predicted_winner": tr["drift"].get("predicted_winner"),
            "drift_ok": tr["drift"].get("ok"),
            "measured_source": tr["drift"].get("measured_source")}
    except Exception as e:  # noqa: BLE001
        legs["roofline_trace"] = {"ok": False, "error": repr(e)}

    # 24. round-21 Concurrency Doctor: the RACE fixtures fire exactly,
    #     the control-plane lock-discipline sweep is clean under the
    #     reviewed allowlist, and the sanitizer's deterministic
    #     self-test + threaded allocator/watchdog hammers run green
    try:
        legs["concurrency_doctor"] = _smoke_concurrency_doctor()
    except Exception as e:  # noqa: BLE001
        legs["concurrency_doctor"] = {"ok": False, "error": repr(e)}

    return {"smoke": True,
            "backend": jax.default_backend(),
            "ok": all(leg.get("ok") for leg in legs.values()),
            **legs}


def _smoke_reshard_parity():
    """Round-12 reshard-engine gate: a dp×mp → shrunk dp×sharding →
    back round trip over a small param dict must be BIT-equal, keep
    every step's transient under the declared cap, and sweep the
    doctor's MEM001 budget clean on the worst step."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from paddle_tpu.parallel.reshard import (check_reshard_budget,
                                             plan_reshard, reshard)

    devs = jax.devices()
    if len(devs) < 8:
        return {"ok": True,
                "skipped": f"needs 8 devices (have {len(devs)}); the "
                           f"tier-1 suite runs this leg on the virtual "
                           f"CPU mesh"}
    mesh_a = Mesh(np.asarray(devs[:8], dtype=object).reshape(4, 2),
                  ("dp", "mp"))
    mesh_b = Mesh(np.asarray(devs[:4], dtype=object).reshape(2, 2),
                  ("dp", "sharding"))
    rng = np.random.default_rng(12)
    host = {"w_big": rng.standard_normal((256, 32)).astype(np.float32),
            "w_tp": rng.standard_normal((32, 32)).astype(np.float32),
            "b": rng.standard_normal((32,)).astype(np.float32)}
    specs_a = {"w_big": P("dp", None), "w_tp": P(None, "mp"), "b": P()}
    specs_b = {"w_big": P(("dp", "sharding"), None),
               "w_tp": P("sharding", None), "b": P()}
    state = {k: jax.device_put(v, NamedSharding(mesh_a, specs_a[k]))
             for k, v in host.items()}

    cap = 16 << 10
    out_b, plan_ab = reshard(state, mesh_b, specs_b,
                             max_transient_bytes=cap)
    back, plan_ba = reshard(out_b, mesh_a, specs_a,
                            max_transient_bytes=cap)
    bit_equal = all(np.array_equal(np.asarray(back[k]), host[k])
                    and np.array_equal(np.asarray(out_b[k]), host[k])
                    for k in host)
    bounded = (plan_ab.max_step_transient <= cap
               and plan_ba.max_step_transient <= cap)
    rep = check_reshard_budget(plan_ab, state, exemptions=())
    return {"ok": bool(bit_equal and bounded and rep.ok),
            "bit_equal": bool(bit_equal),
            "bounded": bool(bounded),
            "doctor_ok": bool(rep.ok),
            "moved_bytes": int(plan_ab.moved_bytes),
            "max_step_transient": int(plan_ab.max_step_transient),
            "steps": len(plan_ab.steps)}


def _smoke_elastic_recovery():
    """Round-12 elastic-recovery gate: kill a worker mid-run through the
    fault-injection harness; the resilient loop must recover within the
    checkpoint_every replay budget and reproduce the uninterrupted loss
    trajectory exactly."""
    import tempfile

    _ensure_tests_path()
    from fault_injection import FaultEvent, run_toy_loop

    with tempfile.TemporaryDirectory() as dref, \
            tempfile.TemporaryDirectory() as dres:
        ref, _ = run_toy_loop(dref, 10, checkpoint_every=4)
        res, cluster = run_toy_loop(
            dres, 10, checkpoint_every=4,
            faults=[FaultEvent(step=6, kind="kill")])
    if len(res.recoveries) != 1:
        return {"ok": False, "error": f"recoveries={res.recoveries}"}
    rec = res.recoveries[0]
    replay_ok = rec.steps_replayed <= 4      # checkpoint_every budget
    parity = (set(res.losses) == set(ref.losses)
              and all(res.losses[s] == ref.losses[s] for s in ref.losses))
    return {"ok": bool(res.final_step == 10 and replay_ok and parity),
            "fault": rec.fault,
            "resume_step": rec.resume_step,
            "steps_replayed": rec.steps_replayed,
            "loss_parity": bool(parity)}


def _smoke_fleet_legs():
    """ONE scripted fleet run feeding BOTH round-13 smoke gates (the
    fleet spawn + jit warmup is the leg's dominant cost, so the two
    gates share it): a mid-decode replica KILL must lose zero requests
    with every greedy stream bit-identical to one-shot generate()
    (router_parity), and the replacement must arrive through the
    CACHED weight-delivery plan — plan once per topology, stream per
    replica — under the doctor's MEM001 budget, within one router tick
    (replica_recovery)."""
    _ensure_tests_path()
    from fault_injection import (ReplicaFaultEvent, build_serving_fleet,
                                 toy_llama)
    from paddle_tpu.models.generation import generate

    cfg, model, params = toy_llama()
    rng = np.random.default_rng(13)
    prompts = [rng.integers(1, cfg.vocab_size, (n,)).astype(np.int32)
               for n in (5, 9, 14, 7)]
    router, rs = build_serving_fleet(
        cfg, params, target=2,
        scripts={0: [ReplicaFaultEvent(step=2, kind="kill")]})
    rids = [router.submit(p, max_new_tokens=6) for p in prompts]
    out = router.run()
    lost = [r for r in rids if r not in out]
    parity = True
    for rid, p in zip(rids, prompts):
        if rid not in out:
            continue
        ref = generate(model, p[None], max_new_tokens=6, do_sample=False)
        ref_new = np.asarray(ref._value if hasattr(ref, "_value")
                             else ref)[0, len(p):]
        parity &= (len(out[rid]) == 6
                   and np.array_equal(out[rid], ref_new))
    faults = [ev.fault for ev in router.telemetry["recoveries"]]
    recs = router.telemetry["recoveries"]
    router_parity = {
        "ok": bool(not lost and parity and faults == ["ReplicaKilled"]),
        "lost": len(lost), "bit_identical": bool(parity),
        "migrations": router.telemetry["migrations"],
        "recoveries": faults}
    delivery = rs.check_delivery_budget()
    ok = (rs.telemetry["plans_built"] == 1
          and rs.telemetry["deliveries"] == 3   # 2 initial + replacement
          and len(recs) == 1
          and recs[0].replacement_id is not None
          and (recs[0].recovery_ticks or 0) <= 1
          and delivery.ok
          and len(rs.serving()) == 2)
    replica_recovery = {
        "ok": bool(ok),
        "plans_built": rs.telemetry["plans_built"],
        "deliveries": rs.telemetry["deliveries"],
        "recovery_ticks": recs[0].recovery_ticks if recs else None,
        "delivery_doctor_ok": bool(delivery.ok),
        "completed": len(out)}
    return router_parity, replica_recovery


def _smoke_overlap_parity():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    import paddle_tpu as paddle
    from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                   build_train_step)
    from paddle_tpu.models.llama import apply_llama_sharding
    from paddle_tpu.parallel.overlap import OverlapConfig

    devs = jax.devices()
    if len(devs) < 8:
        return {"ok": True,
                "skipped": f"needs 8 devices (have {len(devs)}); the "
                           f"tier-1 suite runs this leg on the virtual "
                           f"CPU mesh"}
    rng = np.random.default_rng(0)
    paddle.seed(11)
    cfg = LlamaConfig.debug(vocab=64, hidden=32, layers=2, heads=4,
                            kv_heads=2, inter=64, max_pos=32)
    model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    state0 = {k: jnp.copy(v)
              for k, v in model.functional_state().items()}
    ids = rng.integers(0, cfg.vocab_size, (8, 16)).astype(np.int32)
    labels = rng.integers(0, cfg.vocab_size, (8, 16)).astype(np.int32)

    def deep(t):
        return {k: jnp.copy(v) for k, v in t.items()}

    flat = build_train_step(model, opt, mesh=None,
                            compute_dtype=jnp.float32)
    l0, p0, _ = flat(deep(state0), opt.init_state(deep(state0)), 0,
                     1e-3, ids, labels)
    mesh = Mesh(np.asarray(devs[:8], dtype=object).reshape(2, 2, 2),
                ("dp", "sharding", "mp"))
    apply_llama_sharding(model, mesh)
    ov = build_train_step(
        model, opt, mesh=mesh, compute_dtype=jnp.float32,
        overlap=OverlapConfig(collective_matmul_min_out_elems=1))
    l1, p1, _ = ov(deep(state0), opt.init_state(deep(state0)), 0,
                   1e-3, ids, labels)
    ok_loss = abs(float(l1) - float(l0)) \
        <= 1e-5 * max(abs(float(l0)), 1.0)
    ok_p = all(np.allclose(np.asarray(p1[k], np.float32),
                           np.asarray(p0[k], np.float32), atol=5e-4)
               for k in p0)
    return {"ok": bool(ok_loss and ok_p), "loss_match": bool(ok_loss),
            "param_match": bool(ok_p)}


def _smoke_memory_parity():
    """Tiny-lattice parity: flat fused step vs (names-remat +
    host-offloaded streamed AdamW) and vs (no-remat + activation
    offload) — losses AND updated params bit-equal (fp32, same
    elementwise math; the lattice-wide sweep lives in
    tests/test_memory_engine.py) — plus an autotune walk under a
    synthetic budget that must return a fitting config."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                   build_train_step)
    from paddle_tpu.models.llama import llama_decay_mask
    from paddle_tpu.parallel.memory import (MemoryConfig,
                                            init_offloaded_state,
                                            tune_memory_config)

    rng = np.random.default_rng(3)
    paddle.seed(23)
    cfg = LlamaConfig.debug(vocab=64, hidden=32, layers=2, heads=4,
                            kv_heads=2, inter=64, max_pos=32)
    model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    state0 = {k: jnp.copy(v)
              for k, v in model.functional_state().items()}
    mask = llama_decay_mask(model)
    ids = rng.integers(0, cfg.vocab_size, (4, 16)).astype(np.int32)
    labels = rng.integers(0, cfg.vocab_size, (4, 16)).astype(np.int32)

    def deep(t):
        return {k: jnp.copy(v) for k, v in t.items()}

    flat = build_train_step(model, opt, compute_dtype=jnp.float32)
    l0, p0, _ = flat(deep(state0),
                     opt.init_flat_state(deep(state0), decay_mask=mask),
                     0, 1e-3, ids, labels)
    results = {}
    for name, mc in (
            ("names_host", MemoryConfig(remat="names",
                                        optimizer_residency="host",
                                        stream_bucket_bytes=8 << 10)),
            ("none_act_offload", MemoryConfig(
                remat="none", activation_offload=True))):
        step = build_train_step(model, opt, compute_dtype=jnp.float32,
                                memory=mc)
        if mc.optimizer_residency == "host":
            st = init_offloaded_state(
                opt, deep(state0), decay_mask=mask,
                bucket_bytes=mc.stream_bucket_bytes)
        else:
            st = opt.init_flat_state(deep(state0), decay_mask=mask)
        l1, p1, _ = step(deep(state0), st, 0, 1e-3, ids, labels)
        ok_l = float(l1) == float(l0)
        ok_p = all(np.array_equal(np.asarray(p1[k]), np.asarray(p0[k]))
                   for k in p0)
        results[name] = bool(ok_l and ok_p)

    def builder(mc):
        step = build_train_step(model, opt, compute_dtype=jnp.float32,
                                memory=mc)
        if mc.optimizer_residency == "host":
            st = init_offloaded_state(opt, deep(state0), decay_mask=mask,
                                      bucket_bytes=mc.stream_bucket_bytes)
        else:
            st = opt.init_flat_state(deep(state0), decay_mask=mask)
        return step, (deep(state0), st, jnp.int32(0), jnp.float32(1e-3),
                      ids, labels)

    from paddle_tpu.parallel.memory import (MEMORY_LATTICE,
                                            measure_step_memory)

    lattice = MEMORY_LATTICE[:4]        # smoke keeps the walk short
    fn0, args0 = builder(lattice[0])
    budget = int(measure_step_memory(fn0, *args0)["peak_bytes"] * 2)
    chosen, records = tune_memory_config(builder, budget,
                                         lattice=lattice)
    # assert on the CHOSEN config's record — records[0] fits by
    # construction (the budget is 2x its measured peak)
    results["autotune_fits"] = bool(
        chosen is not None
        and records[lattice.index(chosen)]["fits"])
    return {"ok": all(results.values()), **results}


def _smoke_memory_budget():
    from paddle_tpu.analysis.fixtures import SEEDED, FixtureUnavailable

    out = {}
    for code in ("MEM001", "MEM002", "HLO003"):
        try:
            rep = SEEDED[code]()
            out[code] = {"ok": set(rep.codes()) == {code},
                         "codes": sorted(set(rep.codes()))}
        except FixtureUnavailable as e:
            out[code] = {"ok": True, "skipped": str(e)}
    # flagship single-chip step under its declared peak-HBM budget
    try:
        import jax.numpy as jnp

        import paddle_tpu.analysis as A
        from paddle_tpu.analysis.self_check import (_flagship,
                                                    FLAGSHIP_HBM_BUDGET)
        from paddle_tpu.models import build_train_step

        cfg, model, opt, params, ids, labels = _flagship()
        step = build_train_step(model, opt, compute_dtype=jnp.float32)
        rep = A.check(
            step, params, opt.init_state(params), 0, 1e-4, ids, labels,
            passes=["memory_budget"],
            options={"memory_budget":
                     {"hbm_bytes": FLAGSHIP_HBM_BUDGET}},
            target="flagship_hbm_budget")
        out["flagship_hbm_budget"] = {
            "ok": rep.ok,
            "findings": [f.format() for f in rep.findings]}
    except Exception as e:  # noqa: BLE001
        out["flagship_hbm_budget"] = {"ok": False, "error": repr(e)}
    return {"ok": all(v.get("ok") for v in out.values()), **out}


def _smoke_sharding_doctor():
    """Round-14 sharding_doctor leg: true-positive proofs for
    SHARD001-005 plus the cross-stack agreement gate — the canonical
    SpecLayout tables extracted from the GSPMD, overlap and hybrid
    stacks must map the llama flagship parameter tree identically
    (table-level, no extra compiles; the compiled reshard audits ride
    the doctor_self_check leg's sharding section)."""
    import jax
    from paddle_tpu.analysis.fixtures import SEEDED, FixtureUnavailable

    out = {}
    for code in ("SHARD001", "SHARD002", "SHARD003", "SHARD004",
                 "SHARD005"):
        try:
            rep = SEEDED[code]()
            out[code] = {"ok": set(rep.codes()) == {code},
                         "codes": sorted(set(rep.codes()))}
        except FixtureUnavailable as e:
            out[code] = {"ok": True, "skipped": str(e)}
    try:
        if len(jax.devices()) < 8:
            out["cross_stack"] = {"ok": True,
                                  "skipped": "needs >= 8 devices"}
        else:
            import numpy as _np
            from jax.sharding import Mesh

            from paddle_tpu.analysis.sharding import (
                check_cross_stack, extract_gspmd_layout,
                extract_hybrid_layout, extract_overlap_layout)
            from paddle_tpu.analysis.self_check import _flagship
            from paddle_tpu.models.llama import apply_llama_sharding
            from paddle_tpu.models.llama_hybrid import hybrid_mesh

            cfg, model, opt, params, ids, labels = _flagship()
            mesh = Mesh(_np.asarray(jax.devices()[:8],
                                    dtype=object).reshape(2, 2, 2),
                        ("dp", "sharding", "mp"))
            apply_llama_sharding(model, mesh)
            layouts = {
                "gspmd": extract_gspmd_layout(model, mesh),
                "overlap": extract_overlap_layout(model, mesh),
                "hybrid": extract_hybrid_layout(
                    model, hybrid_mesh(jax.devices(), pp=2, dp=1,
                                       sharding=2, sep=1, mp=2)),
            }
            rep = check_cross_stack(layouts)
            n = min(len(lo.entries) for lo in layouts.values())
            out["cross_stack"] = {
                "ok": bool(rep.ok and n >= 10),
                "tensors": n,
                "findings": [f.format() for f in rep.findings]}
    except Exception as e:  # noqa: BLE001
        out["cross_stack"] = {"ok": False, "error": repr(e)}
    return {"ok": all(v.get("ok") for v in out.values()), **out}


def _smoke_concurrency_doctor():
    """Round-21 concurrency_doctor leg: the RACE001-004 fixtures fire
    exactly their codes (RACE004 = the minimized pre-fix watchdog
    race), the lock-discipline sweep over the control plane is clean
    under the reviewed allowlist (no stale entries), and the dynamic
    sanitizer's deterministic self-test + small genuinely-threaded
    hammers (PageAllocator storm, watchdog scanner-vs-completion race)
    run green.  Shares the memoized doctor section — one sweep per
    process."""
    from paddle_tpu.analysis.fixtures import SEEDED, FixtureUnavailable
    from paddle_tpu.analysis.lock_sanitizer import (hammer_page_allocator,
                                                    hammer_watchdog)
    from paddle_tpu.analysis.self_check import _concurrency_section

    out = {}
    for code in ("RACE001", "RACE002", "RACE003", "RACE004"):
        try:
            rep = SEEDED[code]()
            out[code] = {"ok": set(rep.codes()) == {code},
                         "codes": sorted(set(rep.codes()))}
        except FixtureUnavailable as e:
            out[code] = {"ok": True, "skipped": str(e)}
    try:
        sec = _concurrency_section()
        out["sweep"] = {"ok": bool(sec.get("sweep", {}).get("ok")),
                        "findings": sec.get("sweep", {}).get("findings"),
                        "unused_allowlist":
                            sec.get("sweep", {}).get("unused_allowlist")}
        out["sanitizer_self_test"] = {
            "ok": bool(sec.get("sanitizer", {}).get("ok"))}
    except Exception as e:  # noqa: BLE001
        out["sweep"] = {"ok": False, "error": repr(e)}
    try:
        h = hammer_page_allocator(num_pages=8, threads=4, ops=80, seed=3)
        out["allocator_hammer"] = {
            "ok": bool(h["ok"]), "acquisitions": h["acquisitions"],
            "order_violations": h["order_violations"]}
        w = hammer_watchdog(threads=4, tasks_per_thread=10, seed=3)
        out["watchdog_hammer"] = {
            "ok": bool(w["ok"]), "timed_out": w["timed_out"],
            "completed": w["completed"],
            "both_terminal": w["both_terminal"],
            "neither_terminal": w["neither_terminal"]}
    except Exception as e:  # noqa: BLE001
        out["hammer"] = {"ok": False, "error": repr(e)}
    return {"ok": all(v.get("ok") for v in out.values()), **out}


def _smoke_comm_bytes():
    """Round-15 quantized-collectives gate: COMM004's seeded fixture
    fires exactly its code, and the comm-bytes trace's >= 3x DCN
    reduction on the flagship bucketed reduce-scatter holds."""
    from paddle_tpu.analysis.fixtures import SEEDED, FixtureUnavailable

    out = {}
    try:
        rep = SEEDED["COMM004"]()
        out["COMM004"] = {"ok": set(rep.codes()) == {"COMM004"},
                          "codes": sorted(set(rep.codes()))}
    except FixtureUnavailable as e:
        out["COMM004"] = {"ok": True, "skipped": str(e)}
    tr = comm_bytes_trace(smoke=True)
    out["trace"] = {"ok": bool(tr.get("ok")),
                    "skipped": tr.get("skipped"),
                    "reducescatter_ratio":
                        tr.get("traced_reducescatter_ratio"),
                    "dcn_ratio": tr.get("traced_dcn_ratio")}
    return {"ok": all(v.get("ok") for v in out.values()), **out}


def _smoke_moe_trace():
    """Round-18 + round-20 moe_trace gate: the COMM004[moe_dispatch]
    AND COMM004[moe_dropless] fixtures each fire exactly their code,
    and both EP engines' traces hold — >= 3x dispatch DCN reduction,
    each engine under its own pinned wire budget, telemetry shape, and
    the dropless leg's structurally-zero dropped rate."""
    from paddle_tpu.analysis.fixtures import SEEDED, FixtureUnavailable

    out = {}
    for code in ("COMM004[moe_dispatch]", "COMM004[moe_dropless]"):
        try:
            rep = SEEDED[code]()
            out[code] = {"ok": set(rep.codes()) == {"COMM004"},
                         "codes": sorted(set(rep.codes()))}
        except FixtureUnavailable as e:
            out[code] = {"ok": True, "skipped": str(e)}
    tr = moe_trace(smoke=True)
    out["trace"] = {"ok": bool(tr.get("ok")),
                    "skipped": tr.get("skipped"),
                    "dispatch_dcn_ratio": tr.get("dispatch_dcn_ratio"),
                    "dropped_token_rate": tr.get("dropped_token_rate"),
                    "load_balance_entropy":
                        tr.get("load_balance_entropy"),
                    "dropless_dispatch_dcn_ratio": tr.get(
                        "dropless", {}).get("dispatch_dcn_ratio"),
                    "dropless_dropped_token_rate": tr.get(
                        "dropless", {}).get("dropped_token_rate"),
                    "tokens_per_s_capacity_vs_dropless": tr.get(
                        "tokens_per_s_capacity_vs_dropless")}
    return {"ok": all(v.get("ok") for v in out.values()), **out}


def _smoke_collective_budget():
    from paddle_tpu.analysis.fixtures import (SEEDED, FixtureUnavailable)

    out = {}
    for code in ("COMM001", "COMM002", "COMM003"):
        try:
            rep = SEEDED[code]()
            out[code] = {"ok": set(rep.codes()) == {code},
                         "codes": sorted(set(rep.codes()))}
        except FixtureUnavailable as e:
            out[code] = {"ok": True, "skipped": str(e)}
    # flagship single-chip zero-collective budget
    try:
        import paddle_tpu.analysis as A
        from paddle_tpu.analysis.self_check import _flagship

        cfg, model, opt, params, ids, labels = _flagship()
        from paddle_tpu.models import build_train_step
        import jax.numpy as jnp

        step = build_train_step(model, opt, compute_dtype=jnp.float32)
        rep = A.check(
            step, params, opt.init_state(params), 0, 1e-4, ids, labels,
            passes=["collective_budget"],
            options={"collective_budget":
                     {k: {"count": 0} for k in
                      ("allreduce", "allgather", "reducescatter",
                       "collectivepermute", "alltoall")}},
            target="flagship_zero_budget")
        out["flagship_zero_budget"] = {"ok": rep.ok,
                                       "findings": [f.format()
                                                    for f in rep.findings]}
    except Exception as e:  # noqa: BLE001
        out["flagship_zero_budget"] = {"ok": False, "error": repr(e)}
    return {"ok": all(v.get("ok") for v in out.values()), **out}


if __name__ == "__main__":
    if "--smoke" in sys.argv:
        res = smoke()
        print(json.dumps(res))
        sys.exit(0 if res["ok"] else 1)
    if "--doctor" in sys.argv:
        res = doctor()
        try:
            with open("DOCTOR.json", "w") as f:
                json.dump(res, f, indent=1, default=str)
        except OSError:
            pass
        print(json.dumps(res, default=str))
        sys.exit(0 if res["ok"] else 1)
    if "--moe-trace" in sys.argv:
        res = moe_trace(smoke="--smoke-trace" in sys.argv)
        try:
            with open("MOE_r02.json", "w") as f:
                json.dump(res, f, indent=1, default=str)
        except OSError:
            pass
        print(json.dumps(res, default=str))
        sys.exit(0 if res["ok"] else 1)
    if "--comm-bytes-trace" in sys.argv:
        res = comm_bytes_trace(smoke="--smoke-trace" in sys.argv)
        try:
            with open("COMM_BYTES_r01.json", "w") as f:
                json.dump(res, f, indent=1, default=str)
        except OSError:
            pass
        print(json.dumps(res, default=str))
        sys.exit(0 if res["ok"] else 1)
    if "--schedule-trace" in sys.argv:
        res = schedule_trace(smoke="--smoke-trace" in sys.argv)
        try:
            with open("SCHEDULE_r01.json", "w") as f:
                json.dump(res, f, indent=1, default=str)
        except OSError:
            pass
        print(json.dumps(res, default=str))
        sys.exit(0 if res["ok"] else 1)
    if "--roofline-trace" in sys.argv:
        res = roofline_trace(smoke="--smoke-trace" in sys.argv)
        try:
            with open("ROOFLINE_r01.json", "w") as f:
                json.dump(res, f, indent=1, default=str)
        except OSError:
            pass
        print(json.dumps(res, default=str))
        sys.exit(0 if res["ok"] else 1)
    if "--serving-trace" in sys.argv:
        res = serving_trace(smoke="--smoke-trace" in sys.argv)
        try:
            with open("SERVING_r01.json", "w") as f:
                json.dump(res, f, indent=1, default=str)
        except OSError:
            pass
        print(json.dumps(res, default=str))
        sys.exit(0 if res["ok"] else 1)
    if "--serving-fleet-trace" in sys.argv:
        res = serving_fleet_trace(smoke="--smoke-trace" in sys.argv)
        try:
            with open("SERVING_FLEET_r01.json", "w") as f:
                json.dump(res, f, indent=1, default=str)
        except OSError:
            pass
        print(json.dumps(res, default=str))
        sys.exit(0 if res["ok"] else 1)
    if "--health-trace" in sys.argv:
        res = health_trace(smoke="--smoke-trace" in sys.argv)
        try:
            with open("HEALTH_r01.json", "w") as f:
                json.dump(res, f, indent=1, default=str)
        except OSError:
            pass
        print(json.dumps(res, default=str))
        sys.exit(0 if res["ok"] else 1)
    if "--serving-disagg-trace" in sys.argv:
        res = serving_disagg_trace(smoke="--smoke-trace" in sys.argv)
        try:
            with open("SERVING_DISAGG_r01.json", "w") as f:
                json.dump(res, f, indent=1, default=str)
        except OSError:
            pass
        print(json.dumps(res, default=str))
        sys.exit(0 if res["ok"] else 1)
    if "--profile" in sys.argv:
        res = profile()
        try:
            with open("PROFILE.json", "w") as f:
                json.dump(res, f, indent=1)
        except OSError:
            pass
        print(json.dumps(res))
        sys.exit(0)
    main()
