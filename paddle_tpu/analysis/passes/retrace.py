"""RT — retrace sentinel.

Recompilation is the silent step-time killer jit makes easy: a caller
that alternates ``0.1`` (python float, weak-typed) with
``jnp.float32(0.1)`` (strong) retraces the WHOLE train step twice; an
object whose repr churns per call (a fresh tuple of floats, a config
dataclass) retraces every step.  Unlike the other doctor passes this is
call-driven — one trace cannot show signature churn — so the sentinel is
a wrapper: it forwards calls, fingerprints every signature, and reports
typed findings.

    step = retrace_sentinel(build_train_step(...))
    ... run ...
    step.report().raise_if_findings()

Codes:
- RT001: two call signatures identical except for weak-type flags — the
  python-scalar vs array churn; every flip is a full retrace.
- RT002: more distinct signatures than ``max_signatures`` — shape or
  static-argument churn (unbucketed lengths, per-call config objects).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.tree_util as jtu

from ..findings import Finding, Report


def _leaf_sig(x) -> Tuple:
    """(kind, shape, dtype, weak) fingerprint of one argument leaf."""
    try:
        aval = jax.core.get_aval(x)
        return ("array", tuple(aval.shape), str(aval.dtype),
                bool(getattr(aval, "weak_type", False)))
    except Exception:
        return ("static", repr(x), "", False)


class RetraceSentinel:
    """Wraps a (usually jitted) callable; counts call signatures and
    flags weak-type/static-arg churn.  ``max_signatures`` bounds healthy
    signature diversity (bucketed prefill lengths are a legitimate
    handful; hundreds are churn)."""

    def __init__(self, fn, max_signatures: int = 8,
                 name: Optional[str] = None):
        self._fn = fn
        self._max = int(max_signatures)
        self.name = name or getattr(fn, "__name__", repr(fn))
        self.signatures: Dict[Tuple, int] = {}
        self._findings: List[Finding] = []
        self._rt002_emitted = False
        functools.update_wrapper(self, fn, updated=())

    # -- call path ----------------------------------------------------------

    def _signature(self, args, kwargs) -> Tuple:
        leaves, treedef = jtu.tree_flatten((args, kwargs))
        return (str(treedef),) + tuple(_leaf_sig(x) for x in leaves)

    @staticmethod
    def _strip_weak(sig: Tuple) -> Tuple:
        return (sig[0],) + tuple(
            leaf[:3] for leaf in sig[1:])

    def __call__(self, *args, **kwargs):
        sig = self._signature(args, kwargs)
        fresh = sig not in self.signatures
        self.signatures[sig] = self.signatures.get(sig, 0) + 1
        if fresh:
            self._on_new_signature(sig)
        return self._fn(*args, **kwargs)

    def _on_new_signature(self, sig: Tuple):
        stripped = self._strip_weak(sig)
        twins = [s for s in self.signatures
                 if s != sig and self._strip_weak(s) == stripped]
        if twins:
            diffs = [i - 1 for i, (a, b) in
                     enumerate(zip(sig, twins[0])) if a != b]
            self._findings.append(Finding(
                code="RT001", pass_name="retrace_sentinel",
                message=(
                    f"{self.name}: call signature differs from an earlier "
                    f"one ONLY in weak-type flags (leaf index(es) "
                    f"{diffs}) — a python scalar and an array are "
                    f"alternating in the same position; each flip "
                    f"retraces and recompiles the whole program.  Pin "
                    f"the caller to one form (e.g. jnp.asarray(lr, "
                    f"jnp.float32))"),
                data={"leaves": diffs}))
        if len(self.signatures) > self._max and not self._rt002_emitted:
            self._rt002_emitted = True
            self._findings.append(Finding(
                code="RT002", pass_name="retrace_sentinel",
                message=(
                    f"{self.name}: {len(self.signatures)} distinct call "
                    f"signatures (> max_signatures={self._max}) — shape "
                    f"or static-argument churn; every new signature is a "
                    f"compile.  Bucket dynamic lengths and hoist "
                    f"per-call objects out of the signature"),
                data={"count": len(self.signatures)}))

    # -- reporting ----------------------------------------------------------

    @property
    def compilations(self) -> Optional[int]:
        """Underlying jit cache size when the wrapped fn (or the jit
        entry behind its wrapper — build_train_step normalizes scalars
        in front of its jit) exposes it."""
        from ..core import _unwrap

        try:
            return int(_unwrap(self._fn)._cache_size())
        except Exception:
            return None

    def report(self) -> Report:
        """Signature findings plus the ground truth: when the entry
        normalized the churn away (compilations < signatures), the
        caller hygiene finding stands but says so."""
        comps = self.compilations
        findings = list(self._findings)
        if comps is not None:
            for f in findings:
                f.data.setdefault("compilations", comps)
                if comps <= 1 and f.code == "RT001":
                    f.severity = "warning"
                    if "entry normalized" not in f.message:
                        f.message += (
                            f"  (this entry normalized the signature "
                            f"before jit — {comps} compile(s) actually "
                            f"happened — but the caller churn is real "
                            f"and other entries will pay for it)")
        return Report(target=self.name, findings=findings,
                      passes_run=("retrace_sentinel",))


def retrace_sentinel(fn, max_signatures: int = 8,
                     name: Optional[str] = None) -> RetraceSentinel:
    return RetraceSentinel(fn, max_signatures=max_signatures, name=name)
