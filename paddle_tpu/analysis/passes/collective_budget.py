"""COMM — collective count/size budget + overlap-schedule conformance.

PR-4's overlap engine made the collective schedule an explicit,
engineered artifact (parallel/overlap.py): so many gathers per layer,
reduce-scatters bucketed, rings rotating uniformly.  This pass keeps it
that way — a regression that reintroduces per-leaf collectives (9L
reduce-scatters instead of L buckets), an accidental psum in an eager
helper, or a malformed pipeline ring should fail the doctor, not
surface as a step-time cliff one TPU session later.

Codes:
- COMM001: the compiled program's collective COUNT or BYTES exceed the
  budget the entry point declared (``options={"collective_budget":
  {"allreduce": {"count": n, "bytes": b}, "allgather": ..., ...}}``).
  Counted from the compiled HLO text, so GSPMD-inserted collectives are
  covered, not just manual ones; async pairs (``all-reduce-start`` /
  ``-done``) count once.  No declared budget -> the pass SKIPS (a
  budget is a per-entry-point contract, not a global default).
- COMM002: a MANUAL collective issued outside an overlap-engine region
  while the entry point declares an overlap engine active
  (``{"overlap_active": True}``).  Region membership is provenance:
  the collective's trace-time call stack must contain one of
  parallel/overlap.py's region functions (or the entry's declared
  ``overlap_region_functions`` additions) — collectives the engine did
  not schedule defeat its bucketing/prefetch plan silently.
- COMM003 (the ROADMAP-queued cross-stage ppermute-ring order check): a
  ppermute inside a scan body (a pipeline tick loop / ring schedule)
  whose perm is NOT a uniform rotation — mixed ring steps mean stage s
  receives from a different relative neighbour than stage s' sends
  toward, the cross-stage pairing bug that deadlocks a static pipeline
  schedule.  (Repeated sources/destinations are COLL002's beat.)
- COMM004 (round-15, the quantized-collective gate): POST-CODEC
  bytes-on-the-wire per axis stage (ICI vs DCN) exceed the declared
  wire budget.  The entry declares ``{"wire": {"dcn_axes": {axis:
  slice_map}, "dcn_bytes": n[, "ici_bytes": m]}}``; the pass walks the
  jaxpr's manual collectives, prices each with the standard ring cost
  model on its ACTUAL payload dtype (an int8 packed payload bills 1
  byte/element — quantization shows up as measured savings, a codec
  silently disabled as a budget blowout), multiplies by enclosing scan
  trip counts, and classifies each collective's stage from its
  axis_index_groups against the declared per-axis slice map (a group
  whose positions span >= 2 slices crosses DCN; a flat collective over
  a slice-spanning axis crosses DCN).  ``collect_wire_table`` is the
  reusable accounting entry (bench --comm-bytes-trace, DOCTOR.json's
  per-stage bytes table).
"""

from __future__ import annotations

import re
from typing import Dict, List

import numpy as np

from ..core import (AnalysisContext, AnalysisPass, SkipPass, format_where,
                    register_pass, sub_jaxprs, walk_eqns)
from ..findings import Finding

# manual (jaxpr-level) wire-traffic primitives, from collective_order
from .collective_order import COLLECTIVE_PRIMS

# HLO op name -> budget key
_HLO_KINDS = {
    "all-reduce": "allreduce",
    "all-gather": "allgather",
    "reduce-scatter": "reducescatter",
    "collective-permute": "collectivepermute",
    "all-to-all": "alltoall",
}

# one collective instruction: everything before the op name on the line
# is the RESULT type — a single array type or a tuple of them (variadic
# all-reduce / async -start ops); operand types live inside the parens
# and must not be tallied
_HLO_LINE_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?\S+\s*=\s*(?P<result>[^=]*?)\s*"
    r"(?P<op>all-reduce|all-gather|reduce-scatter|collective-permute"
    r"|all-to-all)(?P<phase>-start|-done)?\(", re.M)

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}


def scan_hlo_collectives(hlo_text: str) -> Dict[str, Dict[str, int]]:
    """Count + byte totals per collective kind from compiled HLO text.
    Async pairs count at the ``-start`` (the ``-done`` is skipped);
    tuple-shaped results (variadic all-reduce — e.g. fused flat-group
    reductions — and the start ops' state tuples) tally EVERY element's
    bytes, not just the last."""
    out: Dict[str, Dict[str, int]] = {
        k: {"count": 0, "bytes": 0} for k in _HLO_KINDS.values()}
    for m in _HLO_LINE_RE.finditer(hlo_text):
        if m.group("phase") == "-done":
            continue
        kind = _HLO_KINDS[m.group("op")]
        nbytes = 0
        for dtype, shape in _SHAPE_RE.findall(m.group("result")):
            elems = 1
            for d in shape.split(","):
                if d.strip():
                    elems *= int(d)
            nbytes += elems * _DTYPE_BYTES.get(dtype, 4)
        out[kind]["count"] += 1
        out[kind]["bytes"] += nbytes
    return out


# jaxpr collective primitive -> budget-kind name (the wire table's keys
# match COMM001's HLO kinds so the two tallies read side-by-side)
_WIRE_PRIMS = {
    "psum": "allreduce", "psum2": "allreduce",
    "all_gather": "allgather", "all_gather_invariant": "allgather",
    "psum_scatter": "reducescatter", "reduce_scatter": "reducescatter",
    "all_to_all": "alltoall",
    "ppermute": "collectivepermute", "pshuffle": "collectivepermute",
}


def _eqn_axes(eqn):
    ax = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if ax is None:
        ax = ()
    return tuple(ax) if isinstance(ax, (tuple, list)) else (ax,)


def _eqn_in_bytes(eqn) -> int:
    total = 0
    for v in eqn.invars:
        aval = getattr(v, "aval", None)
        shape = getattr(aval, "shape", None)
        dtype = getattr(aval, "dtype", None)
        if shape is None or dtype is None:
            continue
        n = 1
        for d in shape:
            n *= int(d)
        total += n * np.dtype(dtype).itemsize
    return total


def _ring_wire_cost(kind: str, nbytes: int, g: int) -> int:
    """Bytes each participant SENDS under the standard ring cost model
    (the structural bytes-on-the-wire currency; constant factors cancel
    in the codec-on/off ratio COMM004 budgets).  Round-20: the single
    copy lives in parallel/roofline.py — the analytic estimator and
    this measured pricing walk share arithmetic by construction."""
    from ...parallel.roofline import ring_wire_cost

    return ring_wire_cost(kind, nbytes, g)


def _wire_group_size(eqn, axis_sizes, axes) -> int:
    groups = eqn.params.get("axis_index_groups")
    if groups:
        return len(groups[0])
    g = 1
    for a in axes:
        g *= int(axis_sizes.get(str(a), 1))
    return g


def _wire_stage(eqn, axes, dcn_axes) -> str:
    """"dcn" when the collective's communication pattern crosses slices
    per the declared per-axis slice maps, else "ici".  With
    axis_index_groups, a group whose positions land on >= 2 distinct
    slices crosses DCN (the two-stage schedule's ICI groups stay within
    one slice by construction); without groups, a flat collective over
    a slice-spanning axis crosses DCN."""
    for a in axes:
        sm = dcn_axes.get(str(a))
        if sm is None:
            continue
        groups = eqn.params.get("axis_index_groups")
        if groups:
            for grp in groups:
                if len({sm[int(p)] for p in grp}) > 1:
                    return "dcn"
        elif len(set(sm)) > 1:
            return "dcn"
    return "ici"


def priced_manual_collectives(jaxpr, dcn_axes: Dict):
    """The single copy of the manual-collective pricing walk: yield
    ``(kind, axes, stage, cost, mult)`` per shard_map collective —
    ring-model bytes on the payload's ACTUAL dtype, multiplied by
    enclosing scan trip counts, staged ICI/DCN against the per-axis
    slice maps.  ``collect_wire_table`` (COMM004's per-stage tally) and
    ``collect_wire_by_axis`` (the schedule trace's per-tactic
    attribution) both consume this, so the cost model cannot fork."""
    for eqn, stack in walk_eqns(jaxpr):
        kind = _WIRE_PRIMS.get(eqn.primitive.name)
        if kind is None:
            continue
        shard_maps = [e for e in stack if e.primitive.name == "shard_map"]
        if not shard_maps:
            continue              # GSPMD-land; COMM001's HLO tally covers
        axes = _eqn_axes(eqn)
        g = _wire_group_size(eqn, _shard_map_axis_sizes(shard_maps[-1]),
                             axes)
        if g <= 1:
            continue
        mult = 1
        for e in stack:
            if e.primitive.name == "scan":
                mult *= int(e.params.get("length", 1) or 1)
        cost = _ring_wire_cost(kind, _eqn_in_bytes(eqn), g) * mult
        yield kind, axes, _wire_stage(eqn, axes, dcn_axes or {}), \
            cost, mult


def collect_wire_table(jaxpr, dcn_axes: Dict) -> Dict[str, Dict]:
    """Post-codec bytes-on-the-wire per (stage, collective kind) from a
    jaxpr's MANUAL (shard_map) collectives.  ``dcn_axes`` maps axis
    name -> slice index per axis position (the fake-2-slice test shape
    and topology.axis_slice_map's output).  Scan-nested collectives
    multiply by their trip counts.  Bytes follow the payload's ACTUAL
    dtype — the whole point: an int8 packed payload prices at 1
    byte/element."""
    table = {s: {"count": 0, "bytes": 0, "kinds": {}}
             for s in ("ici", "dcn")}
    for kind, _axes, stage_name, cost, mult in \
            priced_manual_collectives(jaxpr, dcn_axes):
        stage = table[stage_name]
        stage["count"] += mult
        stage["bytes"] += cost
        ent = stage["kinds"].setdefault(kind, {"count": 0, "bytes": 0})
        ent["count"] += mult
        ent["bytes"] += cost
    return table


def collect_wire_by_axis(jaxpr, dcn_axes: Dict) -> Dict[str, Dict]:
    """The same priced walk keyed by the collective's AXIS TUPLE
    (``"sharding"``, ``"dp+sharding"``, ...) — a multi-axis collective
    is ONE entry under its joint key, so the per-axis table sums to the
    per-stage table exactly (no double counting).  The schedule trace
    maps the keys onto named tactics."""
    out: Dict[str, Dict] = {}
    for kind, axes, stage, cost, mult in \
            priced_manual_collectives(jaxpr, dcn_axes):
        key = "+".join(str(a) for a in axes)
        ent = out.setdefault(key, {"ici_bytes": 0, "dcn_bytes": 0,
                                   "count": 0, "kinds": {}})
        ent[stage + "_bytes"] += cost
        ent["count"] += mult
        ent["kinds"][kind] = ent["kinds"].get(kind, 0) + mult
    return out


def _overlap_region_funcs(extra=()) -> frozenset:
    from ...parallel.overlap import OVERLAP_REGION_FUNCS

    return OVERLAP_REGION_FUNCS | frozenset(extra)


def _ring_steps(perm, size: int) -> List[int]:
    return [(int(d) - int(s)) % size for s, d in perm]


def _shard_map_axis_sizes(eqn) -> Dict[str, int]:
    mesh = eqn.params.get("mesh")
    try:
        return {str(a): int(mesh.shape[a]) for a in mesh.axis_names}
    except Exception:
        return {}


@register_pass
class CollectiveBudgetPass(AnalysisPass):
    name = "collective_budget"
    codes = ("COMM001", "COMM002", "COMM003", "COMM004")
    # the budget needs the compiled HLO, but the pass only compiles when
    # a budget is actually declared (COMM002/3/4 are jaxpr-level)
    requires = "jaxpr"

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        opts = ctx.options.get(self.name, {}) if ctx.options else {}
        budget = {k: v for k, v in opts.items()
                  if k in set(_HLO_KINDS.values())}
        overlap_active = bool(opts.get("overlap_active"))
        extra_funcs = tuple(opts.get("overlap_region_functions", ()))
        wire = opts.get("wire") or {}
        if not budget and not overlap_active and not wire:
            # COMM003 still applies (it needs no declaration), but a
            # target with no shard_map region has nothing to check
            if not self._has_shard_map(ctx):
                raise SkipPass(
                    "no collective budget declared, no overlap engine "
                    "active, no wire budget, and no shard_map region "
                    "to ring-check")
        findings: List[Finding] = []
        if budget:
            findings.extend(self._check_budget(ctx, budget))
        if overlap_active:
            findings.extend(self._check_overlap_regions(ctx, extra_funcs))
        findings.extend(self._check_ring_order(ctx))
        if wire:
            findings.extend(self._check_wire(ctx, wire))
        return findings

    # ---- COMM001 ----------------------------------------------------------

    def _check_budget(self, ctx, budget) -> List[Finding]:
        counts = scan_hlo_collectives(ctx.compiled_text)
        findings = []
        for kind, lim in sorted(budget.items()):
            got = counts.get(kind, {"count": 0, "bytes": 0})
            for dim in ("count", "bytes"):
                if dim in lim and got[dim] > lim[dim]:
                    unit = "" if dim == "count" else " bytes"
                    findings.append(self.finding(
                        "COMM001",
                        f"{kind}: {got[dim]}{unit} per step exceeds the "
                        f"declared budget of {lim[dim]}{unit} "
                        f"(full tally: {got['count']} ops, "
                        f"{got['bytes']} bytes) — the collective "
                        f"schedule regressed past this entry point's "
                        f"contract",
                        data={"kind": kind, "dim": dim,
                              "measured": got, "budget": dict(lim)}))
        return findings

    # ---- COMM002 ----------------------------------------------------------

    def _has_shard_map(self, ctx) -> bool:
        return any(eqn.primitive.name == "shard_map"
                   for eqn, _ in walk_eqns(ctx.jaxpr))

    def _check_overlap_regions(self, ctx, extra_funcs) -> List[Finding]:
        region = _overlap_region_funcs(extra_funcs)
        findings = []
        for eqn, stack in walk_eqns(ctx.jaxpr):
            if eqn.primitive.name not in COLLECTIVE_PRIMS:
                continue
            if not any(e.primitive.name == "shard_map" for e in stack):
                continue          # auto-land; GSPMD's problem, not ours
            where, data = format_where(eqn)
            fns = set(data.get("stack_functions") or ())
            if fns & region:
                continue
            findings.append(self.finding(
                "COMM002",
                f"{eqn.primitive.name} issued outside an overlap-engine "
                f"region while an overlap engine is active — collectives "
                f"the engine did not schedule run serialized against its "
                f"prefetch/bucket plan (stack: "
                f"{sorted(fns) or ['<no provenance>']})",
                where=where, data=data))
        return findings

    # ---- COMM004 ----------------------------------------------------------

    def _check_wire(self, ctx, wire) -> List[Finding]:
        table = collect_wire_table(ctx.jaxpr, wire.get("dcn_axes", {}))
        findings = []
        for stage in ("dcn", "ici"):
            lim = wire.get(f"{stage}_bytes")
            got = table[stage]["bytes"]
            if lim is not None and got > int(lim):
                findings.append(self.finding(
                    "COMM004",
                    f"{stage.upper()} stage moves {got} post-codec "
                    f"bytes-on-the-wire per step against a declared "
                    f"budget of {int(lim)} (per-kind: "
                    f"{table[stage]['kinds']}) — either the codec is "
                    f"silently disabled on this entry or the schedule "
                    f"grew past its wire contract",
                    data={"stage": stage, "measured": got,
                          "budget": int(lim), "table": table}))
        return findings

    # ---- COMM003 ----------------------------------------------------------

    def _check_ring_order(self, ctx) -> List[Finding]:
        findings: List[Finding] = []
        for eqn, stack in walk_eqns(ctx.jaxpr):
            if eqn.primitive.name != "ppermute":
                continue
            shard_maps = [e for e in stack
                          if e.primitive.name == "shard_map"]
            if not shard_maps:
                continue
            if not any(e.primitive.name == "scan" for e in stack):
                continue          # one-shot permute, not a ring schedule
            perm = [tuple(int(v) for v in p)
                    for p in eqn.params.get("perm", ())]
            if len(perm) < 2:
                continue
            axes = eqn.params.get("axis_name", ())
            axes = axes if isinstance(axes, (tuple, list)) else (axes,)
            sizes = _shard_map_axis_sizes(shard_maps[-1])
            size = sizes.get(str(axes[0])) if axes else None
            if not size:
                # axis size unresolvable (jax-internal param drift /
                # abstract mesh): without the modulus the wrap-around
                # pair (n-1 -> 0) of a CORRECT +1 ring reads as a
                # different step — judging unnormalized deltas would
                # false-positive every valid schedule, so skip this eqn
                continue
            steps = set(_ring_steps(perm, size))
            if len(steps) > 1:
                where, data = format_where(eqn)
                findings.append(self.finding(
                    "COMM003",
                    f"ppermute ring inside a scanned pipeline schedule "
                    f"mixes rotation steps {sorted(steps)} (perm "
                    f"{perm}): stages would pair sends with the wrong "
                    f"relative neighbour across ticks — a static ring "
                    f"must rotate uniformly",
                    where=where, data={**data, "perm": perm,
                                       "steps": sorted(steps)}))
        return findings
