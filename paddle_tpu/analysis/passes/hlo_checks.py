"""HLO — post-compile checks over XLA's output.

Some regressions only exist after GSPMD runs: the partitioner falling
back to "Involuntary full rematerialization" (replicating a tensor every
step because no collective sequence reaches the target sharding — the
round-4 embedding/CE-gather bug class), or a ZeRO-3 step whose parameters
get all-gathered WHOLESALE instead of layer-by-layer (the memory win of
sharding stage 3 silently gone).  This pass compiles the target (stderr
captured at the fd level — the warnings come from C++) and checks both.

Codes:
- HLO001: the SPMD partitioner reported involuntary full
  rematerialization while compiling (each hit replicates a tensor per
  step on a real pod).  Tests that wrap their own compile+run
  (tests/test_no_involuntary_remat.py) use ``core.capture_stderr`` +
  ``scan_compile_warnings`` directly.
- HLO002: an all-gather in the optimized HLO produces a result larger
  than the biggest single argument leaf — for a stage-3/FSDP step that
  is a full-param-set gather, not the expected per-layer one.  Threshold
  overridable via ``options={"hlo_post_checks": {"max_allgather_bytes":
  N}}``.
- HLO003 (the ROADMAP round-8-queued while-loop peeling detector): a
  collective issued inside a ``while`` body (a scanned decoder stack)
  appears MORE THAN ``max_peeled_copies`` times (default 1) with the
  identical (op, result-type) signature in the computation hosting the
  while — XLA peeled/unrolled the scanned layer body, duplicating its
  collectives outside the loop.  Each duplicated collective is compiled
  code and schedule the overlap engine never planned (and on a pod it
  re-serializes the prefetch schedule).  The ONE allowed copy is the
  engine's own double-buffered prologue (layer 0's gather is issued
  before the scan by design — gathered_layer_scan); override via
  ``options={"hlo_post_checks": {"max_peeled_copies": N}}``.
"""

from __future__ import annotations

import re
from collections import Counter, defaultdict
from typing import Dict, List, Tuple

import jax.tree_util as jtu

from ..core import AnalysisContext, AnalysisPass, register_pass
from ..findings import Finding

INVOLUNTARY_REMAT_RE = re.compile(
    r"Involuntary full rematerialization[^\n]*")

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}


def scan_compile_warnings(text: str) -> List[Finding]:
    """HLO001 findings from captured compile-time stderr."""
    return [Finding(
        code="HLO001", pass_name="hlo_post_checks",
        message=("SPMD partitioner fell back to involuntary full "
                 "rematerialization (a per-step full replicate of the "
                 "tensor on a real pod): " + hit[:300]),
        data={"warning": hit[:300]})
        for hit in INVOLUNTARY_REMAT_RE.findall(text)]


def _shape_bytes(dtype: str, dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


_AG_LINE_RE = re.compile(r"=\s*([^=]*?)all-gather(-start)?\(")


def scan_allgather_sizes(hlo_text: str) -> List[Tuple[int, str]]:
    """(result_bytes, line_snippet) for every all-gather in HLO text.
    Matches the op on the RHS of the assignment (the LHS instruction NAME
    also contains "all-gather"); -done ops are skipped so async gathers
    count once.  An ``all-gather-start`` result tuple is (operands...,
    results...) — only the second half are gather RESULTS, so counting
    every tuple shape would inflate async gathers ~1.5x and false-trip
    HLO002 on legitimate per-layer gathers (TPU emits the async form)."""
    out = []
    for line in hlo_text.splitlines():
        if "all-gather" not in line:
            continue
        m = _AG_LINE_RE.search(line)
        if m is None:
            continue
        shapes = _SHAPE_RE.findall(m.group(1))
        if not shapes:
            continue
        if m.group(2) and len(shapes) >= 2:      # async -start form
            shapes = shapes[len(shapes) // 2:]
        total = sum(_shape_bytes(dt, dims) for dt, dims in shapes)
        out.append((total, line.strip()[:200]))
    return out


# one collective instruction's (op, result-type) signature — the RHS
# before the op name is the result type; whitespace-normalized so the
# same collective formats identically inside and outside the loop body
_COLL_SIG_RE = re.compile(
    r"=\s*(?P<result>[^=]*?)\s*"
    r"(?P<op>all-reduce|all-gather|reduce-scatter|collective-permute"
    r"|all-to-all)(?P<phase>-start|-done)?\(")

_WHILE_BODY_RE = re.compile(r"\bbody=\s*%?([\w.\-]+)")


def scan_while_peeling(hlo_text: str, max_peeled_copies: int = 1
                       ) -> List[Finding]:
    """HLO003 findings from compiled HLO text: collectives of a while
    body duplicated (beyond the allowed prologue copy) into the
    computation hosting the while.  Computation headers sit at column 0
    and end with '{' in XLA's text dump; instructions are indented."""
    colls: Dict[str, List[Tuple[str, str]]] = defaultdict(list)
    whiles: List[Tuple[str, str]] = []       # (parent_comp, body_comp)
    comp = None
    for raw in hlo_text.splitlines():
        if raw and not raw[0].isspace() and raw.rstrip().endswith("{"):
            m = re.match(r"(?:ENTRY\s+)?%?([\w.\-]+)", raw.strip())
            comp = m.group(1) if m else None
            continue
        if comp is None:
            continue
        m = _COLL_SIG_RE.search(raw)
        if m and m.group("phase") != "-done":
            colls[comp].append((m.group("op"),
                                re.sub(r"\s+", "", m.group("result"))))
        if "while(" in raw:
            mb = _WHILE_BODY_RE.search(raw)
            if mb:
                whiles.append((comp, mb.group(1)))
    findings: List[Finding] = []
    for parent, body in whiles:
        body_sigs = colls.get(body, [])
        if not body_sigs:
            continue
        parent_counts = Counter(colls.get(parent, []))
        for sig in sorted(set(body_sigs)):
            copies = parent_counts.get(sig, 0)
            if copies <= max_peeled_copies:
                continue
            findings.append(Finding(
                code="HLO003", pass_name="hlo_post_checks",
                message=(
                    f"while body {body!r} issues a {sig[0]} "
                    f"({sig[1]}) that appears {copies}x outside the "
                    f"loop in {parent!r} (allowed prologue copies: "
                    f"{max_peeled_copies}) — XLA peeled/unrolled the "
                    f"scanned layer body, duplicating its collectives "
                    f"into straight-line code the overlap schedule "
                    f"never planned"),
                data={"body": body, "parent": parent, "op": sig[0],
                      "result": sig[1][:200], "copies": copies,
                      "allowed": max_peeled_copies}))
    return findings


@register_pass
class HloPostChecksPass(AnalysisPass):
    name = "hlo_post_checks"
    codes = ("HLO000", "HLO001", "HLO002", "HLO003")
    requires = "compiled"

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        try:
            _, stderr_text = ctx.compile()
        except Exception as e:
            # an ERROR finding, not a SkipPass: skips don't fail
            # Report.ok, and a flagship step that cannot compile at all
            # must gate bench --doctor / self-check red, not green
            return [self.finding(
                "HLO000",
                f"target failed to XLA-compile — every post-compile "
                f"check is moot and the step cannot run: {e!r}"[:500],
                data={"error": repr(e)[:300]})]
        findings = scan_compile_warnings(stderr_text)
        findings.extend(self._check_allgathers(ctx))
        findings.extend(scan_while_peeling(
            ctx.compiled_text,
            ctx.opt(self.name, "max_peeled_copies", 1)))
        return findings

    def _max_arg_leaf_bytes(self, ctx) -> int:
        biggest = 0
        lowered = ctx.lowered
        if lowered is None:
            return 0
        for _, info in jtu.tree_flatten_with_path(lowered.args_info)[0]:
            try:
                n = 1
                for d in info.shape:
                    n *= int(d)
                biggest = max(biggest, n * info.dtype.itemsize)
            except Exception:
                continue
        return biggest

    def _check_allgathers(self, ctx) -> List[Finding]:
        limit = ctx.opt(self.name, "max_allgather_bytes", None)
        if limit is None:
            limit = self._max_arg_leaf_bytes(ctx)
        if not limit:
            return []      # no sizing information — nothing to gate on
        findings = []
        for nbytes, snippet in scan_allgather_sizes(ctx.compiled_text):
            if nbytes <= limit:
                continue
            findings.append(self.finding(
                "HLO002",
                f"all-gather result of {nbytes / 1e6:.2f} MB exceeds the "
                f"largest single argument leaf ({limit / 1e6:.2f} MB) — "
                f"a sharded (stage-3) step is gathering more than one "
                f"parameter wholesale instead of per-layer: {snippet}",
                data={"bytes": nbytes, "limit": int(limit),
                      "hlo": snippet}))
        return findings
