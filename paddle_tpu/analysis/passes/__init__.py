"""Graph Doctor passes.  Importing this package populates the pass
registry (core.PASS_REGISTRY); each module self-registers via
@register_pass."""

from . import collective_budget  # noqa: F401
from . import collective_order  # noqa: F401
from . import donation  # noqa: F401
from . import dtype_promotion  # noqa: F401
from . import health_probe  # noqa: F401
from . import hlo_checks  # noqa: F401
from . import memory_budget  # noqa: F401
from . import sharding_consistency  # noqa: F401
from .retrace import RetraceSentinel, retrace_sentinel  # noqa: F401
