"""SHARD — cross-stack partition-consistency analysis.

Three stacks hand-encode sharding (flat GSPMD ``build_train_step``, the
full-manual overlap engine, the hybrid gpipe/sched bodies) and nothing
checked that they agree, that GSPMD didn't silently insert a reshard, or
that the weight update is cross-replica sharded (PAPERS.md 2004.13336).
This pass is the static-analysis groundwork for the unified-partitioning
refactor (PartIR, 2401.11202): the canonical SpecLayout tables come from
``paddle_tpu.analysis.sharding`` (one per stack); this pass audits them
and the compiled programs.

Codes:
- SHARD001: the compiled HLO carries MORE reshard-class collectives
  (``all-to-all`` / ``collective-permute`` / spec-changing
  ``all-gather``) than the entry point's declared schedule.  The
  declared schedule defaults to the MANUAL jaxpr-level collectives (the
  overlap/hybrid engines' own ops, attributed exactly like
  collective_budget counts them); GSPMD-boundary extras are declared
  per entry (``options={"sharding_consistency": {"declared":
  {"alltoall": n, ...}}}``, an upper bound like COMM001's budgets).
  Anything above that is a reshard GSPMD inserted silently — layout
  conversions the schedule never planned.
- SHARD002: a leaf over ``replicated_min_bytes`` sits REPLICATED along
  a mesh axis its dims are divisible by — memory the at-rest plan left
  on the table, reported bytes-weighted.  Runs over a canonical
  ``layout`` table.
- SHARD003: the same logical parameter maps to DIFFERENT canonical
  specs in two stacks' tables (``layouts={"gspmd": ..., "overlap":
  ...}``) — compared after restriction to the mesh axes both stacks
  know, so a hybrid table's pp layer-stacking doesn't false-diverge
  against a pp-less mesh.
- SHARD004: a shard dim not divisible by its axis degree — XLA pads
  every shard to the ceiling; the padded bytes are dead weight on every
  transfer of that leaf.  The at-rest extractors can't produce this
  (their rule falls back to replication); concrete arrays and
  hand-written specs can.
- SHARD005: the optimizer update chain runs replicated where the
  2004.13336 cross-replica weight-update sharding applies — the exact
  miscompile-adjacent region PR 5 pinned by hand (``Adam.apply_flat``'s
  ``flat_sharding``).  With ``expect_update_pin`` declared, the entry
  must carry at least one ``sharding_constraint`` over a large fp32
  1-D buffer (the flat update wire format) whose spec actually names a
  mesh axis; a qualifying buffer pinned to REPLICATED fires too.
"""

from __future__ import annotations

import math
from typing import Dict, List

from ..core import (AnalysisContext, AnalysisPass, SkipPass, format_where,
                    register_pass, walk_eqns)
from ..findings import Finding
from .collective_budget import scan_hlo_collectives

# the reshard-class HLO collective kinds (COMM001's naming): layout
# conversions, not reductions — an all-reduce never changes a spec
RESHARD_KINDS = ("alltoall", "collectivepermute", "allgather")

# manual jaxpr primitive -> reshard kind (the attribution machinery
# collective_budget uses, specialized to the reshard classes)
MANUAL_RESHARD_PRIMS = {
    "all_to_all": "alltoall",
    "ppermute": "collectivepermute",
    "pshuffle": "collectivepermute",
    "all_gather": "allgather",
    "all_gather_invariant": "allgather",
    "pgather": "allgather",
}

#: production default for SHARD002 (debug-shaped sweeps pass their own)
REPLICATED_MIN_BYTES = 1 << 20
#: production default for SHARD005's qualifying-buffer floor
UPDATE_MIN_BYTES = 64 << 10


def _finding(code, message, **kw) -> Finding:
    return Finding(code=code, message=message, severity="error",
                   pass_name="sharding_consistency", **kw)


def _itemsize(dtype: str) -> int:
    import jax.numpy as jnp

    return jnp.dtype(dtype).itemsize


# ---------------------------------------------------------------------------
# table-level checks (pure functions over SpecLayout — the analysis
# helpers in analysis/sharding.py reuse them without a traced target)
# ---------------------------------------------------------------------------


def replication_waste_findings(layout, min_bytes: int = REPLICATED_MIN_BYTES,
                               ignore_axes=()) -> List[Finding]:
    """SHARD002 over one canonical table.  ``ignore_axes`` names the
    pure DATA axes (dp, pp, sep) the plan replicates params over BY
    DESIGN — the grad all-reduce rides them; only replication along a
    weights-capable axis is left-on-the-table memory."""
    findings = []
    sizes = {a: n for a, n in layout.mesh_axes
             if n > 1 and a not in set(ignore_axes)}
    for name, ts in sorted(layout.items()):
        if ts.nbytes < min_bytes:
            continue
        candidates = {}
        for axis, n in sizes.items():
            if axis in ts.axes_used:
                continue
            if any(not axes and d % n == 0 and d >= n
                   for d, axes in zip(ts.shape, ts.dim_axes)):
                candidates[axis] = ts.nbytes - ts.nbytes // n
        if not candidates:
            continue
        best_axis = max(candidates, key=lambda a: candidates[a])
        findings.append(_finding(
            "SHARD002",
            f"{name} ({ts.describe()}, {ts.nbytes} bytes) is replicated "
            f"along mesh axis '{best_axis}' "
            f"(x{sizes[best_axis]}) though a replicated dim divides it — "
            f"{candidates[best_axis]} bytes of per-device residency the "
            f"at-rest plan leaves on the table"
            + (f" (also applicable: "
               f"{sorted(set(candidates) - {best_axis})})"
               if len(candidates) > 1 else ""),
            arg_path=name,
            data={"tensor": name, "bytes": ts.nbytes,
                  "wasted_bytes": candidates[best_axis],
                  "axes": {a: candidates[a] for a in sorted(candidates)}}))
    return findings


def shard_padding_findings(layout) -> List[Finding]:
    """SHARD004 over one canonical table."""
    findings = []
    sizes = dict(layout.mesh_axes)
    for name, ts in sorted(layout.items()):
        for d, (dim, axes) in enumerate(zip(ts.shape, ts.dim_axes)):
            if not axes:
                continue
            ways = math.prod(sizes.get(a, 1) for a in axes)
            if ways <= 1 or dim % ways == 0:
                continue
            per_shard = -(-dim // ways)            # ceil
            pad_elems = (per_shard * ways - dim) * max(
                1, math.prod(ts.shape) // max(dim, 1))
            pad_bytes = pad_elems * _itemsize(ts.dtype)
            findings.append(_finding(
                "SHARD004",
                f"{name} dim {d} (size {dim}) shards over "
                f"{'/'.join(axes)} ({ways} ways) without dividing — "
                f"XLA pads every shard to {per_shard} "
                f"(~{pad_bytes} padded bytes riding every transfer of "
                f"this leaf); re-plan the dim or fall back to "
                f"replication like the at-rest rule",
                arg_path=name,
                data={"tensor": name, "dim": d, "size": dim,
                      "ways": ways, "padded_bytes": pad_bytes}))
    return findings


def cross_stack_findings(layouts: Dict[str, object]) -> List[Finding]:
    """SHARD003 over two or more stacks' canonical tables: every
    logical tensor present in a pair of tables must carry the SAME spec
    after restriction to the axes both tables know."""
    findings = []
    names = sorted(layouts)
    for i, a_name in enumerate(names):
        for b_name in names[i + 1:]:
            a, b = layouts[a_name], layouts[b_name]
            shared = a.active_axes() & b.active_axes()
            for key in sorted(set(a.entries) & set(b.entries)):
                ta = a[key].restrict(shared)
                tb = b[key].restrict(shared)
                diffs = []
                if ta.shape != tb.shape:
                    diffs.append(f"shape {ta.shape} vs {tb.shape}")
                if ta.dim_axes != tb.dim_axes:
                    diffs.append(f"dims ({ta.describe()}) vs "
                                 f"({tb.describe()})")
                if ta.memory_kind != tb.memory_kind:
                    diffs.append(f"memory {ta.memory_kind} vs "
                                 f"{tb.memory_kind}")
                if not diffs:
                    continue
                findings.append(_finding(
                    "SHARD003",
                    f"{key}: stacks '{a_name}' and '{b_name}' map the "
                    f"same logical parameter to different canonical "
                    f"specs over shared axes {sorted(shared)} — "
                    f"{'; '.join(diffs)}.  Divergent at-rest layouts "
                    f"mean every cross-stack handoff (checkpoint "
                    f"restore, replica delivery, the future unified "
                    f"schedule) pays a silent reshard",
                    arg_path=key,
                    data={"tensor": key, "stacks": [a_name, b_name],
                          "shared_axes": sorted(shared),
                          a_name: a[key].describe(),
                          b_name: b[key].describe()}))
    return findings


# ---------------------------------------------------------------------------
# the registered pass (program-level SHARD001/SHARD005 + table plumbing)
# ---------------------------------------------------------------------------


@register_pass
class ShardingConsistencyPass(AnalysisPass):
    name = "sharding_consistency"
    # SCHED001 (round-19) is table-level only: the unified
    # PartitionSchedule's derivations vs the hand-written stacks'
    # extracted tables (analysis/sharding.check_schedule_derivation) —
    # byte-identity is the refactor's acceptance gate
    codes = ("SHARD001", "SHARD002", "SHARD003", "SHARD004", "SHARD005",
             "SCHED001")
    # SHARD001 compiles, but only when the entry opts into the reshard
    # audit — table/jaxpr checks stay cheap (COMM-pass convention)
    requires = "jaxpr"

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        opts = ctx.options.get(self.name, {}) if ctx.options else {}
        findings: List[Finding] = []
        ran = False
        if opts.get("audit_resharding") or "declared" in opts:
            ran = True
            findings.extend(self._check_resharding(
                ctx, opts.get("declared", {})))
        if "layout" in opts:
            ran = True
            mb = opts.get("replicated_min_bytes", REPLICATED_MIN_BYTES)
            findings.extend(replication_waste_findings(
                opts["layout"], mb,
                ignore_axes=opts.get("replication_ignore_axes", ())))
            findings.extend(shard_padding_findings(opts["layout"]))
        if "layouts" in opts:
            ran = True
            findings.extend(cross_stack_findings(opts["layouts"]))
        if opts.get("expect_update_pin"):
            ran = True
            findings.extend(self._check_update_pin(
                ctx, opts.get("update_min_bytes", UPDATE_MIN_BYTES)))
        if not ran:
            raise SkipPass(
                "no sharding contract declared (audit_resharding / "
                "declared / layout / layouts / expect_update_pin) — a "
                "partition contract is per-entry-point, like the "
                "collective and memory budgets")
        return findings

    # ---- SHARD001 ---------------------------------------------------------

    def _manual_counts(self, ctx) -> Dict[str, int]:
        counts = {k: 0 for k in RESHARD_KINDS}
        for eqn, _ in walk_eqns(ctx.jaxpr):
            kind = MANUAL_RESHARD_PRIMS.get(eqn.primitive.name)
            if kind is not None:
                counts[kind] += 1
        return counts

    def _check_resharding(self, ctx, declared) -> List[Finding]:
        hlo = scan_hlo_collectives(ctx.compiled_text)
        manual = self._manual_counts(ctx)
        findings = []
        for kind in RESHARD_KINDS:
            got = hlo.get(kind, {"count": 0, "bytes": 0})
            allowed = int(declared.get(kind, manual[kind]))
            if got["count"] <= allowed:
                continue
            findings.append(self.finding(
                "SHARD001",
                f"{kind}: {got['count']} in the compiled HLO "
                f"({got['bytes']} bytes) against a declared reshard "
                f"schedule of {allowed} "
                f"({manual[kind]} manual jaxpr-level"
                f"{', declared override ' + str(declared[kind]) if kind in declared else ''}) "
                f"— GSPMD inserted layout conversions this entry point "
                f"never scheduled; pin the producing specs or declare "
                f"the reshard deliberately",
                data={"kind": kind, "hlo": dict(got),
                      "manual": manual[kind], "allowed": allowed}))
        return findings

    # ---- SHARD005 ---------------------------------------------------------

    def _check_update_pin(self, ctx, min_bytes: int) -> List[Finding]:
        import jax.numpy as jnp

        findings = []
        sharded_pin = False
        for eqn, _ in walk_eqns(ctx.jaxpr):
            if eqn.primitive.name != "sharding_constraint":
                continue
            aval = eqn.invars[0].aval
            try:
                if aval.ndim != 1 or aval.dtype != jnp.float32:
                    continue
                nbytes = int(aval.size) * 4
            except Exception:
                continue
            if nbytes < min_bytes:
                continue
            spec = getattr(eqn.params.get("sharding"), "spec", None)
            entries = tuple(spec) if spec is not None else ()
            if any(e is not None for e in entries):
                sharded_pin = True
                continue
            where, data = format_where(eqn)
            findings.append(self.finding(
                "SHARD005",
                f"flat update buffer ({nbytes} bytes fp32) explicitly "
                f"pinned REPLICATED — the optimizer read-modify-write "
                f"runs in full on every device instead of sharding "
                f"cross-replica (arxiv 2004.13336), and the unpinned "
                f"concat→update→slice chain is the exact region the "
                f"0.4.x GSPMD partitioner mis-lowers (see "
                f"Adam.apply_flat)",
                where=where, data={**data, "bytes": nbytes}))
        if not sharded_pin and not findings:
            findings.append(self.finding(
                "SHARD005",
                f"entry declares a sharded weight update "
                f"(expect_update_pin) but carries NO sharding_constraint "
                f"over any fp32 1-D buffer >= {min_bytes} bytes — the "
                f"flat optimizer chain runs wherever GSPMD propagation "
                f"lands it: replicated update traffic (2004.13336) and "
                f"the unconstrained concat→update→slice layout the "
                f"0.4.x toolchain mis-compiles (build_train_step must "
                f"supply flat_sharding whenever a mesh is present)",
                data={"min_bytes": min_bytes}))
        return findings
