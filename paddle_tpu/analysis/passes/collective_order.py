"""COLL — collective-order checker for shard_map regions.

SPMD programs deadlock (or silently corrupt) when ranks disagree on the
next collective: the canonical source is a ``lax.cond`` inside a
shard_map body whose branches issue DIFFERENT collective sequences over
some mesh axis — ranks that take different branches then pair a psum
with nothing (hang) or with the wrong collective (garbage).  The
array-redistribution literature (arxiv 2112.01075) treats collective
sequences as statically checkable artifacts; this pass does the same
over our jaxprs.

Codes:
- COLL001: cond branches inside a shard_map body issue mismatched
  collective sequences for a mesh axis (deadlock/race analog).
- COLL002: a ppermute whose (source, dest) pairs repeat a source or a
  destination — two sends racing into one receive buffer (or one rank
  sending twice), malformed by the ppermute contract.
"""

from __future__ import annotations

from typing import List, Tuple

from ..core import (AnalysisContext, AnalysisPass, format_where,
                    register_pass, sub_jaxprs, walk_eqns)
from ..findings import Finding

# communication primitives whose cross-rank ORDER matters.  pbroadcast /
# pvary are replication-bookkeeping markers inserted by shard_map's
# check_rep rewrite — no wire traffic, excluded on purpose.
COLLECTIVE_PRIMS = frozenset({
    "psum", "psum2", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_gather_invariant", "all_to_all", "psum_scatter", "reduce_scatter",
    "pgather",
})


def _axes_of(eqn) -> Tuple[str, ...]:
    ax = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if ax is None:
        ax = ()
    if not isinstance(ax, (tuple, list)):
        ax = (ax,)
    return tuple(str(a) for a in ax)


def _routing_of(eqn):
    """Pairing-relevant params beyond the axes: two branches both doing
    a ppermute still deadlock if their perms differ (ranks consult
    different send/recv tables)."""
    if eqn.primitive.name == "ppermute":
        # sorted: the pair LIST's order is not semantic — only the
        # send/recv pairing itself is
        return tuple(sorted(tuple(int(x) for x in p)
                            for p in eqn.params.get("perm", ())))
    if eqn.primitive.name == "all_to_all":
        return (eqn.params.get("split_axis"),
                eqn.params.get("concat_axis"))
    return None


def _collective_seq(jaxpr) -> List[Tuple[str, Tuple[str, ...], object]]:
    """Program-order sequence of (primitive, axes, routing) collectives
    in a jaxpr, including nested control flow (nested cond divergence is
    reported at its own cond; for the parent comparison the full
    flattened sequence is what a rank would execute)."""
    seq = []
    for eqn, _ in walk_eqns(jaxpr):
        if eqn.primitive.name in COLLECTIVE_PRIMS:
            seq.append((eqn.primitive.name, _axes_of(eqn),
                        _routing_of(eqn)))
    return seq


@register_pass
class CollectiveOrderPass(AnalysisPass):
    name = "collective_order"
    codes = ("COLL001", "COLL002")
    requires = "jaxpr"

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for eqn, _stack in walk_eqns(ctx.jaxpr):
            if eqn.primitive.name != "shard_map":
                continue
            for _, body in sub_jaxprs(eqn):
                findings.extend(self._check_body(body))
        return findings

    # ---- per-region checks ------------------------------------------------

    def _check_body(self, body) -> List[Finding]:
        findings: List[Finding] = []
        for eqn, _ in walk_eqns(body):
            if eqn.primitive.name == "cond":
                findings.extend(self._check_cond(eqn))
            elif eqn.primitive.name == "ppermute":
                findings.extend(self._check_ppermute(eqn))
        return findings

    def _check_cond(self, eqn) -> List[Finding]:
        branches = [j for _, j in sub_jaxprs(eqn)]
        seqs = [_collective_seq(b) for b in branches]
        axes = sorted({a for s in seqs for _, ax, _ in s for a in ax})
        findings = []
        for axis in axes:
            per_branch = [tuple((p, r) for p, ax, r in s if axis in ax)
                          for s in seqs]
            if len(set(per_branch)) > 1:
                where, data = format_where(eqn)
                findings.append(self.finding(
                    "COLL001",
                    f"cond branches inside shard_map issue mismatched "
                    f"collective sequences over mesh axis {axis!r}: "
                    + " vs ".join(str(list(s)) for s in per_branch)
                    + " — ranks taking different branches will pair "
                      "collectives incorrectly (deadlock/race)",
                    where=where, data={**data, "axis": axis,
                                       "sequences": per_branch}))
        return findings

    def _check_ppermute(self, eqn) -> List[Finding]:
        perm = [tuple(int(x) for x in pair)
                for pair in eqn.params.get("perm", ())]
        srcs = [s for s, _ in perm]
        dsts = [d for _, d in perm]
        findings = []
        if len(set(srcs)) != len(srcs) or len(set(dsts)) != len(dsts):
            where, data = format_where(eqn)
            findings.append(self.finding(
                "COLL002",
                f"ppermute perm {perm} repeats a "
                f"{'source' if len(set(srcs)) != len(srcs) else 'destination'}"
                f" — not a partial permutation (two transfers race into "
                f"one buffer / one rank double-sends)",
                where=where, data={**data, "perm": perm}))
        return findings
