"""DON — donation analyzer.

A jitted step that is handed params/optimizer-state without donating them
holds TWO copies of the model in HBM for the duration of the step (input
buffers stay live while outputs materialize) — at 8B-param scale that is
the difference between fitting and OOM.  The flip side is use-after-
donate: passing one buffer into two donated positions (or re-passing a
donated buffer) hands XLA the same storage twice and the second read is
garbage.

Codes:
- DON001: a large dynamic argument of a jit entry point is not donated
  (double-residency).  Aggregated per top-level argument — "opt_state
  (14.2 MB over 12 leaves) not donated", not 12 findings.  Arguments
  that legitimately persist across calls (serving weights streamed every
  chunk) are declared via ``options={"donation": {"persistent": (0,)}}``.
- DON002: the same concrete buffer appears in more than one leaf of the
  call with at least one occurrence donated — a use-after-donate hazard
  XLA only reports at runtime, if at all.

This pass needs the Lowered (donation metadata lives there, not in the
jaxpr): plain un-jitted functions are skipped — there is no donation
contract to audit.
"""

from __future__ import annotations

from typing import Any, List

import jax.tree_util as jtu

from ..core import AnalysisContext, AnalysisPass, SkipPass, register_pass
from ..findings import Finding


def _resolve_path(root, path):
    """Best-effort walk of a tree_flatten_with_path path into the concrete
    (args, kwargs) structure; None when it cannot be resolved (static
    positional args shift args_info indices)."""
    obj = root
    for key in path:
        try:
            if hasattr(key, "idx"):
                obj = obj[key.idx]
            elif hasattr(key, "key"):
                obj = obj[key.key]
            elif hasattr(key, "name"):
                obj = getattr(obj, key.name)
            else:
                return None
        except Exception:
            return None
    return obj


def _top_label(path) -> str:
    """Human label for the top-level argument a leaf belongs to:
    "arg0", "arg2", or "kwarg 'kv_scales'"."""
    if not path:
        return "args"
    first = path[0]
    if hasattr(first, "idx") and first.idx == 0:
        # inside the positional-args tuple: the next key is the argnum
        if len(path) > 1 and hasattr(path[1], "idx"):
            return f"arg{path[1].idx}"
        return "args"
    if len(path) > 1 and hasattr(path[1], "key"):
        return f"kwarg {path[1].key!r}"
    return jtu.keystr(path[:2])


@register_pass
class DonationPass(AnalysisPass):
    name = "donation"
    codes = ("DON001", "DON002")
    requires = "lowered"

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        if not ctx.is_jit_entry:
            raise SkipPass("target is not a jit entry point — no donation "
                           "contract to audit")
        min_bytes = ctx.opt(self.name, "min_bytes", 1 << 20)
        persistent = set(ctx.opt(self.name, "persistent", ()))
        lowered = ctx.lowered
        leaves = jtu.tree_flatten_with_path(lowered.args_info)[0]

        findings: List[Finding] = []
        findings.extend(self._undonated(leaves, min_bytes, persistent))
        findings.extend(self._use_after_donate(ctx, leaves))
        return findings

    # ---- DON001 -----------------------------------------------------------

    @staticmethod
    def _leaf_bytes(info) -> int:
        try:
            size = 1
            for d in info.shape:
                size *= int(d)
            return size * info.dtype.itemsize
        except Exception:
            return 0

    def _undonated(self, leaves, min_bytes, persistent) -> List[Finding]:
        per_arg: dict = {}
        for path, info in leaves:
            if getattr(info, "donated", False):
                continue
            argnum = path[1].idx if (len(path) > 1 and hasattr(path[0], "idx")
                                     and path[0].idx == 0
                                     and hasattr(path[1], "idx")) else None
            if argnum in persistent:
                continue
            label = _top_label(path)
            slot = per_arg.setdefault(label, {"bytes": 0, "leaves": 0,
                                              "biggest": ("", 0)})
            b = self._leaf_bytes(info)
            slot["bytes"] += b
            slot["leaves"] += 1
            if b > slot["biggest"][1]:
                slot["biggest"] = (jtu.keystr(path), b)
        findings = []
        for label, slot in sorted(per_arg.items()):
            if slot["bytes"] < min_bytes:
                continue
            big_path, big_bytes = slot["biggest"]
            findings.append(self.finding(
                "DON001",
                f"{label}: {slot['bytes'] / 1e6:.2f} MB across "
                f"{slot['leaves']} leaf array(s) passed to a jit entry "
                f"without donation — input and output copies are both "
                f"HBM-resident for the step (largest leaf {big_path}, "
                f"{big_bytes / 1e6:.2f} MB); donate it, or declare it "
                f"persistent if it is reused across calls",
                arg_path=label,
                data={"bytes": slot["bytes"], "leaves": slot["leaves"]}))
        return findings

    # ---- DON002 -----------------------------------------------------------

    def _use_after_donate(self, ctx, leaves) -> List[Finding]:
        root = (ctx.args, ctx.kwargs)
        by_buffer: dict = {}
        for path, info in leaves:
            val = _resolve_path(root, path)
            if val is None or not hasattr(val, "shape") \
                    or tuple(val.shape) != tuple(info.shape):
                continue       # path misaligned (static positional args)
            by_buffer.setdefault(id(val), []).append(
                (jtu.keystr(path), bool(getattr(info, "donated", False))))
        findings = []
        for _, uses in by_buffer.items():
            if len(uses) < 2 or not any(donated for _, donated in uses):
                continue
            paths = [p for p, _ in uses]
            findings.append(self.finding(
                "DON002",
                f"the same buffer is passed in {len(uses)} argument "
                f"positions {paths} with at least one donated — after "
                f"donation the other alias reads freed storage "
                f"(use-after-donate)",
                arg_path=paths[0], data={"paths": paths}))
        return findings
