"""MEM — peak-HBM and host-transfer budgets per entry point.

The HBM memory engine (parallel/memory.py) makes residency an engineered
artifact; this pass keeps it that way.  A declared entry point carries a
capacity contract the way round-9 steps carry a collective budget: the
compiled program's peak bytes must fit the declared HBM budget, and the
host↔device streaming traffic must stay inside the declared streaming
budget — an accidental FULL-state round trip (one un-bucketed
device_put of a whole optimizer group, a forgotten fallback that
gathers every offloaded leaf per step) fails the doctor, not a TPU
session with an OOM or a step-time cliff.

Codes:
- MEM000: the target failed to XLA-compile — the capacity numbers are
  moot and the step cannot run (same contract as HLO000: a compile
  regression gates red, never skips).
- MEM001: ``compiled.memory_analysis()`` peak bytes (arguments +
  outputs + temporaries − donation aliasing) exceed the entry point's
  declared budget, ``options={"memory_budget": {"hbm_bytes": N}}``.
  No declared budget → that check is skipped (a budget is a
  per-entry-point contract, not a global default).
- MEM002: the summed bytes of memory-kind transfers (``device_put``
  eqns whose target names a memory kind — the offload engine's
  streaming primitive) exceed the declared streaming budget,
  ``options={"memory_budget": {"host_transfer_bytes": N}}``.  Counted
  at the jaxpr level so the audit is backend-independent (on CPU the
  transfers are aliases, but the eqns — and a regression to
  monolithic full-state movement — are equally visible).
"""

from __future__ import annotations

from typing import List

from ..core import (AnalysisContext, AnalysisPass, SkipPass, aval_size,
                    format_where, register_pass, walk_eqns)
from ..findings import Finding


def _transfer_memory_kind(eqn):
    """The target memory kind of a device_put eqn, or None when the
    transfer carries no explicit memory-kind (plain device placement /
    sharding constraint)."""
    for dev in eqn.params.get("devices", ()):
        kind = getattr(dev, "memory_kind", None)
        if kind is not None:
            return str(kind)
    return None


def scan_memory_transfers(jaxpr):
    """(bytes, kind, eqn) for every explicit memory-kind transfer in
    the program (nested jaxprs included — the streamed optimizer apply
    lives inside the jitted step's body)."""
    out = []
    for eqn, _stack in walk_eqns(jaxpr):
        if eqn.primitive.name != "device_put":
            continue
        kind = _transfer_memory_kind(eqn)
        if kind is None:
            continue
        nbytes = sum(aval_size(v.aval) * v.aval.dtype.itemsize
                     for v in eqn.outvars
                     if hasattr(v.aval, "dtype"))
        out.append((nbytes, kind, eqn))
    return out


@register_pass
class MemoryBudgetPass(AnalysisPass):
    name = "memory_budget"
    codes = ("MEM000", "MEM001", "MEM002")
    # MEM001 needs the compiled executable, but only when an HBM budget
    # is actually declared; MEM002 is jaxpr-level
    requires = "jaxpr"

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        opts = ctx.options.get(self.name, {}) if ctx.options else {}
        hbm = opts.get("hbm_bytes")
        host = opts.get("host_transfer_bytes")
        if hbm is None and host is None:
            raise SkipPass(
                "no memory budget declared for this entry point "
                "(options={'memory_budget': {'hbm_bytes': ..., "
                "'host_transfer_bytes': ...}})")
        findings: List[Finding] = []
        if hbm is not None:
            findings.extend(self._check_peak(ctx, int(hbm)))
        if host is not None:
            findings.extend(self._check_transfers(ctx, int(host)))
        return findings

    # ---- MEM001 ----------------------------------------------------------

    def _check_peak(self, ctx, hbm: int) -> List[Finding]:
        try:
            compiled, _ = ctx.compile()
            ma = compiled.memory_analysis()
        except Exception as e:  # noqa: BLE001 — gate red, never skip
            return [self.finding(
                "MEM000",
                f"target failed to XLA-compile — the peak-memory check "
                f"is moot and the step cannot run: {e!r}"[:500],
                data={"error": repr(e)[:300]})]
        arg = int(ma.argument_size_in_bytes)
        out = int(ma.output_size_in_bytes)
        temp = int(ma.temp_size_in_bytes)
        alias = int(ma.alias_size_in_bytes)
        peak = arg + out + temp - alias
        if peak <= hbm:
            return []
        return [self.finding(
            "MEM001",
            f"compiled peak memory {peak / 1e6:.2f} MB exceeds the "
            f"declared HBM budget of {hbm / 1e6:.2f} MB "
            f"(arguments {arg / 1e6:.2f} + outputs {out / 1e6:.2f} + "
            f"temporaries {temp / 1e6:.2f} − donation aliasing "
            f"{alias / 1e6:.2f}) — pick a heavier point on the "
            f"remat/offload lattice (parallel.memory.tune_memory_config)"
            f" or raise the declared budget deliberately",
            data={"peak_bytes": peak, "budget_bytes": hbm,
                  "argument_bytes": arg, "output_bytes": out,
                  "temp_bytes": temp, "alias_bytes": alias})]

    # ---- MEM002 ----------------------------------------------------------

    def _check_transfers(self, ctx, budget: int) -> List[Finding]:
        transfers = scan_memory_transfers(ctx.jaxpr)
        total = sum(nb for nb, _, _ in transfers)
        if total <= budget:
            return []
        worst = sorted(transfers, key=lambda t: -t[0])[:3]
        where, data = format_where(worst[0][2]) if worst else (None, {})
        return [self.finding(
            "MEM002",
            f"memory-kind transfer traffic of {total / 1e6:.2f} MB per "
            f"step exceeds the declared streaming budget of "
            f"{budget / 1e6:.2f} MB over {len(transfers)} transfers — "
            f"an un-bucketed full-state round trip defeats the offload "
            f"engine's size-capped streaming (largest: "
            f"{', '.join(f'{nb / 1e6:.2f} MB→{k}' for nb, k, _ in worst)})",
            where=where,
            data={**data, "total_bytes": total, "budget_bytes": budget,
                  "transfers": len(transfers),
                  "largest_bytes": [int(nb) for nb, _, _ in worst]})]
