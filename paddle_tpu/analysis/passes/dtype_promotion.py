"""DT — dtype-promotion audit.

A declared-bf16 compute region (compute_dtype=bf16 train steps, bf16
serving) wins its milliseconds from MXU-native matmuls and half-width HBM
traffic; one silent ``astype(float32)`` in the wrong place gives them
back without failing any numeric test.  This pass walks the jaxpr and
flags the upcasts that matter:

- DT001: a large matmul (dot_general) running in fp32/f64 inside a
  declared-bf16 region — a silently-upcast MXU op (4-8x the bf16 cycle
  cost on TPU).
- DT002: any float64 value anywhere — f64 cannot exist unless x64 crept
  in, and on TPU it software-emulates.
- DT003: an INNERMOST accumulation loop (lax.scan) carrying a large fp32
  buffer in a declared-bf16 region — the read-modify-write of that carry
  is fp32-width HBM traffic every iteration (the class of cost the
  round-7 bf16 grad-accum carry removed; the masked grad-accum branch is
  the tracked exemption EX-DT003-masked-grad-accum).  Outer fold carries
  are exempt by construction: a scan whose body contains another
  large-carry scan is a fold loop, not the hot accumulation loop.

The declared dtype comes from ``check(..., declared_dtype=...)`` or is
inferred: if any matmul in the program runs in bf16/f16, the program
declared low-precision compute and the audit applies.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp

from ..core import (AnalysisContext, AnalysisPass, aval_size, format_where,
                    register_pass, walk_eqns)
from ..findings import Finding

LOW_PRECISION = ("bfloat16", "float16")


def _dtype(v) -> str:
    try:
        return str(v.aval.dtype)
    except Exception:
        return ""


def _infer_declared(jaxpr):
    """The region's declared compute dtype: the lowest-precision dtype any
    dot_general runs in (bf16 beats fp32 — one bf16 matmul means the
    author opted into low-precision compute)."""
    seen = set()
    for eqn, _ in walk_eqns(jaxpr):
        if eqn.primitive.name == "dot_general":
            seen.update(_dtype(v) for v in eqn.invars)
    for lp in LOW_PRECISION:
        if lp in seen:
            return lp
    return None


@register_pass
class DtypePromotionPass(AnalysisPass):
    name = "dtype_promotion"
    codes = ("DT001", "DT002", "DT003")
    requires = "jaxpr"

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        min_elems = ctx.opt(self.name, "min_elements", 4096)
        declared = ctx.declared_dtype
        declared = str(jnp.dtype(declared)) if declared is not None \
            else _infer_declared(ctx.jaxpr)
        low_precision_region = declared in LOW_PRECISION

        findings: List[Finding] = []
        for eqn, stack in walk_eqns(ctx.jaxpr):
            findings.extend(self._check_f64(eqn))
            if not low_precision_region:
                continue
            if eqn.primitive.name == "dot_general":
                findings.extend(self._check_dot(eqn, declared, min_elems))
            elif eqn.primitive.name == "scan":
                findings.extend(self._check_scan_carry(eqn, declared,
                                                       min_elems))
        return findings

    # ---- DT002 ------------------------------------------------------------

    def _check_f64(self, eqn) -> List[Finding]:
        for v in eqn.outvars:
            if _dtype(v) == "float64":
                where, data = format_where(eqn)
                return [self.finding(
                    "DT002",
                    f"float64 value produced by {eqn.primitive.name} "
                    f"(shape {getattr(v.aval, 'shape', '?')}) — f64 "
                    f"software-emulates on TPU; an x64-enabled input "
                    f"leaked into the program",
                    where=where, data=data)]
        return []

    # ---- DT001 ------------------------------------------------------------

    def _check_dot(self, eqn, declared, min_elems) -> List[Finding]:
        in_dtypes = [_dtype(v) for v in eqn.invars]
        floats = [dt for dt in in_dtypes
                  if dt in LOW_PRECISION + ("float32", "float64")]
        if not floats:
            return []          # int8/int32 dots (quantized) are fine
        if not any(dt in ("float32", "float64") for dt in floats):
            return []
        size = max(aval_size(v.aval) for v in eqn.invars)
        if size < min_elems:
            return []          # small glue math may legitimately be fp32
        # a MIXED bf16 x f32 dot is the sneakiest form: promotion upcasts
        # the bf16 operand and the dot runs full-precision anyway (the
        # rope-table bug produced exactly these across every layer)
        mixed = any(dt in LOW_PRECISION for dt in floats)
        where, data = format_where(eqn)
        shapes = [tuple(v.aval.shape) for v in eqn.invars]
        kind = (f"mixed-precision matmul {list(zip(shapes, in_dtypes))} — "
                f"promotion upcasts the {declared} operand and the dot "
                f"runs fp32" if mixed else
                f"fp32 matmul {shapes} — a silent upcast is paying "
                f"full-precision MXU cycles")
        return [self.finding(
            "DT001",
            f"{kind} inside a declared-{declared} compute region; cast "
            f"the operands to {declared} or add a tracked exemption",
            where=where, data={**data, "shapes": shapes, "mixed": mixed})]

    # ---- DT003 ------------------------------------------------------------

    def _carry_avals(self, eqn):
        body = eqn.params["jaxpr"].jaxpr
        nc, nk = eqn.params["num_consts"], eqn.params["num_carry"]
        return body, body.invars[nc:nc + nk], body.outvars[:nk]

    def _has_large_carry(self, eqn, min_elems) -> bool:
        _, carries, _ = self._carry_avals(eqn)
        return any(aval_size(v.aval) >= min_elems for v in carries)

    def _check_scan_carry(self, eqn, declared, min_elems) -> List[Finding]:
        body, carries, carry_outs = self._carry_avals(eqn)
        # innermost only: a body containing another large-carry scan is a
        # fold loop around the real accumulation loop (the bf16-carry
        # scheme's fp32 fold carry is absorbed once per fold, not per
        # micro-step — that is the design, not the hazard)
        for inner, _ in walk_eqns(body):
            if inner.primitive.name == "scan" \
                    and self._has_large_carry(inner, min_elems):
                return []
        hot = [(i, v) for i, v in enumerate(carries)
               if _dtype(v) == "float32" and aval_size(v.aval) >= min_elems]
        if not hot:
            return []
        total = sum(aval_size(v.aval) for _, v in hot) * 4
        # provenance: the eqn that PRODUCES the largest fp32 carry inside
        # the body (the accumulate op) names the function to exempt
        idx = max(hot, key=lambda iv: aval_size(iv[1].aval))[0]
        out_var = carry_outs[idx]
        where, data = format_where(eqn)
        for beqn in reversed(body.eqns):
            if out_var in beqn.outvars:
                where, data = format_where(beqn)
                break
        return [self.finding(
            "DT003",
            f"innermost scan carries {len(hot)} fp32 buffer(s) "
            f"({total / 1e6:.2f} MB) in a declared-{declared} region — "
            f"the carry's read-modify-write is full-width HBM traffic "
            f"every micro-step; use a bounded-depth bf16 carry with fp32 "
            f"folds, or add a tracked exemption",
            where=where,
            data={**data, "num_buffers": len(hot), "bytes": total})]
