"""HEALTH — the round-17 probe-fusion contract.

The training health guardian (distributed/health.py) fuses its probe —
global grad-norm, per-bucket nonfinite counts, loss, update/param ratio
— INTO the train step so detection costs one tiny transfer.  That claim
only stays true if the probe remains REDUCTIONS over buffers the step
already holds; this pass pins it the doctor's way, against the
UNPROBED entry's measured numbers:

- HEALTH001: the probed entry's compiled peak exceeds
  ``baseline_peak_bytes + probe_overhead_bytes`` — the probe (or its
  no-op guard) materialized something tree-sized (the classic
  regression: a host-style probe that concatenates every grad leaf
  into one fp32 buffer, or casts the full tree to fp32 "for the
  norm").  ``options={"health_probe": {"baseline_peak_bytes": N,
  "probe_overhead_bytes": M}}``; the baseline is the SAME entry built
  without ``health=`` (self_check measures it in-process).
- HEALTH002: the probed entry's compiled HLO carries MORE collectives
  of some kind than ``baseline_collectives`` declares — the probe
  added communication (a psum'd scalar probe on the single-chip entry,
  an all-gathered grad tree "for the global norm").  On the flagship
  single-chip step the baseline is zero of every kind, so ANY
  collective fires.  ``options={"health_probe":
  {"baseline_collectives": {kind: count}}}`` (missing kinds default
  to 0).

Both checks need a declared option to run (a budget is a per-entry
contract); with neither, the pass skips.
"""

from __future__ import annotations

from typing import List

from ..core import AnalysisContext, AnalysisPass, SkipPass, register_pass
from ..findings import Finding
from .collective_budget import scan_hlo_collectives


def compiled_peak_bytes(ctx: AnalysisContext) -> int:
    """arguments + outputs + temporaries − donation aliasing, the same
    peak MEM001 prices (shared so self_check can measure the unprobed
    baseline with the identical formula)."""
    compiled, _ = ctx.compile()
    ma = compiled.memory_analysis()
    return (int(ma.argument_size_in_bytes) + int(ma.output_size_in_bytes)
            + int(ma.temp_size_in_bytes) - int(ma.alias_size_in_bytes))


@register_pass
class HealthProbePass(AnalysisPass):
    name = "health_probe"
    codes = ("HEALTH001", "HEALTH002")
    requires = "compiled"

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        opts = ctx.options.get(self.name, {}) if ctx.options else {}
        baseline_peak = opts.get("baseline_peak_bytes")
        baseline_coll = opts.get("baseline_collectives")
        if baseline_peak is None and baseline_coll is None:
            raise SkipPass(
                "no probe-fusion contract declared for this entry point "
                "(options={'health_probe': {'baseline_peak_bytes': ..., "
                "'probe_overhead_bytes': ..., "
                "'baseline_collectives': {...}}})")
        findings: List[Finding] = []
        if baseline_peak is not None:
            findings.extend(self._check_peak(
                ctx, int(baseline_peak),
                int(opts.get("probe_overhead_bytes", 64 << 10))))
        if baseline_coll is not None:
            findings.extend(self._check_collectives(ctx, baseline_coll))
        return findings

    # ---- HEALTH001: no extra full-tree materialization -------------------

    def _check_peak(self, ctx, baseline: int, overhead: int):
        try:
            peak = compiled_peak_bytes(ctx)
        except Exception as e:  # noqa: BLE001 — gate red, never skip
            return [self.finding(
                "HEALTH001",
                f"probed target failed to XLA-compile — the fusion "
                f"check is moot and the step cannot run: {e!r}"[:500],
                data={"error": repr(e)[:300]})]
        budget = baseline + overhead
        if peak <= budget:
            return []
        return [self.finding(
            "HEALTH001",
            f"probed step's compiled peak {peak / 1e6:.2f} MB exceeds "
            f"the unprobed baseline {baseline / 1e6:.2f} MB by more "
            f"than the declared probe overhead {overhead / 1e6:.2f} MB "
            f"— the health probe materialized tree-sized intermediates "
            f"instead of fusing its reductions into buffers the step "
            f"already holds (distributed/health.make_probe is the "
            f"reductions-only reference)",
            data={"peak_bytes": peak, "baseline_bytes": baseline,
                  "overhead_bytes": overhead, "budget_bytes": budget})]

    # ---- HEALTH002: zero added collectives -------------------------------

    def _check_collectives(self, ctx, baseline):
        counts = scan_hlo_collectives(ctx.compiled_text)
        over = {}
        for kind, c in counts.items():
            allowed = int(baseline.get(kind, 0))
            if c["count"] > allowed:
                over[kind] = {"count": c["count"], "allowed": allowed,
                              "bytes": c["bytes"]}
        if not over:
            return []
        detail = ", ".join(f"{k} {v['count']}>{v['allowed']}"
                           for k, v in sorted(over.items()))
        return [self.finding(
            "HEALTH002",
            f"probed step's compiled HLO carries collectives beyond "
            f"the unprobed baseline ({detail}) — the health probe "
            f"added communication; on the single-chip flagship the "
            f"probe must add ZERO collectives (scalar reductions over "
            f"local shards only; a mesh entry's probe rides the "
            f"reductions GSPMD already schedules for the loss)",
            data={"over": over})]
