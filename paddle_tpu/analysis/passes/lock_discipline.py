"""Lock-discipline static analysis for the host-side control plane
(the Concurrency Doctor's static half — RACE001-004).

The compiled side of this repo is gated by jaxpr/HLO passes; the
HOST side (serving engine tick, fleet/disagg routers, watchdog,
TCPStore, checkpoint writer) is ordinary threaded Python, and it has
already shipped real lock/flag races (the PR-6 watchdog handler/flag
race).  This module is the source-level analog of the Graph Doctor
passes: a per-module AST walk that

1. discovers the module's LOCKS — attributes or globals bound to
   ``threading.Lock/RLock/Condition/Semaphore`` constructors, plus any
   ``with``-target whose name looks lock-ish (``*_lock``, ``_cv``,
   ``*_mutex``) — and tracks the held-lock set through ``with`` bodies;
2. infers each field's GUARDING lock from the writes observed under
   locks (a field written under ``self._lock`` anywhere is treated as
   ``_lock``-guarded module-wide — deliberately name-based, so a
   ``CommTask`` flag written under the manager's lock in one method and
   mutated lock-free elsewhere still correlates);
3. reports typed findings:

   - **RACE001** — a guarded field is WRITTEN both under its inferred
     lock and outside any lock (``__init__``-family constructors are
     exempt: construction is single-threaded by definition).
   - **RACE002** — lock-order inversion: a cycle in the inter-lock
     acquisition graph (edges from every held lock to each newly
     acquired one, including locks acquired transitively through
     ``self.helper()`` calls made while holding a lock).
   - **RACE003** — a blocking call while holding a lock (``time.sleep``,
     socket recv/accept, ``subprocess.run``, fsync, barrier,
     jit/lower/compile, ``block_until_ready`` …): a latency or deadlock
     hazard inside a serving tick.  Calls on the held lock itself
     (``cv.wait()`` — which RELEASES the lock) are excluded.
   - **RACE004** — check-then-act: an ``if``/``while`` TEST reads a
     guarded field while NOT holding its guard, and the same function
     then acquires that guard — exactly the shipped watchdog bug's
     shape (completion checked ``task.timed_out`` outside the manager
     lock, then committed the terminal transition under it).

Scope notes (documented limitations, not bugs): ``lock.acquire()`` /
``.release()`` call pairs are NOT tracked as held regions (the repo's
style is ``with``; raw pairs belong to the dynamic sanitizer), and the
guard inference is name-based per module — a false pair is silenced via
``concurrency_allowlist.txt`` with a written justification, never by
weakening the pass.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from ..findings import Finding

PASS_NAME = "lock_discipline"
CODES = ("RACE001", "RACE002", "RACE003", "RACE004")

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
# with-target names that are locks even without a visible constructor
_LOCK_NAME_RE = re.compile(r"(?:^|_)(?:lock|cv|cond|mutex)$", re.I)
_INIT_FUNCS = {"__init__", "__new__", "__post_init__"}
# attribute-method calls that mutate their receiver in place
_MUTATORS = {"append", "extend", "insert", "pop", "popitem", "remove",
             "clear", "update", "setdefault", "add", "discard",
             "appendleft", "popleft", "sort", "reverse"}
# leaf call names that block (only flagged while a lock is held)
_BLOCKING_LEAVES = {"sleep", "recv", "recvfrom", "recv_into", "accept",
                    "fsync", "barrier", "block_until_ready",
                    "device_put", "wait_save", "check_call",
                    "check_output", "communicate", "getaddrinfo",
                    "wait", "jit", "lower"}
# dotted chains that block (module.func — catches the generic leaves we
# cannot safely match by name alone, e.g. ``subprocess.run``)
_BLOCKING_CHAINS = {("subprocess", "run"), ("time", "sleep"),
                    ("os", "fsync")}


def _dotted(node: ast.AST) -> Optional[str]:
    """'self._lock' / '_cv' / 'os.path.join' for Name/Attribute chains,
    None for anything dynamic (subscripts, calls)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _Lock:
    """One discovered lock: a scoped identity for the order graph and a
    bare name for guard matching."""

    __slots__ = ("scoped", "bare", "expr")

    def __init__(self, scoped: str, bare: str, expr: str):
        self.scoped = scoped      # "CommTaskManager._lock" | "_cv"
        self.bare = bare          # "_lock" | "_cv"
        self.expr = expr          # source expr: "self._lock" | "_cv"


class _Access:
    __slots__ = ("attr", "kind", "held", "qual", "line", "in_init")

    def __init__(self, attr, kind, held, qual, line, in_init):
        self.attr = attr          # field name (attr or module global)
        self.kind = kind          # "read" | "write"
        self.held = held          # tuple of bare lock names held
        self.qual = qual
        self.line = line
        self.in_init = in_init


class _ModuleAnalysis:
    """One file's walk state + finding synthesis."""

    def __init__(self, tree: ast.Module, rel: str):
        self.tree = tree
        self.rel = rel
        self.lock_names: Set[str] = set()       # bare names known locks
        self.module_globals: Set[str] = set()
        self.accesses: List[_Access] = []
        # lock-order graph: scoped -> {scoped: (qual, line)}
        self.edges: Dict[str, Dict[str, Tuple[str, int]]] = {}
        # per (class, method): scoped locks acquired directly
        self.method_acquires: Dict[Tuple[str, str], Set[str]] = {}
        # deferred self-calls made while holding locks:
        # (class, method_called, held scoped tuple, qual, line)
        self.pending_calls: List[Tuple[str, str, Tuple[str, ...],
                                       str, int]] = []
        # blocking calls observed under locks
        self.blocking: List[Tuple[str, str, str, int]] = []
        # (chain, held bare names, qual, line)
        # check-then-act candidates:
        # (field, held bares, locks acquired in function, qual, line)
        self.checks: List[Tuple[str, Tuple[str, ...], Set[str],
                                str, int]] = []

    # -- phase 1: lock discovery ------------------------------------------
    def discover(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign):
                v = node.value
                if (isinstance(v, ast.Call)
                        and isinstance(v.func, (ast.Attribute, ast.Name))):
                    leaf = (v.func.attr if isinstance(v.func, ast.Attribute)
                            else v.func.id)
                    if leaf in _LOCK_CTORS:
                        for tgt in node.targets:
                            name = _dotted(tgt)
                            if name:
                                self.lock_names.add(name.split(".")[-1])
            if isinstance(node, ast.With):
                for item in node.items:
                    name = _dotted(item.context_expr)
                    if name and _LOCK_NAME_RE.search(name.split(".")[-1]):
                        self.lock_names.add(name.split(".")[-1])
        for stmt in self.tree.body:
            if isinstance(stmt, ast.Assign):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        self.module_globals.add(tgt.id)
            elif isinstance(stmt, (ast.AnnAssign,)) \
                    and isinstance(stmt.target, ast.Name):
                self.module_globals.add(stmt.target.id)

    # -- phase 2: the main walk -------------------------------------------
    def _as_lock(self, expr: ast.AST, cls: Optional[str]) -> Optional[_Lock]:
        name = _dotted(expr)
        if name is None:
            return None
        bare = name.split(".")[-1]
        if bare not in self.lock_names:
            return None
        root = name.split(".")[0]
        if root in ("self", "cls") and cls:
            return _Lock(f"{cls}.{bare}", bare, name)
        return _Lock(bare, bare, name)

    def walk(self):
        for stmt in self.tree.body:
            self._walk_stmt(stmt, cls=None, func=None, qual="<module>",
                            held=[], fn_acquires=None, global_decls=set())

    def _walk_stmt(self, node, *, cls, func, qual, held, fn_acquires,
                   global_decls):
        if isinstance(node, ast.ClassDef):
            for sub in node.body:
                self._walk_stmt(sub, cls=node.name, func=None,
                                qual=node.name, held=[],
                                fn_acquires=None, global_decls=set())
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            q = f"{cls}.{node.name}" if cls else node.name
            acquires = self._fn_lock_bares(node, cls)
            gdecls = {n for sub in ast.walk(node)
                      if isinstance(sub, ast.Global) for n in sub.names}
            if cls is not None:
                self.method_acquires.setdefault(
                    (cls, node.name),
                    self._fn_lock_scoped(node, cls))
            for sub in node.body:
                self._walk_stmt(sub, cls=cls, func=node.name, qual=q,
                                held=[], fn_acquires=acquires,
                                global_decls=gdecls)
            return
        if isinstance(node, ast.With):
            new_locks = []
            for item in node.items:
                lk = self._as_lock(item.context_expr, cls)
                if lk is not None:
                    # self-edges are skipped: re-entering an RLock is
                    # legal, and no swept module nests a plain Lock on
                    # itself (the sanitizer catches that at runtime)
                    for h in held:
                        if h.scoped != lk.scoped:
                            self.edges.setdefault(
                                h.scoped, {}).setdefault(
                                lk.scoped, (qual, node.lineno))
                    new_locks.append(lk)
                else:
                    # a non-lock context manager: its expr may still
                    # contain calls/reads
                    self._walk_expr(item.context_expr, cls=cls, qual=qual,
                                    held=held, func=func,
                                    fn_acquires=fn_acquires,
                                    global_decls=global_decls)
            inner = held + new_locks
            for sub in node.body:
                self._walk_stmt(sub, cls=cls, func=func, qual=qual,
                                held=inner, fn_acquires=fn_acquires,
                                global_decls=global_decls)
            return
        if isinstance(node, (ast.If, ast.While)):
            self._record_check(node.test, cls=cls, func=func, qual=qual,
                               held=held, fn_acquires=fn_acquires)
            self._walk_expr(node.test, cls=cls, qual=qual, held=held,
                            func=func, fn_acquires=fn_acquires,
                            global_decls=global_decls)
            for sub in node.body + node.orelse:
                self._walk_stmt(sub, cls=cls, func=func, qual=qual,
                                held=held, fn_acquires=fn_acquires,
                                global_decls=global_decls)
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            self._record_store_target(node.target, cls, qual, held, func,
                                      global_decls)
            self._walk_expr(node.iter, cls=cls, qual=qual, held=held,
                            func=func, fn_acquires=fn_acquires,
                            global_decls=global_decls)
            for sub in node.body + node.orelse:
                self._walk_stmt(sub, cls=cls, func=func, qual=qual,
                                held=held, fn_acquires=fn_acquires,
                                global_decls=global_decls)
            return
        if isinstance(node, (ast.Try,)):
            for sub in (node.body + node.orelse + node.finalbody
                        + [s for h in node.handlers for s in h.body]):
                self._walk_stmt(sub, cls=cls, func=func, qual=qual,
                                held=held, fn_acquires=fn_acquires,
                                global_decls=global_decls)
            return
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                self._record_store_target(tgt, cls, qual, held, func,
                                          global_decls)
            self._walk_expr(node.value, cls=cls, qual=qual, held=held,
                            func=func, fn_acquires=fn_acquires,
                            global_decls=global_decls)
            return
        if isinstance(node, ast.AugAssign):
            self._record_store_target(node.target, cls, qual, held, func,
                                      global_decls)
            self._walk_expr(node.value, cls=cls, qual=qual, held=held,
                            func=func, fn_acquires=fn_acquires,
                            global_decls=global_decls)
            return
        if isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self._record_store_target(node.target, cls, qual, held,
                                          func, global_decls)
                self._walk_expr(node.value, cls=cls, qual=qual, held=held,
                                func=func, fn_acquires=fn_acquires,
                                global_decls=global_decls)
            return
        if isinstance(node, ast.Delete):
            for tgt in node.targets:
                self._record_store_target(tgt, cls, qual, held, func,
                                          global_decls)
            return
        # generic statement: walk its expressions
        for sub in ast.iter_child_nodes(node):
            if isinstance(sub, ast.stmt):
                self._walk_stmt(sub, cls=cls, func=func, qual=qual,
                                held=held, fn_acquires=fn_acquires,
                                global_decls=global_decls)
            elif isinstance(sub, ast.expr):
                self._walk_expr(sub, cls=cls, qual=qual, held=held,
                                func=func, fn_acquires=fn_acquires,
                                global_decls=global_decls)

    # -- helpers ----------------------------------------------------------
    def _fn_lock_bares(self, fn, cls) -> Set[str]:
        out = set()
        for sub in ast.walk(fn):
            if isinstance(sub, ast.With):
                for item in sub.items:
                    lk = self._as_lock(item.context_expr, cls)
                    if lk is not None:
                        out.add(lk.bare)
        return out

    def _fn_lock_scoped(self, fn, cls) -> Set[str]:
        out = set()
        for sub in ast.walk(fn):
            if isinstance(sub, ast.With):
                for item in sub.items:
                    lk = self._as_lock(item.context_expr, cls)
                    if lk is not None:
                        out.add(lk.scoped)
        return out

    def _held_bares(self, held) -> Tuple[str, ...]:
        return tuple(h.bare for h in held)

    def _field_of_target(self, tgt) -> Optional[str]:
        """Field name written by an assignment target: the attribute for
        ``self.x = / self.x[i] =``, the global name for module-global
        stores; None for plain locals."""
        node = tgt
        while isinstance(node, (ast.Subscript, ast.Starred)):
            node = node.value
        if isinstance(node, ast.Attribute):
            return node.attr
        if isinstance(node, ast.Name):
            return node.id if node.id in self.module_globals else None
        if isinstance(node, (ast.Tuple, ast.List)):
            return None               # handled element-wise by caller
        return None

    def _record_store_target(self, tgt, cls, qual, held, func,
                             global_decls):
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._record_store_target(el, cls, qual, held, func,
                                          global_decls)
            return
        node = tgt
        while isinstance(node, (ast.Subscript, ast.Starred)):
            if isinstance(node, ast.Subscript):
                # index expression may itself read/call
                pass
            node = node.value
        field = None
        if isinstance(node, ast.Attribute):
            field = node.attr
        elif isinstance(node, ast.Name) and (
                node.id in global_decls or (func is None
                                            and node.id
                                            in self.module_globals)):
            # a bare-name store is a module-global write only under an
            # explicit ``global`` declaration (or at module level)
            field = node.id
        if field is None or field in self.lock_names \
                or field.startswith("__"):
            return
        self.accesses.append(_Access(
            field, "write", self._held_bares(held), qual,
            getattr(tgt, "lineno", 0), func in _INIT_FUNCS or func is None))

    def _record_check(self, test, *, cls, func, qual, held, fn_acquires):
        if func is None or not fn_acquires:
            return
        held_bares = set(self._held_bares(held))
        for sub in ast.walk(test):
            field = None
            if isinstance(sub, ast.Attribute) \
                    and isinstance(sub.ctx, ast.Load):
                field = sub.attr
            elif isinstance(sub, ast.Name) \
                    and isinstance(sub.ctx, ast.Load) \
                    and sub.id in self.module_globals:
                field = sub.id
            if field is None or field in self.lock_names:
                continue
            self.checks.append((field, tuple(held_bares),
                                set(fn_acquires), qual,
                                getattr(sub, "lineno", test.lineno)))

    def _walk_expr(self, node, *, cls, qual, held, func, fn_acquires,
                   global_decls):
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            chain = _dotted(sub.func)
            if chain is None:
                continue
            parts = chain.split(".")
            leaf = parts[-1]
            # mutating receiver call: ``self.timed_out.append(t)`` is a
            # WRITE of ``timed_out``; ``_inflight.pop(...)`` of the
            # module global ``_inflight``
            if leaf in _MUTATORS and len(parts) >= 2:
                base_leaf = parts[-2]
                is_field = (len(parts) >= 3
                            or base_leaf in self.module_globals)
                if is_field and base_leaf not in self.lock_names:
                    self.accesses.append(_Access(
                        base_leaf, "write", self._held_bares(held), qual,
                        sub.lineno,
                        func in _INIT_FUNCS or func is None))
            if held:
                # blocking call under a held lock?  calls on the held
                # lock object itself (cv.wait releases it) are fine
                base = ".".join(parts[:-1])
                held_exprs = {h.expr for h in held}
                if base in held_exprs or chain in held_exprs:
                    continue
                if (leaf in _BLOCKING_LEAVES
                        or tuple(parts[-2:]) in _BLOCKING_CHAINS):
                    self.blocking.append((chain,
                                          ",".join(self._held_bares(held)),
                                          qual, sub.lineno))
                # helper-method call while holding: collect for the
                # transitive lock-order edges
                if (cls is not None and len(parts) == 2
                        and parts[0] == "self"):
                    self.pending_calls.append(
                        (cls, leaf, tuple(h.scoped for h in held), qual,
                         sub.lineno))

    # -- phase 3: findings -------------------------------------------------
    def findings(self) -> List[Finding]:
        out: List[Finding] = []
        guards = self._guard_map()

        # RACE001: guarded field written lock-free
        for field, (lock, guarded_site) in sorted(guards.items()):
            bad = [a for a in self.accesses
                   if a.attr == field and a.kind == "write"
                   and not a.held and not a.in_init]
            if not bad:
                continue
            b = bad[0]
            out.append(Finding(
                code="RACE001", pass_name=PASS_NAME,
                message=(f"field '{field}' is written under lock "
                         f"'{lock}' (at {self.rel}:{guarded_site}) but "
                         f"also written lock-free in {b.qual}"),
                where=f"{self.rel}:{b.line} ({b.qual})",
                data={"field": field, "lock": lock, "qual": b.qual,
                      "guarded_line": guarded_site,
                      "unguarded_line": b.line}))

        # RACE002: resolve deferred helper calls, then find cycles
        self._close_call_edges()
        for cycle, (qual, line) in self._cycles():
            out.append(Finding(
                code="RACE002", pass_name=PASS_NAME,
                message=("lock-order inversion: acquisition cycle "
                         + " -> ".join(cycle + (cycle[0],))),
                where=f"{self.rel}:{line} ({qual})",
                data={"cycle": list(cycle), "qual": qual}))

        # RACE003: blocking call while holding a lock
        for chain, held, qual, line in self.blocking:
            out.append(Finding(
                code="RACE003", pass_name=PASS_NAME,
                message=(f"blocking call '{chain}(...)' while holding "
                         f"lock(s) {held} — latency/deadlock hazard in "
                         f"the control-plane tick"),
                where=f"{self.rel}:{line} ({qual})",
                data={"call": chain, "held": held, "qual": qual}))

        # RACE004: check-then-act on a guarded field
        seen = set()
        for field, held_bares, fn_locks, qual, line in self.checks:
            g = guards.get(field)
            if g is None:
                continue
            lock = g[0]
            if lock in held_bares or lock not in fn_locks:
                continue
            key = (field, qual, line)
            if key in seen:
                continue
            seen.add(key)
            out.append(Finding(
                code="RACE004", pass_name=PASS_NAME,
                message=(f"check-then-act: '{field}' (guarded by "
                         f"'{lock}') is tested OUTSIDE the lock, then "
                         f"{qual} acquires '{lock}' — the guarded state "
                         f"can change between the check and the act "
                         f"(the watchdog handler/flag race shape)"),
                where=f"{self.rel}:{line} ({qual})",
                data={"field": field, "lock": lock, "qual": qual}))
        return out

    def _guard_map(self) -> Dict[str, Tuple[str, int]]:
        """field -> (bare guard lock, example guarded-write line):
        inferred from writes observed under held locks."""
        guards: Dict[str, Dict[str, int]] = {}
        for a in self.accesses:
            if a.kind != "write" or not a.held:
                continue
            guards.setdefault(a.attr, {}).setdefault(a.held[-1], a.line)
        out = {}
        for field, locks in guards.items():
            # innermost lock of the FIRST guarded write wins; multiple
            # candidate guards for one field are rare and allowlistable
            lock, line = next(iter(locks.items()))
            out[field] = (lock, line)
        return out

    def _close_call_edges(self):
        # transitive closure of per-method direct acquisitions over the
        # intra-class self-call graph
        callgraph: Dict[Tuple[str, str], Set[str]] = {}
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for fn in node.body:
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                callees = set()
                for sub in ast.walk(fn):
                    if isinstance(sub, ast.Call):
                        chain = _dotted(sub.func)
                        if chain and chain.startswith("self.") \
                                and chain.count(".") == 1:
                            callees.add(chain.split(".")[1])
                callgraph[(node.name, fn.name)] = callees
        closed: Dict[Tuple[str, str], Set[str]] = {
            k: set(v) for k, v in self.method_acquires.items()}

        def acq(key, seen):
            if key in seen:
                return set()
            seen.add(key)
            base = set(closed.get(key, set()))
            for callee in callgraph.get(key, ()):
                base |= acq((key[0], callee), seen)
            return base

        for cls, method, held_scoped, qual, line in self.pending_calls:
            for target in acq((cls, method), set()):
                for h in held_scoped:
                    if target != h:
                        self.edges.setdefault(h, {}).setdefault(
                            target, (qual, line))

    def _cycles(self):
        """Yield each acquisition-graph cycle once, as (node tuple,
        example edge site)."""
        seen_cycles = set()
        for start in sorted(self.edges):
            stack = [(start, (start,))]
            while stack:
                node, path = stack.pop()
                for nxt in sorted(self.edges.get(node, {})):
                    if nxt == start:
                        canon = tuple(sorted(path))
                        if canon in seen_cycles:
                            continue
                        seen_cycles.add(canon)
                        yield path, self.edges[node][nxt]
                    elif nxt not in path and len(path) < 8:
                        stack.append((nxt, path + (nxt,)))


def analyze_source(source: str, rel: str) -> List[Finding]:
    """Run the lock-discipline analysis over one module's source.
    Returns raw findings (no allowlist applied — that is
    ``analysis.concurrency``'s job)."""
    tree = ast.parse(source)
    mod = _ModuleAnalysis(tree, rel)
    mod.discover()
    if not mod.lock_names:
        return []                    # lock-free module: nothing to guard
    mod.walk()
    return mod.findings()


def analyze_file(path: str, rel: Optional[str] = None) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as f:
        return analyze_source(f.read(), rel or path)


def guarded_write_map(source: str, rel: str) -> Dict[str, Dict[str, list]]:
    """The inferred static lock map, for the dynamic sanitizer's
    cross-check: {lock_bare_name: {field: [qualname, ...]}} over the
    module's under-lock writes.  The lock sanitizer's hammer compares
    this against the functions it OBSERVED acquiring each instrumented
    lock at runtime."""
    tree = ast.parse(source)
    mod = _ModuleAnalysis(tree, rel)
    mod.discover()
    if not mod.lock_names:
        return {}
    mod.walk()
    out: Dict[str, Dict[str, list]] = {}
    for a in mod.accesses:
        if a.kind != "write" or not a.held:
            continue
        quals = out.setdefault(a.held[-1], {}).setdefault(a.attr, [])
        if a.qual not in quals:
            quals.append(a.qual)
    return out
