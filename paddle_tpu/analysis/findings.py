"""Typed findings for the Graph Doctor pass framework.

A Finding is one statically-detected hazard in a compiled program (or in
repo source, for the AST lint): a stable CODE (grep-able, documented in
ANALYSIS.md), a severity, a human message, and enough location breadcrumbs
(source file/function from jaxpr eqn provenance, arg path for
donation-level findings) that the report is actionable without re-running
the pass under a debugger.

A Report is what ``paddle_tpu.analysis.check`` returns: active findings,
suppressed findings (matched by a tracked exemption — see exemptions.py),
and which passes ran.  ``report.ok`` is the gate the tests and
``bench.py --doctor`` assert on.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

SEVERITIES = ("error", "warning", "info")


@dataclasses.dataclass
class Finding:
    code: str                       # stable id, e.g. "COLL001"
    message: str
    severity: str = "error"
    pass_name: str = ""
    where: Optional[str] = None     # "models/llama.py:585 (micro_step_masked)"
    arg_path: Optional[str] = None  # for per-argument findings (donation)
    data: Dict[str, Any] = dataclasses.field(default_factory=dict)
    exemption_id: Optional[str] = None   # set when suppressed

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(f"severity {self.severity!r} not in {SEVERITIES}")

    def format(self) -> str:
        loc = f" @ {self.where}" if self.where else ""
        ap = f" [{self.arg_path}]" if self.arg_path else ""
        ex = f" (exempt: {self.exemption_id})" if self.exemption_id else ""
        return f"{self.code} {self.severity.upper()}{loc}{ap}: " \
               f"{self.message}{ex}"


@dataclasses.dataclass
class Report:
    target: str                                  # label of the checked fn
    findings: List[Finding] = dataclasses.field(default_factory=list)
    suppressed: List[Finding] = dataclasses.field(default_factory=list)
    passes_run: Tuple[str, ...] = ()
    skipped: Dict[str, str] = dataclasses.field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.findings

    def codes(self) -> List[str]:
        return [f.code for f in self.findings]

    def by_code(self, code: str) -> List[Finding]:
        return [f for f in self.findings if f.code == code]

    def summary(self) -> str:
        lines = [f"doctor report for {self.target}: "
                 f"{len(self.findings)} finding(s), "
                 f"{len(self.suppressed)} suppressed, "
                 f"passes={','.join(self.passes_run) or '-'}"]
        for f in self.findings:
            lines.append("  " + f.format())
        for f in self.suppressed:
            lines.append("  (suppressed) " + f.format())
        for name, why in self.skipped.items():
            lines.append(f"  (skipped {name}: {why})")
        return "\n".join(lines)

    def raise_if_findings(self):
        if self.findings:
            raise AnalysisError(self)


class AnalysisError(AssertionError):
    """Raised by Report.raise_if_findings — an AssertionError so pytest
    renders the full report text."""

    def __init__(self, report: Report):
        self.report = report
        super().__init__(report.summary())
