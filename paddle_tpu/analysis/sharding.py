"""The canonical SpecLayout extractor — one per-logical-tensor
``(mesh_axes, dim_map, memory_kind)`` table per stack.

Three stacks hand-encode sharding independently; the ROADMAP's
unified-partitioning item (PartIR, PAPERS.md 2401.11202) says they
should all be DERIVED from one canonical per-tensor spec table.  Before
that refactor can land safely, the specs have to be pinned: this module
walks each entry point's placement rule — the GSPMD path's
``NamedSharding``/``PartitionSpec`` plan, the overlap engine's manual
shard_map layout + bucket plan, the hybrid bodies' axis choices, the
serving engine's concrete arrays — and produces one comparable
``parallel.specs.SpecLayout`` per stack.  The tables are what
``passes/sharding_consistency.py`` audits (SHARD002-004 directly,
SHARD003 across stacks) and the artifact the future unified schedule
will consume (DOCTOR.json carries the flagship table).

Canonical keys collapse the layer index: every stack places all decoder
layers identically, so ``model.layers.3.self_attn.q_proj.weight``
canonicalizes to ``model.layers.*.self_attn.q_proj.weight`` — one
logical tensor per layer ROLE.  The hybrid stack's leading [L] stacking
dim (sharded over pp) is layer-SET placement, not tensor placement, so
its canonical per-layer entries drop it; ``hybrid_param_spec`` still
exposes the full stacked spec for callers that place state.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..parallel.schedule import canonical_key  # noqa: F401 (re-export —
# the layer-collapse rule moved to the schedule layer in round 19; the
# extractor and the schedule must key tables identically)
from ..parallel.specs import (SpecLayout, TensorSpec, layout_from_arrays,
                              layout_mesh_axes, spec_to_dim_axes)
from .exemptions import apply_exemptions
from .findings import Finding, Report

_PASS = "sharding_consistency"


def collapse_layers(layout: SpecLayout) -> SpecLayout:
    """Fold per-layer entries onto their canonical key, asserting every
    layer carries the SAME spec — a per-layer divergence inside one
    stack is a broken plan, not a cross-stack finding."""
    entries: Dict[str, TensorSpec] = {}
    for name, ts in layout.items():
        key = canonical_key(name)
        prev = entries.get(key)
        if prev is not None and prev != ts:
            raise ValueError(
                f"{key}: layers disagree within one stack "
                f"({prev.describe()} vs {ts.describe()}) — the "
                f"canonical table assumes one spec per layer role")
        entries[key] = ts
    return SpecLayout(mesh_axes=layout.mesh_axes, entries=entries)


# ---------------------------------------------------------------------------
# per-stack extractors
# ---------------------------------------------------------------------------


def extract_gspmd_layout(model, mesh, plan=None) -> SpecLayout:
    """Canonical table of the flat GSPMD stack (``build_train_step``
    without overlap): the declared plan (``LLAMA_SHARDING_PLAN`` by
    default) under the shared at-rest divisibility rule — exactly what
    ``apply_llama_sharding`` places."""
    from ..models.llama import plan_spec_for
    from ..parallel.specs import filter_divisible_spec

    entries: Dict[str, TensorSpec] = {}
    for name, p in model.named_parameters():
        shape = tuple(int(d) for d in p.shape)
        spec = filter_divisible_spec(plan_spec_for(name, plan), shape,
                                     mesh)
        entries[name] = TensorSpec(
            shape=shape, dtype=str(p.dtype),
            dim_axes=spec_to_dim_axes(spec, len(shape)))
    return collapse_layers(
        SpecLayout(mesh_axes=layout_mesh_axes(mesh), entries=entries))


def extract_overlap_layout(model, mesh, oc=None, plan=None) -> SpecLayout:
    """Canonical table of the communication-overlap stack: decoder-layer
    leaves from the engine's own layout plan
    (``overlap.stack_layout_plan`` — the at-rest ZeRO-3/TP shard_map
    in_specs the region slices by, bucket plan included in the
    introspection), and the GSPMD rule for the leaves that stay outside
    the manual region (embedding, final norm, LM head)."""
    from ..models.llama import _LAYER_PREFIX, plan_spec_for
    from ..parallel import overlap as _ov
    from ..parallel.specs import filter_divisible_spec

    oc = oc if oc is not None else _ov.OverlapConfig()
    cfg = model.cfg
    shapes = _ov.llama_layer_shapes(cfg)

    def spec_for(suffix):
        from ..models.llama import _filter_spec_to_mesh

        return _filter_spec_to_mesh(plan_spec_for(suffix, plan), mesh)

    layout, buckets, sync = _ov.stack_layout_plan(shapes, mesh, spec_for,
                                                  oc)
    entries: Dict[str, TensorSpec] = {}
    params = dict(model.named_parameters())
    for suffix, place in layout.items():
        dims = [() for _ in place.shape]
        if place.sh_dim is not None:
            dims[place.sh_dim] = ("sharding",)
        if place.mp_dim is not None:
            dims[place.mp_dim] = ("mp",)
        dtype = str(params[f"{_LAYER_PREFIX}0.{suffix}"].dtype)
        entries[f"{_LAYER_PREFIX}*.{suffix}"] = TensorSpec(
            shape=place.shape, dtype=dtype, dim_axes=tuple(dims))
    for name, p in params.items():
        if name.startswith(_LAYER_PREFIX):
            continue          # decoder leaves: engine-owned, done above
        shape = tuple(int(d) for d in p.shape)
        spec = filter_divisible_spec(plan_spec_for(name, plan), shape,
                                     mesh)
        entries[name] = TensorSpec(
            shape=shape, dtype=str(p.dtype),
            dim_axes=spec_to_dim_axes(spec, len(shape)))
    out = SpecLayout(mesh_axes=layout_mesh_axes(mesh), entries=entries)
    out.buckets = buckets          # introspection riders (not compared)
    out.sync_suffixes = sync
    return out


def extract_hybrid_layout(model, mesh, plan=None) -> SpecLayout:
    """Canonical table of the hybrid gpipe/sched stack: the
    ``hybrid_param_spec`` placement hook over the stacked state's
    shapes.  Stacked leaves drop the leading [L] dim (layer-set
    placement over pp, not tensor placement) so each per-layer role
    compares 1:1 against the other stacks."""
    from ..models.llama import _LAYER_PREFIX
    from ..models.llama_hybrid import hybrid_param_spec

    L = model.cfg.num_hidden_layers
    entries: Dict[str, TensorSpec] = {}
    for name, p in model.named_parameters():
        shape = tuple(int(d) for d in p.shape)
        if name.startswith(_LAYER_PREFIX):
            suffix = name[len(_LAYER_PREFIX):].split(".", 1)[1]
            full = hybrid_param_spec(_LAYER_PREFIX + suffix, (L,) + shape,
                                     mesh, plan)
            dims = spec_to_dim_axes(full, len(shape) + 1)[1:]   # drop [L]
            entries[name] = TensorSpec(
                shape=shape, dtype=str(p.dtype), dim_axes=dims)
        else:
            spec = hybrid_param_spec(name, shape, mesh, plan)
            entries[name] = TensorSpec(
                shape=shape, dtype=str(p.dtype),
                dim_axes=spec_to_dim_axes(spec, len(shape)))
    return collapse_layers(
        SpecLayout(mesh_axes=layout_mesh_axes(mesh), entries=entries))


def extract_serving_layout(engine) -> SpecLayout:
    """Canonical table of the serving stack: the CONCRETE at-rest truth
    of the engine's committed params (single-chip today: replicated
    specs, quantized dtypes visible)."""
    return collapse_layers(layout_from_arrays(engine.params))


def extract_moe_ep_layout(cfg, mesh, dtype: str = "float32") -> SpecLayout:
    """Canonical table of the round-18 EP MoE stack: the declared plan
    (``parallel.expert.moe_ep_spec_for`` — expert-stacked leaves lead
    [E] on ``ep`` via the shared ``specs.expert_leaf_spec`` rule,
    shared leaves replicate) under the at-rest divisibility rule.
    ``ep`` rides ``mesh_axes`` like any other axis, so SHARD002-004 and
    the SHARD003 cross-stack gate cover expert parallelism for free;
    self_check diffs this table against ``layout_from_arrays`` of the
    placed params (``moe_ep_cross_stack``)."""
    from ..parallel.expert import moe_ep_layout

    return moe_ep_layout(cfg, mesh, dtype=dtype)


# ---------------------------------------------------------------------------
# Report-producing helpers (table-level checks without a traced target —
# the check_reshard_budget convention)
# ---------------------------------------------------------------------------


def check_layout(layout: SpecLayout, *, replicated_min_bytes=None,
                 ignore_axes=(), exemptions=None,
                 target: str = "layout") -> Report:
    """SHARD002 (replication waste) + SHARD004 (shard padding) over one
    canonical table.  ``ignore_axes``: the pure data axes the plan
    replicates params over by design (dp/pp/sep)."""
    from .passes.sharding_consistency import (REPLICATED_MIN_BYTES,
                                              replication_waste_findings,
                                              shard_padding_findings)

    mb = (REPLICATED_MIN_BYTES if replicated_min_bytes is None
          else replicated_min_bytes)
    findings = replication_waste_findings(layout, mb,
                                          ignore_axes=ignore_axes) \
        + shard_padding_findings(layout)
    active, suppressed = apply_exemptions(findings, exemptions)
    return Report(target=target, findings=active, suppressed=suppressed,
                  passes_run=(_PASS,))


def check_cross_stack(layouts: Dict[str, SpecLayout], *, exemptions=None,
                      target: str = "cross_stack") -> Report:
    """SHARD003 across two or more stacks' canonical tables."""
    from .passes.sharding_consistency import cross_stack_findings

    findings = cross_stack_findings(layouts)
    active, suppressed = apply_exemptions(findings, exemptions)
    return Report(target=target, findings=active, suppressed=suppressed,
                  passes_run=(_PASS,))


# ---------------------------------------------------------------------------
# round-19: the SCHED doctor entry — the unified PartitionSchedule's
# derivations must be BYTE-IDENTICAL to the hand-written stacks' tables
# (the acceptance gate of the unified-partitioning refactor: deriving
# from one schedule object must not move a single placement)
# ---------------------------------------------------------------------------


def schedule_divergence_findings(schedule, layouts: Dict[str, SpecLayout]
                                 ) -> List[Finding]:
    """SCHED001: the schedule-derived canonical table differs from a
    hand-written stack's extracted table — EXACT comparison (key set +
    TensorSpec equality), stronger than SHARD003's shared-axis
    restriction: a derivation that moves any placement is a broken
    derivation, not a tolerable divergence."""
    findings = []
    st = schedule.table
    for stack, lo in sorted(layouts.items()):
        only_sched = sorted(set(st.entries) - set(lo.entries))
        only_stack = sorted(set(lo.entries) - set(st.entries))
        for name in only_sched:
            findings.append(Finding(
                code="SCHED001", pass_name=_PASS, severity="error",
                message=f"{name}: in the schedule's table but absent "
                        f"from stack '{stack}' — the derivation and "
                        f"the hand-written table disagree on the "
                        f"tensor set", arg_path=name,
                data={"tensor": name, "stack": stack,
                      "kind": "missing_in_stack"}))
        for name in only_stack:
            findings.append(Finding(
                code="SCHED001", pass_name=_PASS, severity="error",
                message=f"{name}: stack '{stack}' places a tensor the "
                        f"schedule does not know — the canonical table "
                        f"is incomplete", arg_path=name,
                data={"tensor": name, "stack": stack,
                      "kind": "missing_in_schedule"}))
        for name in sorted(set(st.entries) & set(lo.entries)):
            a, b = st[name], lo[name]
            if a == b:
                continue
            findings.append(Finding(
                code="SCHED001", pass_name=_PASS, severity="error",
                message=f"{name}: schedule derives "
                        f"({a.describe()}) but stack '{stack}' "
                        f"hand-writes ({b.describe()}) — the unified "
                        f"derivation moved a placement; byte-identity "
                        f"is the refactor's acceptance gate",
                arg_path=name,
                data={"tensor": name, "stack": stack,
                      "schedule": a.describe(), "stack_spec": b.describe()}))
    return findings


def check_schedule_derivation(schedule, layouts: Dict[str, SpecLayout],
                              *, exemptions=None,
                              target: str = "schedule_derivation"
                              ) -> Report:
    """SCHED001 over the schedule vs one or more extracted stack
    tables (Report form, the check_cross_stack convention)."""
    findings = schedule_divergence_findings(schedule, layouts)
    active, suppressed = apply_exemptions(findings, exemptions)
    return Report(target=target, findings=active, suppressed=suppressed,
                  passes_run=(_PASS,))


def check_stack_plan_derivation(schedule, model, mesh, oc=None,
                                *, exemptions=None,
                                target: str = "schedule_stack_plan"
                                ) -> Report:
    """SCHED001 over the OVERLAP derivation: the schedule's
    ``stack_plan`` (leaf layout, bucket plan, sync leaves) must be
    byte-identical to the hand path (``overlap.stack_layout_plan``
    seeded from the model's own spec rule)."""
    from ..models.llama import _filter_spec_to_mesh, plan_spec_for
    from ..parallel import overlap as _ov

    oc = oc if oc is not None else _ov.OverlapConfig()
    shapes = _ov.llama_layer_shapes(model.cfg)
    layout, buckets, sync = _ov.stack_layout_plan(
        shapes, mesh,
        lambda sfx: _filter_spec_to_mesh(plan_spec_for(sfx), mesh), oc)
    plan = schedule.stack_plan(oc, shapes=shapes)
    findings = []
    if (plan.layout, plan.buckets, plan.sync_suffixes) \
            != (layout, buckets, sync):
        diffs = []
        if plan.layout != layout:
            moved = [s for s in sorted(shapes)
                     if plan.layout.get(s) != layout.get(s)]
            diffs.append(f"leaf placements differ on {moved}")
        if plan.buckets != buckets:
            diffs.append(f"bucket plan {plan.buckets} vs {buckets}")
        if plan.sync_suffixes != sync:
            diffs.append(f"sync leaves {plan.sync_suffixes} vs {sync}")
        findings.append(Finding(
            code="SCHED001", pass_name=_PASS, severity="error",
            message="schedule.stack_plan diverges from the overlap "
                    "engine's hand-written stack_layout_plan: "
                    + "; ".join(diffs),
            data={"diffs": diffs}))
    active, suppressed = apply_exemptions(findings, exemptions)
    return Report(target=target, findings=active, suppressed=suppressed,
                  passes_run=(_PASS,))


def flagship_layouts(model, mesh, overlap_config=None
                     ) -> Dict[str, SpecLayout]:
    """The three training stacks' canonical tables for one model on one
    mesh — the SHARD003 cross-stack probe and DOCTOR.json's
    ``sharding.canonical_table`` source.  The hybrid table is only
    extracted when the mesh carries the hybrid axes."""
    out = {"gspmd": extract_gspmd_layout(model, mesh),
           "overlap": extract_overlap_layout(model, mesh,
                                             oc=overlap_config)}
    try:
        from ..models.llama_hybrid import HYBRID_AXES

        if all(a in mesh.axis_names for a in HYBRID_AXES):
            out["hybrid"] = extract_hybrid_layout(model, mesh)
    except Exception:
        pass
    return out
