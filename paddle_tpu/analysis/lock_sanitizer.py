"""The Concurrency Doctor's dynamic half: instrumented locks + the
thread hammer.

The static pass (``passes/lock_discipline.py``) reasons about source;
this module watches the same discipline at RUNTIME:

- ``SanitizedLock`` wraps a real ``threading.Lock``/``RLock`` and
  records, per acquisition, the acquiring thread, the locks it already
  held (the runtime acquisition-ORDER graph) and the function it
  acquired from (the acquisition SITES — the dynamic mirror of the
  static guarded-write map).
- ``LockMonitor`` aggregates the records: ``order_violations()``
  reports lock pairs observed in BOTH orders (a runtime lock-order
  inversion — the dynamic RACE002), ``unguarded()`` reports fields a
  hammer op touched without the lock the discipline demands (dynamic
  RACE001), and ``cross_check(static_map)`` compares acquisition sites
  against ``lock_discipline.guarded_write_map``'s prediction.
- the HAMMER harnesses drive real control-plane objects (PageAllocator,
  the watchdog's CommTaskManager, a fleet/disagg router) from
  concurrent threads — or, for reproducible tests, from a
  barrier-stepped FAKE scheduler (``BarrierScheduler``) that interleaves
  the same ops in one real thread under a seeded order, so a hammer
  failure replays exactly.

Instrumentation is swap-in (``instrument_lock(obj)`` replaces
``obj._lock``); production code never imports this module.
"""

from __future__ import annotations

import random
import sys
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple


class LockMonitor:
    """Aggregated runtime observations.  Thread-safe via its own
    internal lock (never instrumented — the watcher must not watch
    itself)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._tls = threading.local()
        # (held_lock, acquired_lock) -> first site "qual"
        self.order_edges: Dict[Tuple[str, str], str] = {}
        # lock -> sorted set of acquiring function names
        self.sites: Dict[str, set] = {}
        # (owner, field) -> set of frozenset(held lock names)
        self.field_holds: Dict[Tuple[str, str], set] = {}
        self.acquisitions = 0

    # -- per-thread held stack --------------------------------------------
    def _held(self) -> List[str]:
        if not hasattr(self._tls, "held"):
            self._tls.held = []
        return self._tls.held

    def held_names(self) -> Tuple[str, ...]:
        return tuple(self._held())

    # -- recording ---------------------------------------------------------
    def on_acquire(self, name: str, site: str):
        held = self._held()
        with self._mu:
            self.acquisitions += 1
            self.sites.setdefault(name, set()).add(site)
            for h in held:
                if h != name:
                    self.order_edges.setdefault((h, name), site)
        held.append(name)

    def on_release(self, name: str):
        held = self._held()
        if name in held:
            held.reverse()
            held.remove(name)
            held.reverse()

    def access(self, owner: str, field: str):
        """Record a guarded-field access site with the CURRENT held-lock
        set (called by hammer ops / probes, inside or outside locks)."""
        snapshot = frozenset(self._held())
        with self._mu:
            self.field_holds.setdefault((owner, field), set()).add(snapshot)

    # -- verdicts ----------------------------------------------------------
    def order_violations(self) -> List[Tuple[str, str]]:
        """Lock pairs observed in both acquisition orders."""
        out = []
        with self._mu:
            for (a, b) in self.order_edges:
                if (b, a) in self.order_edges and a < b:
                    out.append((a, b))
        return sorted(out)

    def unguarded(self, lock: str) -> List[Tuple[str, str]]:
        """(owner, field) pairs accessed at least once WITHOUT ``lock``
        held, among fields that were also accessed WITH it (the dynamic
        mirror of RACE001's both-sides rule)."""
        out = []
        with self._mu:
            for key, holds in self.field_holds.items():
                seen_with = any(lock in h for h in holds)
                seen_without = any(lock not in h for h in holds)
                if seen_with and seen_without:
                    out.append(key)
        return sorted(out)

    def cross_check(self, static_map: Dict[str, Dict[str, list]],
                    lock: str) -> Dict[str, Any]:
        """Compare the static guarded-write map for ``lock`` against the
        functions observed acquiring the instrumented lock.  A static
        write-site the hammer exercised must show up as a runtime
        acquisition site; a missing one means either dead code or a
        code path that mutates guarded state WITHOUT the lock."""
        want = set()
        for field, quals in static_map.get(lock, {}).items():
            for q in quals:
                want.add(q.split(".")[-1])
        with self._mu:
            got = set(self.sites.get(lock, set()))
        return {"static_sites": sorted(want),
                "runtime_sites": sorted(got),
                "covered": sorted(want & got),
                "unexercised": sorted(want - got)}


class SanitizedLock:
    """Drop-in lock wrapper feeding a LockMonitor.  Supports the
    context-manager protocol plus acquire/release, so it substitutes for
    ``threading.Lock``/``RLock`` in the instrumented object."""

    def __init__(self, name: str, monitor: LockMonitor,
                 inner: Optional[Any] = None):
        self.name = name
        self.monitor = monitor
        self.inner = inner if inner is not None else threading.Lock()

    def _site(self) -> str:
        f = sys._getframe(2)
        return f.f_code.co_name

    def acquire(self, *args, **kwargs):
        got = self.inner.acquire(*args, **kwargs)
        if got:
            self.monitor.on_acquire(self.name, self._site())
        return got

    def release(self):
        self.monitor.on_release(self.name)
        self.inner.release()

    def __enter__(self):
        self.inner.acquire()
        self.monitor.on_acquire(self.name, self._site())
        return self

    def __exit__(self, *exc):
        self.monitor.on_release(self.name)
        self.inner.release()
        return False

    def locked(self):
        return self.inner.locked()


def instrument_lock(obj: Any, attr: str = "_lock",
                    monitor: Optional[LockMonitor] = None,
                    name: Optional[str] = None) -> LockMonitor:
    """Swap ``obj.<attr>`` for a SanitizedLock wrapping the original;
    returns the monitor (a fresh one unless given)."""
    monitor = monitor or LockMonitor()
    inner = getattr(obj, attr)
    if isinstance(inner, SanitizedLock):
        inner = inner.inner
    label = name or f"{type(obj).__name__}.{attr}"
    setattr(obj, attr, SanitizedLock(label, monitor, inner))
    return monitor


class BarrierScheduler:
    """Deterministic fake scheduler: N virtual threads' op lists are
    interleaved in ONE real thread under a seeded order — every "context
    switch" happens between ops, chosen by the rng, so a hammer run is
    exactly reproducible from its seed.  The genuinely-threaded hammers
    reuse the same op lists; this is the replay/debug mode."""

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self.trace: List[Tuple[int, int]] = []   # (vthread, op index)

    def run(self, ops_per_thread: Sequence[Sequence[Callable[[], Any]]]):
        rng = random.Random(self.seed)
        cursors = [0] * len(ops_per_thread)
        live = [i for i, ops in enumerate(ops_per_thread) if ops]
        while live:
            i = rng.choice(live)
            op = ops_per_thread[i][cursors[i]]
            self.trace.append((i, cursors[i]))
            op()
            cursors[i] += 1
            if cursors[i] >= len(ops_per_thread[i]):
                live.remove(i)
        return self.trace


def run_threaded(ops_per_thread: Sequence[Sequence[Callable[[], Any]]],
                 timeout: float = 30.0) -> None:
    """Run each op list in its own real thread, started together behind
    a barrier.  Exceptions re-raise in the caller (first one wins)."""
    barrier = threading.Barrier(len(ops_per_thread))
    errors: List[BaseException] = []
    emu = threading.Lock()

    def runner(ops):
        barrier.wait()
        try:
            for op in ops:
                op()
        except BaseException as e:  # noqa: BLE001
            with emu:
                errors.append(e)

    threads = [threading.Thread(target=runner, args=(ops,), daemon=True)
               for ops in ops_per_thread]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    if errors:
        raise errors[0]


# ---------------------------------------------------------------------------
# hammers: real control-plane objects under concurrent (or replayed) ops
# ---------------------------------------------------------------------------


def _allocator_ops(alloc, monitor: LockMonitor, n_ops: int, seed: int):
    """One virtual thread's seeded alloc/acquire/release workload; every
    op leaves the thread's ref accounting balanced by the end."""
    rng = random.Random(seed)
    owned: List[int] = []

    def step():
        monitor.access("PageAllocator", "free")
        monitor.access("PageAllocator", "refs")
        roll = rng.random()
        if owned and roll < 0.45:
            alloc.release([owned.pop(rng.randrange(len(owned)))])
        elif owned and roll < 0.55:
            p = owned[rng.randrange(len(owned))]
            alloc.acquire(p)
            owned.append(p)
        else:
            p = alloc.alloc()
            if p is not None:
                owned.append(p)

    def drain():
        while owned:
            alloc.release([owned.pop()])

    return [step] * n_ops + [drain]


def hammer_page_allocator(num_pages: int = 8, threads: int = 4,
                          ops: int = 120, seed: int = 0,
                          deterministic: bool = False) -> Dict[str, Any]:
    """Concurrent alloc/acquire/release storm on a PageAllocator with an
    instrumented lock; asserts ``assert_consistent()`` afterwards and
    cross-checks the static lock map against the observed acquisition
    sites.  ``deterministic=True`` replays the same ops through the
    barrier-stepped fake scheduler (single real thread, seeded order)."""
    import os

    from ..inference.serving import PageAllocator

    alloc = PageAllocator(num_pages)
    monitor = instrument_lock(alloc, "_lock", name="_lock")
    op_lists = [_allocator_ops(alloc, monitor, ops, seed * 997 + i)
                for i in range(threads)]
    trace_len = None
    if deterministic:
        sched = BarrierScheduler(seed)
        sched.run(op_lists)
        trace_len = len(sched.trace)
    else:
        run_threaded(op_lists)
    alloc.assert_consistent()       # the checked contract, under fire
    src = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "inference", "serving.py")
    from .passes.lock_discipline import guarded_write_map

    with open(src, "r", encoding="utf-8") as f:
        static_map = guarded_write_map(f.read(), "inference/serving.py")
    xc = monitor.cross_check(static_map, "_lock")
    ok = (not monitor.order_violations()
          and alloc.available == alloc.total
          and not xc["unexercised"])
    return {"ok": ok, "acquisitions": monitor.acquisitions,
            "order_violations": monitor.order_violations(),
            "cross_check": xc,
            "deterministic_trace_len": trace_len}


def hammer_watchdog(threads: int = 4, tasks_per_thread: int = 12,
                    seed: int = 0) -> Dict[str, Any]:
    """The regression pin for the PR-6 handler/flag race: N threads
    register+complete tasks (some pre-aged past their deadline) while
    the scanner thread flags timeouts.  The FIXED single-writer
    transition must hold: every task ends in EXACTLY one of
    done/timed_out, and the instrumented manager lock shows no order
    violation."""
    from ..distributed import watchdog as _wd
    from ..distributed.watchdog import CommTaskManager

    mgr = CommTaskManager(scan_interval=0.001)
    # the hammer MANUFACTURES dozens of timeouts; the scanner's
    # per-timeout error trace is signal in production and noise here
    prev_disabled = _wd.logger.disabled
    _wd.logger.disabled = True
    monitor = instrument_lock(mgr, "_lock", name="manager._lock")
    all_tasks: List[Any] = []
    mu = threading.Lock()

    def ops_for(tid: int):
        rng = random.Random(seed * 31 + tid)
        ops = []

        def one():
            t = mgr.register(f"collective-{tid}", timeout_s=30.0)
            aged = rng.random() < 0.5
            if aged:
                # age the task past its deadline so the scanner races
                # the completion for the terminal transition; linger a
                # few scan intervals so the scanner actually competes
                t.start_time -= 60.0
                threading.Event().wait(0.004)
            with mu:
                all_tasks.append(t)
            mgr.complete(t)

        ops.extend([one] * tasks_per_thread)
        return ops

    try:
        run_threaded([ops_for(i) for i in range(threads)])
        # let the scanner drain what completion lost the race for
        deadline = 50
        while mgr._tasks and deadline:
            threading.Event().wait(0.002)
            deadline -= 1
    finally:
        mgr.shutdown()
        _wd.logger.disabled = prev_disabled
    both = [t for t in all_tasks if t.done and t.timed_out]
    neither = [t for t in all_tasks if not t.done and not t.timed_out]
    ok = (not both and not neither and not monitor.order_violations())
    return {"ok": ok, "tasks": len(all_tasks),
            "timed_out": sum(1 for t in all_tasks if t.timed_out),
            "completed": sum(1 for t in all_tasks if t.done),
            "both_terminal": len(both), "neither_terminal": len(neither),
            "order_violations": monitor.order_violations()}


def hammer_router(router, prompts, *, steps: int = 64,
                  max_new_tokens: int = 4, vthreads: int = 3,
                  seed: int = 0, discipline: bool = True
                  ) -> Dict[str, Any]:
    """Drive a REAL FleetRouter/DisaggRouter's submit/step ops through
    the deterministic scheduler under a sanitized TICK LOCK.

    The routers are single-threaded BY DESIGN (their docstring
    contract); the hammer encodes the discipline that makes concurrent
    callers legal — every op serializes on the tick lock — and the
    monitor proves it held: with ``discipline=True`` every router-state
    access is recorded under the lock (``unguarded() == []``); with
    ``discipline=False`` the same workload records the violation the
    sanitizer exists to catch (the detection self-test)."""
    monitor = LockMonitor()
    tick_lock = SanitizedLock("router_tick", monitor)

    def guarded(fn, *a, **kw):
        if discipline:
            with tick_lock:
                monitor.access("FleetRouter", "queue")
                return fn(*a, **kw)
        monitor.access("FleetRouter", "queue")
        return fn(*a, **kw)

    rids: List[int] = []
    submit_ops = [(lambda p=p: rids.append(
        guarded(router.submit, p, max_new_tokens=max_new_tokens)))
        for p in prompts]
    step_ops = [lambda: guarded(router.step)] * steps
    # split the step budget across the other virtual threads
    per = max(1, steps // max(1, vthreads - 1))
    op_lists = [submit_ops] + [step_ops[i * per:(i + 1) * per]
                               for i in range(max(1, vthreads - 1))]
    sched = BarrierScheduler(seed)
    sched.run(op_lists)
    while router.pending():
        guarded(router.step)
    out = router.results()
    # a disciplined run leaves no unguarded access; an undisciplined
    # run must record at least one (else the sanitizer is blind)
    unguarded = monitor.unguarded("router_tick")
    ok = (sorted(out) == sorted(rids)
          and (not unguarded if discipline else bool(unguarded)))
    return {"ok": ok, "completed": len(out), "submitted": len(rids),
            "unguarded": [list(u) for u in unguarded],
            "trace_len": len(sched.trace),
            "order_violations": monitor.order_violations()}


def sanitizer_self_test() -> Dict[str, Any]:
    """Fast, deterministic self-test for the DOCTOR.json block: the
    order-inversion detector fires on a scripted ab/ba sequence, and the
    barrier-stepped PageAllocator hammer sweeps clean with a stable
    trace.  No real thread timing — reproducible by construction."""
    # 1) detection: a scripted lock-order inversion must be observed
    mon = LockMonitor()
    a = SanitizedLock("A", mon)
    b = SanitizedLock("B", mon)
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    detects = mon.order_violations() == [("A", "B")]

    # 2) clean deterministic hammer, trace stable across two runs
    h1 = hammer_page_allocator(num_pages=6, threads=3, ops=40, seed=7,
                               deterministic=True)
    h2 = hammer_page_allocator(num_pages=6, threads=3, ops=40, seed=7,
                               deterministic=True)
    stable = (h1["deterministic_trace_len"]
              == h2["deterministic_trace_len"]
              and h1["acquisitions"] == h2["acquisitions"])
    ok = bool(detects and h1["ok"] and h2["ok"] and stable)
    return {"ok": ok, "order_inversion_detected": detects,
            "deterministic_hammer": h1, "trace_stable": stable}
