"""The Concurrency Doctor's sweep driver (static half).

Runs the lock-discipline pass (``passes/lock_discipline.py``,
RACE001-004) over the host-side CONTROL-PLANE modules — the threaded
surface the ROADMAP's multi-host serving item multiplies — and applies
the reviewed allowlist, exactly the AST-lint workflow:

- ``CONTROL_PLANE_MODULES`` is the swept set (serving engine + page
  pool, fleet/disagg routers, watchdog, resilience driver, TCPStore,
  health guardian, checkpoint manager/writer);
- ``concurrency_allowlist.txt`` holds the ACCEPTED findings
  (``relpath::qualname::CODE  # reason``) — intentional design points
  with a written justification, moved to ``report.suppressed`` so the
  hazard stays DETECTED, never silenced;
- an allowlist entry no live finding matches FAILS the sweep (liveness:
  the table tracks decisions, not history), mirroring the exemption
  table's staleness rule.

``concurrency_section()`` is the self_check/DOCTOR.json block; the
dynamic half (instrumented locks + thread hammer) lives in
``analysis/lock_sanitizer.py``.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

from .findings import Finding, Report
from .passes.lock_discipline import PASS_NAME, analyze_file

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ALLOWLIST_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "concurrency_allowlist.txt")

# the host-side control plane: every module that owns threads, locks, or
# state a concurrent serving/elastic driver mutates.  Lock-free modules
# cost one ast.parse and report clean by construction — keeping them in
# the sweep means a lock ADDED there is analyzed from its first commit.
CONTROL_PLANE_MODULES = (
    "inference/serving.py",
    "inference/fleet.py",
    "inference/disagg.py",
    "distributed/watchdog.py",
    "distributed/resilience.py",
    "distributed/store.py",
    "distributed/health.py",
    "distributed/checkpoint/manager.py",
    "distributed/checkpoint/save_state_dict.py",
)


def load_allowlist(path: str = ALLOWLIST_PATH) -> Dict[Tuple[str, str, str],
                                                       str]:
    """{(relpath, qualname, CODE): reason}.  Entries must carry a
    non-empty ``# reason`` — an allowlisted hazard without a written
    justification is rejected at load time (the review rule)."""
    table: Dict[Tuple[str, str, str], str] = {}
    if not os.path.exists(path):
        return table
    with open(path, "r", encoding="utf-8") as f:
        for lineno, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            entry, _, comment = line.partition("#")
            reason = comment.strip()
            parts = [p.strip() for p in entry.strip().split("::")]
            if len(parts) != 3:
                raise ValueError(
                    f"{path}:{lineno}: malformed entry {line!r} "
                    f"(want relpath::qualname::CODE  # reason)")
            if not reason:
                raise ValueError(
                    f"{path}:{lineno}: entry {entry.strip()!r} has no "
                    f"justification — every accepted concurrency hazard "
                    f"needs a written reason")
            table[(parts[0], parts[1], parts[2])] = reason
    return table


def _match_key(finding: Finding) -> Tuple[str, str, str]:
    rel = (finding.where or "").split(":", 1)[0]
    return rel, str(finding.data.get("qual", "")), finding.code


def sweep_control_plane(
        modules: Sequence[str] = CONTROL_PLANE_MODULES,
        allowlist: Optional[Dict[Tuple[str, str, str], str]] = None,
) -> Tuple[Report, List[str]]:
    """(report, unused_allowlist_keys): the lock-discipline sweep over
    the control plane with the reviewed allowlist applied.  The gate is
    ``report.ok AND not unused`` — a finding only an allowlist entry
    explains stays visible in ``report.suppressed``; an entry nothing
    matches is stale and fails."""
    if allowlist is None:
        allowlist = load_allowlist()
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    used = set()
    for rel in modules:
        path = os.path.join(_PKG_ROOT, rel)
        for f in analyze_file(path, rel):
            key = _match_key(f)
            if key in allowlist:
                f.exemption_id = f"ALLOW:{key[1]}:{key[2]}"
                suppressed.append(f)
                used.add(key)
            else:
                findings.append(f)
    unused = ["::".join(k) for k in sorted(set(allowlist) - used)]
    report = Report(target="concurrency:control-plane",
                    findings=findings, suppressed=suppressed,
                    passes_run=(PASS_NAME,))
    return report, unused


def concurrency_section() -> dict:
    """The self_check / DOCTOR.json ``concurrency`` block: the static
    sweep plus the deterministic sanitizer self-test (barrier-stepped —
    no real thread timing, so the block is reproducible)."""
    out: dict = {}
    try:
        report, unused = sweep_control_plane()
        out["sweep"] = {
            "ok": report.ok and not unused,
            "modules": list(CONTROL_PLANE_MODULES),
            "findings": [f.format() for f in report.findings],
            "suppressed": [f.format() for f in report.suppressed],
            "unused_allowlist": unused,
        }
    except Exception as e:  # noqa: BLE001
        out["sweep"] = {"ok": False, "error": repr(e)}
    try:
        from .lock_sanitizer import sanitizer_self_test

        out["sanitizer"] = sanitizer_self_test()
    except Exception as e:  # noqa: BLE001
        out["sanitizer"] = {"ok": False, "error": repr(e)}
    return out
