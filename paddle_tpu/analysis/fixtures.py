"""Seeded-bug fixtures: one deliberately-planted hazard per pass.

Every Graph Doctor pass must have a TRUE-POSITIVE proof, not just a
clean-run test — a pass that never fires is indistinguishable from a
pass that cannot fire.  Each fixture here builds a tiny program seeded
with exactly one bug of the class its pass hunts, runs the pass in
isolation (``exemptions=()`` so the standing table cannot mask a
regression in the pass itself), and returns the Report.  The self-check
(``python -m paddle_tpu.analysis --self-check``, the ``doctor_self_check``
smoke leg, and tests/test_analysis_passes.py) assert each report contains
its intended finding code and nothing else.

Fixtures that need capabilities the environment lacks (a multi-device
mesh on a bare single-CPU invocation) raise FixtureUnavailable, which
callers record as a skip — never a silent pass.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .core import check
from .findings import Report
from .passes.hlo_checks import scan_compile_warnings
from .passes.retrace import retrace_sentinel


class FixtureUnavailable(RuntimeError):
    """The environment cannot host this fixture (e.g. needs >= 2 devices)."""


def _mesh(min_devices: int = 1):
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < min_devices:
        raise FixtureUnavailable(
            f"needs >= {min_devices} devices, have {len(devs)} "
            f"(run under XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    n = max(min_devices, 2) if len(devs) >= 2 else 1
    return Mesh(np.asarray(devs[:n], dtype=object), ("x",))


# ---------------------------------------------------------------------------
# collective_order
# ---------------------------------------------------------------------------


def seeded_collective_order() -> Report:
    """COLL001: a shard_map cond whose true branch psums and whose false
    branch does not — ranks disagreeing on the predicate deadlock."""
    from jax.sharding import PartitionSpec as P

    from ..common.jax_compat import shard_map

    mesh = _mesh(1)

    def body(v):
        return jax.lax.cond(v.sum() > 0.0,
                            lambda u: jax.lax.psum(u, "x"),
                            lambda u: u, v)

    fn = shard_map(body, mesh=mesh, in_specs=(P("x"),), out_specs=P("x"))
    x = jnp.ones((8 * mesh.shape["x"],), jnp.float32)
    return check(fn, x, passes=["collective_order"], exemptions=(),
                 target="seeded:COLL001")


def seeded_ppermute_race() -> Report:
    """COLL002: a ppermute with two sources targeting one destination."""
    from jax.sharding import PartitionSpec as P

    from ..common.jax_compat import shard_map

    mesh = _mesh(2)

    def body(v):
        return jax.lax.ppermute(v, "x", [(0, 1), (1, 1)])

    fn = shard_map(body, mesh=mesh, in_specs=(P("x"),), out_specs=P("x"))
    x = jnp.ones((2 * mesh.shape["x"],), jnp.float32)
    return check(fn, x, passes=["collective_order"], exemptions=(),
                 target="seeded:COLL002")


# ---------------------------------------------------------------------------
# dtype_promotion
# ---------------------------------------------------------------------------


def seeded_fp32_matmul() -> Report:
    """DT001: a bf16 program whose second matmul silently upcasts."""

    def bug(a, b):
        h = a @ b                                     # bf16 — declares it
        return (h.astype(jnp.float32)
                @ b.astype(jnp.float32)).sum()        # the silent upcast

    a = jnp.ones((128, 128), jnp.bfloat16)
    return check(bug, a, a, passes=["dtype_promotion"], exemptions=(),
                 target="seeded:DT001")


def seeded_f64_leak() -> Report:
    """DT002: an x64-enabled input drags float64 through the program."""
    from jax.experimental import enable_x64

    def bug(a):
        return (a * np.float64(2.0)).sum()

    with enable_x64():
        return check(bug, np.ones((64, 64), np.float64),
                     passes=["dtype_promotion"], exemptions=(),
                     target="seeded:DT002")


def seeded_fp32_carry() -> Report:
    """DT003: a bf16 micro-step loop accumulating into a full-width fp32
    carry — the exact HBM-traffic bug the round-7 bf16 grad carry fixed."""

    def bug(w, xs):
        def micro(acc, x):
            g = x @ w                                  # bf16 compute
            return acc + g.astype(jnp.float32), ()     # fp32 accumulate
        acc, _ = jax.lax.scan(
            micro, jnp.zeros((128, 128), jnp.float32), xs)
        return acc

    w = jnp.ones((128, 128), jnp.bfloat16)
    xs = jnp.ones((4, 128, 128), jnp.bfloat16)
    return check(bug, w, xs, passes=["dtype_promotion"], exemptions=(),
                 target="seeded:DT003")


# ---------------------------------------------------------------------------
# donation
# ---------------------------------------------------------------------------


def seeded_undonated_state() -> Report:
    """DON001: a param-sized pytree rides a jit entry without donation."""

    @jax.jit
    def bug(params, grads):
        return {k: v - 1e-3 * grads[k] for k, v in params.items()}

    params = {"w": jnp.ones((768, 768), jnp.float32)}
    grads = {"w": jnp.ones((768, 768), jnp.float32)}
    return check(bug, params, grads, passes=["donation"], exemptions=(),
                 target="seeded:DON001")


def seeded_use_after_donate() -> Report:
    """DON002: one buffer passed to a donated AND a read position."""
    from functools import partial

    @partial(jax.jit, donate_argnums=(0,))
    def bug(a, b):
        return a * 2.0 + b

    x = jnp.ones((128, 128), jnp.float32)   # small: below DON001's bar
    return check(bug, x, x, passes=["donation"], exemptions=(),
                 target="seeded:DON002")


# ---------------------------------------------------------------------------
# retrace_sentinel
# ---------------------------------------------------------------------------


def seeded_weak_type_churn() -> Report:
    """RT001: alternating python-float and array lr retraces per flip."""
    step = retrace_sentinel(jax.jit(lambda x, lr: x * lr),
                            name="seeded:RT001")
    x = jnp.ones((8,), jnp.float32)
    step(x, 0.1)                       # weak f32 scalar
    step(x, jnp.float32(0.1))          # strong f32 scalar — same but weak
    return step.report()


def seeded_signature_churn() -> Report:
    """RT002: unbucketed lengths — every call is a fresh compile."""
    step = retrace_sentinel(jax.jit(lambda x: x.sum()), max_signatures=3,
                            name="seeded:RT002")
    for n in (1, 2, 3, 4):
        step(jnp.ones((n,), jnp.float32))
    return step.report()


# ---------------------------------------------------------------------------
# hlo_post_checks
# ---------------------------------------------------------------------------


def seeded_involuntary_remat() -> Report:
    """HLO001 over a captured-warning sample: the detector itself (the
    compile-and-capture plumbing is exercised by the clean-run checks and
    tests/test_no_involuntary_remat.py; XLA's fallback cannot be seeded
    portably on one CPU device)."""
    sample = (
        "2026-08-03 12:00:00.000000: W external/xla/xla/service/spmd/"
        "spmd_partitioner.cc:584] Involuntary full rematerialization. "
        "The compiled was not able to go from sharding "
        "{devices=[2,2]<=[4]} to {replicated} without doing a full "
        "rematerialization of the tensor.\n")
    findings = scan_compile_warnings(sample)
    return Report(target="seeded:HLO001", findings=findings,
                  passes_run=("hlo_post_checks",))


def seeded_full_param_allgather() -> Report:
    """HLO002: a stage-3-sharded param replicated wholesale inside the
    step.  The threshold is the documented stage-3 gate: no all-gather
    may exceed the largest per-layer parameter."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _mesh(2)
    p = jax.device_put(jnp.ones((1024, 64), jnp.float32),
                       NamedSharding(mesh, P("x", None)))

    @jax.jit
    def bug(a):
        full = jax.lax.with_sharding_constraint(
            a, NamedSharding(mesh, P()))     # gathers the whole param
        return full * 2.0

    return check(
        bug, p, passes=["hlo_post_checks"], exemptions=(),
        target="seeded:HLO002",
        options={"hlo_post_checks":
                 {"max_allgather_bytes": 1024 * 64 * 4 // 2}})


# ---------------------------------------------------------------------------
# collective_budget
# ---------------------------------------------------------------------------


def seeded_collective_budget() -> Report:
    """COMM001: a step whose compiled HLO carries TWO all-reduces against
    a declared budget of one (the per-leaf-collective regression class
    the bucketed overlap engine exists to prevent)."""
    from jax.sharding import PartitionSpec as P

    from ..common.jax_compat import shard_map

    mesh = _mesh(2)

    def body(a, b):
        return jax.lax.psum(a, "x") + jax.lax.psum(b * 2.0, "x")

    fn = shard_map(body, mesh=mesh, in_specs=(P("x"), P("x")),
                   out_specs=P(), check_vma=False)
    x = jnp.ones((2 * mesh.shape["x"], 8), jnp.float32)
    return check(fn, x, x + 1.0, passes=["collective_budget"],
                 exemptions=(), target="seeded:COMM001",
                 options={"collective_budget":
                          {"allreduce": {"count": 1}}})


def seeded_unscheduled_collective() -> Report:
    """COMM002: with an overlap engine declared active, a shard_map body
    issues a bare psum whose call stack contains none of the engine's
    region functions — traffic the engine never scheduled."""
    from jax.sharding import PartitionSpec as P

    from ..common.jax_compat import shard_map

    mesh = _mesh(1)

    def rogue_reduce(v):
        return jax.lax.psum(v, "x")

    fn = shard_map(rogue_reduce, mesh=mesh, in_specs=(P("x"),),
                   out_specs=P(), check_vma=False)
    x = jnp.ones((4 * mesh.shape["x"],), jnp.float32)
    return check(fn, x, passes=["collective_budget"], exemptions=(),
                 target="seeded:COMM002",
                 options={"collective_budget": {"overlap_active": True}})


def seeded_ppermute_ring_order() -> Report:
    """COMM003: a scanned pipeline ring whose perm mixes rotation steps
    (+1, +1, +2, 0) — stage pairings drift across ticks."""
    from jax.sharding import PartitionSpec as P

    from ..common.jax_compat import shard_map

    mesh = _mesh(4)
    n = mesh.shape["x"]
    if n < 4:
        raise FixtureUnavailable("non-uniform ring needs an axis of >= 4")

    def body(v):
        def tick(c, _):
            return jax.lax.ppermute(
                c, "x", [(0, 1), (1, 2), (2, 0), (3, 3)]), None
        c, _ = jax.lax.scan(tick, v, None, length=2)
        return c

    fn = shard_map(body, mesh=mesh, in_specs=(P("x"),),
                   out_specs=P("x"), check_vma=False)
    x = jnp.ones((2 * n,), jnp.float32)
    return check(fn, x, passes=["collective_budget"], exemptions=(),
                 target="seeded:COMM003")


def seeded_codec_disabled() -> Report:
    """COMM004: a fake-2-slice hierarchical reduce-scatter whose codec
    is silently DISABLED, checked against the DCN wire budget its
    QUANTIZED schedule honors — the packed int8 payload prices at ~1/4
    the fp32 bytes, so the unquantized DCN stage blows straight through
    the post-codec contract (the regression class the codec knob makes
    possible: one dropped ``codec=`` kwarg re-inflates every DCN hop)."""
    from jax.sharding import PartitionSpec as P

    from ..common.jax_compat import shard_map
    from ..distributed.topology import hierarchical_axis
    from ..parallel.codec import CollectiveCodec
    from ..parallel.overlap import hier_psum_scatter
    from .passes.collective_budget import collect_wire_table

    mesh = _mesh(4)
    if mesh.shape["x"] < 4:
        raise FixtureUnavailable("fake 2-slice split needs an axis of 4")
    sm = (0, 0, 1, 1)
    hier = hierarchical_axis(mesh, "x", slice_map=sm)
    codec = CollectiveCodec(block=64)

    def coded(v):
        return hier_psum_scatter(v, "x", hier, codec=codec)

    def uncoded(v):                      # the seeded bug: codec dropped
        return hier_psum_scatter(v, "x", hier)

    def wrap(body):
        return shard_map(body, mesh=mesh, in_specs=(P(),),
                         out_specs=P("x"), check_vma=False)

    x = jnp.ones((16, 64), jnp.float32)
    # the declared budget IS the quantized schedule's measured DCN bytes
    coded_jaxpr = jax.make_jaxpr(wrap(coded))(x).jaxpr
    budget = collect_wire_table(coded_jaxpr, {"x": sm})["dcn"]["bytes"]
    return check(wrap(uncoded), x, passes=["collective_budget"],
                 exemptions=(), target="seeded:COMM004",
                 options={"collective_budget":
                          {"wire": {"dcn_axes": {"x": list(sm)},
                                    "dcn_bytes": budget}}})


def seeded_moe_dispatch_codec_off() -> Report:
    """COMM004 on the round-18 EP dispatch: a fake-2-slice expert
    all-to-all whose codec is silently DISABLED, checked against the
    DCN wire budget its QUANTIZED schedule honors — the EP twin of the
    reduce-scatter fixture (one dropped ``codec=`` kwarg on the MoE
    dispatch re-inflates every DCN-crossing token payload to fp wire,
    blowing the post-codec contract the EP step is pinned to)."""
    from jax.sharding import PartitionSpec as P

    from ..common.jax_compat import shard_map
    from ..distributed.topology import hierarchical_axis
    from ..parallel.codec import CollectiveCodec
    from ..parallel.expert import make_ep_all_to_all
    from .passes.collective_budget import collect_wire_table

    mesh = _mesh(4)
    if mesh.shape["x"] < 4:
        raise FixtureUnavailable("fake 2-slice split needs an axis of 4")
    sm = (0, 0, 1, 1)
    hier = hierarchical_axis(mesh, "x", slice_map=sm)
    codec = CollectiveCodec(block=64)

    def coded(v):
        return make_ep_all_to_all("x", hier=hier, codec=codec)(v)

    def uncoded(v):                      # the seeded bug: codec dropped
        return make_ep_all_to_all("x", hier=hier)(v)

    def wrap(body):
        return shard_map(body, mesh=mesh, in_specs=(P(),),
                         out_specs=P("x"), check_vma=False)

    x = jnp.ones((16, 64), jnp.float32)   # [E, C*d]-shaped send buffer
    # the declared budget IS the quantized dispatch's measured DCN bytes
    coded_jaxpr = jax.make_jaxpr(wrap(coded))(x).jaxpr
    budget = collect_wire_table(coded_jaxpr, {"x": sm})["dcn"]["bytes"]
    return check(wrap(uncoded), x, passes=["collective_budget"],
                 exemptions=(), target="seeded:COMM004[moe_dispatch]",
                 options={"collective_budget":
                          {"wire": {"dcn_axes": {"x": list(sm)},
                                    "dcn_bytes": budget}}})


def seeded_moe_dropless_codec_off() -> Report:
    """COMM004 on the round-20 DROPLESS dispatch composite: the sorted
    ragged dispatch is TWO exchanges — an uncoded int32 count exchange
    (the control plane stays bit-exact) followed by the coded token
    payload windows.  The seeded bug silently drops the codec on the
    payload leg only; the cheap count leg stays put while every
    DCN-crossing token window re-inflates to fp wire, blowing the
    budget the dropless step is pinned to."""
    from jax.sharding import PartitionSpec as P

    from ..common.jax_compat import shard_map
    from ..distributed.topology import hierarchical_axis
    from ..parallel.codec import CollectiveCodec
    from ..parallel.expert import make_ep_all_to_all
    from .passes.collective_budget import collect_wire_table

    mesh = _mesh(4)
    if mesh.shape["x"] < 4:
        raise FixtureUnavailable("fake 2-slice split needs an axis of 4")
    sm = (0, 0, 1, 1)
    hier = hierarchical_axis(mesh, "x", slice_map=sm)
    codec = CollectiveCodec(block=64)
    counts_a2a = make_ep_all_to_all("x", hier=hier)   # always uncoded

    def dispatch(payload_codec):
        pay = make_ep_all_to_all("x", hier=hier, codec=payload_codec)

        def body(c, v):
            return counts_a2a(c), pay(v)

        return shard_map(body, mesh=mesh, in_specs=(P(), P()),
                         out_specs=(P("x"), P("x")), check_vma=False)

    c = jnp.ones((4, 4), jnp.int32)       # [ep, e_local] counts
    x = jnp.ones((16, 64), jnp.float32)   # [ep*W, d] payload windows
    # the declared budget IS the coded composite's measured DCN bytes
    # (counts uncoded + payload coded)
    coded_jaxpr = jax.make_jaxpr(dispatch(codec))(c, x).jaxpr
    budget = collect_wire_table(coded_jaxpr, {"x": sm})["dcn"]["bytes"]
    return check(dispatch(None), c, x, passes=["collective_budget"],
                 exemptions=(), target="seeded:COMM004[moe_dropless]",
                 options={"collective_budget":
                          {"wire": {"dcn_axes": {"x": list(sm)},
                                    "dcn_bytes": budget}}})


# ---------------------------------------------------------------------------
# memory_budget
# ---------------------------------------------------------------------------


def seeded_peak_over_budget() -> Report:
    """MEM001: a step whose compiled peak (arguments alone, here) blows
    through a deliberately tiny declared HBM budget."""

    @jax.jit
    def bug(a, b):
        return (a @ b).sum()

    a = jnp.ones((512, 512), jnp.float32)          # 1 MB per operand
    return check(bug, a, a, passes=["memory_budget"], exemptions=(),
                 target="seeded:MEM001",
                 options={"memory_budget": {"hbm_bytes": 64 << 10}})


def seeded_host_round_trip() -> Report:
    """MEM002: a whole buffer round-tripped host↔device in one
    monolithic pair of transfers against a streaming budget sized for
    half of it — the accidental full-state movement the size-capped
    bucket engine exists to prevent."""
    from ..common.jax_compat import transfer_to_memory_kind
    from ..core.device import default_memory_kind, host_memory_kind

    kind = host_memory_kind()
    if kind is None or transfer_to_memory_kind(kind) is None:
        raise FixtureUnavailable(
            "toolchain/backend exposes no host memory kind to transfer "
            "to (very old jax)")
    from ..common.jax_compat import device_put_memory_kind

    @jax.jit
    def bug(a):
        h = device_put_memory_kind(a, kind)                 # all out...
        back = device_put_memory_kind(h, default_memory_kind())
        return back * 2.0                                   # ...all back

    a = jnp.ones((512, 512), jnp.float32)          # 1 MB each direction
    return check(bug, a, passes=["memory_budget"], exemptions=(),
                 target="seeded:MEM002",
                 options={"memory_budget":
                          {"host_transfer_bytes": 1 << 20}})


def seeded_prefill_chunk_over_budget() -> Report:
    """MEM001 on the SERVING entry: a unified ragged serving step whose
    prefill chunk (prefill_token_budget=48) blows through an HBM budget
    declared for the decode-sized launch (1 MB fits the chunk-8 step at
    ~0.97 MB; chunk-48 compiles to ~1.13 MB) — the round-11 overrun the
    serving budget pin exists to catch: bumping the token budget must
    re-justify the declared budget, not silently grow the hot path."""
    import paddle_tpu as paddle
    from ..inference.serving import ContinuousBatchingEngine
    from ..models import LlamaConfig, LlamaForCausalLM

    state = paddle.get_rng_state()
    paddle.seed(20260803)
    cfg = LlamaConfig.debug(vocab=128, hidden=64, layers=2, heads=4,
                            kv_heads=2, inter=128, max_pos=64)
    model = LlamaForCausalLM(cfg)
    paddle.set_rng_state(state)
    params = {k: jnp.asarray(v)
              for k, v in model.functional_state().items()}
    eng = ContinuousBatchingEngine(cfg, params, max_slots=2,
                                   num_pages=17, page_size=16,
                                   max_seq_len=64,
                                   prefill_token_budget=48)
    fn, args, kwargs, _ = eng.analysis_entry()
    return check(fn, *args, kwargs=kwargs, passes=["memory_budget"],
                 exemptions=(), target="seeded:MEM001[prefill_chunk]",
                 options={"memory_budget": {"hbm_bytes": 1 << 20}})


def seeded_reshard_over_budget() -> Report:
    """MEM001 on the round-12 reshard entry: an UNBOUNDED reshard plan
    (``max_transient_bytes=None`` — one step, whole leaves, the layout a
    hand-rolled device_put loop degenerates to) moves a 1 MB replicated
    leaf through a redistribution entry whose declared transient budget
    is 64 KB — the overrun the size-capped planner exists to prevent,
    and the budget pin that keeps it honest when someone bypasses the
    cap."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.reshard import check_reshard_budget, plan_reshard

    mesh = _mesh(1)
    tree = {"w": jax.device_put(jnp.ones((512, 512), jnp.float32),
                                NamedSharding(mesh, P()))}
    plan = plan_reshard(tree, mesh, {"w": P("x", None)},
                        max_transient_bytes=None)
    return check_reshard_budget(plan, tree, budget_bytes=64 << 10,
                                exemptions=(),
                                target="seeded:MEM001[reshard_plan]")


def seeded_replica_delivery_over_budget() -> Report:
    """MEM001 on the round-13 replica weight-delivery entry: an
    UNBOUNDED delivery plan (``max_transient_bytes=None`` — whole
    leaves in one step, the shape an ad-hoc per-replica device_put
    sweep degenerates to) streams a 1 MB host weight tree against a
    64 KB declared budget.  ``ReplicaSet.spawn`` always streams through
    the size-capped cached plan; this proves the budget pin fires when
    someone bypasses the cap."""
    from ..inference.fleet import FleetConfig, ReplicaSet

    host = {"w": np.ones((512, 512), np.float32)}     # 1 MB, host-side
    rs = ReplicaSet(host, engine_factory=lambda p: None,
                    config=FleetConfig(max_transient_bytes=None))
    return rs.check_delivery_budget(
        budget_bytes=64 << 10, exemptions=(),
        target="seeded:MEM001[replica_delivery]")


def seeded_kv_handoff_over_budget() -> Report:
    """MEM001 on the round-16 disaggregated KV-handoff entry: an
    UNBOUNDED handoff plan (``max_transient_bytes=None`` — whole page
    tree in one step, the shape an ad-hoc per-handoff device_put sweep
    degenerates to) streams a 256 KB fp32 KV page tree against a 64 KB
    declared budget.  ``DisaggRouter`` always streams through the
    planner's size-capped cached plan; this proves the budget pin
    fires when someone bypasses the cap."""
    from ..inference.disagg import KVHandoffPlanner

    # [L=2, npages=8, kvh=2, page=16, d=64] fp32 = 128 KB per pool side
    tree = {"k": np.ones((2, 8, 2, 16, 64), np.float32),
            "v": np.ones((2, 8, 2, 16, 64), np.float32)}
    planner = KVHandoffPlanner(max_transient_bytes=None)
    return planner.check_handoff_budget(
        tree, budget_bytes=64 << 10, exemptions=(),
        target="seeded:MEM001[kv_handoff]")


def seeded_while_peeling() -> Report:
    """HLO003 over a captured-HLO sample: a scanned body's all-gather
    duplicated TWICE into the hosting computation (XLA's peel+unroll
    cannot be forced portably on one CPU device, so — like HLO001 — the
    fixture proves the detector; the compile-and-scan plumbing rides
    the clean flagship sweeps)."""
    from .passes.hlo_checks import scan_while_peeling

    sample = """\
HloModule peeled_layer_stack

%body.7 (p.1: (f32[128,8], u32[])) -> (f32[128,8], u32[]) {
  %p.1 = (f32[128,8], u32[]) parameter(0)
  %x.1 = f32[128,8] get-tuple-element(%p.1), index=0
  %ag.1 = f32[256,8] all-gather(%x.1), replica_groups={}, dimensions={0}
  %r.1 = f32[128,8] slice(%ag.1), slice={[0:128], [0:8]}
}

%cond.7 (c.1: (f32[128,8], u32[])) -> pred[] {
  %c.1 = (f32[128,8], u32[]) parameter(0)
}

ENTRY %main.42 (a.1: f32[128,8]) -> f32[128,8] {
  %a.1 = f32[128,8] parameter(0)
  %ag.peel0 = f32[256,8] all-gather(%a.1), replica_groups={}, dimensions={0}
  %ag.peel1 = f32[256,8] all-gather(%a.1), replica_groups={}, dimensions={0}
  %t.1 = (f32[128,8], u32[]) tuple(%a.1)
  %w.1 = (f32[128,8], u32[]) while(%t.1), condition=%cond.7, body=%body.7
  %out.1 = f32[128,8] get-tuple-element(%w.1), index=0
}
"""
    findings = scan_while_peeling(sample)
    return Report(target="seeded:HLO003", findings=findings,
                  passes_run=("hlo_post_checks",))


# ---------------------------------------------------------------------------
# health_probe (round-17: the training health guardian)
# ---------------------------------------------------------------------------


def seeded_unfused_health_probe() -> Report:
    """HEALTH001: a "probe" whose output carries TREE-SIZED buffers —
    per-leaf finite masks returned alongside the scalars (the classic
    host-style detector ported naively: materialize, then look).  The
    fused contract is a handful of scalars + one bucket vector; the
    budget here is the UNPROBED step's measured peak + a deliberately
    small overhead, so the mask tree blows straight through it."""
    from .core import AnalysisContext
    from .passes.health_probe import compiled_peak_bytes

    params = {f"w{i}": jnp.ones((128, 128), jnp.float32)
              for i in range(8)}
    grads = {k: v * 1e-3 for k, v in params.items()}

    @jax.jit
    def base(params, grads):
        new = {k: v - 1e-3 * grads[k] for k, v in params.items()}
        return sum(jnp.sum(g) for g in grads.values()), new

    @jax.jit
    def bug(params, grads):
        new = {k: v - 1e-3 * grads[k] for k, v in params.items()}
        loss = sum(jnp.sum(g) for g in grads.values())
        probe = {
            "grad_norm": jnp.sqrt(sum(jnp.sum(jnp.square(g))
                                      for g in grads.values())),
            # the seeded bug: the probe OUTPUT is a full tree of masks
            "finite_mask": {k: jnp.isfinite(g) for k, g in grads.items()},
        }
        return loss, new, probe

    baseline = compiled_peak_bytes(
        AnalysisContext(base, (params, grads), {}))
    return check(bug, params, grads, passes=["health_probe"],
                 exemptions=(), target="seeded:HEALTH001",
                 options={"health_probe":
                          {"baseline_peak_bytes": baseline,
                           "probe_overhead_bytes": 16 << 10}})


def seeded_collective_health_probe() -> Report:
    """HEALTH002: a probe that psums its grad-norm across the mesh
    inside an entry whose declared baseline carries ZERO collectives —
    communication the probe added (on the single-chip flagship, ANY
    collective is the regression)."""
    from jax.sharding import PartitionSpec as P

    from ..common.jax_compat import shard_map

    mesh = _mesh(2)

    def body(g):
        gnorm = jnp.sqrt(jax.lax.psum(jnp.sum(g * g), "x"))  # the bug
        return g * 2.0, gnorm

    fn = shard_map(body, mesh=mesh, in_specs=(P("x"),),
                   out_specs=(P("x"), P()), check_vma=False)
    x = jnp.ones((4 * mesh.shape["x"], 8), jnp.float32)
    return check(fn, x, passes=["health_probe"], exemptions=(),
                 target="seeded:HEALTH002",
                 options={"health_probe": {"baseline_collectives": {}}})


# ---------------------------------------------------------------------------
# sharding_consistency (round-14: the Sharding Doctor)
# ---------------------------------------------------------------------------


def seeded_gspmd_reshard() -> Report:
    """SHARD001: a step whose body re-constrains a sharded operand to
    the TRANSPOSED spec — GSPMD silently lowers the layout conversion
    to an all-to-all no schedule ever declared (the reshard class the
    unified-partitioning refactor must see, not discover on a TPU
    profile)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _mesh(2)
    x = jax.device_put(jnp.ones((8, 8), jnp.float32),
                       NamedSharding(mesh, P("x", None)))

    @jax.jit
    def bug(a):
        b = jax.lax.with_sharding_constraint(
            a * 2.0, NamedSharding(mesh, P(None, "x")))   # spec transpose
        return b.sum()

    return check(bug, x, passes=["sharding_consistency"], exemptions=(),
                 target="seeded:SHARD001",
                 options={"sharding_consistency":
                          {"audit_resharding": True}})


def seeded_replication_waste() -> Report:
    """SHARD002: a 1 MB leaf left fully replicated on a 4-way axis its
    dims divide — 0.75 MB of per-device residency the plan ignores."""
    from ..parallel.specs import SpecLayout, TensorSpec
    from .sharding import check_layout

    layout = SpecLayout(
        mesh_axes=(("x", 4),),
        entries={"model.layers.*.mlp.up_proj.weight": TensorSpec(
            shape=(512, 512), dtype="float32", dim_axes=((), ()))})
    return check_layout(layout, replicated_min_bytes=256 << 10,
                        exemptions=(), target="seeded:SHARD002")


def seeded_cross_stack_divergence() -> Report:
    """SHARD003: two stacks mapping the same logical parameter to
    TRANSPOSED specs — every cross-stack handoff of that leaf pays a
    silent reshard."""
    from ..parallel.specs import SpecLayout, TensorSpec
    from .sharding import check_cross_stack

    key = "model.layers.*.self_attn.q_proj.weight"
    a = SpecLayout(mesh_axes=(("sharding", 2), ("mp", 2)),
                   entries={key: TensorSpec(
                       shape=(64, 64), dtype="float32",
                       dim_axes=(("sharding",), ("mp",)))})
    b = SpecLayout(mesh_axes=(("sharding", 2), ("mp", 2)),
                   entries={key: TensorSpec(
                       shape=(64, 64), dtype="float32",
                       dim_axes=(("mp",), ("sharding",)))})
    return check_cross_stack({"gspmd": a, "overlap": b}, exemptions=(),
                             target="seeded:SHARD003")


def seeded_shard_padding() -> Report:
    """SHARD004: a hand-written spec sharding a 129-row leaf 4 ways —
    XLA pads every shard to 33 rows; the at-rest rule would have fallen
    back to replication, a hand-rolled NamedSharding bypasses it (jax
    refuses such a device_put, but jit in_shardings and manual specs
    still reach it)."""
    from ..parallel.specs import SpecLayout, TensorSpec
    from .sharding import check_layout

    layout = SpecLayout(
        mesh_axes=(("x", 4),),
        entries={"lm_head.weight": TensorSpec(
            shape=(129, 64), dtype="float32",
            dim_axes=(("x",), ()))})
    return check_layout(layout, exemptions=(), target="seeded:SHARD004")


def seeded_unsharded_update() -> Report:
    """SHARD005: a flat optimizer update chain on a mesh with NO
    cross-replica sharding pin — the update runs replicated
    (2004.13336) and the unconstrained concat→update→slice layout is
    the exact region the 0.4.x GSPMD partitioner mis-lowers (PR 5's
    hand fix; Adam.apply_flat's flat_sharding is the pin this proves
    the doctor demands)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _mesh(2)
    m = jax.device_put(jnp.ones((1 << 15,), jnp.float32),
                       NamedSharding(mesh, P()))

    @jax.jit
    def bug(master, g):
        return master - 0.1 * g        # no flat_sharding pin anywhere

    return check(bug, m, m * 0.5, passes=["sharding_consistency"],
                 exemptions=(), target="seeded:SHARD005",
                 options={"sharding_consistency":
                          {"expect_update_pin": True,
                           "update_min_bytes": 1 << 10}})


def seeded_schedule_divergence() -> Report:
    """SCHED001: a hand-written stack table whose q_proj placement is
    TRANSPOSED against the unified schedule's derivation — the
    byte-identity gate of the round-19 unified-partitioning refactor
    (deriving three stacks from one schedule object is only safe while
    the derivation moves NO placement)."""
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from ..parallel.schedule import PartitionSchedule
    from ..parallel.specs import SpecLayout, TensorSpec
    from .sharding import check_schedule_derivation

    devs = jax.devices()
    if len(devs) < 4:
        raise FixtureUnavailable("needs >= 4 devices")
    mesh = Mesh(np.asarray(devs[:4], dtype=object).reshape(2, 2),
                ("sharding", "mp"))
    key = "model.layers.*.self_attn.q_proj.weight"
    sched = PartitionSchedule.from_plan(
        mesh, {key: (64, 64)}, lambda n: P("sharding", "mp"))
    hand = SpecLayout(
        mesh_axes=(("sharding", 2), ("mp", 2)),
        entries={key: TensorSpec(shape=(64, 64), dtype="float32",
                                 dim_axes=(("mp",), ("sharding",)))})
    return check_schedule_derivation(sched, {"gspmd": hand},
                                     exemptions=(),
                                     target="seeded:SCHED001")


# ---------------------------------------------------------------------------
# lock_discipline (round-21: the Concurrency Doctor)
# ---------------------------------------------------------------------------


def _race_report(code: str, src: str) -> Report:
    import textwrap

    from .passes.lock_discipline import analyze_source

    rel = f"seeded/{code.lower()}.py"
    findings = analyze_source(textwrap.dedent(src), rel)
    return Report(target=f"seeded:{code}", findings=findings,
                  passes_run=("lock_discipline",))


def seeded_unguarded_write() -> Report:
    """RACE001: a counter bumped under its lock but reset lock-free —
    the reset can interleave between the bump's read and write."""
    return _race_report("RACE001", """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.value = 0

            def bump(self):
                with self._lock:
                    self.value += 1

            def reset(self):
                self.value = 0
        """)


def seeded_lock_order_inversion() -> Report:
    """RACE002: one path nests send->recv, the other holds recv and
    reaches send THROUGH A HELPER CALL — the cross-method edge the
    analyzer must close over, and the classic two-thread deadlock."""
    return _race_report("RACE002", """
        import threading

        class Transfer:
            def __init__(self):
                self._send_lock = threading.Lock()
                self._recv_lock = threading.Lock()
                self.sent = 0
                self.received = 0

            def one(self):
                with self._send_lock:
                    with self._recv_lock:
                        self.sent += 1
                        self.received += 1

            def _locked_step(self):
                with self._send_lock:
                    self.sent += 1

            def other(self):
                with self._recv_lock:
                    self._locked_step()
                    self.received += 1
        """)


def seeded_blocking_under_lock() -> Report:
    """RACE003: a sleep inside the critical section — every other
    tick blocks on the lock for the full sleep (the serving-tick
    latency/deadlock hazard class: jit compile, collective, recv,
    fsync under a lock)."""
    return _race_report("RACE003", """
        import threading
        import time

        class Poller:
            def __init__(self):
                self._lock = threading.Lock()
                self.last = None

            def poll(self):
                with self._lock:
                    time.sleep(0.05)
                    self.last = 1
        """)


def seeded_check_then_act() -> Report:
    """RACE004: the PRE-FIX watchdog handler/flag race, minimized —
    ``complete`` checks ``task.timed_out`` OUTSIDE the lock, then
    acquires it to act, while the scanner flags ``timed_out`` under
    the same lock: the flag can flip between check and act, yielding
    a task both completed and flagged hung (the bug fixed in PRs 6-7;
    the pass must catch the bug we actually shipped)."""
    return _race_report("RACE004", """
        import threading

        class Manager:
            def __init__(self):
                self._lock = threading.Lock()
                self.tasks = {}

            def complete(self, task):
                if task.timed_out:          # check OUTSIDE the lock
                    return
                with self._lock:            # act UNDER it
                    task.done = True
                    self.tasks.pop(task.seq, None)

            def _scan(self):
                with self._lock:
                    for t in list(self.tasks.values()):
                        t.timed_out = True
        """)


SEEDED = {
    "COLL001": seeded_collective_order,
    "COLL002": seeded_ppermute_race,
    "COMM001": seeded_collective_budget,
    "COMM002": seeded_unscheduled_collective,
    "COMM003": seeded_ppermute_ring_order,
    # round-15: post-codec bytes-on-the-wire — a silently-disabled
    # quantized-DCN codec blows the declared DCN wire budget
    "COMM004": seeded_codec_disabled,
    # round-18: a second COMM004 proof on the EP MoE dispatch — the
    # codec silently off on the expert all-to-all blows the DCN wire
    # budget the quantized dispatch schedule honors
    "COMM004[moe_dispatch]": seeded_moe_dispatch_codec_off,
    # round-20: a third COMM004 proof on the DROPLESS dispatch
    # composite — codec silently off on the payload leg (counts stay
    # uncoded by design) blows the dropless step's measured DCN budget
    "COMM004[moe_dropless]": seeded_moe_dropless_codec_off,
    "DT001": seeded_fp32_matmul,
    "DT002": seeded_f64_leak,
    "DT003": seeded_fp32_carry,
    "DON001": seeded_undonated_state,
    "DON002": seeded_use_after_donate,
    "RT001": seeded_weak_type_churn,
    "RT002": seeded_signature_churn,
    "HLO001": seeded_involuntary_remat,
    "HLO002": seeded_full_param_allgather,
    "HLO003": seeded_while_peeling,
    # round-17: the training health guardian's probe-fusion contract —
    # a tree-sized probe output blows the fusion budget, a psum'd probe
    # adds collectives the baseline never had
    "HEALTH001": seeded_unfused_health_probe,
    "HEALTH002": seeded_collective_health_probe,
    "MEM001": seeded_peak_over_budget,
    # a second MEM001 proof on the round-11 serving entry — registry
    # keys carry a [variant] suffix; consumers expect the BARE code
    # before the bracket
    "MEM001[prefill_chunk]": seeded_prefill_chunk_over_budget,
    # a third on the round-12 reshard entry: an unbounded redistribution
    # plan overruns its declared transient budget
    "MEM001[reshard_plan]": seeded_reshard_over_budget,
    # a fourth on the round-13 replica weight-delivery entry: an
    # unbounded fleet delivery plan overruns its declared budget
    "MEM001[replica_delivery]": seeded_replica_delivery_over_budget,
    # a fifth on the round-16 disaggregated KV-handoff entry: an
    # unbounded handoff plan overruns its declared transient budget
    "MEM001[kv_handoff]": seeded_kv_handoff_over_budget,
    "MEM002": seeded_host_round_trip,
    # round-14: the Sharding Doctor (cross-stack partition consistency)
    "SHARD001": seeded_gspmd_reshard,
    "SHARD002": seeded_replication_waste,
    "SHARD003": seeded_cross_stack_divergence,
    "SHARD004": seeded_shard_padding,
    "SHARD005": seeded_unsharded_update,
    # round-19: the unified partitioning schedule's byte-identity gate —
    # a derivation that moves any placement against the hand-written
    # stack tables must fire, or deriving three stacks from one
    # schedule object is unverified
    "SCHED001": seeded_schedule_divergence,
    # round-21: the Concurrency Doctor (host-side lock discipline);
    # RACE004 is the minimized pre-fix watchdog race
    "RACE001": seeded_unguarded_write,
    "RACE002": seeded_lock_order_inversion,
    "RACE003": seeded_blocking_under_lock,
    "RACE004": seeded_check_then_act,
}


# Every fixture compiles a small seeded program, and one tier-1 process
# reaches the registry from THREE consumers (the parametrized fixture
# test, self_check inside the doctor smoke leg, and the per-round trace
# legs).  Reports are read-only, the programs deterministic — memoize
# per (code, backend) so the sweep is paid once per process (round-17
# tier-1 wall management).  FixtureUnavailable is never cached: an
# environment gaining devices mid-process should un-skip.
_REPORT_MEMO: dict = {}


def _memoized_fixture(code, fn):
    def run() -> Report:
        key = (code, jax.default_backend(), len(jax.devices()))
        rep = _REPORT_MEMO.get(key)
        if rep is None:
            rep = fn()
            _REPORT_MEMO[key] = rep
        return rep

    run.__name__ = fn.__name__
    run.__doc__ = fn.__doc__
    run.__wrapped__ = fn
    return run


SEEDED = {code: _memoized_fixture(code, fn)
          for code, fn in SEEDED.items()}
