"""Tracked exemptions for the Graph Doctor.

An exemption is an ACCEPTED finding: the pass still detects it, but the
report moves it to ``report.suppressed`` instead of failing the gate.
Every entry carries an id, the finding code it covers, a source-location
match (passes attach jaxpr eqn provenance to findings), and a reason —
so accepted fp32 regions / undonated buffers are design decisions with a
paper trail, not silence.  ANALYSIS.md documents the workflow; the
self-check (``python -m paddle_tpu.analysis --self-check``) asserts each
entry still matches a live finding, so stale exemptions rot loudly.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

from .findings import Finding


@dataclasses.dataclass(frozen=True)
class Exemption:
    id: str                      # stable handle, e.g. "EX-DT003-masked-accum"
    code: str                    # finding code this entry covers
    file_pattern: str            # substring of the finding's source file
    reason: str
    function: Optional[str] = None   # optional exact function-name match

    def matches(self, finding: Finding) -> bool:
        if finding.code != self.code:
            return False
        where = finding.where or ""
        if self.file_pattern not in where:
            return False
        if self.function is not None:
            fns = tuple(finding.data.get("stack_functions") or ())
            fns += (finding.data.get("function"),)
            if self.function not in fns:
                return False
        return True


# The standing table.  Add entries here (never inline in call sites) so
# ``git log`` on this file is the history of accepted hazards.
EXEMPTIONS: Sequence[Exemption] = (
    Exemption(
        id="EX-DT003-masked-grad-accum",
        code="DT003",
        file_pattern="models/llama.py",
        function="micro_step_masked",
        reason=(
            "token-weighted gradient merge keeps an fp32 accumulator by "
            "design: micro-grads are scaled by per-micro token counts and "
            "partial sums span the whole accum window, so there is no "
            "bounded-depth fold point for a bf16 carry (the unmasked path "
            "has one and uses it).  Accepted fp32 region; the headline "
            "bench runs unmasked.  Design note: models/llama.py "
            "micro_step_masked."),
    ),
)


def apply_exemptions(findings, exemptions=None):
    """Split findings into (active, suppressed) under the exemption table.
    Suppressed findings get their ``exemption_id`` stamped."""
    table = EXEMPTIONS if exemptions is None else exemptions
    active, suppressed = [], []
    for f in findings:
        hit = next((e for e in table if e.matches(f)), None)
        if hit is None:
            active.append(f)
        else:
            f.exemption_id = hit.id
            suppressed.append(f)
    return active, suppressed
