"""Graph Doctor core: the pass framework.

``check(fn, *args, **kwargs)`` traces ``fn`` exactly as jit would, hands
the closed jaxpr (and, for passes that need it, the lowered/compiled HLO)
to every registered AnalysisPass, and returns a typed findings Report.
The framework generalizes the one-off HLO-grep regression tests (round-4's
involuntary-remat gate) into reusable machinery: PartIR-style, partitioning
and precision decisions over our programs are inspectable artifacts, not
side effects (PAPERS.md; arxiv 2112.01075 for statically-checkable
collective sequences).

Cost model: passes declare what they need — ``"jaxpr"`` (a trace, cheap),
``"lowered"`` (StableHLO lowering, adds donation metadata), or
``"compiled"`` (full XLA compile with fd-level stderr capture, the
expensive one) — and the context materializes each artifact at most once
per check() call.
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import jax
from jax import core as jax_core

from .exemptions import apply_exemptions
from .findings import Finding, Report

# ---------------------------------------------------------------------------
# jaxpr walking utilities (shared by passes)
# ---------------------------------------------------------------------------


def sub_jaxprs(eqn) -> Iterator[Tuple[str, Any]]:
    """Yield (param_name, Jaxpr) for every inner jaxpr of an eqn —
    pjit/remat ``jaxpr``, scan ``jaxpr``, cond ``branches``, while
    ``cond_jaxpr``/``body_jaxpr``, custom_* ``call_jaxpr``/``fun_jaxpr``,
    shard_map ``jaxpr`` — without hardcoding the primitive zoo."""
    for name, val in eqn.params.items():
        vals = val if isinstance(val, (tuple, list)) else (val,)
        for v in vals:
            if isinstance(v, jax_core.ClosedJaxpr):
                yield name, v.jaxpr
            elif isinstance(v, jax_core.Jaxpr):
                yield name, v


def walk_eqns(jaxpr, _stack: Tuple = ()) -> Iterator[Tuple[Any, Tuple]]:
    """Depth-first traversal of every eqn in ``jaxpr`` and all nested
    jaxprs.  Yields (eqn, stack) where ``stack`` is the tuple of ancestor
    eqns (outermost first) — passes use it for region context (inside a
    shard_map? nested in a scan?)."""
    for eqn in jaxpr.eqns:
        yield eqn, _stack
        for _, inner in sub_jaxprs(eqn):
            yield from walk_eqns(inner, _stack + (eqn,))


def eqn_source(eqn) -> Tuple[str, int, str]:
    """(file, line, function) provenance of an eqn, from its traceback.
    Returns ("", 0, "") when jax carries no source info (e.g. synthetic
    eqns from transposition)."""
    try:
        from jax._src import source_info_util as siu

        frame = siu.user_frame(eqn.source_info)
        if frame is None:
            return "", 0, ""
        return frame.file_name, int(frame.start_line), frame.function_name
    except Exception:  # pragma: no cover - jax-internal API drift
        return "", 0, ""


def format_where(eqn) -> Tuple[Optional[str], Dict[str, Any]]:
    """(where-string, data-dict) from eqn provenance, for Finding fields.
    ``data["stack_functions"]`` carries the full user-code call stack at
    trace time (innermost first) — exemptions match on it, so a hazard
    produced by a lambda inside ``micro_step_masked`` is still
    attributable to that function."""
    fname, line, func = eqn_source(eqn)
    if not fname:
        return None, {}
    stack: Tuple[str, ...] = ()
    try:
        from jax._src import source_info_util as siu

        stack = tuple(fr.function_name
                      for fr in siu.user_frames(eqn.source_info))
    except Exception:  # pragma: no cover - jax-internal API drift
        stack = (func,)
    short = os.path.join(*fname.split(os.sep)[-2:]) if os.sep in fname \
        else fname
    return f"{short}:{line} ({func})", {"function": func, "file": fname,
                                        "line": line,
                                        "stack_functions": stack}


def aval_size(aval) -> int:
    try:
        size = 1
        for d in aval.shape:
            size *= int(d)
        return size
    except Exception:
        return 0


def capture_stderr(fn: Callable[[], Any]) -> Tuple[Any, str]:
    """Run ``fn`` with fd-level stderr capture (XLA C++ warnings bypass
    sys.stderr).  Returns (result, captured_text)."""
    import sys

    sys.stderr.flush()
    saved = os.dup(2)
    tmp = tempfile.TemporaryFile(mode="w+b")
    os.dup2(tmp.fileno(), 2)
    try:
        result = fn()
    finally:
        sys.stderr.flush()
        os.dup2(saved, 2)
        os.close(saved)
    tmp.seek(0)
    text = tmp.read().decode(errors="replace")
    tmp.close()
    return result, text


# ---------------------------------------------------------------------------
# Context
# ---------------------------------------------------------------------------


def _unwrap(fn):
    """Follow ``__wrapped__`` DOWN to a jit entry, and only to a jit
    entry: build_train_step returns a scalar-normalizing plain wrapper
    around its jitted step, and the doctor must audit the jit boundary
    (donation lives there).  A fn that is already a jit entry stays put
    (jit itself sets __wrapped__ to the raw python body — unwrapping
    past it would lose the entry), and plain wrappers over plain
    functions (shard_map over a collective body) stay put too (the raw
    body is not traceable outside its wrapper)."""
    seen = set()
    while not hasattr(fn, "lower") and id(fn) not in seen:
        seen.add(id(fn))
        inner = getattr(fn, "__wrapped__", None)
        if inner is None or not hasattr(inner, "lower"):
            break
        fn = inner
    return fn


class AnalysisContext:
    """Everything a pass may ask for about one (fn, args) target, built
    lazily and cached: the closed jaxpr, the Lowered (with donation
    metadata), the compiled executable plus the stderr XLA emitted while
    compiling, and per-pass options."""

    def __init__(self, fn, args, kwargs, target: str = "",
                 declared_dtype=None, options: Optional[Dict] = None):
        self.fn = fn
        self.inner_fn = _unwrap(fn)
        self.args = args
        self.kwargs = kwargs or {}
        self.target = target or getattr(fn, "__name__", repr(fn))
        self.declared_dtype = declared_dtype
        self.options = options or {}
        self._jaxpr = None
        self._lowered = ...
        self._compiled = None
        self._compile_stderr = None

    def opt(self, pass_name: str, key: str, default=None):
        return self.options.get(pass_name, {}).get(key, default)

    @property
    def closed_jaxpr(self):
        if self._jaxpr is None:
            if self.is_jit_entry and hasattr(self.inner_fn, "trace"):
                # AOT trace respects the entry's static_argnums/argnames
                # (make_jaxpr would abstractify config objects like the
                # serving chunk's cfg_id and crash)
                self._jaxpr = self.inner_fn.trace(
                    *self.args, **self.kwargs).jaxpr
            else:
                self._jaxpr = jax.make_jaxpr(self.inner_fn)(
                    *self.args, **self.kwargs)
        return self._jaxpr

    @property
    def jaxpr(self):
        return self.closed_jaxpr.jaxpr

    @property
    def is_jit_entry(self) -> bool:
        """True when the (unwrapped) target is a jit-compiled entry point
        — only those carry a donation contract worth auditing."""
        return hasattr(self.inner_fn, "lower") \
            and not isinstance(self.inner_fn, type)

    @property
    def lowered(self):
        """jax Lowered for jit entries (None for plain functions)."""
        if self._lowered is ...:
            if self.is_jit_entry:
                self._lowered = self.inner_fn.lower(*self.args,
                                                    **self.kwargs)
            else:
                self._lowered = None
        return self._lowered

    def compile(self):
        """(compiled, compile_stderr_text); compiles at most once.  Plain
        functions are jitted first (no donation) — HLO text checks still
        apply."""
        if self._compiled is None:
            lowered = self.lowered
            if lowered is None:
                lowered = jax.jit(self.inner_fn).lower(*self.args,
                                                       **self.kwargs)
            self._compiled, self._compile_stderr = capture_stderr(
                lowered.compile)
        return self._compiled, self._compile_stderr

    @property
    def compiled_text(self) -> str:
        compiled, _ = self.compile()
        try:
            return compiled.as_text()
        except Exception:  # pragma: no cover - backend without HLO dump
            return ""


# ---------------------------------------------------------------------------
# Pass base + registry
# ---------------------------------------------------------------------------

PASS_REGISTRY: Dict[str, type] = {}


def register_pass(cls):
    PASS_REGISTRY[cls.name] = cls
    return cls


class AnalysisPass:
    name: str = ""
    codes: Tuple[str, ...] = ()
    #: artifacts this pass forces: "jaxpr" | "lowered" | "compiled"
    requires: str = "jaxpr"

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        raise NotImplementedError

    def finding(self, code, message, severity="error", **kw) -> Finding:
        return Finding(code=code, message=message, severity=severity,
                       pass_name=self.name, **kw)


def resolve_passes(passes=None) -> List[AnalysisPass]:
    """None -> all registered passes; names/classes/instances accepted."""
    from . import passes as _passes  # noqa: F401 - populates the registry

    if passes is None:
        return [cls() for cls in PASS_REGISTRY.values()]
    out = []
    for p in passes:
        if isinstance(p, str):
            if p not in PASS_REGISTRY:
                raise KeyError(
                    f"unknown pass {p!r}; registered: "
                    f"{sorted(PASS_REGISTRY)}")
            out.append(PASS_REGISTRY[p]())
        elif isinstance(p, type):
            out.append(p())
        else:
            out.append(p)
    return out


def check(fn, *args, passes: Optional[Sequence] = None, target: str = "",
          declared_dtype=None, options: Optional[Dict] = None,
          exemptions=None, kwargs: Optional[Dict] = None) -> Report:
    """Run the Graph Doctor over one entry point.

    ``fn`` — the function to analyze (a jitted entry, a wrapper around
    one, or a plain traceable function); ``args``/``kwargs`` — example
    arguments with the real shapes/dtypes/shardings;
    ``passes`` — pass names/instances (None = all registered);
    ``declared_dtype`` — the declared compute dtype for the dtype audit
    (None = infer from the dominant matmul dtype);
    ``options`` — per-pass knobs, ``{"donation": {"persistent": (0,)}}``;
    ``exemptions`` — exemption table (None = the tracked standing table,
    ``()`` = none).

    Returns a Report; ``report.ok`` is the gate.
    """
    ctx = AnalysisContext(fn, args, kwargs, target=target,
                          declared_dtype=declared_dtype, options=options)
    instances = resolve_passes(passes)
    findings: List[Finding] = []
    skipped: Dict[str, str] = {}
    for p in instances:
        try:
            findings.extend(p.run(ctx))
        except SkipPass as e:
            skipped[p.name] = str(e)
    active, suppressed = apply_exemptions(findings, exemptions)
    return Report(target=ctx.target, findings=active, suppressed=suppressed,
                  passes_run=tuple(p.name for p in instances),
                  skipped=skipped)


class SkipPass(Exception):
    """A pass raises this when its preconditions don't hold for the
    target (e.g. HLO sharding checks on a single-device program) —
    recorded on the report instead of failing the run."""
