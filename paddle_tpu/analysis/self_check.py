"""Graph Doctor self-check: the doctor proving it can still detect.

Three layers, all required green:
1. every seeded-bug fixture (fixtures.py) triggers EXACTLY its intended
   finding code — true-positive coverage per pass;
2. the clean flagship entry points (build_train_step unmasked-bf16 in
   both accum regimes, llama fwd/bwd, the serving decode chunk) report
   ZERO findings — false-positive coverage;
3. every standing exemption entry still matches a live suppressed
   finding — stale exemptions rot loudly (the masked grad-accum fp32
   carry must still be detected AND suppressed by
   EX-DT003-masked-grad-accum).

Wired into ``python -m paddle_tpu.analysis --self-check``, the
``doctor_self_check`` leg of ``bench.py --smoke``, and
tests/test_analysis_passes.py.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

# jaxpr/lowering-level passes (no XLA compile) — used for the fast clean
# sweeps; the accum train step and the serving decode chunk also run the
# compiled HLO checks.
FAST_PASSES = ("collective_order", "dtype_promotion", "donation")
ALL_PASSES = None

# The sweeps run DEBUG-shaped models (~200 KB of params), far below the
# donation pass's production default of 1 MB — at the default the gate
# would be VACUOUS (deleting donate_argnums from build_train_step would
# still pass).  Lower the bar to the debug param scale so the sweeps
# actually verify the donation contracts; the liveness test
# (tests/test_analysis_passes.py) asserts an undonated params dict of
# this size trips DON001 at this threshold.
DONATION_MIN_BYTES = 4 << 10

# Round-10 capacity contracts for the DEBUG-shaped flagship (see the
# step-2 comment below and BASELINE.md round-10): peak ~2.24 MB ->
# budget 3 MB; the memory-engine step streams the two fp32 moment
# groups (~1 MB each) in and out once per step (~4.2 MB of memory-kind
# transfers) -> streaming budget 6 MB.  Snug on purpose: one extra
# full-group round trip (+2 MB) or an un-donated params copy (+1 MB)
# fails the doctor.
FLAGSHIP_HBM_BUDGET = 3 << 20
FLAGSHIP_STREAM_BUDGET = 6 << 20

# Round-15 wire contract for the debug-shaped flagship on the fake
# 2-slice hierarchical mesh (dp1 x sharding4[2 slices] x mp2) with the
# DCN codec ON: the quantized schedule measures ~19.5 KB of post-codec
# DCN bytes per step (int8 payload + bf16 scale sidecars; the
# unquantized schedule moves ~56 KB).  24 KB pins it with ~20%
# headroom — silently dropping the codec (or re-inflating a DCN hop to
# a float dtype) blows COMM004 here, not a multislice TPU session.
FLAGSHIP_DCN_WIRE_BUDGET = 24 << 10
FLAGSHIP_SLICE_MAP = (0, 0, 1, 1)

# Round-18 wire contract for the debug-shaped EP MoE train step on the
# fake-2-slice dp1 x sharding2 x ep4 mesh (ep spans the slices) with
# the block-64 DCN codec ON: the quantized dispatch/combine schedule
# measures ~1.9 KB of post-codec DCN bytes per step (int8 token
# payloads + bf16 scale sidecars on the all-to-alls, plus the tiny
# uncoded fp32 gate-grad psum) vs ~4.6 KB uncoded — the dispatch
# all-to-alls alone shrink 3.88x (the >= 3x acceptance bar).  2.25 KB
# pins it with ~20% headroom: silently dropping the codec on the EP
# dispatch blows COMM004 here, not a multislice TPU session.
MOE_DCN_WIRE_BUDGET = 2304
MOE_SLICE_MAP = (0, 0, 1, 1)

# Round-20 wire contract for the DROPLESS EP MoE train step (sorted
# ragged dispatch + grouped matmul, no capacity buffer) on the same
# fake-2-slice dp1 x sharding2 x ep4 mesh with the block-64 DCN codec
# ON: the quantized dispatch/combine schedule measures ~2.4 KB of
# post-codec DCN bytes per step (the int32 count exchange stays uncoded
# by design — the control plane is bit-exact — while the token payload
# windows ship int8 + bf16 scale sidecars; the tiny fp32 gate-grad psum
# rides uncoded) vs ~6.9 KB uncoded, the dispatch all-to-alls alone
# shrinking 3.85x (the >= 3x acceptance bar).  3 KB pins it with ~20%
# headroom: silently dropping the codec on the payload leg blows
# COMM004 here, not a multislice TPU session.
MOE_DROPLESS_DCN_WIRE_BUDGET = 3072

# Round-17 probe-fusion contract (HEALTH001) for the health-probed
# flagship step: the probed entry's compiled peak may exceed the
# UNPROBED entry's measured peak by at most this allowance.  Measured
# delta on the container toolchain: ~82 KB on the accum1 entry (probe
# scalars + the no-op guard's select slack); 192 KB pins it with ~2x
# headroom while a tree-sized probe regression (fp32 grad concat
# ~560 KB, even bool masks ~200 KB at debug shapes) fails loudly.
HEALTH_PROBE_OVERHEAD = 192 << 10

# Round-11 capacity contract for the debug-shaped UNIFIED serving step
# (radix prefix cache + chunked prefill + speculative verify in one
# ragged launch): the self-check engine (2 slots, 9 pages, chunk 8)
# compiles to ~0.72 MB peak; 1 MB pins it with ~0.28 MB headroom — a
# materialized fp32 logits buffer over the packed rows or an un-donated
# pool copy fails MEM001 here, and the seeded MEM001[prefill_chunk]
# fixture proves a prefill_token_budget bump (48 -> ~1.13 MB) blows
# this same decode-sized contract.
SERVING_HBM_BUDGET = 1 << 20


def _memory_target(donation_opts):
    """The memory-engine flagship sweep: MemoryConfig(names, host) —
    named-saveable remat + host-offloaded bucket-streamed AdamW — under
    the peak + streaming budgets, donation, and the dtype audit."""
    from .core import check
    from paddle_tpu.models import build_train_step
    from paddle_tpu.models.llama import llama_decay_mask
    from paddle_tpu.parallel.memory import (MemoryConfig,
                                            init_offloaded_state)

    cfg, model, opt, params, ids, labels = _flagship()
    mask_all = llama_decay_mask(model)
    mc = MemoryConfig(remat="names", optimizer_residency="host",
                      stream_bucket_bytes=256 << 10)
    step = build_train_step(model, opt, compute_dtype=jnp.bfloat16,
                            memory=mc)
    st = init_offloaded_state(opt, params, decay_mask=mask_all,
                              bucket_bytes=mc.stream_bucket_bytes)
    return check(
        step, params, st, 0, 1e-4, ids, labels,
        passes=["dtype_promotion", "donation", "memory_budget"],
        options={**donation_opts,
                 "memory_budget":
                     {"hbm_bytes": FLAGSHIP_HBM_BUDGET,
                      "host_transfer_bytes": FLAGSHIP_STREAM_BUDGET}},
        declared_dtype=jnp.bfloat16,
        target="memory_train_step[names,host]")


def _flagship():
    """Tiny flagship bundle shared by the clean sweeps (debug shapes —
    the jaxprs have the same STRUCTURE as the bench config; only dims
    shrink)."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    state = paddle.get_rng_state()
    paddle.seed(20260803)
    cfg = LlamaConfig.debug(vocab=128, hidden=64, layers=2, heads=4,
                            kv_heads=2, inter=128, max_pos=64)
    model = LlamaForCausalLM(cfg)
    paddle.set_rng_state(state)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    params = {k: jnp.asarray(v) for k, v in model.functional_state().items()}
    rng = np.random.default_rng(3)
    ids = rng.integers(0, cfg.vocab_size, (4, 16)).astype(np.int32)
    labels = rng.integers(0, cfg.vocab_size, (4, 16)).astype(np.int32)
    return cfg, model, opt, params, ids, labels


def _clean_targets():
    """Yield (name, report) for the flagship clean sweeps."""
    from .core import check
    from paddle_tpu.models import build_train_step
    from paddle_tpu.models.llama import llama_decay_mask

    cfg, model, opt, params, ids, labels = _flagship()
    mask_all = llama_decay_mask(model)

    def deep(t):
        return jax.tree_util.tree_map(jnp.copy, t)

    # 1. single-batch bf16 step (fast passes — structure is a subset of
    # the accum step checked in full below)
    donation = {"donation": {"min_bytes": DONATION_MIN_BYTES}}
    # declared_dtype is pinned, not inferred: a regression that upcasts
    # EVERY matmul to fp32 also removes the bf16 dots the inference
    # keys on, and the audit would silently stand down exactly when it
    # is needed most (the sweeps KNOW compute_dtype=bf16)
    step1 = build_train_step(model, opt, compute_dtype=jnp.bfloat16)
    yield "build_train_step[bf16]", check(
        step1, deep(params), opt.init_state(deep(params)), 0, 1e-4, ids,
        labels, passes=list(FAST_PASSES), options=donation,
        declared_dtype=jnp.bfloat16, target="build_train_step[bf16]")

    # 2. grad-accum bf16-carry step with the fused flat optimizer — the
    # headline training config; full pass suite incl. compiled HLO.
    # The collective budget here is the single-chip contract: ZERO
    # collectives of any kind (an accidental psum in an eager helper
    # fails the doctor, not the next TPU session).  Round-10 adds the
    # capacity contract: the debug-shaped flagship compiles to ~2.24 MB
    # peak (arguments + outputs + temporaries − donation aliasing);
    # the declared FLAGSHIP_HBM_BUDGET pins it with ~0.8 MB headroom,
    # so an un-donated params copy (+1 MB) or a materialized fp32
    # logits buffer fails MEM001 here, not a TPU session.
    zero_budget = {k: {"count": 0} for k in
                   ("allreduce", "allgather", "reducescatter",
                    "collectivepermute", "alltoall")}
    step4 = build_train_step(model, opt, compute_dtype=jnp.bfloat16,
                             accum_steps=4)
    yield "build_train_step[bf16,accum4]", check(
        step4, deep(params),
        opt.init_flat_state(deep(params), decay_mask=mask_all), 0, 1e-4,
        ids.reshape(4, 1, 16), labels.reshape(4, 1, 16),
        passes=ALL_PASSES,
        options={**donation, "collective_budget": zero_budget,
                 "memory_budget": {"hbm_bytes": FLAGSHIP_HBM_BUDGET}},
        declared_dtype=jnp.bfloat16,
        target="build_train_step[bf16,accum4]")

    # 2c. round-17: the health-probed flagship step — the probe-fusion
    # contract pinned against the UNPROBED accum1 entry's peak measured
    # in-process (HEALTH001), zero added collectives on the single-chip
    # probe (HEALTH002: every baseline kind is 0), plus donation + the
    # dtype audit over the probed program.  The probed entry runs with
    # the production all-open gates array so the audited program IS the
    # one the guardian drives.  Memoized per backend like the sharding
    # section: the target compiles the flagship TWICE (baseline +
    # probed) and is reached from self_check, the doctor smoke leg and
    # the analysis test suite in one tier-1 process.
    key = (jax.default_backend(), len(jax.devices()))
    rep = _HEALTH_MEMO.get(key)
    if rep is None:
        from .core import AnalysisContext
        from .passes.health_probe import compiled_peak_bytes
        from paddle_tpu.distributed.health import (HealthConfig,
                                                   default_gates)

        base_peak = compiled_peak_bytes(AnalysisContext(
            step1, (deep(params), opt.init_state(deep(params)), 0, 1e-4,
                    ids, labels), {}))
        hstep = build_train_step(model, opt, compute_dtype=jnp.bfloat16,
                                 health=HealthConfig())
        rep = check(
            hstep, deep(params), opt.init_state(deep(params)), 0, 1e-4,
            ids, labels,
            kwargs={"health_gates": jnp.asarray(default_gates())},
            passes=["health_probe", "dtype_promotion", "donation"],
            options={**donation,
                     "health_probe": {
                         "baseline_peak_bytes": base_peak,
                         "probe_overhead_bytes": HEALTH_PROBE_OVERHEAD,
                         "baseline_collectives": {}}},
            declared_dtype=jnp.bfloat16,
            target="health_probed_step[bf16]")
        if rep.ok:          # never memoize a one-off compile hiccup red
            _HEALTH_MEMO[key] = rep
    yield "health_probed_step[bf16]", rep

    # 2a. the HBM memory engine's train step (round-10): named-policy
    # remat + host-offloaded bucket-streamed AdamW, audited under BOTH
    # capacity contracts — the peak budget and the streaming budget
    # (a regression to monolithic full-state round trips fails MEM002)
    # — plus donation (host-resident state must still donate cleanly)
    # and the dtype audit
    yield "memory_train_step[names,host]", _memory_target(donation)

    # 2b. the overlap-engine train step on the 8-virtual-device hybrid
    # mesh (dp2 x sharding2 x mp2): the engine's collective schedule
    # must stay within its declared per-step budget AND every manual
    # collective must be engine-attributed (COMM002) — self-skips on
    # hosts without the virtual mesh
    if len(jax.devices()) >= 8:
        for name, rep in _overlap_target():
            yield name, rep
        # 2d. round-18: the EP MoE train step under its pinned
        # post-codec DCN wire budget (COMM004) on the fake-2-slice
        # dp1 x sharding2 x ep4 mesh
        for name, rep in _moe_ep_target():
            yield name, rep

        # 2e. round-20: the DROPLESS EP train step under its own pinned
        # post-codec DCN wire budget (COMM004) on the same mesh
        for name, rep in _moe_ep_dropless_target():
            yield name, rep

    # 3. llama forward/backward in isolation (no optimizer): params are
    # read-only here, so they are declared persistent for the donation
    # audit
    from paddle_tpu.autograd import no_grad
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models.llama import _gold_logit

    def fwd_bwd(p, ids_, labels_):
        def loss(pp):
            cast = {k: (v.astype(jnp.bfloat16)
                        if jnp.issubdtype(v.dtype, jnp.floating) else v)
                    for k, v in pp.items()}
            with no_grad():
                logits = model.functional_call(cast, Tensor(ids_))
            lv = logits._value
            lse = jax.scipy.special.logsumexp(lv.astype(jnp.float32),
                                              axis=-1)
            return (lse - _gold_logit(lv, labels_)).mean()
        return jax.value_and_grad(loss)(p)

    yield "llama_fwd_bwd[bf16]", check(
        jax.jit(fwd_bwd), params, ids, labels, passes=list(FAST_PASSES),
        options={"donation": {"persistent": (0,),
                              "min_bytes": DONATION_MIN_BYTES}},
        declared_dtype=jnp.bfloat16, target="llama_fwd_bwd[bf16]")

    # 4. serving decode chunk (paged pipelined engine) — full suite
    from paddle_tpu.inference.serving import ContinuousBatchingEngine

    eng = ContinuousBatchingEngine(cfg, params, max_slots=2, num_pages=9,
                                   page_size=16, max_seq_len=64,
                                   decode_chunk_steps=2)
    fn, args, kwargs, options = eng.analysis_entry()
    yield "serving_decode_chunk", check(
        fn, *args, kwargs=kwargs, options=options, passes=ALL_PASSES,
        target="serving_decode_chunk")

    # 4a. round-11 unified serving step (chunked prefill + speculative
    # verify rows mixed into the decode launch) — gated like the
    # training flagship: ZERO collectives on the single-chip serving
    # path (COMM001) and the pinned peak-HBM contract (MEM001), plus
    # the full pass suite over the ragged program
    ueng = ContinuousBatchingEngine(cfg, params, max_slots=2,
                                    num_pages=9, page_size=16,
                                    max_seq_len=64,
                                    prefill_token_budget=8)
    ufn, uargs, ukwargs, uoptions = ueng.analysis_entry()
    zero_budget = {k: {"count": 0} for k in
                   ("allreduce", "allgather", "reducescatter",
                    "collectivepermute", "alltoall")}
    yield "serving_unified_step", check(
        ufn, *uargs, kwargs=ukwargs, passes=ALL_PASSES,
        options={**uoptions, "collective_budget": zero_budget,
                 "memory_budget": {"hbm_bytes": SERVING_HBM_BUDGET}},
        target="serving_unified_step")


def _moe_ep_flagship():
    """Debug-shaped EP MoE bundle shared by the EP clean sweep, the
    sharding section and the bench moe trace (fake-2-slice
    dp1 x sharding2 x ep4 mesh; shapes shrink, structure doesn't)."""
    from jax.sharding import Mesh

    from paddle_tpu.parallel.expert import MoEEPConfig, init_moe_ep_params

    cfg = MoEEPConfig(d_model=16, d_hidden=32, num_expert=8, top_k=2,
                      capacity_factor=2.0, aux_weight=0.01)
    mesh = Mesh(np.asarray(jax.devices()[:8], dtype=object).reshape(
        1, 2, 4), ("dp", "sharding", "ep"))
    params = init_moe_ep_params(cfg, mesh)
    rng = np.random.default_rng(7)
    x2d = jnp.asarray(rng.standard_normal((64, 16), np.float32))
    tgt = jnp.asarray(rng.standard_normal((64, 16), np.float32))
    return cfg, mesh, params, x2d, tgt


def _moe_ep_target():
    """Round-18 EP clean sweep: the expert-parallel MoE train step on
    the fake-2-slice mesh with the DCN codec ON, pinned to its
    post-codec wire budget (COMM004 — a silently-dropped codec on the
    dispatch all-to-alls fails here) with every manual collective
    engine-attributed (COMM002)."""
    from .core import check
    from paddle_tpu.parallel.codec import CollectiveCodec
    from paddle_tpu.parallel.expert import build_moe_ep_train_step
    from paddle_tpu.parallel.overlap import OverlapConfig

    cfg, mesh, params, x2d, tgt = _moe_ep_flagship()
    oc = OverlapConfig(hierarchical="on", slice_map=MOE_SLICE_MAP,
                       codec=CollectiveCodec(block=64))
    step = build_moe_ep_train_step(cfg, mesh, oc=oc)
    yield "moe_ep_train_step[hier2slice,codec]", check(
        step, params, x2d, tgt,
        passes=["collective_budget"],
        options={"collective_budget": {
            "overlap_active": True,
            "wire": {"dcn_axes": {"ep": list(MOE_SLICE_MAP)},
                     "dcn_bytes": MOE_DCN_WIRE_BUDGET}}},
        target="moe_ep_train_step[hier2slice,codec]")


def _moe_ep_dropless_target():
    """Round-20 dropless clean sweep: the sorted-ragged-dispatch EP
    train step on the fake-2-slice mesh with the DCN codec ON, pinned
    to its own measured post-codec wire budget (COMM004 — dropping the
    codec on the payload windows fails here; the uncoded int32 count
    exchange is part of the budget by design) with every manual
    collective engine-attributed (COMM002)."""
    from .core import check
    from paddle_tpu.parallel.codec import CollectiveCodec
    from paddle_tpu.parallel.expert import build_moe_ep_dropless_train_step
    from paddle_tpu.parallel.overlap import OverlapConfig

    cfg, mesh, params, x2d, tgt = _moe_ep_flagship()
    oc = OverlapConfig(hierarchical="on", slice_map=MOE_SLICE_MAP,
                       codec=CollectiveCodec(block=64))
    step = build_moe_ep_dropless_train_step(cfg, mesh, oc=oc)
    yield "moe_ep_dropless_train_step[hier2slice,codec]", check(
        step, params, x2d, tgt,
        passes=["collective_budget"],
        options={"collective_budget": {
            "overlap_active": True,
            "wire": {"dcn_axes": {"ep": list(MOE_SLICE_MAP)},
                     "dcn_bytes": MOE_DROPLESS_DCN_WIRE_BUDGET}}},
        target="moe_ep_dropless_train_step[hier2slice,codec]")


def _overlap_target():
    """Clean sweep over the communication-overlap engine's train step
    (parallel/overlap.py via build_train_step(overlap=...)): donation
    (the double-buffered gather carry must not defeat DON001's
    contract), collective order, and the collective budget with
    overlap_active — run on the dp2 x sharding2 x mp2 virtual mesh."""
    from jax.sharding import Mesh

    from .core import check
    from paddle_tpu.models import build_train_step
    from paddle_tpu.models.llama import apply_llama_sharding
    from paddle_tpu.parallel.overlap import OverlapConfig

    cfg, model, opt, params, ids, labels = _flagship()
    mesh = Mesh(np.asarray(jax.devices()[:8], dtype=object).reshape(
        2, 2, 2), ("dp", "sharding", "mp"))
    apply_llama_sharding(model, mesh)
    step = build_train_step(model, opt, mesh=mesh,
                            compute_dtype=jnp.bfloat16,
                            overlap=OverlapConfig())
    params = {k: jnp.asarray(v)
              for k, v in model.functional_state().items()}
    # per-step budget for the L=2 debug stack on this mesh, set snugly
    # above the engine's measured schedule (fwd gathers + bwd
    # reduce-scatters + TP/batch reductions + boundary reshards); a
    # per-leaf-collective regression (9 leaves x L x fwd/bwd) blows
    # straight through it
    budget = {"overlap_active": True,
              "allreduce": {"count": 48},
              "allgather": {"count": 24},
              "reducescatter": {"count": 12}}
    yield "overlap_train_step[dp2,sharding2,mp2]", check(
        step, params, opt.init_state(params), 0, 1e-4, ids, labels,
        passes=["collective_budget", "collective_order", "donation"],
        options={"donation": {"min_bytes": DONATION_MIN_BYTES},
                 "collective_budget": budget},
        declared_dtype=jnp.bfloat16,
        target="overlap_train_step[dp2,sharding2,mp2]")

    # round-15: the hierarchical fake-2-slice step with the quantized-
    # DCN codec ON, pinned to its post-codec wire budget (COMM004) —
    # and every coded collective still engine-attributed (COMM002)
    from paddle_tpu.parallel.codec import CollectiveCodec

    hmesh = Mesh(np.asarray(jax.devices()[:8], dtype=object).reshape(
        1, 4, 2), ("dp", "sharding", "mp"))
    apply_llama_sharding(model, hmesh)
    hoc = OverlapConfig(hierarchical="on",
                        slice_map=FLAGSHIP_SLICE_MAP,
                        codec=CollectiveCodec())
    hstep = build_train_step(model, opt, mesh=hmesh,
                             compute_dtype=jnp.bfloat16, overlap=hoc)
    hparams = {k: jnp.asarray(v)
               for k, v in model.functional_state().items()}
    yield "overlap_train_step[hier2slice,codec]", check(
        hstep, hparams, opt.init_state(hparams), 0, 1e-4, ids, labels,
        passes=["collective_budget"],
        options={"collective_budget": {
            "overlap_active": True,
            "wire": {"dcn_axes":
                     {"sharding": list(FLAGSHIP_SLICE_MAP)},
                     "dcn_bytes": FLAGSHIP_DCN_WIRE_BUDGET}}},
        declared_dtype=jnp.bfloat16,
        target="overlap_train_step[hier2slice,codec]")


# ---------------------------------------------------------------------------
# round-14: the Sharding Doctor section (cross-stack partition
# consistency).  Each flagship stack's entry is audited for
# GSPMD-inserted resharding (SHARD001) against a DECLARED allowance,
# its canonical SpecLayout table for replication waste / shard padding
# (SHARD002/004), the flat-update entries for the 2004.13336
# cross-replica pin (SHARD005), and the stacks' tables against each
# other (SHARD003 — must be EMPTY on the llama flagship tree; this
# table is the artifact the unified-partitioning refactor consumes).
# ---------------------------------------------------------------------------

# SHARD001 allowances for the debug-shaped flagship entries, measured
# on the container toolchain and pinned as COMM001-style upper bounds.
# Round-14 pinned the flat accum-4 bill at 23 all-to-alls / 148
# collective-permutes / 75 all-gathers — almost entirely the fused
# flat-optimizer boundary: every leaf's row-major flatten (and the
# slice-back) was a GSPMD reshard against the at-rest placement.
# Round-19's unified schedule derives the flat-update wire format FROM
# the at-rest tactics (parallel/schedule.FlatUpdateLayout: shard-major
# flatten = a LOCAL relayout), so the accum-4 entry now compiles to
# 5 / 14 / 57 — the new, smaller bill is PINNED here; any regression
# above it fires the doctor.  (An explicit at-rest pin on the merged
# grad tree was tried on top and rejected: −3 collective-permutes for
# +17 all-reduces.)
SHARDING_RESHARD_ALLOWANCES = {
    "gspmd[accum1]": {"alltoall": 6, "collectivepermute": 0,
                      "allgather": 33},
    "gspmd[accum4]": {"alltoall": 5, "collectivepermute": 14,
                      "allgather": 57},
    # overlap: 2 manual bucket gathers; the rest is the GSPMD boundary
    # (embedding/norm/head/loss outside the manual region)
    "overlap": {"alltoall": 6, "collectivepermute": 0, "allgather": 7},
    "hybrid[gpipe]": {"alltoall": 4, "collectivepermute": 8,
                      "allgather": 3},
    "hybrid[1F1B]": {"alltoall": 0, "collectivepermute": 2,
                     "allgather": 3},
}

# SHARD002 floor for the debug-shaped tables (production default is
# 1 MB; debug leaves top out at ~64 KB) — at this floor an accidentally
# replicated projection leaf (16 KB) FAILS the sweep
SHARDING_REPLICATED_MIN_BYTES = 4 << 10

# params are replicated over the pure data axes by design (the grad
# all-reduce rides them); only sharding/mp replication is waste
SHARDING_DATA_AXES = ("dp", "pp", "sep")

_SHARDING_MEMO: Dict = {}
_HEALTH_MEMO: Dict = {}


def _sharding_section() -> Dict[str, dict]:
    """The per-stack sharding sweeps; memoized per backend (the hybrid
    entries each compile the whole flagship, and the section is reached
    from self_check, the smoke leg and the test suite in one process)."""
    key = (jax.default_backend(), len(jax.devices()))
    if key in _SHARDING_MEMO:
        return _SHARDING_MEMO[key]
    if len(jax.devices()) < 8:
        return {"_skipped": {
            "ok": True,
            "skipped": f"needs >= 8 devices, have {len(jax.devices())} "
                       f"(run under "
                       f"XLA_FLAGS=--xla_force_host_platform_device_count"
                       f"=8)"}}
    out: Dict[str, dict] = {}
    try:
        for name, rep in _sharding_targets():
            out[name] = {"ok": rep.ok,
                         "findings": [f.format() for f in rep.findings],
                         "suppressed": len(rep.suppressed),
                         "skipped_passes": dict(rep.skipped)}
    except Exception as e:  # noqa: BLE001 - structured failure, not a crash
        # report the failure but do NOT memoize it: a one-off compile
        # hiccup must not pin the doctor red for the process lifetime
        out["_sweep_error"] = {"ok": False, "error": repr(e)}
        return out
    _SHARDING_MEMO[key] = out
    return out


def _sharding_targets():
    """Yield (name, report) for the sharding sweeps + the cross-stack
    table check; also stashes the canonical table on the section via
    flagship_sharding_table()."""
    from jax.sharding import Mesh

    from .core import check
    from .sharding import (check_cross_stack, check_layout,
                           extract_gspmd_layout, extract_hybrid_layout,
                           extract_overlap_layout)
    from paddle_tpu.models import build_train_step
    from paddle_tpu.models.llama import apply_llama_sharding, llama_decay_mask
    from paddle_tpu.parallel.overlap import OverlapConfig

    cfg, model, opt, params0, ids, labels = _flagship()
    mesh = Mesh(np.asarray(jax.devices()[:8], dtype=object).reshape(
        2, 2, 2), ("dp", "sharding", "mp"))
    apply_llama_sharding(model, mesh)
    params = {k: jnp.asarray(v)
              for k, v in model.functional_state().items()}
    mask_all = llama_decay_mask(model)

    glayout = extract_gspmd_layout(model, mesh)
    table = {"layout": glayout,
             "replicated_min_bytes": SHARDING_REPLICATED_MIN_BYTES,
             "replication_ignore_axes": SHARDING_DATA_AXES}

    # 1. flat GSPMD, single-batch (per-param optimizer: no flat pin to
    # demand — the per-param update shards with the params themselves)
    step1 = build_train_step(model, opt, mesh=mesh,
                             compute_dtype=jnp.bfloat16)
    yield "gspmd_train_step[accum1]", check(
        step1, params, opt.init_state(params), 0, 1e-4, ids, labels,
        passes=["sharding_consistency"],
        options={"sharding_consistency": {
            **table,
            "declared": SHARDING_RESHARD_ALLOWANCES["gspmd[accum1]"]}},
        target="sharding:gspmd_train_step[accum1]")

    # 2. flat GSPMD, grad-accum + fused flat optimizer: the entry that
    # must carry the 2004.13336 flat-update pin (deleting
    # build_train_step's flat_sharding fails SHARD005 here, not a
    # wrong-values session on the 0.4.x toolchain).  Since round 19 the
    # opt state is built in the schedule-derived SHARD-MAJOR wire
    # format (PartitionSchedule.flat_update_layout) — the entry whose
    # reshard bill the unified schedule shrank; the smaller allowance
    # pins the win (a fallback to the row-major wire format blows it)
    from paddle_tpu.parallel.schedule import PartitionSchedule

    psched = PartitionSchedule.from_model(model, mesh)
    step4 = build_train_step(model, opt, mesh=mesh,
                             compute_dtype=jnp.bfloat16, accum_steps=4,
                             schedule=psched)
    yield "gspmd_train_step[accum4]", check(
        step4, params,
        opt.init_flat_state(params, decay_mask=mask_all,
                            flat_layout=psched.flat_update_layout()),
        0, 1e-4, ids.reshape(4, 1, 16), labels.reshape(4, 1, 16),
        passes=["sharding_consistency"],
        options={"sharding_consistency": {
            **table, "expect_update_pin": True,
            "declared": SHARDING_RESHARD_ALLOWANCES["gspmd[accum4]"]}},
        target="sharding:gspmd_train_step[accum4]")

    # 3. the overlap engine: manual bucket gathers attribute via the
    # jaxpr; the declared extras are the GSPMD-land boundary
    olayout = extract_overlap_layout(model, mesh)
    ostep = build_train_step(model, opt, mesh=mesh,
                             compute_dtype=jnp.bfloat16,
                             overlap=OverlapConfig())
    yield "overlap_train_step", check(
        ostep, params, opt.init_state(params), 0, 1e-4, ids, labels,
        passes=["sharding_consistency"],
        options={"sharding_consistency": {
            "layout": olayout,
            "replicated_min_bytes": SHARDING_REPLICATED_MIN_BYTES,
            "replication_ignore_axes": SHARDING_DATA_AXES,
            "declared": SHARDING_RESHARD_ALLOWANCES["overlap"]}},
        target="sharding:overlap_train_step")

    # 4. both hybrid bodies on the 5-axis mesh (pp2 x sharding2 x mp2)
    from paddle_tpu.models.llama_hybrid import (hybrid_mesh,
                                                shard_hybrid_state,
                                                stack_llama_state)

    hmesh = hybrid_mesh(jax.devices(), pp=2, dp=1, sharding=2, sep=1,
                        mp=2)
    hlayout = extract_hybrid_layout(model, hmesh)
    # one stacked+placed state serves both schedule sweeps: check()
    # only traces/compiles, never executes or donates the buffers
    hstate = shard_hybrid_state(
        stack_llama_state(dict(params), cfg.num_hidden_layers), hmesh)
    for sched, tag in (("gpipe", "hybrid[gpipe]"), ("1F1B",
                                                    "hybrid[1F1B]")):
        from paddle_tpu.models.llama_hybrid import build_hybrid_train_step

        hstep = build_hybrid_train_step(cfg, opt, hmesh,
                                        num_microbatches=2,
                                        compute_dtype=jnp.float32,
                                        schedule=sched)
        yield f"hybrid_train_step[{sched}]", check(
            hstep, hstate, opt.init_state(hstate), 0, 1e-4, ids, labels,
            passes=["sharding_consistency"],
            options={"sharding_consistency": {
                "layout": hlayout,
                "replicated_min_bytes": SHARDING_REPLICATED_MIN_BYTES,
                "replication_ignore_axes": SHARDING_DATA_AXES,
                "declared": SHARDING_RESHARD_ALLOWANCES[tag]}},
            target=f"sharding:hybrid_train_step[{sched}]")

    # 5. serving stack: the engine's CONCRETE committed params — the
    # single-chip flagship (params0, not the training-mesh copies; the
    # compiled unified step's zero-reshard contract rides the
    # serving_unified_step clean sweep via analysis_entry's options)
    from paddle_tpu.inference.serving import ContinuousBatchingEngine

    eng = ContinuousBatchingEngine(cfg, params0, max_slots=2,
                                   num_pages=9, page_size=16,
                                   max_seq_len=64,
                                   prefill_token_budget=8)
    yield "serving_param_layout", check_layout(
        eng.param_layout(),
        replicated_min_bytes=SHARDING_REPLICATED_MIN_BYTES,
        target="sharding:serving_param_layout")

    # 6. the cross-stack agreement gate: GSPMD, overlap and hybrid must
    # map the llama flagship parameter tree to the SAME canonical specs
    # (SHARD003 empty) — the precondition for deriving all three from
    # one schedule object
    yield "cross_stack", check_cross_stack(
        {"gspmd": glayout, "overlap": olayout, "hybrid": hlayout},
        target="sharding:cross_stack")

    # 6b. round-19: the unified-schedule derivation gates (SCHED001) —
    # the PartitionSchedule's canonical table must be BYTE-IDENTICAL to
    # the hand-written GSPMD table, its overlap stack_plan identical to
    # the engine's own stack_layout_plan, and the schedule recovered
    # from the Doctor's round-14 table artifact must re-derive the SAME
    # placements (table round-trip: the from_table constructor is the
    # elastic/pod-scale entry point)
    from .sharding import (check_schedule_derivation,
                           check_stack_plan_derivation)

    yield "schedule_derivation", check_schedule_derivation(
        psched, {"gspmd": glayout},
        target="sharding:schedule_derivation")
    yield "schedule_stack_plan", check_stack_plan_derivation(
        psched, model, mesh, target="sharding:schedule_stack_plan")
    rt = PartitionSchedule.from_table(psched.table.to_table(), mesh=mesh)
    yield "schedule_table_roundtrip", check_schedule_derivation(
        rt.rederive(mesh), {"declared": psched.table},
        target="sharding:schedule_table_roundtrip")

    # 7. round-18: the EP MoE stack — the DECLARED plan table
    # (expert.moe_ep_layout: leading [E] on ``ep``, shared gate
    # replicated) vs the CONCRETE at-rest placement of the placed
    # params; SHARD003 must be empty with ``ep`` among the canonical
    # mesh axes (the fourth named tactic covered by the same gate),
    # plus the SHARD002/004 table checks on the plan
    from paddle_tpu.parallel.expert import moe_ep_layout
    from paddle_tpu.parallel.specs import layout_from_arrays

    mcfg, mmesh, mparams, _, _ = _moe_ep_flagship()
    mplan = moe_ep_layout(mcfg, mmesh)
    mrest = layout_from_arrays(mparams, mesh=mmesh)
    # in the EP stack 'sharding' is a PURE batch axis (tokens ride it
    # into the dispatch; there is no ZeRO layer here) — expert weights
    # replicate over it by design, exactly like dp
    yield "moe_ep_layout", check_layout(
        mplan, replicated_min_bytes=SHARDING_REPLICATED_MIN_BYTES,
        ignore_axes=SHARDING_DATA_AXES + ("sharding",),
        target="sharding:moe_ep_layout")
    yield "moe_ep_cross_stack", check_cross_stack(
        {"moe_ep_plan": mplan, "moe_ep_at_rest": mrest},
        target="sharding:moe_ep_cross_stack")


# ---------------------------------------------------------------------------
# round-19: the joint partition x memory x overlap autotune section —
# DOCTOR.json carries the chosen schedule (the acceptance artifact of
# the unified-partitioning round)
# ---------------------------------------------------------------------------

# Joint budgets for the params-heavy debug flagship (vocab 512, hidden
# 128 — partitioning must move real bytes for the walk to mean
# anything) on the fake-2-slice 8-device pool.  Measured on the
# container toolchain:
#   hybrid4 (dp2 x sharding2 x mp2, 4-way params)  codec-off:
#       peak 3 618 908, DCN 446 208;  codec-on: 3 585 756 / 150 916
#   tp8     (sharding4 x mp2, 8-way params)        codec-off:
#       peak 3 037 660, DCN 226 048;  codec-on: 3 037 788 /  76 612
# The pinned budgets sit BETWEEN the partition points' peaks and
# between the codec-on/off wire bytes, so the three walks land on
# THREE different lattice points:
#   HBM alone  -> tp8/codec-off   (first peak under budget),
#   DCN alone  -> hybrid4/codec-on (first wire under budget),
#   BOTH       -> tp8/codec-on    — a partitioning point neither
# budget alone forces, and one no hand-listed (codec-off, or
# hand-partition memory x codec) point reaches.  Margins >= 180 KB on
# peak and >= 20 KB on wire.
JOINT_HBM_BUDGET = 3_407_872          # 3.25 MB
JOINT_DCN_WIRE_BUDGET = 172_032       # 168 KB
JOINT_SLICE_MAPS = {"hybrid4": (0, 1), "tp8": (0, 0, 1, 1)}

_JOINT_MEMO: Dict = {}


def joint_flagship_config():
    """Shapes of the joint-autotune flagship (also the roofline drift
    check's cost-sheet input — one copy)."""
    from paddle_tpu.models import LlamaConfig

    return LlamaConfig.debug(vocab=512, hidden=128, layers=2, heads=8,
                             kv_heads=4, inter=256, max_pos=64)


#: batch/seq of the joint flagship step (ids/labels shape)
JOINT_FLAGSHIP_BATCH, JOINT_FLAGSHIP_SEQ = 8, 16


def _joint_flagship():
    """The params-heavy debug flagship of the joint autotune section
    (partitioning must dominate the capacity picture, so vocab/hidden
    grow over _flagship's shapes; structure unchanged)."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM

    state = paddle.get_rng_state()
    paddle.seed(20260804)
    cfg = joint_flagship_config()
    model = LlamaForCausalLM(cfg)
    paddle.set_rng_state(state)
    rng = np.random.default_rng(5)
    shape = (JOINT_FLAGSHIP_BATCH, JOINT_FLAGSHIP_SEQ)
    ids = rng.integers(0, cfg.vocab_size, shape).astype(np.int32)
    labels = rng.integers(0, cfg.vocab_size, shape).astype(np.int32)
    return cfg, model, ids, labels


def joint_schedule_points():
    """The partition points of the joint lattice, cheapest predicted
    step time first (the hand hybrid composition, then the 8-way
    ZeRO-3 x TP point)."""
    from paddle_tpu.parallel.schedule import PartitionPoint

    return (
        PartitionPoint("hybrid4",
                       (("dp", 2), ("sharding", 2), ("mp", 2)),
                       slice_map=JOINT_SLICE_MAPS["hybrid4"]),
        PartitionPoint("tp8", (("dp", 1), ("sharding", 4), ("mp", 2)),
                       slice_map=JOINT_SLICE_MAPS["tp8"]),
    )


def joint_schedule_section() -> dict:
    """Run the joint partition x memory x overlap autotune on the
    fake-2-slice lattice under the pinned budgets; memoized per
    backend (4 flagship compiles — self_check, the bench schedule
    trace and tests/test_schedule.py all read one payment).  The
    result is DOCTOR.json's ``unified_schedule.joint_autotune``."""
    import paddle_tpu as paddle
    from paddle_tpu.models import build_train_step
    from paddle_tpu.models.llama import apply_llama_sharding
    from paddle_tpu.parallel.memory import MemoryConfig
    from paddle_tpu.parallel.schedule import (choose_joint_config,
                                              joint_schedule_lattice,
                                              tune_schedule_config)

    if len(jax.devices()) < 8:
        return {"ok": True, "skipped": "needs >= 8 devices"}
    key = (jax.default_backend(), len(jax.devices()))
    if key in _JOINT_MEMO:
        return _JOINT_MEMO[key]
    from paddle_tpu.parallel.codec import CollectiveCodec

    cfg, model, ids, labels = _joint_flagship()
    # two codec points (off / stochastic-int8), not the full
    # three-point codec lattice: the fp8 point prices IDENTICALLY to
    # int8 on both budget axes (same wire bytes, same peak) so it
    # would re-compile the flagship twice for two duplicate records —
    # tier-1 wall management (round-19), the full lattice rides
    # ``-m slow`` breadth if ever needed
    lattice = joint_schedule_lattice(
        joint_schedule_points(),
        memory_lattice=(MemoryConfig(remat="none"),),
        codec_points=(None, CollectiveCodec()))

    def builder(jc):
        mesh = jc.partition.mesh()
        apply_llama_sharding(model, mesh)
        params = {k: jnp.asarray(v)
                  for k, v in model.functional_state().items()}
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())
        step = build_train_step(model, opt, mesh=mesh,
                                compute_dtype=jnp.bfloat16,
                                overlap=jc.overlap, memory=jc.memory)
        return step, (params, opt.init_state(params), jnp.int32(0),
                      jnp.float32(1e-4), ids, labels)

    chosen, records = tune_schedule_config(
        builder, JOINT_HBM_BUDGET, lattice,
        dcn_wire_bytes=JOINT_DCN_WIRE_BUDGET)
    hbm_only = choose_joint_config(records, hbm_bytes=JOINT_HBM_BUDGET)
    dcn_only = choose_joint_config(records,
                                   dcn_wire_bytes=JOINT_DCN_WIRE_BUDGET)
    joint = choose_joint_config(records, hbm_bytes=JOINT_HBM_BUDGET,
                                dcn_wire_bytes=JOINT_DCN_WIRE_BUDGET)
    # hand-listed points: the codec-off hand configs of each partition
    # point AND the round-15-style memory x codec walk pinned on the
    # hand partition (hybrid4) — none may satisfy both budgets, or the
    # partitioning axis added nothing
    hand = [i for i, r in enumerate(records)
            if r["label"].startswith("hybrid4")
            or r["label"].endswith("codec-off")]
    hand_fits = [i for i in hand
                 if r_fits(records[i])]
    ok = (chosen is not None and joint is not None
          and records[joint]["label"] == chosen.label()
          and hbm_only is not None and dcn_only is not None
          and len({hbm_only, dcn_only, joint}) == 3
          and joint > max(hbm_only, dcn_only)
          and not hand_fits)
    out = {"ok": bool(ok),
           "hbm_budget": JOINT_HBM_BUDGET,
           "dcn_wire_budget": JOINT_DCN_WIRE_BUDGET,
           "records": [{"label": r["label"],
                        "peak_bytes": r["peak_bytes"],
                        "dcn_wire_bytes": r.get("dcn_wire_bytes"),
                        "config": r["config"]} for r in records],
           "picked": {"hbm_only": records[hbm_only]["label"]
                      if hbm_only is not None else None,
                      "dcn_only": records[dcn_only]["label"]
                      if dcn_only is not None else None,
                      "joint": records[joint]["label"]
                      if joint is not None else None},
           "chosen": chosen.to_json() if chosen is not None else None,
           "chosen_label": chosen.label() if chosen is not None else None}
    if ok:                  # never memoize a one-off compile hiccup red
        _JOINT_MEMO[key] = out
    return out


def r_fits(rec) -> bool:
    """One record against BOTH pinned joint budgets."""
    return (rec["peak_bytes"] <= JOINT_HBM_BUDGET
            and rec.get("dcn_wire_bytes", 0) <= JOINT_DCN_WIRE_BUDGET)


#: The measured joint-autotune records (container toolchain, 8 fake
#: devices) in lattice order — the compile-free reference the roofline
#: drift check (and bench --roofline-trace --smoke-trace) falls back to
#: when the memoized compiled section isn't available in-process.
#: MUST track DOCTOR.json's ``unified_schedule.joint_autotune.records``.
RECORDED_JOINT_RECORDS = (
    {"label": "hybrid4(dp2xsharding2xmp2)[2slice]/none/device/"
              "codec-off",
     "peak_bytes": 3_618_908, "dcn_wire_bytes": 446_208},
    {"label": "hybrid4(dp2xsharding2xmp2)[2slice]/none/device/"
              "codec[g=int8/sr,w=fp8,b=256]",
     "peak_bytes": 3_585_756, "dcn_wire_bytes": 150_916},
    {"label": "tp8(sharding4xmp2)[2slice]/none/device/codec-off",
     "peak_bytes": 3_037_660, "dcn_wire_bytes": 226_048},
    {"label": "tp8(sharding4xmp2)[2slice]/none/device/"
              "codec[g=int8/sr,w=fp8,b=256]",
     "peak_bytes": 3_037_788, "dcn_wire_bytes": 76_612},
)


def roofline_drift_section(joint: Optional[dict] = None) -> dict:
    """Round-20: estimator-vs-measured drift gate.  The analytic
    roofline estimate re-ranks the fake-2-slice joint lattice and its
    PREDICTED winner (cheapest predicted point whose predicted peak +
    wire fit the pinned budgets, peak one-point-calibrated on the
    first measured record) must equal the MEASURED joint-autotune pick;
    per-record predicted fit/no-fit must agree with the measured
    frontier, and the predicted DCN wire bytes must track the measured
    pins (the wire model mirrors the overlap engine's collective
    schedule — byte-exact today; drift here means the engine's
    schedule and the estimator's mirror diverged).

    Compile-free: reads the memoized joint section when available
    (``joint`` argument / _JOINT_MEMO), else the RECORDED pins with a
    paper trail."""
    from paddle_tpu.parallel import roofline as rf
    from paddle_tpu.parallel.codec import CollectiveCodec
    from paddle_tpu.parallel.memory import MemoryConfig
    from paddle_tpu.parallel.schedule import joint_schedule_lattice

    if joint is None:
        joint = _JOINT_MEMO.get((jax.default_backend(),
                                 len(jax.devices())))
    measured_src = "compiled"
    records = (joint or {}).get("records")
    if not records:
        records = [dict(r) for r in RECORDED_JOINT_RECORDS]
        measured_src = "recorded"
    measured_pick = next((r["label"] for r in records if r_fits(r)),
                         None)

    lattice = joint_schedule_lattice(
        joint_schedule_points(),
        memory_lattice=(MemoryConfig(remat="none"),),
        codec_points=(None, CollectiveCodec()))
    by_label = {jc.label(): jc for jc in lattice}
    if set(by_label) != {r["label"] for r in records}:
        return {"ok": False, "target": "roofline:drift",
                "error": "lattice/record label mismatch",
                "lattice": sorted(by_label),
                "records": [r["label"] for r in records]}

    sheet = rf.llama_cost_sheet(joint_flagship_config())
    cal = rf.calibration_offset_from(
        records[0], by_label[records[0]["label"]], sheet,
        batch=JOINT_FLAGSHIP_BATCH, seq=JOINT_FLAGSHIP_SEQ)
    ests = {}
    for rec in records:
        ests[rec["label"]] = rf.estimate_joint_config(
            by_label[rec["label"]], sheet,
            batch=JOINT_FLAGSHIP_BATCH, seq=JOINT_FLAGSHIP_SEQ,
            hbm_budget=JOINT_HBM_BUDGET,
            dcn_budget=JOINT_DCN_WIRE_BUDGET,
            calibration_offset=cal)
    order = sorted(records, key=lambda r: ests[r["label"]].total_s)
    predicted_pick = next((r["label"] for r in order
                           if ests[r["label"]].fits), None)

    table = []
    frontier_ok = True
    max_wire_err = 0.0
    for rec in records:
        e = ests[rec["label"]]
        meas_fit = r_fits(rec)
        frontier_ok = frontier_ok and (e.fits == meas_fit)
        md = rec.get("dcn_wire_bytes") or 0
        if md:
            max_wire_err = max(max_wire_err,
                               abs(e.dcn_wire_bytes - md) / md)
        table.append({"label": rec["label"],
                      "predicted": e.to_json(),
                      "measured": {"peak_bytes": rec["peak_bytes"],
                                   "dcn_wire_bytes": md,
                                   "fits": meas_fit}})
    # the wire mirror is structural: > 10% relative drift on any pin
    # means the engine's schedule changed under the estimator
    ok = (predicted_pick is not None
          and predicted_pick == measured_pick
          and frontier_ok and max_wire_err <= 0.10)
    return {"ok": bool(ok), "target": "roofline:drift",
            "measured_source": measured_src,
            "predicted_winner": predicted_pick,
            "measured_pick": measured_pick,
            "frontier_parity": bool(frontier_ok),
            "max_dcn_wire_rel_err": max_wire_err,
            "calibration_offset": cal,
            "predicted_order": [r["label"] for r in order],
            "table": table}


_WIRE_MEMO: Dict = {}


def flagship_wire_table() -> dict:
    """Pre/post-codec ICI/DCN bytes-on-the-wire tables for the flagship
    overlap step on the fake-2-slice hierarchical mesh — DOCTOR.json's
    ``comm_wire`` per-stage bytes artifact (round-15).  Memoized per
    backend: both the bench smoke leg and the test suite read it in one
    process, and each variant traces the whole flagship."""
    from jax.sharding import Mesh

    from .core import AnalysisContext
    from .passes.collective_budget import collect_wire_table
    from paddle_tpu.models import build_train_step
    from paddle_tpu.models.llama import apply_llama_sharding
    from paddle_tpu.parallel.codec import CollectiveCodec
    from paddle_tpu.parallel.overlap import OverlapConfig

    if len(jax.devices()) < 8:
        return {"skipped": "needs >= 8 devices"}
    key = (jax.default_backend(), len(jax.devices()))
    if key in _WIRE_MEMO:
        return _WIRE_MEMO[key]
    cfg, model, opt, params0, ids, labels = _flagship()
    mesh = Mesh(np.asarray(jax.devices()[:8], dtype=object).reshape(
        1, 4, 2), ("dp", "sharding", "mp"))
    apply_llama_sharding(model, mesh)
    params = {k: jnp.asarray(v)
              for k, v in model.functional_state().items()}
    dcn_axes = {"sharding": list(FLAGSHIP_SLICE_MAP)}
    out: Dict[str, dict] = {"slice_map": list(FLAGSHIP_SLICE_MAP),
                            "dcn_budget": FLAGSHIP_DCN_WIRE_BUDGET}
    for name, codec in (("codec_off", None),
                        ("codec_on", CollectiveCodec())):
        oc = OverlapConfig(hierarchical="on",
                           slice_map=FLAGSHIP_SLICE_MAP, codec=codec)
        step = build_train_step(model, opt, mesh=mesh,
                                compute_dtype=jnp.bfloat16, overlap=oc)
        ctx = AnalysisContext(step, (params, opt.init_state(params), 0,
                                     1e-4, ids, labels), {})
        out[name] = collect_wire_table(ctx.jaxpr, dcn_axes)
    off_dcn, on_dcn = out["codec_off"]["dcn"], out["codec_on"]["dcn"]
    out["dcn_ratio"] = (off_dcn["bytes"] / on_dcn["bytes"]
                        if on_dcn["bytes"] else None)
    # the acceptance metric: the bucketed grad reduce-scatter's DCN leg
    # (fp-wire psum_scatter off, packed int8 all_to_all on)
    rs_off = off_dcn["kinds"].get("reducescatter", {}).get("bytes", 0)
    rs_on = on_dcn["kinds"].get("alltoall", {}).get("bytes", 0)
    out["reducescatter_ratio"] = rs_off / rs_on if rs_on else None
    _WIRE_MEMO[key] = out
    return out


def flagship_sharding_table() -> dict:
    """The canonical SpecLayout table of the flagship GSPMD stack on
    the 8-device hybrid-compatible mesh — DOCTOR.json's
    ``sharding.canonical_table``, the artifact the future unified
    partitioning schedule consumes (ROADMAP)."""
    from jax.sharding import Mesh

    from .sharding import extract_gspmd_layout
    from paddle_tpu.models.llama import apply_llama_sharding

    if len(jax.devices()) < 8:
        return {"skipped": "needs >= 8 devices"}
    cfg, model, opt, params, ids, labels = _flagship()
    mesh = Mesh(np.asarray(jax.devices()[:8], dtype=object).reshape(
        2, 2, 2), ("dp", "sharding", "mp"))
    apply_llama_sharding(model, mesh)
    return extract_gspmd_layout(model, mesh).to_table()


def moe_ep_sharding_table() -> dict:
    """The canonical SpecLayout table of the EP MoE stack on the
    fake-2-slice dp x sharding x ep mesh — DOCTOR.json's round-18
    rider: ``ep`` appears as a first-class axis in the canonical
    vocabulary the unified partitioning schedule consumes."""
    from .sharding import extract_moe_ep_layout

    if len(jax.devices()) < 8:
        return {"skipped": "needs >= 8 devices"}
    cfg, mesh, _, _, _ = _moe_ep_flagship()
    return extract_moe_ep_layout(cfg, mesh).to_table()


def _probe_masked_grad_accum():
    """Liveness probe for EX-DT003-masked-grad-accum: the masked accum
    branch still carries its by-design fp32 buffer and the audit still
    sees (and suppresses) it."""
    from .core import check
    from paddle_tpu.models import build_train_step

    cfg, model, opt, params, ids, labels = _flagship()
    stepm = build_train_step(model, opt, compute_dtype=jnp.bfloat16,
                             accum_steps=4)
    amask = np.ones((4, 1, 16), np.int32)
    amask[:, :, -4:] = 0
    return check(stepm, params, opt.init_state(params), 0, 1e-4,
                 ids.reshape(4, 1, 16), labels.reshape(4, 1, 16), amask,
                 passes=["dtype_promotion"], declared_dtype=jnp.bfloat16,
                 target="build_train_step[bf16,accum4,masked]")


# every standing exemption needs a probe that reproduces its finding —
# an Exemption without one FAILS self-check (a suppression whose hazard
# can no longer be demonstrated is either stale or untested)
_LIVENESS_PROBES = {
    "EX-DT003-masked-grad-accum": _probe_masked_grad_accum,
}


def _exemption_liveness() -> Dict[str, dict]:
    """Each standing exemption must still match a live suppressed finding
    in ITS OWN probe's report — one baked-in sweep cannot witness
    exemptions added later for other passes/targets."""
    from .exemptions import EXEMPTIONS

    out = {}
    for ex in EXEMPTIONS:
        probe = _LIVENESS_PROBES.get(ex.id)
        if probe is None:
            out[ex.id] = {"ok": False,
                          "error": f"no liveness probe registered for "
                                   f"{ex.id} — add one to "
                                   f"_LIVENESS_PROBES"}
            continue
        rep = probe()
        hit = [f for f in rep.suppressed if f.exemption_id == ex.id]
        out[ex.id] = {
            "ok": bool(hit) and not rep.findings,
            "matched": len(hit),
            "unsuppressed": [f.format() for f in rep.findings],
        }
    return out


_SEEDED_MEMO: Dict = {}


def _seeded_section() -> Dict[str, dict]:
    """The seeded-fixture sweep, memoized per backend: every fixture
    compiles a small program, the sweep is reached from self_check AND
    the parametrized test suite runs the same fixtures in the same
    tier-1 process — one payment is enough (a fixture regression still
    fails: the parametrized sweep calls the fixtures directly)."""
    from .fixtures import SEEDED, FixtureUnavailable

    key = (jax.default_backend(), len(jax.devices()))
    if key in _SEEDED_MEMO:
        return _SEEDED_MEMO[key]
    seeded = {}
    ok_all = True
    for code, fx in SEEDED.items():
        try:
            rep = fx()
        except FixtureUnavailable as e:
            seeded[code] = {"ok": True, "skipped": str(e)}
            continue
        except Exception as e:  # noqa: BLE001 - report, don't crash the CLI
            seeded[code] = {"ok": False, "error": repr(e)}
            ok_all = False
            continue
        codes = set(rep.codes())
        # registry keys may carry a "[variant]" suffix (two proofs of
        # one code on different entry points); the report must contain
        # the BARE code exactly
        expect = code.split("[", 1)[0]
        seeded[code] = {"ok": codes == {expect},
                        "codes": sorted(codes),
                        "n": len(rep.findings)}
        ok_all = ok_all and seeded[code]["ok"]
    if ok_all:          # never memoize a red sweep (one-off hiccups)
        _SEEDED_MEMO[key] = seeded
    return seeded


_CLEAN_MEMO: Dict = {}


def _clean_section() -> Dict[str, dict]:
    """The clean-flagship sweep as a JSON-able dict, memoized per
    backend (the targets compile several flagship variants and the
    section is reached from self_check, the doctor smoke leg and
    tests/test_analysis_passes.py in one tier-1 process)."""
    key = (jax.default_backend(), len(jax.devices()))
    if key in _CLEAN_MEMO:
        return _CLEAN_MEMO[key]
    clean_out = {}
    try:
        for name, rep in _clean_targets():
            clean_out[name] = {
                "ok": rep.ok,
                "findings": [f.format() for f in rep.findings],
                "suppressed": len(rep.suppressed),
                "skipped_passes": dict(rep.skipped)}
    except Exception as e:  # noqa: BLE001
        clean_out["_sweep_error"] = {"ok": False, "error": repr(e)}
        return clean_out
    if all(v.get("ok") for v in clean_out.values()):
        _CLEAN_MEMO[key] = clean_out
    return clean_out


_CONC_MEMO: Dict = {}


def _concurrency_section() -> dict:
    """Round-21 Concurrency Doctor block: the lock-discipline sweep over
    the host-side control plane plus the deterministic sanitizer
    self-test.  Backend-independent (pure AST + a barrier-stepped
    single-thread hammer) and reached from self_check, the smoke leg and
    tests in one tier-1 process — memoized per process, green runs
    only."""
    if "x" in _CONC_MEMO:
        return _CONC_MEMO["x"]
    from .concurrency import concurrency_section

    out = concurrency_section()
    if all(isinstance(v, dict) and v.get("ok") for v in out.values()):
        _CONC_MEMO["x"] = out
    return out


def self_check(clean: bool = True, joint: bool = True) -> dict:
    """Run the full self-check; returns a JSON-able dict with ``ok``.

    ``joint=False`` skips the round-19 joint-autotune section's 3
    flagship compiles (tier-1 wall management: the smoke legs pass it —
    the forcing CONTRACT is pinned by the seeded walk in
    tests/test_schedule.py and the byte-identity gates ride the
    sharding section; the real walk runs in the CLI ``--doctor`` /
    ``--schedule-trace`` (DOCTOR.json / SCHEDULE_r01.json carry the
    chosen schedule) and re-asserts under ``-m slow``)."""
    result = {"seeded": _seeded_section()}
    # round-21: the Concurrency Doctor — static lock-discipline sweep
    # over the control plane + the deterministic sanitizer self-test.
    # Cheap (no compiles) and host-side, so it runs in EVERY mode.
    try:
        result["concurrency"] = _concurrency_section()
    except Exception as e:  # noqa: BLE001
        result["concurrency"] = {"_section_error": {"ok": False,
                                                    "error": repr(e)}}
    if clean:
        # a sweep blowing up (toolchain drift, engine construction) must
        # degrade to a structured failure, not a raw traceback — the CLI
        # contract is "JSON report + non-zero exit", and DOCTOR.json
        # still gets written for the targets that did run
        result["clean"] = _clean_section()
        try:
            result["exemptions"] = _exemption_liveness()
        except Exception as e:  # noqa: BLE001
            result["exemptions"] = {"_liveness_error": {"ok": False,
                                                        "error": repr(e)}}
        # round-14: the Sharding Doctor section — per-stack reshard
        # audits, canonical-table checks and the cross-stack agreement
        # gate; DOCTOR.json additionally carries the canonical table
        # itself (the unified-partitioning refactor's input artifact)
        try:
            result["sharding"] = _sharding_section()
        except Exception as e:  # noqa: BLE001
            result["sharding"] = {"_section_error": {"ok": False,
                                                     "error": repr(e)}}
        try:
            result["sharding_canonical_table"] = flagship_sharding_table()
        except Exception as e:  # noqa: BLE001
            result["sharding_canonical_table"] = {"error": repr(e)}
        # round-18: the EP MoE stack's canonical table — ``ep`` as a
        # first-class axis in the vocabulary (the fourth named tactic)
        try:
            result["moe_ep_canonical_table"] = moe_ep_sharding_table()
        except Exception as e:  # noqa: BLE001
            result["moe_ep_canonical_table"] = {"error": repr(e)}
        # round-15: the per-stage (ICI/DCN) bytes-on-the-wire table for
        # the flagship hierarchical step, codec off vs on — the COMM004
        # contract's measurement artifact
        try:
            result["comm_wire"] = flagship_wire_table()
        except Exception as e:  # noqa: BLE001
            result["comm_wire"] = {"error": repr(e)}
        # round-19: the unified partitioning schedule — DOCTOR.json
        # carries the pinned (shrunk) reshard bill and the joint
        # partition x memory x overlap autotune's CHOSEN schedule (the
        # round's acceptance artifact); the derivation gates themselves
        # ride the sharding section above
        try:
            jsec = (joint_schedule_section() if joint
                    else {"ok": True,
                          "skipped": "joint=False (tier-1 wall): the "
                                     "real walk rides --doctor / "
                                     "--schedule-trace and -m slow; "
                                     "the forcing contract is pinned "
                                     "by tests/test_schedule.py's "
                                     "seeded walk"})
            result["unified_schedule"] = {
                "joint_autotune": jsec,
                # round-20: the estimator-drift gate (compile-free —
                # reads the joint records when compiled, else the
                # recorded pins)
                "roofline_drift": roofline_drift_section(
                    jsec if jsec.get("records") else None),
                "pinned_reshard_allowances":
                    {k: dict(v)
                     for k, v in SHARDING_RESHARD_ALLOWANCES.items()},
            }
        except Exception as e:  # noqa: BLE001
            result["unified_schedule"] = {
                "joint_autotune": {"ok": False, "error": repr(e)}}

    def _all_ok(d):
        return all(v.get("ok") for v in d.values()) if d else True

    result["ok"] = all(_all_ok(result.get(k, {}))
                       for k in ("seeded", "clean", "exemptions",
                                 "sharding", "concurrency")) \
        and (not clean
             or (bool(result.get("unified_schedule", {})
                      .get("joint_autotune", {}).get("ok"))
                 and bool(result.get("unified_schedule", {})
                          .get("roofline_drift", {"ok": True})
                          .get("ok"))))
    result["backend"] = jax.default_backend()
    result["num_devices"] = len(jax.devices())
    return result
