"""CLI: ``python -m paddle_tpu.analysis --self-check``.

Runs every seeded-bug fixture (each pass must produce exactly its
intended finding code), the clean flagship sweeps (zero findings), and
the exemption-liveness check; prints a JSON report and exits non-zero on
any failure.  ``--seeded-only`` skips the flagship sweeps (fast mode for
pre-commit hooks).  ``bench.py --doctor`` is the companion that runs the
suite over the BENCHED step configurations.
"""

from __future__ import annotations

import json
import sys


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--self-check" not in argv and "--seeded-only" not in argv:
        print(__doc__)
        return 2
    from .self_check import self_check

    res = self_check(clean="--seeded-only" not in argv)
    print(json.dumps(res, indent=1, default=str))
    return 0 if res["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
