"""paddle_tpu.analysis — the Graph Doctor.

A jaxpr/HLO static-analysis pass framework that gates the hot paths:
PRs 1-2 made the train and serving steps fast by hand; this package
keeps them fast by construction.  ``check(fn, *args)`` walks the closed
jaxpr (and compiled HLO where needed) of an entry point and returns a
typed findings Report; the pass suite covers the regression classes that
silently give back the won milliseconds or deadlock a pod:

- collective_order  — COLL001/COLL002: mismatched collective sequences
  between shard_map cond branches, malformed ppermutes;
- dtype_promotion   — DT001/DT002/DT003: silent fp32/f64 upcasts inside
  declared-bf16 compute regions (matmuls, f64 leaks, fp32 accumulation
  carries);
- donation          — DON001/DON002: undonated params/opt-state on jit
  entry points (HBM double-residency), use-after-donate aliasing;
- retrace_sentinel  — RT001/RT002: a call-driven wrapper counting
  compilations per signature, flagging weak-type/static-arg churn;
- hlo_post_checks   — HLO001/HLO002: involuntary-full-rematerialization
  compile warnings, unexpected full-param all-gathers in stage-3 steps;
- sharding_consistency — SHARD001-005 (round-14, the Sharding Doctor):
  GSPMD-inserted resharding beyond the declared schedule, replication
  waste, cross-stack canonical-spec divergence, non-divisible shard
  padding, and the missing 2004.13336 flat-update sharding pin.  The
  canonical SpecLayout tables come from ``analysis.sharding`` (one
  extractor per stack) — the groundwork for the ROADMAP's
  unified-partitioning refactor.

See ANALYSIS.md for finding codes, the exemption workflow, and
``bench.py --doctor`` / ``python -m paddle_tpu.analysis --self-check``.
"""

from .core import (AnalysisContext, AnalysisPass, PASS_REGISTRY, SkipPass,
                   capture_stderr, check, register_pass, resolve_passes)
from .exemptions import EXEMPTIONS, Exemption, apply_exemptions
from .findings import AnalysisError, Finding, Report
from .passes import RetraceSentinel, retrace_sentinel
from .self_check import roofline_drift_section, self_check

__all__ = [
    "AnalysisContext", "AnalysisError", "AnalysisPass", "EXEMPTIONS",
    "Exemption", "Finding", "PASS_REGISTRY", "Report", "RetraceSentinel",
    "SkipPass", "apply_exemptions", "capture_stderr", "check",
    "register_pass", "resolve_passes", "retrace_sentinel",
    "roofline_drift_section", "self_check",
]
