"""AST-level repo lint: host-library leaks into traced code.

Three bug classes keep re-entering jit-adjacent code by muscle memory:

- AST001 — ``np.*`` calls: numpy executes on HOST at trace time.  Inside
  a traced function the result is silently baked in as a constant (wrong
  once inputs change) or forces a device->host transfer; inside a Pallas
  kernel it simply crashes.  Host-side precompute (rope tables, schedule
  math) is legitimate — that is what the allowlist records, per function,
  with the reviewer's reasoning kept in the file.
- AST002 — python ``if``/``while`` on tracer-suspect expressions
  (``jnp.*``/``lax.*`` calls or ``.any()/.all()/.item()`` in the test):
  under jit these raise ConcretizationTypeError, and the "fix" people
  reach for (``bool(...)`` + an isinstance guard) belongs behind an
  allowlist entry, not scattered unreviewed.
- AST003 (round-14, the Sharding Doctor satellite) — hand-written
  ``PartitionSpec(...)`` literals inside ``models/`` and ``inference/``:
  partition specs are SCHEDULE decisions and belong in the parallel/
  layer (the canonical SpecLayout the unified-partitioning refactor
  derives the stacks from).  Every spec scattered through a model body
  is a site the refactor must find and a chance for two stacks to
  diverge (SHARD003's beat at the source level).  Today's legitimate
  sites — the declared plans themselves and the batch/activation
  constraints the entry layers still own — are the seeded allowlist;
  the list is the refactor's work-list.

Scope: AST001/AST002 over ``ops/pallas/``, ``models/``, ``parallel/``
(the traced/kernel layers, ISSUE 3 satellite); AST003 over ``models/``
and ``inference/``.  Run as a tier-1 pytest (tests/test_ast_lint.py)
against the explicit allowlist ``ast_allowlist.txt``; unused allowlist
entries fail the test too, so the list cannot rot.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .findings import Finding

LINT_DIRS = ("ops/pallas", "models", "parallel")
#: AST003 scope — spec literals belong in the parallel/ schedule layer
SPEC_DIRS = ("models", "inference")
ALL_CODES = frozenset({"AST001", "AST002", "AST003"})
NUMPY_ROOTS = ("np", "numpy")
TRACED_ROOTS = ("jnp", "lax")
TRACER_METHODS = ("any", "all", "item")
SPEC_NAME = "PartitionSpec"
# jnp.* predicates that operate on DTYPES, not values — never a tracer
# bool, so branching on them is fine
HOST_SAFE_ATTRS = ("issubdtype", "dtype", "result_type", "promote_types")
ALLOWLIST_FILE = os.path.join(os.path.dirname(__file__),
                              "ast_allowlist.txt")


def _attr_root(node) -> Optional[str]:
    """Root Name of a dotted attribute chain: np.linalg.norm -> 'np'."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _dotted(node) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


class _Visitor(ast.NodeVisitor):
    def __init__(self, rel: str, codes: Optional[Set[str]] = None):
        self.rel = rel
        self.codes = set(ALL_CODES if codes is None else codes)
        self.scope: List[str] = []
        self.findings: List[Finding] = []
        #: names the module binds to jax.sharding.PartitionSpec
        #: ("P" by repo idiom; the bare name counts too)
        self.spec_aliases: Set[str] = {SPEC_NAME}

    # -- import tracking (AST003 alias resolution) --------------------------

    def visit_ImportFrom(self, node):
        for alias in node.names:
            if alias.name == SPEC_NAME:
                self.spec_aliases.add(alias.asname or alias.name)
        self.generic_visit(node)

    # -- scope tracking -----------------------------------------------------

    def _qual(self) -> str:
        return ".".join(self.scope) if self.scope else "<module>"

    def visit_FunctionDef(self, node):
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    # -- AST001: np.* calls / AST003: PartitionSpec literals ----------------

    def visit_Call(self, node):
        if "AST001" in self.codes \
                and isinstance(node.func, ast.Attribute) \
                and _attr_root(node.func) in NUMPY_ROOTS:
            self.findings.append(Finding(
                code="AST001", pass_name="ast_lint",
                message=(f"host numpy call {_dotted(node.func)}() in "
                         f"traced-layer code — runs at trace time (baked "
                         f"constant / host sync; crash under Pallas); use "
                         f"jnp, or allowlist this function as host-side "
                         f"precompute"),
                where=f"{self.rel}:{node.lineno} ({self._qual()})",
                data={"function": self._qual(), "line": node.lineno}))
        if "AST003" in self.codes and self._is_spec_literal(node.func):
            self.findings.append(Finding(
                code="AST003", pass_name="ast_lint",
                message=(f"hand-written {_dotted(node.func) or SPEC_NAME}"
                         f"(...) literal in the model/serving layer — "
                         f"partition specs are schedule decisions and "
                         f"belong in parallel/ (the canonical SpecLayout "
                         f"the unified-partitioning refactor derives the "
                         f"stacks from); route through the plan/spec "
                         f"helpers, or allowlist this function as a "
                         f"declared plan / entry-layer constraint"),
                where=f"{self.rel}:{node.lineno} ({self._qual()})",
                data={"function": self._qual(), "line": node.lineno}))
        self.generic_visit(node)

    def _is_spec_literal(self, func) -> bool:
        if isinstance(func, ast.Name):
            return func.id in self.spec_aliases
        return isinstance(func, ast.Attribute) and func.attr == SPEC_NAME

    # -- AST002: python branch on tracer-suspect test -----------------------

    def _tracer_suspect(self, test) -> Optional[str]:
        for sub in ast.walk(test):
            if not isinstance(sub, ast.Call):
                continue
            if isinstance(sub.func, ast.Attribute):
                if sub.func.attr in HOST_SAFE_ATTRS:
                    continue
                if _attr_root(sub.func) in TRACED_ROOTS:
                    return _dotted(sub.func)
                if sub.func.attr in TRACER_METHODS and not sub.args:
                    return f".{sub.func.attr}()"
        return None

    def _check_branch(self, node, kind: str):
        if "AST002" not in self.codes:
            return
        sus = self._tracer_suspect(node.test)
        if sus is not None:
            self.findings.append(Finding(
                code="AST002", pass_name="ast_lint",
                message=(f"python `{kind}` on a tracer-suspect test "
                         f"({sus}) — raises ConcretizationTypeError under "
                         f"jit; use lax.cond/jnp.where, or allowlist if "
                         f"the value is provably concrete here"),
                where=f"{self.rel}:{node.lineno} ({self._qual()})",
                data={"function": self._qual(), "line": node.lineno}))

    def visit_If(self, node):
        self._check_branch(node, "if")
        self.generic_visit(node)

    def visit_While(self, node):
        self._check_branch(node, "while")
        self.generic_visit(node)


def lint_source(source: str, rel: str,
                codes: Optional[Set[str]] = None) -> List[Finding]:
    tree = ast.parse(source, filename=rel)
    v = _Visitor(rel, codes)
    v.visit(tree)
    return v.findings


def load_allowlist(path: str = ALLOWLIST_FILE) -> List[Tuple[str, str, str]]:
    """Entries are ``relpath::qualname::CODE`` (comments/# and blanks
    skipped)."""
    entries = []
    if not os.path.exists(path):
        return entries
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split("::")
            if len(parts) != 3:
                raise ValueError(f"malformed allowlist line: {line!r} "
                                 f"(want relpath::qualname::CODE)")
            entries.append((parts[0], parts[1], parts[2]))
    return entries


def _entry_matches(entry, finding: Finding) -> bool:
    rel, qual, code = entry
    if code != finding.code:
        return False
    where = finding.where or ""
    return where.startswith(rel + ":") \
        and finding.data.get("function") == qual


def lint_repo(root: Optional[str] = None,
              dirs: Optional[Sequence[str]] = None,
              allowlist: Optional[Iterable[Tuple[str, str, str]]] = None):
    """Lint the traced-layer dirs (AST001/AST002) and the spec-literal
    dirs (AST003) — each file linted ONCE with the union of the codes
    its directories opt into.  Returns (active_findings,
    allowlisted_findings, unused_allowlist_entries)."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    entries = list(load_allowlist() if allowlist is None else allowlist)
    scopes = ([(d, {"AST001", "AST002"}) for d in LINT_DIRS]
              + [(d, {"AST003"}) for d in SPEC_DIRS]) \
        if dirs is None else [(d, set(ALL_CODES)) for d in dirs]
    per_file: Dict[str, Set[str]] = {}
    for d, codes in scopes:
        base = os.path.join(root, d)
        for dirpath, _, names in sorted(os.walk(base)):
            for name in sorted(names):
                if not name.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, name), root)
                per_file.setdefault(rel, set()).update(codes)
    findings: List[Finding] = []
    for rel in sorted(per_file):
        with open(os.path.join(root, rel)) as f:
            findings.extend(lint_source(f.read(), rel, per_file[rel]))
    active, allowed, used = [], [], set()
    for f in findings:
        hit = next((e for e in entries if _entry_matches(e, f)), None)
        if hit is None:
            active.append(f)
        else:
            used.add(hit)
            f.exemption_id = "::".join(hit)
            allowed.append(f)
    unused = [e for e in entries if e not in used]
    return active, allowed, unused
