"""Inference decode-time attention ops.

Analogs of the reference's LLM-serving attention kernels
(python/paddle/incubate/nn/functional/masked_multihead_attention.py,
block_multihead_attention.py, memory_efficient_attention.py; CUDA kernels
under paddle/phi/kernels/fusion/gpu/). TPU-native shapes:

- ``masked_multihead_attention``: one autoregressive decode step against a
  dense KV cache — the q·Kᵀ row is a [B,H,1,D]×[B,H,T,D] batched matmul
  (MXU-friendly), masked by per-sequence lengths.
- ``block_multihead_attention``: decode against a PAGED cache (blocks +
  per-sequence block tables, the vLLM layout the reference serves with);
  gathers are jnp.take on the block axis, which XLA lowers to dynamic
  slices.
- ``memory_efficient_attention``: full-sequence attention that never
  materializes the [Sq, Sk] matrix — an online-softmax ``lax.scan`` over
  KV chunks (differentiable; the xformers-analog fallback when the Pallas
  flash kernel's shape constraints don't fit).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...ops.registry import register

__all__ = ["masked_multihead_attention", "block_multihead_attention",
           "memory_efficient_attention", "flash_decoding"]


# --------------------------------------------------------------------------
# int8 KV-cache quantization (reference: fused_ops.yaml:46-67
# block_multihead_attention's cache_k/v_quant_scales /
# cache_k/v_dequant_scales / dynamic_cachekv_quant / quant_round_type /
# max_bound / min_bound args; kernel
# paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu)
# --------------------------------------------------------------------------

def _quant_round(x, round_type: int):
    """0 = round-nearest-ties-even; 1 = round-half-away-from-zero (the
    reference's two quant_round_type modes)."""
    if int(round_type) == 0:
        return jnp.rint(x)
    return jnp.sign(x) * jnp.floor(jnp.abs(x) + 0.5)


def quant_to_int8(x, scale, round_type: int = 1, max_bound: float = 127.0,
                  min_bound: float = -127.0):
    """Quantize [..., KVH, D] values with per-head ``scale`` ([KVH]
    static or [B, KVH] dynamic) into int8 cache entries."""
    s = jnp.asarray(scale, jnp.float32)
    if s.ndim == 1:                 # [KVH] -> broadcast over batch
        s = s[None]
    y = _quant_round(x.astype(jnp.float32) * s[..., None], round_type)
    return jnp.clip(y, min_bound, max_bound).astype(jnp.int8)


def _expand_kv_scale_to_q_heads(scale, b, h, kvh):
    """[KVH] or [B, KVH] dequant scale -> [B, H, 1] over the GQA group
    (each q head uses its kv head's scale)."""
    s = jnp.asarray(scale, jnp.float32)
    if s.ndim == 1:
        s = jnp.broadcast_to(s[None], (b, kvh))
    return jnp.repeat(s, h // kvh, axis=1)[..., None]   # [B, H, 1]


def _dynamic_absmax_scales(x, max_bound=127.0):
    """Per-(batch, head) dynamic quant scales from the new token's
    absmax: quant = bound/absmax, dequant = absmax/bound (the
    dynamic_cachekv_quant mode computes scales on the fly)."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)   # [B, KVH]
    absmax = jnp.maximum(absmax, 1e-6)
    return max_bound / absmax, absmax / max_bound


def flash_decoding(q, k_cache, v_cache, seq_lens, scale=None):
    """Pallas flash-decoding step (ops/pallas/decode_attention.py): one
    query token per sequence against a dense KV cache, HBM traffic
    scaling with the actual ``seq_lens`` rather than the cache capacity.
    q [B, H, D]; k_cache/v_cache [B, KVH, T, D] (GQA group-major);
    seq_lens [B] = valid rows.  Returns [B, H, D]."""
    from ...ops.pallas.decode_attention import flash_decoding_op

    return flash_decoding_op(q, k_cache, v_cache, seq_lens, scale=scale)


@register("masked_multihead_attention", amp="white")
def _mmha_op(x, cache_kv, seq_lens, rotary_embs=None, *, num_heads: int,
             head_dim: int, scale=None, cache_k_quant_scales=None,
             cache_v_quant_scales=None, cache_k_dequant_scales=None,
             cache_v_dequant_scales=None, quant_round_type=1,
             max_bound=127.0, min_bound=-127.0):
    """One decode step. x [B, 3*H*D] fused qkv; cache_kv [2, B, H, T, D]
    (bf16/f32 or INT8 with the cache_*_scales quant args); seq_lens [B]
    current lengths (new token is written at that offset).
    Returns (out [B, H*D], new_cache_kv)."""
    b = x.shape[0]
    h, d = num_heads, head_dim
    qkv = x.reshape(b, 3, h, d)
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]           # [B, H, D]
    if rotary_embs is not None:
        cos, sin = rotary_embs                          # [B, D] each
        def rot(t):
            t1, t2 = jnp.split(t, 2, axis=-1)
            rotated = jnp.concatenate([-t2, t1], axis=-1)
            return t * cos[:, None, :] + rotated * sin[:, None, :]
        q, k = rot(q), rot(k)
    quantized = cache_kv.dtype == jnp.int8
    if quantized:
        if cache_k_quant_scales is None or cache_v_quant_scales is None \
                or cache_k_dequant_scales is None \
                or cache_v_dequant_scales is None:
            raise ValueError(
                "int8 KV cache needs cache_k/v_quant_scales AND "
                "cache_k/v_dequant_scales (reference "
                "masked_multihead_attention cachekv_quant contract)")
        k = quant_to_int8(k, cache_k_quant_scales, quant_round_type,
                          max_bound, min_bound)
        v = quant_to_int8(v, cache_v_quant_scales, quant_round_type,
                          max_bound, min_bound)
    bidx = jnp.arange(b)
    kc = cache_kv[0].at[bidx, :, seq_lens, :].set(k)    # [B, H, T, D]
    vc = cache_kv[1].at[bidx, :, seq_lens, :].set(v)
    # attention itself is the Pallas flash-decoding kernel: KV streamed
    # once with online softmax, HBM traffic bounded by seq_lens not T
    # (int8 caches stream HALF the bytes; dequant scales fold into q and
    # the output — see block_multihead_attention)
    from ...ops.pallas.decode_attention import flash_decode_raw

    qk = q
    if quantized:
        qk = (q.astype(jnp.float32) * _expand_kv_scale_to_q_heads(
            cache_k_dequant_scales, b, h, h)).astype(q.dtype)
    out = flash_decode_raw(qk, kc, vc, seq_lens + 1, scale=scale)
    if quantized:
        out = out.astype(jnp.float32) * _expand_kv_scale_to_q_heads(
            cache_v_dequant_scales, b, h, h)
    return (out.reshape(b, h * d).astype(x.dtype),
            jnp.stack([kc, vc], axis=0))


# reference-name alias: the _-suffixed (inplace-signature) op variant
# (paddle/phi/ops/yaml/ops.yaml masked_multihead_attention_) — same
# math; "inplace" is a buffer-reuse contract XLA donation handles
register("masked_multihead_attention_", amp="white")(_mmha_op.raw_fn)


def masked_multihead_attention(x, cache_kv, seq_lens, rotary_embs=None,
                               num_heads: Optional[int] = None,
                               head_dim: Optional[int] = None, scale=None,
                               cache_k_quant_scales=None,
                               cache_v_quant_scales=None,
                               cache_k_dequant_scales=None,
                               cache_v_dequant_scales=None,
                               quant_round_type=1, max_bound=127.0,
                               min_bound=-127.0, **kw):
    """Public wrapper (reference masked_multihead_attention_): infers
    (num_heads, head_dim) from the cache when not given."""
    if num_heads is None:
        num_heads = cache_kv.shape[2]
    if head_dim is None:
        head_dim = cache_kv.shape[-1]
    return _mmha_op(x, cache_kv, seq_lens, rotary_embs,
                    num_heads=num_heads, head_dim=head_dim, scale=scale,
                    cache_k_quant_scales=cache_k_quant_scales,
                    cache_v_quant_scales=cache_v_quant_scales,
                    cache_k_dequant_scales=cache_k_dequant_scales,
                    cache_v_dequant_scales=cache_v_dequant_scales,
                    quant_round_type=quant_round_type,
                    max_bound=max_bound, min_bound=min_bound)


@register("block_multihead_attention", amp="white")
def _block_mha_op(qkv, key_cache, value_cache, seq_lens, block_tables, *,
                  scale=None, cache_k_quant_scales=None,
                  cache_v_quant_scales=None, cache_k_dequant_scales=None,
                  cache_v_dequant_scales=None, quant_round_type=1,
                  max_bound=127.0, min_bound=-127.0):
    """Paged decode step.

    qkv [B, 3, H, D]; key/value_cache [NBlocks, H, BS, D] (bf16/f32, or
    INT8 with the cache_*_scales quant args — the serving memory-bound
    path where int8 halves the cache stream); seq_lens [B] (tokens
    already in cache); block_tables [B, MaxBlocksPerSeq] int32 (-1 =
    unused).  Writes the new token then attends over the pages.
    Returns (out [B, H, D], key_cache, value_cache)."""
    b, _, h, d = qkv.shape
    kvh = key_cache.shape[1]
    bs = key_cache.shape[2]
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
    quantized = key_cache.dtype == jnp.int8
    if quantized:
        if cache_k_quant_scales is None or cache_v_quant_scales is None \
                or cache_k_dequant_scales is None \
                or cache_v_dequant_scales is None:
            raise ValueError(
                "int8 KV cache needs cache_k/v_quant_scales AND "
                "cache_k/v_dequant_scales ([num_head] static or "
                "[batch, num_head] dynamic — reference fused_ops.yaml "
                "block_multihead_attention)")
        kq = quant_to_int8(k, cache_k_quant_scales, quant_round_type,
                           max_bound, min_bound)
        vq = quant_to_int8(v, cache_v_quant_scales, quant_round_type,
                           max_bound, min_bound)
    else:
        kq, vq = k, v
    # write the new token into its page slot
    blk_idx = seq_lens // bs
    slot = seq_lens % bs
    bidx = jnp.arange(b)
    phys = block_tables[bidx, blk_idx]                  # [B]
    key_cache = key_cache.at[phys, :, slot, :].set(kq)
    value_cache = value_cache.at[phys, :, slot, :].set(vq)
    # attention via the Pallas paged kernel: the page indirection lives
    # in the DMA index map — no gathered [B, MB, H, BS, D] copy.  The
    # per-head dequant scales fold OUTSIDE the kernel: k's into q (they
    # multiply q·k^T linearly), v's into the output — the kernel only
    # widens int8 blocks after the (halved) DMA.
    from ...ops.pallas.decode_attention import paged_decode_raw

    qk = q
    if quantized:
        qk = (q.astype(jnp.float32) * _expand_kv_scale_to_q_heads(
            cache_k_dequant_scales, b, h, kvh)).astype(q.dtype)
    out = paged_decode_raw(qk, key_cache, value_cache, seq_lens + 1,
                           block_tables, scale=scale)
    if quantized:
        out = out.astype(jnp.float32) * _expand_kv_scale_to_q_heads(
            cache_v_dequant_scales, b, h, kvh)
    return out.astype(qkv.dtype), key_cache, value_cache


def block_multihead_attention(qkv, key_cache, value_cache, seq_lens,
                              block_tables, scale=None,
                              cache_k_quant_scales=None,
                              cache_v_quant_scales=None,
                              cache_k_dequant_scales=None,
                              cache_v_dequant_scales=None,
                              use_dynamic_cachekv_quant=False,
                              quant_round_type=1, max_bound=127.0,
                              min_bound=-127.0, **kw):
    """Reference-parity entry (incubate/nn/functional/
    block_multihead_attention.py): static scales are [num_head]; with
    ``use_dynamic_cachekv_quant`` the caller maintains [batch, num_head]
    running-absmax scales (helper: ``_dynamic_absmax_scales``) — the
    running-max contract means a sequence's whole cache is covered by its
    current scale.  The flag is validated against the scale RANK so a
    mode/shape mismatch fails loudly instead of mis-broadcasting."""
    if key_cache.dtype == jnp.int8 and cache_k_quant_scales is not None:
        want = 2 if use_dynamic_cachekv_quant else 1
        for nm, s in (("cache_k_quant_scales", cache_k_quant_scales),
                      ("cache_v_quant_scales", cache_v_quant_scales),
                      ("cache_k_dequant_scales", cache_k_dequant_scales),
                      ("cache_v_dequant_scales", cache_v_dequant_scales)):
            if s is not None and jnp.ndim(s) != want:
                raise ValueError(
                    f"{nm}: expected rank {want} "
                    f"({'[batch, num_head] dynamic' if want == 2 else '[num_head] static'}"
                    f" — use_dynamic_cachekv_quant={use_dynamic_cachekv_quant}),"
                    f" got shape {jnp.shape(s)}")
    return _block_mha_op(qkv, key_cache, value_cache, seq_lens,
                         block_tables, scale=scale,
                         cache_k_quant_scales=cache_k_quant_scales,
                         cache_v_quant_scales=cache_v_quant_scales,
                         cache_k_dequant_scales=cache_k_dequant_scales,
                         cache_v_dequant_scales=cache_v_dequant_scales,
                         quant_round_type=quant_round_type,
                         max_bound=max_bound, min_bound=min_bound)


@register("memory_efficient_attention", amp="white")
def _mea_op(query, key, value, attn_bias=None, *, p: float = 0.0,
            scale=None, causal: bool = False, chunk: int = 512):
    """Online-softmax attention over KV chunks; [B, S, H, D] layout.
    Never materializes [Sq, Sk]; O(Sq * chunk) working set."""
    b, sq, h, d = query.shape
    sk = key.shape[1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    qt = jnp.moveaxis(query, 1, 2).astype(jnp.float32) * scale  # [B,H,Sq,D]
    kt = jnp.moveaxis(key, 1, 2).astype(jnp.float32)
    vt = jnp.moveaxis(value, 1, 2).astype(jnp.float32)
    nchunk = -(-sk // chunk)
    pad = nchunk * chunk - sk
    if pad:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, pad), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, pad), (0, 0)))
        if attn_bias is not None:
            attn_bias = jnp.pad(attn_bias, ((0, 0),) * (attn_bias.ndim - 1)
                                + ((0, pad),), constant_values=-jnp.inf)
    kcs = kt.reshape(b, h, nchunk, chunk, d)
    vcs = vt.reshape(b, h, nchunk, chunk, d)

    def step(carry, inputs):
        m, l, acc = carry
        kc, vc, j = inputs
        s = jnp.einsum("bhqd,bhkd->bhqk", qt, kc)       # [B,H,Sq,chunk]
        kpos = j * chunk + jnp.arange(chunk)
        valid = kpos < sk
        if attn_bias is not None:
            bias = jax.lax.dynamic_slice_in_dim(
                attn_bias, j * chunk, chunk, axis=attn_bias.ndim - 1)
            s = s + bias.astype(jnp.float32)
        if causal:
            qpos = jnp.arange(sq)
            s = jnp.where(qpos[None, None, :, None] >= kpos[None, None,
                                                           None, :],
                          s, -jnp.inf)
        s = jnp.where(valid[None, None, None, :], s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(-1))
        alpha = jnp.exp(m - m_new)
        pchunk = jnp.exp(s - m_new[..., None])
        l_new = l * alpha + pchunk.sum(-1)
        acc_new = acc * alpha[..., None] + jnp.einsum("bhqk,bhkd->bhqd",
                                                      pchunk, vc)
        return (m_new, l_new, acc_new), None

    init = (jnp.full((b, h, sq), -jnp.inf),
            jnp.zeros((b, h, sq)),
            jnp.zeros((b, h, sq, d)))
    kcs_t = jnp.moveaxis(kcs, 2, 0)                     # [n, B, H, chunk, D]
    vcs_t = jnp.moveaxis(vcs, 2, 0)
    (m, l, acc), _ = jax.lax.scan(step, init,
                                  (kcs_t, vcs_t, jnp.arange(nchunk)))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.moveaxis(out, 1, 2).astype(query.dtype)  # [B, Sq, H, D]


def memory_efficient_attention(query, key, value, attn_bias=None, p=0.0,
                               scale=None, training=True, causal=False,
                               chunk=None, **kw):
    """xformers-style memory-efficient attention (reference
    incubate/nn/functional/memory_efficient_attention.py); dropout ``p``
    is accepted for parity (inference path ignores it).  The KV chunk
    size defaults to FLAGS_multi_block_attention_min_partition_size
    (the GPU multi-block decode partition knob)."""
    if chunk is None:
        from ...common import flags as _flags

        chunk = int(_flags.get_flag(
            "FLAGS_multi_block_attention_min_partition_size"))
    return _mea_op(query, key, value, attn_bias, p=p, scale=scale,
                   causal=causal, chunk=chunk)
