"""Inference decode-time attention ops.

Analogs of the reference's LLM-serving attention kernels
(python/paddle/incubate/nn/functional/masked_multihead_attention.py,
block_multihead_attention.py, memory_efficient_attention.py; CUDA kernels
under paddle/phi/kernels/fusion/gpu/). TPU-native shapes:

- ``masked_multihead_attention``: one autoregressive decode step against a
  dense KV cache — the q·Kᵀ row is a [B,H,1,D]×[B,H,T,D] batched matmul
  (MXU-friendly), masked by per-sequence lengths.
- ``block_multihead_attention``: decode against a PAGED cache (blocks +
  per-sequence block tables, the vLLM layout the reference serves with);
  gathers are jnp.take on the block axis, which XLA lowers to dynamic
  slices.
- ``memory_efficient_attention``: full-sequence attention that never
  materializes the [Sq, Sk] matrix — an online-softmax ``lax.scan`` over
  KV chunks (differentiable; the xformers-analog fallback when the Pallas
  flash kernel's shape constraints don't fit).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...ops.registry import register

__all__ = ["masked_multihead_attention", "block_multihead_attention",
           "memory_efficient_attention", "flash_decoding"]


def flash_decoding(q, k_cache, v_cache, seq_lens, scale=None):
    """Pallas flash-decoding step (ops/pallas/decode_attention.py): one
    query token per sequence against a dense KV cache, HBM traffic
    scaling with the actual ``seq_lens`` rather than the cache capacity.
    q [B, H, D]; k_cache/v_cache [B, KVH, T, D] (GQA group-major);
    seq_lens [B] = valid rows.  Returns [B, H, D]."""
    from ...ops.pallas.decode_attention import flash_decoding_op

    return flash_decoding_op(q, k_cache, v_cache, seq_lens, scale=scale)


@register("masked_multihead_attention", amp="white")
def _mmha_op(x, cache_kv, seq_lens, rotary_embs=None, *, num_heads: int,
             head_dim: int, scale=None):
    """One decode step. x [B, 3*H*D] fused qkv; cache_kv [2, B, H, T, D];
    seq_lens [B] current lengths (new token is written at that offset).
    Returns (out [B, H*D], new_cache_kv)."""
    b = x.shape[0]
    h, d = num_heads, head_dim
    qkv = x.reshape(b, 3, h, d)
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]           # [B, H, D]
    if rotary_embs is not None:
        cos, sin = rotary_embs                          # [B, D] each
        def rot(t):
            t1, t2 = jnp.split(t, 2, axis=-1)
            rotated = jnp.concatenate([-t2, t1], axis=-1)
            return t * cos[:, None, :] + rotated * sin[:, None, :]
        q, k = rot(q), rot(k)
    bidx = jnp.arange(b)
    kc = cache_kv[0].at[bidx, :, seq_lens, :].set(k)    # [B, H, T, D]
    vc = cache_kv[1].at[bidx, :, seq_lens, :].set(v)
    # attention itself is the Pallas flash-decoding kernel: KV streamed
    # once with online softmax, HBM traffic bounded by seq_lens not T
    from ...ops.pallas.decode_attention import flash_decode_raw

    out = flash_decode_raw(q, kc, vc, seq_lens + 1, scale=scale)
    return (out.reshape(b, h * d).astype(x.dtype),
            jnp.stack([kc, vc], axis=0))


# reference-name alias: the _-suffixed (inplace-signature) op variant
# (paddle/phi/ops/yaml/ops.yaml masked_multihead_attention_) — same
# math; "inplace" is a buffer-reuse contract XLA donation handles
register("masked_multihead_attention_", amp="white")(_mmha_op.raw_fn)


def masked_multihead_attention(x, cache_kv, seq_lens, rotary_embs=None,
                               num_heads: Optional[int] = None,
                               head_dim: Optional[int] = None, scale=None,
                               **kw):
    """Public wrapper (reference masked_multihead_attention_): infers
    (num_heads, head_dim) from the cache when not given."""
    if num_heads is None:
        num_heads = cache_kv.shape[2]
    if head_dim is None:
        head_dim = cache_kv.shape[-1]
    return _mmha_op(x, cache_kv, seq_lens, rotary_embs,
                    num_heads=num_heads, head_dim=head_dim, scale=scale)


@register("block_multihead_attention", amp="white")
def _block_mha_op(qkv, key_cache, value_cache, seq_lens, block_tables, *,
                  scale=None):
    """Paged decode step.

    qkv [B, 3, H, D]; key/value_cache [NBlocks, H, BS, D]; seq_lens [B]
    (tokens already in cache); block_tables [B, MaxBlocksPerSeq] int32
    (-1 = unused). Writes the new token then attends over the pages.
    Returns (out [B, H, D], key_cache, value_cache)."""
    b, _, h, d = qkv.shape
    bs = key_cache.shape[2]
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
    # write the new token into its page slot
    blk_idx = seq_lens // bs
    slot = seq_lens % bs
    bidx = jnp.arange(b)
    phys = block_tables[bidx, blk_idx]                  # [B]
    key_cache = key_cache.at[phys, :, slot, :].set(k)
    value_cache = value_cache.at[phys, :, slot, :].set(v)
    # attention via the Pallas paged kernel: the page indirection lives
    # in the DMA index map — no gathered [B, MB, H, BS, D] copy
    from ...ops.pallas.decode_attention import paged_decode_raw

    out = paged_decode_raw(q, key_cache, value_cache, seq_lens + 1,
                           block_tables, scale=scale)
    return out.astype(qkv.dtype), key_cache, value_cache


def block_multihead_attention(qkv, key_cache, value_cache, seq_lens,
                              block_tables, scale=None, **kw):
    return _block_mha_op(qkv, key_cache, value_cache, seq_lens,
                         block_tables, scale=scale)


@register("memory_efficient_attention", amp="white")
def _mea_op(query, key, value, attn_bias=None, *, p: float = 0.0,
            scale=None, causal: bool = False, chunk: int = 512):
    """Online-softmax attention over KV chunks; [B, S, H, D] layout.
    Never materializes [Sq, Sk]; O(Sq * chunk) working set."""
    b, sq, h, d = query.shape
    sk = key.shape[1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    qt = jnp.moveaxis(query, 1, 2).astype(jnp.float32) * scale  # [B,H,Sq,D]
    kt = jnp.moveaxis(key, 1, 2).astype(jnp.float32)
    vt = jnp.moveaxis(value, 1, 2).astype(jnp.float32)
    nchunk = -(-sk // chunk)
    pad = nchunk * chunk - sk
    if pad:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, pad), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, pad), (0, 0)))
        if attn_bias is not None:
            attn_bias = jnp.pad(attn_bias, ((0, 0),) * (attn_bias.ndim - 1)
                                + ((0, pad),), constant_values=-jnp.inf)
    kcs = kt.reshape(b, h, nchunk, chunk, d)
    vcs = vt.reshape(b, h, nchunk, chunk, d)

    def step(carry, inputs):
        m, l, acc = carry
        kc, vc, j = inputs
        s = jnp.einsum("bhqd,bhkd->bhqk", qt, kc)       # [B,H,Sq,chunk]
        kpos = j * chunk + jnp.arange(chunk)
        valid = kpos < sk
        if attn_bias is not None:
            bias = jax.lax.dynamic_slice_in_dim(
                attn_bias, j * chunk, chunk, axis=attn_bias.ndim - 1)
            s = s + bias.astype(jnp.float32)
        if causal:
            qpos = jnp.arange(sq)
            s = jnp.where(qpos[None, None, :, None] >= kpos[None, None,
                                                           None, :],
                          s, -jnp.inf)
        s = jnp.where(valid[None, None, None, :], s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(-1))
        alpha = jnp.exp(m - m_new)
        pchunk = jnp.exp(s - m_new[..., None])
        l_new = l * alpha + pchunk.sum(-1)
        acc_new = acc * alpha[..., None] + jnp.einsum("bhqk,bhkd->bhqd",
                                                      pchunk, vc)
        return (m_new, l_new, acc_new), None

    init = (jnp.full((b, h, sq), -jnp.inf),
            jnp.zeros((b, h, sq)),
            jnp.zeros((b, h, sq, d)))
    kcs_t = jnp.moveaxis(kcs, 2, 0)                     # [n, B, H, chunk, D]
    vcs_t = jnp.moveaxis(vcs, 2, 0)
    (m, l, acc), _ = jax.lax.scan(step, init,
                                  (kcs_t, vcs_t, jnp.arange(nchunk)))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.moveaxis(out, 1, 2).astype(query.dtype)  # [B, Sq, H, D]


def memory_efficient_attention(query, key, value, attn_bias=None, p=0.0,
                               scale=None, training=True, causal=False,
                               chunk=None, **kw):
    """xformers-style memory-efficient attention (reference
    incubate/nn/functional/memory_efficient_attention.py); dropout ``p``
    is accepted for parity (inference path ignores it).  The KV chunk
    size defaults to FLAGS_multi_block_attention_min_partition_size
    (the GPU multi-block decode partition knob)."""
    if chunk is None:
        from ...common import flags as _flags

        chunk = int(_flags.get_flag(
            "FLAGS_multi_block_attention_min_partition_size"))
    return _mea_op(query, key, value, attn_bias, p=p, scale=scale,
                   causal=causal, chunk=chunk)
