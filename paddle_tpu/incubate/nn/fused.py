"""Fused LLM ops (analog of python/paddle/incubate/nn/functional/:
fused_rms_norm.py, fused_layer_norm.py, fused_rotary_position_embedding.py,
swiglu.py, fused_matmul_bias.py).

On TPU "fusion" is XLA's job: these are single jnp expressions that XLA
fuses into one kernel; the Pallas variants (paddle_tpu.ops.pallas) replace
them on hot paths when profitable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ...ops.registry import register, dispatch


@register("fused_rms_norm", amp="black")
def _fused_rms_norm_op(x, weight=None, epsilon=1e-6):
    if weight is not None:
        # custom-vjp path: saves rrms so the backward's dw/dx reductions
        # stay single-level (see ops/nn_ops._rms_norm_weighted_bwd — the
        # autodiff fusion re-derived var inside the cross-token dw reduce
        # at ~20% of the whole 574M bench step)
        from ...ops.nn_ops import _rms_norm_weighted

        return _rms_norm_weighted(x, jnp.asarray(weight), float(epsilon))
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    return (xf * lax.rsqrt(var + epsilon)).astype(dtype)


def fused_rms_norm(x, weight=None, epsilon=1e-6):
    return dispatch("fused_rms_norm", x, weight, epsilon=epsilon)


@register("fused_layer_norm", amp="black")
def _fused_layer_norm_op(x, weight=None, bias=None, epsilon=1e-5,
                         residual=None):
    if residual is not None:
        x = x + residual
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    out = (xf - mean) * lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(dtype)


def fused_layer_norm(x, weight=None, bias=None, epsilon=1e-5, residual=None):
    return dispatch("fused_layer_norm", x, weight, bias, epsilon=epsilon,
                    residual=residual)


@register("swiglu")
def _swiglu_op(x, y=None):
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(x) * y


def swiglu(x, y=None):
    return dispatch("swiglu", x, y)


def _rope_rotate_half(x):
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


@register("fused_rotary_position_embedding")
def _fused_rope_op(q, k=None, v=None, sin=None, cos=None, position_ids=None,
                   use_neox_rotary_style=True):
    """Rotary embedding; layout (batch, seq, heads, head_dim).
    Reference: fused_rotary_position_embedding.py (incubate).

    sin/cos are cast to q's dtype before the rotation: the rope tables
    are precomputed fp32 buffers, and mixed-dtype multiply would PROMOTE
    bf16 q/k to fp32 — from where the upcast propagates through
    attention, the residual stream, and the whole backward (the Graph
    Doctor's dtype audit flagged exactly this: DT001 fp32 matmuls across
    every layer of a declared-bf16 train step; the serving path's
    _apply_rope already cast at its call site).  bf16 rope phases are
    standard practice — the angle tables quantize once, not per step."""
    if sin is not None and q is not None:
        sin = sin.astype(q.dtype)
    if cos is not None and q is not None:
        cos = cos.astype(q.dtype)

    def apply(x):
        if x is None:
            return None
        if use_neox_rotary_style:
            return x * cos + _rope_rotate_half(x) * sin
        # interleaved (GPT-J) style
        x1 = x[..., ::2]
        x2 = x[..., 1::2]
        c = cos[..., ::2]
        s = sin[..., ::2]
        o1 = x1 * c - x2 * s
        o2 = x2 * c + x1 * s
        return jnp.stack([o1, o2], axis=-1).reshape(x.shape)

    return tuple(r for r in (apply(q), apply(k), apply(v)) if r is not None)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True):
    outs = dispatch("fused_rotary_position_embedding", q, k, v, sin=sin, cos=cos,
                    position_ids=position_ids,
                    use_neox_rotary_style=use_neox_rotary_style)
    return outs


@register("fused_matmul_bias", amp="white")
def _fused_matmul_bias_op(x, y, bias=None, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y)
    if bias is not None:
        out = out + bias
    return out


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False):
    return dispatch("fused_matmul_bias", x, y, bias,
                    transpose_x=transpose_x, transpose_y=transpose_y)


@register("fused_linear_activation", amp="white")
def _fused_linear_activation_op(x, y, bias=None, activation="gelu"):
    out = jnp.matmul(x, y)
    if bias is not None:
        out = out + bias
    if activation == "gelu":
        return jax.nn.gelu(out)
    if activation == "relu":
        return jax.nn.relu(out)
    return out


def fused_linear_activation(x, y, bias=None, activation="gelu"):
    return dispatch("fused_linear_activation", x, y, bias, activation=activation)


@register("fused_moe", amp="white")
def _fused_moe_op(x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias=None,
                  ffn2_bias=None, moe_topk=2, norm_topk_prob=True,
                  activation="gelu"):
    """Reference: python/paddle/incubate/nn/functional/fused_moe.py — the
    fused inference-path MoE FFN (gate -> top-k -> expert FFNs ->
    weighted combine) with NO token dropping.  TPU formulation: dense
    per-expert evaluation (every expert runs every token on the MXU,
    cost E x FFN) + a scatter of normalized top-k weights; exact and
    fusion-friendly at decode/inference scales.  Capacity-based
    EP-sharded training should use MoELayer (moe_forward op) instead.

    x [..., m]; gate_weight [m, E]; ffn1_weight [E, m, h] (2h for
    swiglu); ffn2_weight [E, h, m]."""
    orig = x.shape
    m = orig[-1]
    x2 = x.reshape(-1, m)
    g = x2.shape[0]
    e = gate_weight.shape[-1]
    logits = x2.astype(jnp.float32) @ gate_weight.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    topv, topi = jax.lax.top_k(probs, moe_topk)          # [G, K]
    if norm_topk_prob:
        topv = topv / jnp.maximum(topv.sum(-1, keepdims=True), 1e-12)
    h = jnp.einsum("gm,emh->egh", x2, ffn1_weight)
    if ffn1_bias is not None:
        h = h + ffn1_bias[:, None, :]
    if activation == "gelu":
        h = jax.nn.gelu(h)
    elif activation == "relu":
        h = jax.nn.relu(h)
    elif activation == "swiglu":
        a, b = jnp.split(h, 2, axis=-1)
        h = jax.nn.silu(a) * b
    else:
        raise ValueError(f"fused_moe: unsupported activation "
                         f"{activation!r} (gelu | relu | swiglu)")
    eo = jnp.einsum("egh,ehm->egm", h, ffn2_weight)
    if ffn2_bias is not None:
        eo = eo + ffn2_bias[:, None, :]
    w_full = jnp.zeros((g, e), jnp.float32).at[
        jnp.arange(g)[:, None], topi].add(topv)
    y = jnp.einsum("ge,egm->gm", w_full.astype(x.dtype), eo)
    return y.reshape(orig)


def fused_moe(x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias=None,
              ffn2_bias=None, moe_topk=2, norm_topk_prob=True,
              activation="gelu"):
    return dispatch("fused_moe", x, gate_weight, ffn1_weight, ffn2_weight,
                    ffn1_bias, ffn2_bias, moe_topk=moe_topk,
                    norm_topk_prob=norm_topk_prob, activation=activation)
