from . import attention, decode_attention, fused
from .decode_attention import (
    block_multihead_attention, flash_decoding, masked_multihead_attention,
    memory_efficient_attention,
)
from .fused import (
    fused_layer_norm, fused_linear_activation, fused_matmul_bias,
    fused_moe, fused_rms_norm, fused_rotary_position_embedding, swiglu,
)
from .attention import flash_attention
from .fused_transformer import FusedMultiTransformer

# paddle-compat namespace: paddle.incubate.nn.functional.* (name-complete
# vs the reference functional __init__, incl. the round-5 serving tail)
from . import functional
from .functional import (blha_get_max_len, fused_bias_act,
                         fused_bias_dropout_residual_layer_norm,
                         fused_dropout_add, fused_feedforward,
                         fused_gate_attention, fused_linear,
                         fused_multi_head_attention,
                         variable_length_memory_efficient_attention)
