from . import attention, decode_attention, fused
from .decode_attention import (
    block_multihead_attention, flash_decoding, masked_multihead_attention,
    memory_efficient_attention,
)
from .fused import (
    fused_layer_norm, fused_linear_activation, fused_matmul_bias,
    fused_moe, fused_rms_norm, fused_rotary_position_embedding, swiglu,
)
from .attention import flash_attention
from .fused_transformer import FusedMultiTransformer

# paddle-compat namespace: paddle.incubate.nn.functional.*
from . import fused as functional
