"""Flash attention entry point.

Analog of the reference's FlashAttention integration
(paddle/phi/kernels/gpu/flash_attn_kernel.cu +
python/paddle/nn/functional/flash_attention.py:195). On TPU the fused
attention kernel is a Pallas kernel (paddle_tpu/ops/pallas/flash_attention.py);
on CPU (tests) or when Pallas is unavailable we fall back to the XLA softmax
path, which XLA still fuses well.
"""

from __future__ import annotations

import jax

from ...ops.registry import dispatch

_PALLAS_OK = None
_WARNED_FALLBACK = False


def _pallas_available() -> bool:
    global _PALLAS_OK
    if _PALLAS_OK is None:
        _PALLAS_OK = jax.default_backend() in ("tpu", "axon")
    return _PALLAS_OK


def _as_padding_segments(attn_mask, query, key):
    """A BOOLEAN [b, sk] (or [b, 1, 1, sk]) keep-mask maps onto the
    kernel's segment ids (valid=1, pad=0); anything else returns None and
    takes the XLA path.  Bool-only on purpose: integer/float masks are
    ADDITIVE in the XLA path (sdpa semantics), so routing them as keep
    masks would change numerics between backends."""
    m = attn_mask._value if hasattr(attn_mask, "_value") else attn_mask
    import jax.numpy as jnp

    if m.ndim == 4 and m.shape[1] == 1 and m.shape[2] == 1:
        m = m[:, 0, 0]
    if m.ndim != 2 or m.shape != (key.shape[0], key.shape[1]):
        return None
    if not jnp.issubdtype(m.dtype, jnp.bool_):
        return None
    if query.shape[1] != key.shape[1]:
        return None
    return m.astype(jnp.int32)


def flash_attention(query, key, value, causal=False, dropout=0.0,
                    attn_mask=None, scale=None, q_segment_ids=None,
                    kv_segment_ids=None):
    """(batch, seq, heads, head_dim) attention, flash-style.  GQA (fewer
    kv heads) is accepted: the Pallas kernel routes q heads to kv groups
    natively; the XLA fallback repeats kv heads.  A [b, sk] boolean
    padding mask — or explicit int [b, s] segment ids (sequence packing)
    — rides the Pallas path splash-attention style; arbitrary additive
    masks and dropout use the XLA path."""
    if (q_segment_ids is None) != (kv_segment_ids is None):
        raise ValueError("q_segment_ids and kv_segment_ids must be given "
                         "together")
    if q_segment_ids is not None:
        if attn_mask is not None:
            raise ValueError("pass either attn_mask or segment ids, "
                             "not both")
        import jax.numpy as jnp

        qsv = q_segment_ids._value if hasattr(q_segment_ids, "_value") \
            else jnp.asarray(q_segment_ids)
        ksv = kv_segment_ids._value if hasattr(kv_segment_ids, "_value") \
            else jnp.asarray(kv_segment_ids)
        seg_pair = (qsv.astype(jnp.int32), ksv.astype(jnp.int32))
    else:
        seg_pair = None
    if attn_mask is not None and dropout == 0.0:
        seg = _as_padding_segments(attn_mask, query, key)
        if seg is not None:
            # the bool keep-mask is fully expressed as segment ids from
            # here on (both backends use the same equality semantics)
            seg_pair = (seg, seg)
            attn_mask = None
    if _pallas_available() and dropout == 0.0 and attn_mask is None:
        try:
            from ...ops.pallas.flash_attention import (FlashUnsupportedError,
                                                       flash_attention_op)

            if seg_pair is not None:
                from ...core.tensor import Tensor as _T

                return dispatch("pallas_flash_attention", query, key, value,
                                q_segment_ids=_T(seg_pair[0]),
                                kv_segment_ids=_T(seg_pair[1]),
                                causal=causal, scale=scale)
            return dispatch("pallas_flash_attention", query, key, value,
                            causal=causal, scale=scale)
        except (ImportError, FlashUnsupportedError):
            # expected unsupported cases (e.g. causal sq != sk decode
            # shapes) — the XLA path handles them
            pass
        except Exception:
            # a real kernel regression must not silently become a ~12x
            # slowdown: warn once, then fall back — unless
            # FLAGS_enable_api_kernel_fallback=false (the phi
            # fallback-to-CPU-kernel gate), which makes it raise
            from ...common import flags as _flags

            if not _flags.get_flag("FLAGS_enable_api_kernel_fallback"):
                raise
            global _WARNED_FALLBACK
            if not _WARNED_FALLBACK:
                _WARNED_FALLBACK = True
                import logging
                import traceback

                logging.getLogger(__name__).warning(
                    "Pallas flash attention failed unexpectedly; falling "
                    "back to the XLA softmax path:\n%s",
                    traceback.format_exc())
    rep = query.shape[2] // key.shape[2]
    if rep > 1:
        from ...ops.manip import repeat_interleave

        key = repeat_interleave(key, rep, axis=2)
        value = repeat_interleave(value, rep, axis=2)
    if attn_mask is None and seg_pair is not None:
        # segment ids on the XLA path: the same equality semantics the
        # Pallas kernel applies (bool keep-masks were folded into
        # seg_pair above, so this is the single masked-fallback branch)
        from ...core.tensor import Tensor

        attn_mask = Tensor(
            (seg_pair[0][:, :, None] == seg_pair[1][:, None, :])[:, None])
    elif attn_mask is not None:
        # masks _as_padding_segments rejected: decode shapes (sq != sk)
        # with a [b, sk] bool keep-mask normalize to the broadcastable
        # form; anything else (additive float/4-D) passes through as-is
        from ...core.tensor import Tensor
        import jax.numpy as jnp

        mv = attn_mask._value if isinstance(attn_mask, Tensor) else \
            jnp.asarray(attn_mask)
        if mv.ndim == 4 and mv.shape[1] == 1 and mv.shape[2] == 1 \
                and jnp.issubdtype(mv.dtype, jnp.bool_):
            mv = mv[:, 0, 0]
        if jnp.issubdtype(mv.dtype, jnp.bool_) and mv.ndim == 2 \
                and mv.shape == (key.shape[0], key.shape[1]):
            # every decode query is a live token; only keys carry padding
            attn_mask = Tensor(mv[:, None, None, :])
    dropout_mask = None
    if dropout > 0.0:
        from ...core.tensor import Tensor
        from ...ops import random as _random
        import jax.numpy as jnp

        b, sq, h, _ = query.shape
        sk = key.shape[1]
        k_ = _random.default_generator().next_key()
        dropout_mask = Tensor(jax.random.bernoulli(k_, 1.0 - dropout, (b, h, sq, sk)))
    return dispatch("scaled_dot_product_attention", query, key, value,
                    attn_mask=attn_mask, dropout_mask=dropout_mask,
                    dropout_p=dropout, is_causal=causal, scale=scale)
