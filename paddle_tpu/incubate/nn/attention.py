"""Flash attention entry point.

Analog of the reference's FlashAttention integration
(paddle/phi/kernels/gpu/flash_attn_kernel.cu +
python/paddle/nn/functional/flash_attention.py:195). On TPU the fused
attention kernel is a Pallas kernel (paddle_tpu/ops/pallas/flash_attention.py);
on CPU (tests) or when Pallas is unavailable we fall back to the XLA softmax
path, which XLA still fuses well.
"""

from __future__ import annotations

import jax

from ...ops.registry import dispatch

_PALLAS_OK = None
_WARNED_FALLBACK = False


def _pallas_available() -> bool:
    global _PALLAS_OK
    if _PALLAS_OK is None:
        _PALLAS_OK = jax.default_backend() in ("tpu", "axon")
    return _PALLAS_OK


def _as_padding_segments(attn_mask, query, key):
    """A BOOLEAN [b, sk] (or [b, 1, 1, sk]) keep-mask maps onto the
    kernel's segment ids (valid=1, pad=0); anything else returns None and
    takes the XLA path.  Bool-only on purpose: integer/float masks are
    ADDITIVE in the XLA path (sdpa semantics), so routing them as keep
    masks would change numerics between backends."""
    m = attn_mask._value if hasattr(attn_mask, "_value") else attn_mask
    import jax.numpy as jnp

    if m.ndim == 4 and m.shape[1] == 1 and m.shape[2] == 1:
        m = m[:, 0, 0]
    if m.ndim != 2 or m.shape != (key.shape[0], key.shape[1]):
        return None
    if not jnp.issubdtype(m.dtype, jnp.bool_):
        return None
    if query.shape[1] != key.shape[1]:
        return None
    return m.astype(jnp.int32)


def flash_attention(query, key, value, causal=False, dropout=0.0,
                    attn_mask=None, scale=None):
    """(batch, seq, heads, head_dim) attention, flash-style.  GQA (fewer
    kv heads) is accepted: the Pallas kernel routes q heads to kv groups
    natively; the XLA fallback repeats kv heads.  A [b, sk] boolean
    padding mask rides the Pallas path as segment ids (splash-attention
    style); arbitrary additive masks and dropout use the XLA path."""
    seg = None
    if _pallas_available() and attn_mask is not None and dropout == 0.0:
        seg = _as_padding_segments(attn_mask, query, key)
    if _pallas_available() and dropout == 0.0 \
            and (attn_mask is None or seg is not None):
        try:
            from ...ops.pallas.flash_attention import (FlashUnsupportedError,
                                                       flash_attention_op)

            if seg is not None:
                from ...core.tensor import Tensor as _T

                return dispatch("pallas_flash_attention", query, key, value,
                                q_segment_ids=_T(seg),
                                kv_segment_ids=_T(seg),
                                causal=causal, scale=scale)
            return dispatch("pallas_flash_attention", query, key, value,
                            causal=causal, scale=scale)
        except (ImportError, FlashUnsupportedError):
            # expected unsupported cases (e.g. causal sq != sk decode
            # shapes) — the XLA path handles them
            pass
        except Exception:
            # a real kernel regression must not silently become a ~12x
            # slowdown: warn once, then fall back
            global _WARNED_FALLBACK
            if not _WARNED_FALLBACK:
                _WARNED_FALLBACK = True
                import logging
                import traceback

                logging.getLogger(__name__).warning(
                    "Pallas flash attention failed unexpectedly; falling "
                    "back to the XLA softmax path:\n%s",
                    traceback.format_exc())
    rep = query.shape[2] // key.shape[2]
    if rep > 1:
        from ...ops.manip import repeat_interleave

        key = repeat_interleave(key, rep, axis=2)
        value = repeat_interleave(value, rep, axis=2)
    if attn_mask is not None:
        # a [b, sk] (or [b,1,1,sk]) bool keep-mask must mean the same
        # thing here as on the Pallas path, where it becomes SEGMENT ids
        # (q attends k iff same segment — padded queries see only padded
        # keys).  Expand to the equivalent [b, 1, sq, sk] equality mask so
        # both backends produce identical outputs at every position.
        from ...core.tensor import Tensor
        import jax.numpy as jnp

        mv = attn_mask._value if isinstance(attn_mask, Tensor) else \
            jnp.asarray(attn_mask)
        if mv.ndim == 4 and mv.shape[1] == 1 and mv.shape[2] == 1 \
                and jnp.issubdtype(mv.dtype, jnp.bool_):
            mv = mv[:, 0, 0]
        if jnp.issubdtype(mv.dtype, jnp.bool_) and mv.ndim == 2 \
                and mv.shape == (key.shape[0], key.shape[1]):
            if query.shape[1] == key.shape[1]:
                attn_mask = Tensor(
                    (mv[:, :, None] == mv[:, None, :])[:, None, :, :])
            else:
                # decode shapes (sq != sk): every query is a live token,
                # only keys carry padding — plain broadcastable keep-mask
                attn_mask = Tensor(mv[:, None, None, :])
    dropout_mask = None
    if dropout > 0.0:
        from ...core.tensor import Tensor
        from ...ops import random as _random
        import jax.numpy as jnp

        b, sq, h, _ = query.shape
        sk = key.shape[1]
        k_ = _random.default_generator().next_key()
        dropout_mask = Tensor(jax.random.bernoulli(k_, 1.0 - dropout, (b, h, sq, sk)))
    return dispatch("scaled_dot_product_attention", query, key, value,
                    attn_mask=attn_mask, dropout_mask=dropout_mask,
                    dropout_p=dropout, is_causal=causal, scale=scale)
