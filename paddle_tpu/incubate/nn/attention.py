"""Flash attention entry point.

Analog of the reference's FlashAttention integration
(paddle/phi/kernels/gpu/flash_attn_kernel.cu +
python/paddle/nn/functional/flash_attention.py:195). On TPU the fused
attention kernel is a Pallas kernel (paddle_tpu/ops/pallas/flash_attention.py);
on CPU (tests) or when Pallas is unavailable we fall back to the XLA softmax
path, which XLA still fuses well.
"""

from __future__ import annotations

import jax

from ...ops.registry import dispatch

_PALLAS_OK = None
_WARNED_FALLBACK = False


def _pallas_available() -> bool:
    global _PALLAS_OK
    if _PALLAS_OK is None:
        _PALLAS_OK = jax.default_backend() in ("tpu", "axon")
    return _PALLAS_OK


def flash_attention(query, key, value, causal=False, dropout=0.0,
                    attn_mask=None, scale=None):
    """(batch, seq, heads, head_dim) attention, flash-style.  GQA (fewer
    kv heads) is accepted: the Pallas kernel routes q heads to kv groups
    natively; the XLA fallback repeats kv heads."""
    if _pallas_available() and attn_mask is None and dropout == 0.0:
        try:
            from ...ops.pallas.flash_attention import (FlashUnsupportedError,
                                                       flash_attention_op)

            return dispatch("pallas_flash_attention", query, key, value,
                            causal=causal, scale=scale)
        except (ImportError, FlashUnsupportedError):
            # expected unsupported cases (e.g. causal sq != sk decode
            # shapes) — the XLA path handles them
            pass
        except Exception:
            # a real kernel regression must not silently become a ~12x
            # slowdown: warn once, then fall back
            global _WARNED_FALLBACK
            if not _WARNED_FALLBACK:
                _WARNED_FALLBACK = True
                import logging
                import traceback

                logging.getLogger(__name__).warning(
                    "Pallas flash attention failed unexpectedly; falling "
                    "back to the XLA softmax path:\n%s",
                    traceback.format_exc())
    rep = query.shape[2] // key.shape[2]
    if rep > 1:
        from ...ops.manip import repeat_interleave

        key = repeat_interleave(key, rep, axis=2)
        value = repeat_interleave(value, rep, axis=2)
    dropout_mask = None
    if dropout > 0.0:
        from ...core.tensor import Tensor
        from ...ops import random as _random
        import jax.numpy as jnp

        b, sq, h, _ = query.shape
        sk = key.shape[1]
        k_ = _random.default_generator().next_key()
        dropout_mask = Tensor(jax.random.bernoulli(k_, 1.0 - dropout, (b, h, sq, sk)))
    return dispatch("scaled_dot_product_attention", query, key, value,
                    attn_mask=attn_mask, dropout_mask=dropout_mask,
                    dropout_p=dropout, is_causal=causal, scale=scale)
