"""FusedMultiTransformer — the reference's fused inference stack.

Analog of python/paddle/incubate/nn/layer/fused_transformer.py:1071 (layer)
over incubate.nn.functional.fused_multi_transformer (CUDA fused kernels).
The TPU formulation runs the whole stack as one traced program per mode:
prefill executes all layers over the full sequence (optionally writing the
K/V caches), decode executes one token per call against the caches at
``time_step`` — the same split the reference's masked-MHA kernel makes,
with reference cache layout [2, B, num_head, max_seq_len, head_dim].

Inference-only (like the reference kernel): outputs are detached.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...core.tensor import Tensor
from ...nn.layer import Layer, Parameter

__all__ = ["FusedMultiTransformer"]


def _ln(x, scale, bias, eps):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = jnp.square(x32 - mu).mean(-1, keepdims=True)
    return (((x32 - mu) * lax.rsqrt(var + eps)) * scale + bias).astype(x.dtype)


class FusedMultiTransformer(Layer):
    def __init__(self, embed_dim: int, num_heads: int, dim_feedforward: int,
                 dropout_rate: float = 0.0, activation: str = "gelu",
                 normalize_before: bool = True, ln_scale_attrs=None,
                 ln_bias_attrs=None, qkv_weight_attrs=None,
                 qkv_bias_attrs=None, linear_weight_attrs=None,
                 linear_bias_attrs=None, ffn_ln_scale_attrs=None,
                 ffn_ln_bias_attrs=None, ffn1_weight_attrs=None,
                 ffn1_bias_attrs=None, ffn2_weight_attrs=None,
                 ffn2_bias_attrs=None, epsilon: float = 1e-5,
                 num_layers: int = -1, nranks: int = 1, trans_qkvw: bool = True,
                 ring_id: int = -1, name=None):
        super().__init__()
        if not normalize_before:
            raise NotImplementedError(
                "FusedMultiTransformer: only pre-LayerNorm is implemented")
        if num_layers < 0:
            num_layers = len(qkv_weight_attrs) if isinstance(
                qkv_weight_attrs, (list, tuple)) else 1
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dim_feedforward = dim_feedforward
        self.activation = activation
        self.num_layers = num_layers
        self._epsilon = epsilon
        h, nh, hd, dff = embed_dim, num_heads, self.head_dim, dim_feedforward
        rng = np.random.RandomState(0)

        def mk(shape, scale=0.02, zeros=False):
            if zeros:
                return Parameter(jnp.zeros(shape, jnp.float32))
            return Parameter(jnp.asarray(rng.randn(*shape) * scale,
                                         jnp.float32))

        self.ln_scales, self.ln_biases = [], []
        self.qkv_weights, self.qkv_biases = [], []
        self.linear_weights, self.linear_biases = [], []
        self.ffn_ln_scales, self.ffn_ln_biases = [], []
        self.ffn1_weights, self.ffn1_biases = [], []
        self.ffn2_weights, self.ffn2_biases = [], []
        for i in range(num_layers):
            def reg(name_, p):
                self.add_parameter(f"{name_}_{i}", p)
                return p

            self.ln_scales.append(reg("ln_scale", Parameter(
                jnp.ones((h,), jnp.float32))))
            self.ln_biases.append(reg("ln_bias", mk((h,), zeros=True)))
            # reference layout (trans_qkvw=True): [3, num_heads, head_dim, h]
            self.qkv_weights.append(reg("qkv_weight", mk((3, nh, hd, h))))
            self.qkv_biases.append(reg("qkv_bias", mk((3, nh, hd), zeros=True)))
            self.linear_weights.append(reg("linear_weight", mk((h, h))))
            self.linear_biases.append(reg("linear_bias", mk((h,), zeros=True)))
            self.ffn_ln_scales.append(reg("ffn_ln_scale", Parameter(
                jnp.ones((h,), jnp.float32))))
            self.ffn_ln_biases.append(reg("ffn_ln_bias", mk((h,), zeros=True)))
            self.ffn1_weights.append(reg("ffn1_weight", mk((h, dff))))
            self.ffn1_biases.append(reg("ffn1_bias", mk((dff,), zeros=True)))
            self.ffn2_weights.append(reg("ffn2_weight", mk((dff, h))))
            self.ffn2_biases.append(reg("ffn2_bias", mk((h,), zeros=True)))

    def _act(self, x):
        return jax.nn.gelu(x, approximate=False) if self.activation == "gelu" \
            else jax.nn.relu(x)

    def _layer(self, i, x, mask, cache=None, ts=None):
        """One shared layer body. x [b, s, h]. Without ``cache``: self
        (prefill) attention over x's own K/V. With ``cache`` ([2, b, nh, M,
        hd]) and ``ts``: write this token's K/V at ts, attend the whole
        cache. Returns (y, k, v, cache) — k/v are x's own (for prefill
        cache writes), cache is the updated one (or None)."""
        nh, hd = self.num_heads, self.head_dim
        b, s, h = x.shape
        eps = self._epsilon
        xin = _ln(x, self.ln_scales[i]._value, self.ln_biases[i]._value, eps)
        w = self.qkv_weights[i]._value.reshape(3 * nh * hd, h)
        qkv = (xin @ w.T + self.qkv_biases[i]._value.reshape(-1)
               ).reshape(b, s, 3, nh, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        k = jnp.moveaxis(k, 1, 2)  # [b, nh, s, hd]
        v = jnp.moveaxis(v, 1, 2)
        if cache is None:
            k_all, v_all = k, v
        else:
            cache = lax.dynamic_update_slice(
                cache, k[None].astype(cache.dtype), (0, 0, 0, ts, 0))
            cache = lax.dynamic_update_slice(
                cache, v[None].astype(cache.dtype), (1, 0, 0, ts, 0))
            k_all, v_all = cache[0], cache[1]
        if cache is not None and s == 1 and mask is None:
            # single-token decode: Pallas flash-decoding kernel over the
            # [b, nh, M, hd] cache (HBM traffic bounded by ts+1, not M)
            from ...ops.pallas.decode_attention import flash_decode_raw

            lens = jnp.broadcast_to(ts + 1, (b,)).astype(jnp.int32)
            ctx = flash_decode_raw(q.reshape(b, nh, hd), k_all, v_all,
                                   lens, scale=hd ** -0.5)
            ctx = ctx.reshape(b, s, nh * hd).astype(x.dtype)
        else:
            qh = jnp.moveaxis(q, 1, 2).astype(jnp.float32)
            scores = jnp.einsum("bnsd,bnSd->bnsS", qh,
                                k_all.astype(jnp.float32)) * (hd ** -0.5)
            if mask is not None:
                scores = scores + mask
            probs = jax.nn.softmax(scores, axis=-1)
            ctx = jnp.einsum("bnsS,bnSd->bnsd", probs,
                             v_all.astype(jnp.float32)).astype(x.dtype)
            ctx = jnp.moveaxis(ctx, 1, 2).reshape(b, s, nh * hd)
        x = x + ctx @ self.linear_weights[i]._value \
            + self.linear_biases[i]._value
        xm = _ln(x, self.ffn_ln_scales[i]._value,
                 self.ffn_ln_biases[i]._value, eps)
        f = self._act(xm @ self.ffn1_weights[i]._value
                      + self.ffn1_biases[i]._value)
        x = x + f @ self.ffn2_weights[i]._value + self.ffn2_biases[i]._value
        return x, k, v, cache

    def forward(self, src, attn_mask=None, caches: Optional[List] = None,
                pre_caches=None, rotary_embs=None, rotary_emb_dims=0,
                beam_offset=None, seq_lens=None, time_step=None):
        # unsupported reference knobs must fail loudly, not change results
        if rotary_embs is not None or rotary_emb_dims:
            raise NotImplementedError(
                "FusedMultiTransformer: rotary_embs not implemented (use the "
                "Llama flagship path for rope models)")
        if pre_caches is not None or beam_offset is not None \
                or seq_lens is not None:
            raise NotImplementedError(
                "FusedMultiTransformer: pre_caches/beam_offset/seq_lens "
                "not implemented")
        x = src._value if isinstance(src, Tensor) else jnp.asarray(src)
        mask = None
        if attn_mask is not None:
            mask = attn_mask._value if isinstance(attn_mask, Tensor) \
                else jnp.asarray(attn_mask)
            mask = mask.astype(jnp.float32)
        cache_vals = None
        if caches is not None:
            cache_vals = [c._value if isinstance(c, Tensor) else jnp.asarray(c)
                          for c in caches]

        if time_step is None:
            out, new_caches = self._prefill(x, mask, cache_vals)
        else:
            ts = int(time_step._value if isinstance(time_step, Tensor)
                     else time_step)
            out, new_caches = self._decode(x, cache_vals, ts, mask)

        out_t = Tensor(out, stop_gradient=True)
        if caches is None:
            return out_t
        return out_t, [Tensor(c, stop_gradient=True) for c in new_caches]

    def _prefill(self, x, mask, cache_vals):
        b, s, _ = x.shape
        new_caches = []
        for i in range(self.num_layers):
            x, k, v, _ = self._layer(i, x, mask)
            if cache_vals is not None:
                c = cache_vals[i]
                c = c.at[0, :, :, :s].set(k.astype(c.dtype))
                c = c.at[1, :, :, :s].set(v.astype(c.dtype))
                new_caches.append(c)
        return x, new_caches

    def _decode(self, x, cache_vals, ts, attn_mask=None):
        if cache_vals is None:
            raise ValueError("decode (time_step given) requires caches")
        if attn_mask is None and x.shape[1] == 1:
            # single-token decode with no user mask: the Pallas
            # flash-decoding kernel bounds attention to positions <= ts
            # itself (and bounds the HBM traffic with it) — no
            # materialised position mask needed
            mask = None
        else:  # user mask, or multi-token chunk: masked XLA path (the
            # position mask is what keeps stale cache slots past ts out)
            M = cache_vals[0].shape[3]
            valid = (jnp.arange(M) <= ts)
            mask = jnp.where(valid, 0.0,
                             -jnp.inf).astype(jnp.float32)[None, None,
                                                           None, :]
            if attn_mask is not None:
                mask = mask + attn_mask
        new_caches = []
        for i in range(self.num_layers):
            x, _, _, c = self._layer(i, x, mask, cache_vals[i], ts)
            new_caches.append(c)
        return x, new_caches
