"""paddle.incubate.nn.functional parity namespace.

Name-complete analog of the reference's
python/paddle/incubate/nn/functional/__init__.py (round-4 verdict
missing#4: the incubate fused functional tail): re-exports the fused ops
implemented across this package and adds the serving/bias-act tail —
``fused_bias_act``, ``fused_dropout_add``, ``fused_gate_attention``,
``variable_length_memory_efficient_attention``, ``blha_get_max_len`` —
plus the classic fused-transformer trio (``fused_multi_head_attention``,
``fused_feedforward``, ``fused_bias_dropout_residual_layer_norm``).

On TPU "fusion" is XLA's job: each function is the reference kernel's
math as one jnp expression; the hot paths route into the Pallas kernels
(flash / flash-decoding) where profitable.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...ops.registry import dispatch, register
from .decode_attention import (block_multihead_attention,  # noqa: F401
                               masked_multihead_attention,
                               memory_efficient_attention, quant_to_int8)
from .fused import (fused_layer_norm, fused_linear_activation,  # noqa: F401
                    fused_matmul_bias, fused_moe, fused_rms_norm,
                    fused_rotary_position_embedding, swiglu)

__all__ = [
    'fused_multi_head_attention',
    'fused_feedforward',
    'fused_multi_transformer',
    'fused_matmul_bias',
    'fused_linear',
    'fused_linear_activation',
    'fused_bias_dropout_residual_layer_norm',
    'fused_moe',
    'fused_dropout_add',
    'fused_rotary_position_embedding',
    'variable_length_memory_efficient_attention',
    'fused_rms_norm',
    'fused_layer_norm',
    'fused_bias_act',
    'fused_gate_attention',
    'masked_multihead_attention',
    'blha_get_max_len',
    'block_multihead_attention',
    'swiglu',
]


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """Reference fused_matmul_bias alias (fused_transformer.py
    fused_linear)."""
    return fused_matmul_bias(x, weight, bias, transpose_y=transpose_weight)


# --------------------------------------------------------------------------
# blha_get_max_len (reference blha_get_max_len.py; phi fused op
# blha_get_max_len — the max-length probe serving runs before
# block_multihead_attention to size its kernel launch)
# --------------------------------------------------------------------------

@register("blha_get_max_len")
def _blha_get_max_len_op(seq_lens_encoder, seq_lens_decoder, batch_size=None):
    enc = jnp.max(jnp.asarray(seq_lens_encoder).astype(jnp.int32))
    dec = jnp.max(jnp.asarray(seq_lens_decoder).astype(jnp.int32))
    return enc.reshape(1), dec.reshape(1)


def blha_get_max_len(seq_lens_encoder, seq_lens_decoder, batch_size=None):
    """(max_enc_len_this_time, max_dec_len_this_time) over the batch —
    signature parity with the reference (batch_size is a shape hint the
    TPU path does not need)."""
    return dispatch("blha_get_max_len", seq_lens_encoder, seq_lens_decoder,
                    batch_size)


# --------------------------------------------------------------------------
# fused_bias_act (reference fused_bias_act.py; kernel
# paddle/phi/kernels/fusion/gpu/fused_bias_act_kernel.cu): optional int
# dequant -> bias -> activation (incl. the glu family) -> smooth-quant
# shift/smooth -> optional int8 quant
# --------------------------------------------------------------------------

_BIAS_ACTS = {
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "fast_gelu": lambda x: jax.nn.gelu(x, approximate=True),
    "relu": jax.nn.relu,
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
}


@register("fused_bias_act", amp="black")
def _fused_bias_act_op(x, bias=None, dequant_scales=None, shift=None,
                       smooth=None, act_method="gelu",
                       compute_dtype="default", quant_scale=-1.0,
                       quant_round_type=0, quant_max_bound=0.0,
                       quant_min_bound=0.0):
    act = act_method.lower()
    out_dtype = x.dtype
    if compute_dtype != "default":
        out_dtype = jnp.dtype(compute_dtype)
    xf = x.astype(jnp.float32)
    if dequant_scales is not None:
        # int32 gemm outputs dequantized per output channel
        xf = xf * jnp.asarray(dequant_scales, jnp.float32)
    if bias is not None:
        xf = xf + jnp.asarray(bias, jnp.float32)
    if act in ("swiglu", "geglu"):
        a, b = jnp.split(xf, 2, axis=-1)
        gate = jax.nn.silu(a) if act == "swiglu" else jax.nn.gelu(a)
        out = gate * b
    elif act in _BIAS_ACTS:
        out = _BIAS_ACTS[act](xf)
    else:
        raise ValueError(f"fused_bias_act: unsupported act_method "
                         f"{act_method!r}")
    if shift is not None:
        out = out + jnp.asarray(shift, jnp.float32)
    if smooth is not None:
        out = out * jnp.asarray(smooth, jnp.float32)
    if quant_scale > 0:
        from .decode_attention import _quant_round

        y = _quant_round(out * quant_scale, quant_round_type)
        return jnp.clip(y, quant_min_bound, quant_max_bound).astype(jnp.int8)
    return out.astype(out_dtype)


def fused_bias_act(x, bias=None, dequant_scales=None, shift=None,
                   smooth=None, act_method="gelu", compute_dtype="default",
                   quant_scale=-1, quant_round_type=0, quant_max_bound=0,
                   quant_min_bound=0):
    return dispatch("fused_bias_act", x, bias, dequant_scales, shift,
                    smooth, act_method=act_method,
                    compute_dtype=compute_dtype,
                    quant_scale=float(quant_scale),
                    quant_round_type=int(quant_round_type),
                    quant_max_bound=float(quant_max_bound),
                    quant_min_bound=float(quant_min_bound))


# --------------------------------------------------------------------------
# fused_dropout_add (reference fused_dropout_add.py): out = dropout(x) + y
# with the seed-offset contract folded into the framework RNG
# --------------------------------------------------------------------------

def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    from ...nn import functional as F

    return F.dropout(x, p=p, training=training, mode=mode) + y


# --------------------------------------------------------------------------
# fused_gate_attention (reference fused_gate_attention.py; AlphaFold-style
# gated attention over [batch, msa, res, dim] inputs)
# --------------------------------------------------------------------------

@register("fused_gate_attention", amp="white")
def _fused_gate_attention_op(query, key=None, query_weight=None,
                             key_weight=None, value_weight=None,
                             qkv_weight=None, gate_linear_weight=None,
                             gate_linear_bias=None, out_linear_weight=None,
                             out_linear_bias=None, nonbatched_bias=None,
                             attn_mask=None, has_gating=True,
                             merge_qkv=True, use_flash_attn=False):
    """The reference pseudo-code verbatim (einsum attention + sigmoid
    gating + output linear).  q [n, b, q, a]; merge_qkv uses qkv_weight
    [3, h, c, a]; separate weights are [a, h, c]."""
    if merge_qkv:
        if qkv_weight is None:
            raise ValueError("merge_qkv=True needs qkv_weight [3, h, c, a]")
        qkv = jnp.einsum("nbqa,thca->tnbqhc", query, qkv_weight)
        q, k, v = qkv[0], qkv[1], qkv[2]
        c = q.shape[-1]
        q = q * (c ** -0.5)
    else:
        if key is None:
            key = query
        c = query_weight.shape[-1]
        q = jnp.einsum("nbqa,ahc->nbqhc", query, query_weight) * (c ** -0.5)
        k = jnp.einsum("nbka,ahc->nbkhc", key, key_weight)
        v = jnp.einsum("nbka,ahc->nbkhc", key, value_weight)
    logits = jnp.einsum("nbqhc,nbkhc->nbhqk", q, k).astype(jnp.float32)
    if attn_mask is not None:
        logits = logits + attn_mask.astype(jnp.float32)
    if nonbatched_bias is not None:
        logits = logits + jnp.expand_dims(nonbatched_bias, 1).astype(
            jnp.float32)
    weights = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    out = jnp.einsum("nbhqk,nbkhc->nbqhc", weights, v)
    if has_gating:
        gate = jnp.einsum("nbqa,ahc->nbqhc", query, gate_linear_weight)
        if gate_linear_bias is not None:
            gate = gate + gate_linear_bias
        out = out * jax.nn.sigmoid(gate)
    res = jnp.einsum("nbqhc,hco->nbqo", out, out_linear_weight)
    if out_linear_bias is not None:
        res = res + out_linear_bias
    return res


def fused_gate_attention(query, key=None, query_weight=None, key_weight=None,
                         value_weight=None, qkv_weight=None,
                         gate_linear_weight=None, gate_linear_bias=None,
                         out_linear_weight=None, out_linear_bias=None,
                         nonbatched_bias=None, attn_mask=None,
                         has_gating=True, merge_qkv=True,
                         use_flash_attn=False):
    return dispatch("fused_gate_attention", query, key, query_weight,
                    key_weight, value_weight, qkv_weight,
                    gate_linear_weight, gate_linear_bias, out_linear_weight,
                    out_linear_bias, nonbatched_bias, attn_mask,
                    has_gating=has_gating, merge_qkv=merge_qkv,
                    use_flash_attn=use_flash_attn)


# --------------------------------------------------------------------------
# variable_length_memory_efficient_attention (reference
# variable_length_memory_efficient_attention.py: per-sequence q/kv valid
# lengths over [b, h, s, d] inputs)
# --------------------------------------------------------------------------

def variable_length_memory_efficient_attention(query, key, value, seq_lens,
                                               kv_seq_lens, mask=None,
                                               scale=None, causal=False,
                                               pre_cache_length=0):
    """Per-sequence variable-length attention.  The self-attention cases
    (no explicit mask, no pre-cache, sq == sk) route into the varlen
    Pallas flash kernel via disjoint padding segments; the general case
    (additive mask / pre-cache prefix / cross lengths) runs the online-
    softmax XLA path — the same split the reference makes between its
    cutlass variable-length kernel and the generic fallback."""
    b, h, sq, d = query.shape
    sk = key.shape[2]
    q_bshd = jnp.moveaxis(query, 1, 2)
    k_bshd = jnp.moveaxis(key, 1, 2)
    v_bshd = jnp.moveaxis(value, 1, 2)
    seq_lens = jnp.asarray(seq_lens, jnp.int32).reshape(b)
    kv_seq_lens = jnp.asarray(kv_seq_lens, jnp.int32).reshape(b)
    if mask is None and pre_cache_length == 0 and sq == sk:
        from ...ops.pallas.flash_attention import (FlashUnsupportedError,
                                                   flash_attention_raw)

        pos_q = jnp.arange(sq, dtype=jnp.int32)[None]
        pos_k = jnp.arange(sk, dtype=jnp.int32)[None]
        # valid tokens share segment 1; q/k padding get DISJOINT ids so
        # padded q rows see no keys at all (the kernel zero-fills them)
        q_seg = jnp.where(pos_q < seq_lens[:, None], 1, 2).astype(jnp.int32)
        k_seg = jnp.where(pos_k < kv_seq_lens[:, None], 1, 3).astype(
            jnp.int32)
        try:
            out = flash_attention_raw(q_bshd, k_bshd, v_bshd,
                                      causal=bool(causal), scale=scale,
                                      q_segment_ids=q_seg,
                                      kv_segment_ids=k_seg)
            return jnp.moveaxis(out, 1, 2)
        except FlashUnsupportedError:
            pass
    # general fallback: additive-bias online-softmax attention
    neg = jnp.float32(-1e30)
    kpos = jnp.arange(sk, dtype=jnp.int32)
    bias = jnp.where(kpos[None, :] < kv_seq_lens[:, None], 0.0, neg)
    bias = bias[:, None, None, :]                       # [b, 1, 1, sk]
    if causal:
        # q row i sits at absolute kv position pre_cache_length + i (the
        # pre-cache prefix is always visible)
        qpos = jnp.arange(sq, dtype=jnp.int32)
        cmask = (qpos[:, None] + pre_cache_length) >= kpos[None, :]
        bias = bias + jnp.where(cmask[None, None], 0.0, neg)
    if mask is not None:
        bias = bias + jnp.asarray(mask, jnp.float32)
    out = memory_efficient_attention(q_bshd, k_bshd, v_bshd,
                                     attn_bias=bias, scale=scale,
                                     causal=False)
    # zero padded q rows (reference writes zeros there)
    qpos = jnp.arange(sq, dtype=jnp.int32)
    qvalid = (qpos[None, :] < seq_lens[:, None])[:, :, None, None]
    out = jnp.where(qvalid, out, jnp.zeros((), out.dtype))
    return jnp.moveaxis(out, 1, 2)


# --------------------------------------------------------------------------
# classic fused-transformer functional trio (reference
# fused_transformer.py): pseudo-code-faithful jnp compositions
# --------------------------------------------------------------------------

def _dropout(x, p, training, mode):
    from ...nn import functional as F

    return F.dropout(x, p=p, training=training, mode=mode)


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5,
                                           ln_epsilon=1e-5, training=True,
                                           mode="upscale_in_train",
                                           name=None):
    """y = layer_norm(residual + dropout(bias + x)) (reference
    fused_transformer.py:334)."""
    h = x if bias is None else x + bias
    h = residual + _dropout(h, dropout_rate, training, mode)
    return fused_layer_norm(h, ln_scale, ln_bias, epsilon=ln_epsilon)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True,
                      mode="upscale_in_train", ring_id=-1,
                      add_residual=True, name=None):
    """residual + dropout2(linear2(dropout1(act(linear1(maybe_ln(x))))))
    (reference fused_transformer.py:47; ring_id=-1 means no tensor-
    parallel allreduce — with a ring the caller runs under a mesh and
    XLA inserts the collective)."""
    residual = x
    out = fused_layer_norm(x, ln1_scale, ln1_bias, epsilon=ln1_epsilon) \
        if pre_layer_norm else x
    out = dispatch("linear", out, linear1_weight, linear1_bias)
    out = dispatch(activation, out)
    out = _dropout(out, dropout1_rate, training, mode)
    out = dispatch("linear", out, linear2_weight, linear2_bias)
    out = _dropout(out, dropout2_rate, training, mode)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = fused_layer_norm(out, ln2_scale, ln2_bias, epsilon=ln2_epsilon)
    return out


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.5,
                               attn_dropout_rate=0.5, ln_epsilon=1e-5,
                               training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, num_heads=-1,
                               transpose_qkv_wb=False, name=None):
    """Self-attention block per the reference pseudo-code
    (fused_transformer.py:513): qkv projection, scaled-dot attention with
    optional additive mask + attn dropout, output linear, residual +
    dropout, layer norm (pre- or post-).  qkv_weight [3, h, hd, dim]
    (or [dim, 3*dim] with transpose_qkv_wb)."""
    residual = x
    out = fused_layer_norm(x, pre_ln_scale, pre_ln_bias,
                           epsilon=pre_ln_epsilon) if pre_layer_norm else x
    b, s, dim = out.shape
    if transpose_qkv_wb:
        if num_heads <= 0:
            raise ValueError("transpose_qkv_wb=True needs num_heads")
        h = num_heads
        hd = dim // h
        qkv = out @ qkv_weight                          # [b, s, 3*dim]
        if qkv_bias is not None:
            qkv = qkv + qkv_bias
        qkv = qkv.reshape(b, s, 3, h, hd)
    else:
        _, h, hd, _ = qkv_weight.shape
        qkv = jnp.einsum("bsd,thcd->bsthc", out, qkv_weight)
        if qkv_bias is not None:
            qkv = qkv + qkv_bias.reshape(3, h, hd)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [b, s, h, hd]
    if cache_kv is not None:
        # [2, b, h, t, hd] prefix cache: prepend
        pk = jnp.moveaxis(cache_kv[0], 2, 1)
        pv = jnp.moveaxis(cache_kv[1], 2, 1)
        k = jnp.concatenate([pk, k], axis=1)
        v = jnp.concatenate([pv, v], axis=1)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) \
        * (hd ** -0.5)
    if attn_mask is not None:
        logits = logits + attn_mask.astype(jnp.float32)
    p = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    p = _dropout(p, attn_dropout_rate, training, mode)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", p, v).reshape(b, -1, h * hd)
    ctx = ctx[:, -s:]                                  # drop cache prefix
    out = ctx @ linear_weight
    if linear_bias is not None:
        out = out + linear_bias
    out = _dropout(out, dropout_rate, training, mode)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = fused_layer_norm(out, ln_scale, ln_bias, epsilon=ln_epsilon)
    return out


def fused_multi_transformer(*args, **kwargs):
    """Functional alias onto the FusedMultiTransformer layer's math — the
    reference exposes both; use paddle_tpu.incubate.nn
    .FusedMultiTransformer for the stateful form."""
    from .fused_transformer import FusedMultiTransformer  # noqa: F401

    raise NotImplementedError(
        "use the FusedMultiTransformer layer (incubate.nn) — the "
        "functional form's 20+ per-layer weight lists exist for the "
        "reference's static-graph mode; the layer covers the capability")
