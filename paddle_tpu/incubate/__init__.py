from . import nn
from . import optimizer
