from . import nn
from . import optimizer
from . import asp
