from . import nn
