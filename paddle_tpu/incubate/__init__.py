"""paddle_tpu.incubate — incubating APIs (reference python/paddle/
incubate).

Top-level re-exports mirror the reference's ``paddle.incubate.*``
``__all__`` (round-6: VERDICT r5 Missing #2 — the implementations lived
under incubate/optimizer and geometric but the entry points were never
wired)."""

from . import nn
from . import optimizer
from . import asp
from . import autotune
from .distributed.models import moe as _moe  # noqa: F401  (registers
#   moe_forward/moe_dropless_forward at import — registry completeness)

from .optimizer import LookAhead, ModelAverage
from .ops import (graph_khop_sampler, graph_reindex,
                  graph_sample_neighbors, graph_send_recv, identity_loss,
                  softmax_mask_fuse, softmax_mask_fuse_upper_triangle)
from ..geometric import segment_max, segment_mean, segment_min, segment_sum

__all__ = [
    "LookAhead",
    "ModelAverage",
    "softmax_mask_fuse_upper_triangle",
    "softmax_mask_fuse",
    "graph_send_recv",
    "graph_khop_sampler",
    "graph_sample_neighbors",
    "graph_reindex",
    "segment_sum",
    "segment_mean",
    "segment_max",
    "segment_min",
    "identity_loss",
    "autotune",
]
