from . import nn
from . import optimizer
from . import asp
from .distributed.models import moe as _moe  # noqa: F401  (registers
#   moe_forward/moe_dropless_forward at import — registry completeness)
