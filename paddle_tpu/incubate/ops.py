"""paddle_tpu.incubate — top-level incubate ops.

Analogs of python/paddle/incubate/operators/{softmax_mask_fuse.py,
softmax_mask_fuse_upper_triangle.py, graph_send_recv.py,
graph_khop_sampler.py} and python/paddle/incubate/nn/loss.py
(identity_loss).  The fused-softmax pair are the transformer-attention
fusions the reference hand-writes in CUDA
(fused_softmax_mask_kernel.cu); on TPU they are single XLA fusions."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from .. import geometric as _geo


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) in one fusion: x [b, h, sq, sk] fp scores,
    mask broadcastable [b, 1, sq, sk] additive (-inf style) mask."""
    xv, mv = _v(x), _v(mask)
    s = xv.astype(jnp.float32) + mv.astype(jnp.float32)
    out = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    out = out / jnp.sum(out, axis=-1, keepdims=True)
    return Tensor(out.astype(xv.dtype))


def softmax_mask_fuse_upper_triangle(x, name=None):
    """Causal fused softmax: positions ABOVE the diagonal are masked
    (the reference's fused_softmax_mask_upper_triangle kernel for
    GPT-style attention scores [b, h, s, s])."""
    xv = _v(x)
    sq, sk = xv.shape[-2], xv.shape[-1]
    causal = jnp.tril(jnp.ones((sq, sk), bool))
    s = jnp.where(causal, xv.astype(jnp.float32), -1e30)
    out = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    out = out / jnp.sum(out, axis=-1, keepdims=True)
    return Tensor(out.astype(xv.dtype))


def identity_loss(x, reduction="none"):
    """python/paddle/incubate/nn/loss.py identity_loss: pass the input
    through as the loss with the requested reduction (int codes are the
    reference's 0=sum, 1=mean, 2=none)."""
    if isinstance(reduction, int):
        reduction = {0: "sum", 1: "mean", 2: "none"}.get(reduction)
    if reduction not in ("none", "mean", "sum"):
        raise ValueError(f"unknown reduction {reduction!r}")
    from .. import ops as _ops  # noqa: F401 (registry populated)
    from ..ops.registry import dispatch

    if reduction == "mean":
        return dispatch("mean", x)
    if reduction == "sum":
        return dispatch("sum", x)
    return x if isinstance(x, Tensor) else Tensor(_v(x))


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """Legacy-name alias of geometric.send_u_recv (the reference keeps
    both entry points; incubate's predates the geometric namespace)."""
    return _geo.send_u_recv(x, src_index, dst_index,
                            reduce_op=pool_type, out_size=out_size)


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    """Legacy-name alias of geometric.sample_neighbors."""
    return _geo.sample_neighbors(row, colptr, input_nodes,
                                 sample_size=sample_size, eids=eids,
                                 return_eids=return_eids,
                                 perm_buffer=perm_buffer)


def graph_reindex(x, neighbors, count, value_buffer=None,
                  index_buffer=None, flag_buffer_hashtable=False,
                  name=None):
    """Legacy-name alias of geometric.reindex_graph."""
    return _geo.reindex_graph(x, neighbors, count,
                              value_buffer=value_buffer,
                              index_buffer=index_buffer)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """K-hop neighbor sampling over a CSC graph (python/paddle/incubate/
    operators/graph_khop_sampler.py): one uniform sample_neighbors pass
    per hop, frontier = previous hop's (deduplicated) neighbors, then
    one global reindex onto contiguous ids.  Host-side and nondiff,
    like the reference's CPU kernel.  Returns
    (edge_src, edge_dst, sample_index, reindex_x) — the sampled edges in
    reindexed ids, the unique node list, and the reindexed seeds."""
    seeds = np.asarray(_v(input_nodes)).reshape(-1).astype(np.int64)
    frontier = seeds
    all_src, all_dst = [], []
    for size in list(sample_sizes):
        if frontier.size == 0:
            break
        neigh, cnt = _geo.sample_neighbors(row, colptr, frontier,
                                           sample_size=int(size))
        nv = np.asarray(_v(neigh)).reshape(-1)
        cv = np.asarray(_v(cnt)).reshape(-1)
        dst = np.repeat(frontier, cv)
        all_src.append(nv)
        all_dst.append(dst)
        frontier = np.unique(nv)
    if all_src:
        src = np.concatenate(all_src)
        dst = np.concatenate(all_dst)
    else:
        src = np.empty(0, np.int64)
        dst = np.empty(0, np.int64)
    # reindex: seeds first (0..n_seed), then new nodes in first-seen order
    mapping = {}
    order = []
    for n in list(seeds) + list(dst) + list(src):
        n = int(n)
        if n not in mapping:
            mapping[n] = len(mapping)
            order.append(n)
    edge_src = np.asarray([mapping[int(n)] for n in src], np.int64)
    edge_dst = np.asarray([mapping[int(n)] for n in dst], np.int64)
    sample_index = np.asarray(order, np.int64)
    reindex_x = np.asarray([mapping[int(n)] for n in seeds], np.int64)
    if return_eids:
        # fail fast rather than fabricate ids: the host sampler does not
        # track which CSC positions were drawn, so real edge ids are not
        # recoverable here — silently wrong ids would corrupt downstream
        # edge-feature lookups
        raise NotImplementedError(
            "graph_khop_sampler(return_eids=True) is not supported: the "
            "host-side sampler does not track sampled edge positions; "
            "sample with return_eids=False and look features up by "
            "(src, dst) instead")
    return (Tensor(jnp.asarray(edge_src)), Tensor(jnp.asarray(edge_dst)),
            Tensor(jnp.asarray(sample_index)),
            Tensor(jnp.asarray(reindex_x)))
