"""paddle_tpu.incubate.asp — automatic structured (n:m) sparsity.

Analog of python/paddle/incubate/asp/asp.py (+ utils.py mask algorithms):
``prune_model`` computes per-layer n:m masks (2:4 by default — the
sparsity pattern TPU/SparseCore-era hardware and the reference's Ampere
target both use) and applies them; ``decorate`` wraps an optimizer so
masks are re-applied after every step, keeping pruned weights at zero
through training.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...nn import Conv2D, Linear
from ...nn.layer import Layer
from ...optimizer import Optimizer

__all__ = ["calculate_density", "create_mask", "check_mask_2d4",
           "prune_model", "decorate", "set_excluded_layers",
           "reset_excluded_layers", "OptimizerWithSparsityGuarantee"]

_excluded: set = set()


def set_excluded_layers(layers: List[str], model: Optional[Layer] = None):
    """Exclude sublayers (by structured name) from pruning."""
    for name in layers:
        _excluded.add(name)


def reset_excluded_layers(main_program=None):
    _excluded.clear()


def calculate_density(x) -> float:
    v = np.asarray(x._value if isinstance(x, Tensor) else x)
    return float(np.count_nonzero(v)) / max(v.size, 1)


def create_mask(x, func_name: str = "mask_1d", n: int = 2, m: int = 4):
    """n:m structured mask along the last dim: keep the ``n``
    largest-|w| of every ``m`` consecutive weights (reference
    utils.py get_mask_1d / create_mask)."""
    v = np.asarray(x._value if isinstance(x, Tensor) else x)
    orig_shape = v.shape
    flat = v.reshape(-1, orig_shape[-1])
    cols = orig_shape[-1]
    pad = (-cols) % m
    if pad:
        flat = np.pad(flat, [(0, 0), (0, pad)])
    groups = np.abs(flat).reshape(flat.shape[0], -1, m)
    order = np.argsort(-groups, axis=-1)
    mask = np.zeros_like(groups)
    np.put_along_axis(mask, order[..., :n], 1.0, axis=-1)
    mask = mask.reshape(flat.shape)[:, :cols].reshape(orig_shape)
    return Tensor(jnp.asarray(mask.astype(v.dtype)))


def check_mask_2d4(x, n: int = 2, m: int = 4) -> bool:
    """True when every m-group along the last dim has <= n nonzeros."""
    v = np.asarray(x._value if isinstance(x, Tensor) else x)
    flat = v.reshape(-1, v.shape[-1])
    pad = (-v.shape[-1]) % m
    if pad:
        flat = np.pad(flat, [(0, 0), (0, pad)])
    groups = flat.reshape(flat.shape[0], -1, m)
    return bool((np.count_nonzero(groups, axis=-1) <= n).all())


def _prunable(model: Layer):
    for name, sub in model.named_sublayers():
        if name in _excluded:
            continue
        if isinstance(sub, (Linear, Conv2D)):
            yield name, sub


def prune_model(model: Layer, n: int = 2, m: int = 4,
                mask_algo: str = "mask_1d", with_mask: bool = True):
    """Apply n:m masks to every Linear/Conv2D weight; masks are remembered
    so ``decorate``d optimizers keep them enforced."""
    pruned = {}
    for name, sub in _prunable(model):
        w = sub.weight
        mask = create_mask(w, mask_algo, n, m)
        w.set_value(w._value * mask._value)
        if with_mask:
            # stored ON the parameter (an id-keyed registry would collide
            # when a collected param's id is recycled)
            w._asp_mask = np.asarray(mask._value)
        pruned[name] = mask
    return pruned


class OptimizerWithSparsityGuarantee:
    """Wrapped optimizer re-applying the recorded masks after each step
    (reference asp.py:233 decorate)."""

    def __init__(self, optimizer: Optimizer):
        self._inner = optimizer

    def step(self, *args, **kwargs):
        out = self._inner.step(*args, **kwargs)
        for p in self._inner._parameters:
            mask = getattr(p, "_asp_mask", None)
            if mask is not None:
                p.set_value(p._value * jnp.asarray(mask))
        return out

    def __getattr__(self, item):
        return getattr(self._inner, item)


def decorate(optimizer: Optimizer) -> OptimizerWithSparsityGuarantee:
    return OptimizerWithSparsityGuarantee(optimizer)
