"""paddle_tpu.incubate.optimizer — LookAhead, LBFGS, GradientMerge.

Analogs of python/paddle/incubate/optimizer/{lookahead.py, lbfgs.py,
gradient_merge.py}. All three are built over the eager Optimizer base:
LookAhead keeps slow weights and interpolates every k steps; LBFGS runs
the classic two-loop recursion with closure re-evaluation; GradientMerge
accumulates k micro-step gradients before delegating one real step.
"""

from __future__ import annotations

from typing import Callable, List, Optional

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...optimizer import Optimizer

__all__ = ["LookAhead", "LBFGS", "GradientMergeOptimizer"]


class LookAhead(Optimizer):
    """lookahead.py:44 — fast weights step with the inner optimizer; every
    ``k`` steps slow weights move ``alpha`` toward them and are copied
    back."""

    def __init__(self, inner_optimizer: Optimizer, alpha: float = 0.5,
                 k: int = 5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = int(k)
        self._step_count = 0
        self._slow = None
        self._parameters = inner_optimizer._parameters

    def _ensure_slow(self):
        if self._slow is None:
            self._slow = [np.asarray(p._value).copy()
                          for p in self._parameters]

    def step(self):
        self._ensure_slow()
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k == 0:
            for i, p in enumerate(self._parameters):
                fast = np.asarray(p._value, np.float32)
                slow = self._slow[i].astype(np.float32)
                slow = slow + self.alpha * (fast - slow)
                self._slow[i] = slow
                p.set_value(jnp.asarray(slow, p.dtype))

    def clear_grad(self, set_to_zero: bool = False):
        self.inner_optimizer.clear_grad(set_to_zero)

    def state_dict(self):
        return {"inner": self.inner_optimizer.state_dict(),
                "slow": self._slow, "step_count": self._step_count}


class GradientMergeOptimizer(Optimizer):
    """gradient_merge.py — accumulate ``k_steps`` micro-batch gradients
    (averaged when ``avg``), then run ONE inner step."""

    def __init__(self, inner_optimizer: Optimizer, k_steps: int = 1,
                 avg: bool = True):
        self.inner_optimizer = inner_optimizer
        self.k_steps = int(k_steps)
        self.avg = avg
        self._acc = None
        self._micro = 0
        self._parameters = inner_optimizer._parameters

    def step(self):
        params = self._parameters
        if self._acc is None:
            self._acc = [None] * len(params)
        for i, p in enumerate(params):
            if p.grad is None:
                continue
            g = p.grad._value
            self._acc[i] = g if self._acc[i] is None else self._acc[i] + g
        self._micro += 1
        # micro-steps only bank the gradient
        self.inner_optimizer.clear_grad()
        if self._micro < self.k_steps:
            return
        for i, p in enumerate(params):
            if self._acc[i] is None:
                continue
            g = self._acc[i] / self.k_steps if self.avg else self._acc[i]
            p._grad = Tensor(g, stop_gradient=True)
        self.inner_optimizer.step()
        self.inner_optimizer.clear_grad()
        self._acc = None
        self._micro = 0

    def clear_grad(self, set_to_zero: bool = False):
        self.inner_optimizer.clear_grad(set_to_zero)


class LBFGS(Optimizer):
    """lbfgs.py — limited-memory BFGS with the two-loop recursion and
    backtracking (Armijo) line search; ``step(closure)`` re-evaluates the
    loss like the reference/torch API."""

    def __init__(self, learning_rate: float = 1.0, max_iter: int = 20,
                 tolerance_grad: float = 1e-7, tolerance_change: float = 1e-9,
                 history_size: int = 100, line_search_fn: Optional[str] = None,
                 parameters: Optional[List] = None, name=None):
        super().__init__(learning_rate, parameters, None, None, name)
        self.max_iter = max_iter
        self.tol_grad = tolerance_grad
        self.tol_change = tolerance_change
        self.history_size = history_size
        self.line_search_fn = line_search_fn
        self._s: List[np.ndarray] = []
        self._y: List[np.ndarray] = []

    # -- flat helpers ------------------------------------------------------
    def _flat_params(self):
        return np.concatenate([np.asarray(p._value, np.float64).ravel()
                               for p in self._parameters])

    def _set_flat(self, flat):
        off = 0
        for p in self._parameters:
            n = int(np.prod(p.shape)) if p.shape else 1
            p.set_value(jnp.asarray(
                flat[off:off + n].reshape(tuple(p.shape)), p.dtype))
            off += n

    def _flat_grad(self):
        gs = []
        for p in self._parameters:
            g = p.grad
            gs.append(np.zeros(int(np.prod(p.shape) or 1))
                      if g is None else np.asarray(g._value,
                                                   np.float64).ravel())
        return np.concatenate(gs)

    def _eval(self, closure):
        self.clear_grad()
        loss = closure()
        return float(np.asarray(loss._value
                                if isinstance(loss, Tensor) else loss))

    def step(self, closure: Callable):
        loss = self._eval(closure)
        g = self._flat_grad()
        for _ in range(self.max_iter):
            if np.abs(g).max() <= self.tol_grad:
                break
            # two-loop recursion
            q = g.copy()
            alphas = []
            for s, y in zip(reversed(self._s), reversed(self._y)):
                rho = 1.0 / max(float(y @ s), 1e-10)
                a = rho * (s @ q)
                alphas.append((a, rho, s, y))
                q -= a * y
            if self._y:
                y_last, s_last = self._y[-1], self._s[-1]
                q *= float(s_last @ y_last) / max(float(y_last @ y_last),
                                                  1e-10)
            for a, rho, s, y in reversed(alphas):
                b = rho * (y @ q)
                q += (a - b) * s
            d = -q
            # backtracking line search on the closure
            x0 = self._flat_params()
            t = self.get_lr() if not self._s else 1.0
            f0, g0d = loss, float(g @ d)
            for _ls in range(20):
                self._set_flat(x0 + t * d)
                f_new = self._eval(closure)
                if f_new <= f0 + 1e-4 * t * g0d or \
                        self.line_search_fn is None:
                    break
                t *= 0.5
            g_new = self._flat_grad()
            s_vec = t * d
            y_vec = g_new - g
            if float(s_vec @ y_vec) > 1e-10:
                self._s.append(s_vec)
                self._y.append(y_vec)
                if len(self._s) > self.history_size:
                    self._s.pop(0)
                    self._y.pop(0)
            if abs(f_new - loss) < self.tol_change:
                loss, g = f_new, g_new
                break
            loss, g = f_new, g_new
        return Tensor(jnp.asarray(loss, jnp.float32))


class ModelAverage(Optimizer):
    """modelaverage.py — maintain a running average of the parameters over
    a sliding window and swap it in for evaluation.

    ``step()`` (called after the inner training step) banks the current
    weights into the accumulators; ``apply()`` swaps the averaged weights
    in (a context manager, like the reference's); ``restore()`` puts the
    trained weights back.  The window grows until
    ``max_average_window`` (or ``average_window_rate`` x steps), then the
    oldest contributions are retired wholesale — the reference's
    sum_1/sum_2/sum_3 rotation, kept here as (old_sum, cur_sum) blocks."""

    def __init__(self, average_window_rate: float,
                 parameters: Optional[List] = None,
                 min_average_window: int = 10000,
                 max_average_window: int = 10000, name=None):
        self.avg_rate = float(average_window_rate)
        self.min_window = int(min_average_window)
        self.max_window = int(max_average_window)
        self._parameters = list(parameters or [])
        # accumulators stay DEVICE arrays: step() only enqueues adds
        # (async dispatch), nothing syncs until apply() reads back
        self._old_sum = [jnp.zeros(p._value.shape, jnp.float32)
                         for p in self._parameters]
        self._old_cnt = 0
        self._cur_sum = [jnp.zeros_like(s) for s in self._old_sum]
        self._cur_cnt = 0
        self._step_count = 0
        self._backup = None

    def step(self):
        self._step_count += 1
        for i, p in enumerate(self._parameters):
            self._cur_sum[i] = self._cur_sum[i] +                 p._value.astype(jnp.float32)
        self._cur_cnt += 1
        window = min(self.max_window,
                     max(self.min_window,
                         int(self.avg_rate * self._step_count)))
        if self._cur_cnt >= window:
            # rotate: current block becomes the retained old block
            self._old_sum = self._cur_sum
            self._old_cnt = self._cur_cnt
            self._cur_sum = [jnp.zeros_like(s) for s in self._old_sum]
            self._cur_cnt = 0

    def _averaged(self, i):
        cnt = self._old_cnt + self._cur_cnt
        if cnt == 0:
            return np.asarray(self._parameters[i]._value, np.float32)
        return np.asarray((self._old_sum[i] + self._cur_sum[i]) / cnt)

    def apply(self, executor=None, need_restore: bool = True):
        """Context manager: parameters hold their AVERAGED values inside
        the block (restored on exit when ``need_restore``)."""
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            self._backup = [np.asarray(p._value).copy()
                            for p in self._parameters]
            for i, p in enumerate(self._parameters):
                p.set_value(jnp.asarray(self._averaged(i), p.dtype))
            try:
                yield
            finally:
                if need_restore:
                    self.restore()

        return _ctx()

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p, b in zip(self._parameters, self._backup):
            p.set_value(jnp.asarray(b, p.dtype))
        self._backup = None

    def clear_grad(self, set_to_zero: bool = False):
        for p in self._parameters:
            p.clear_gradient(set_to_zero)

    def state_dict(self):
        return {"old_sum": self._old_sum, "old_cnt": self._old_cnt,
                "cur_sum": self._cur_sum, "cur_cnt": self._cur_cnt,
                "step_count": self._step_count}


__all__.append("ModelAverage")
