"""paddle.incubate.autotune — runtime autotuning config entry.

Analog of python/paddle/incubate/autotune.py set_config: a JSON-ish dict
(or file) toggling kernel autotuning.  On this stack the consumer is
ops/autotune.py (Pallas block sizes, paged-decode pages-per-step, the
varlen packed/dense dispatcher), switched by FLAGS_use_autotune."""

from __future__ import annotations

import json

__all__ = ["set_config"]


def set_config(config=None):
    """Enable/disable kernel autotune.  ``config`` may be None (enable
    everything, the reference default), a dict like
    {"kernel": {"enable": True, "tuning_range": [1, 10]}}, or a path to
    a JSON file with that layout.  Only the kernel section is meaningful
    on TPU (layout/dataloader tuning is discharged onto XLA/the input
    pipeline)."""
    from ..common import flags as _flags

    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    enable = True
    if isinstance(config, dict):
        enable = bool(config.get("kernel", {}).get("enable", True))
    _flags.set_flags({"FLAGS_use_autotune": enable})
