from .gate import GShardGate, NaiveGate, SwitchGate
from .moe_layer import MoELayer
