from .gate import GShardGate, NaiveGate, SwitchGate
from .grad_clip import ClipGradForMOEByGlobalNorm
from .moe_layer import MoELayer
