"""MoE layer — expert-parallel mixture of experts.

Analog of the reference's ``MoELayer``
(python/paddle/incubate/distributed/models/moe/moe_layer.py:263) with its
MoEScatter/MoEGather alltoall PyLayers (:99,:149) and global_scatter/
global_gather kernels.

TPU-native design: the whole layer is ONE masked-einsum program (GShard
formulation).  Expert weights are stacked [E, ...] and Shard(0) over the
``ep`` mesh axis; the dispatch einsum  ``gec,gm->ecm``  then forces XLA to
emit exactly the token alltoall the reference hand-writes, fused with the
expert matmuls.  The forward is one registered op, so the eager tape
records a single VJP for the entire mixture.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .....core.tensor import Tensor
from .....nn.layer import Layer, Parameter
from .....ops.registry import register
from .gate import (GShardGate, NaiveGate, SwitchGate,
                   top_k_masks_with_drops)


@register("moe_forward", amp="white")
def _moe_forward_op(x2d, gate_w, w_up, b_up, w_down, b_down, *,
                    topk: int, capacity: int, aux_fn=None, activation="gelu"):
    """x2d: [G, m]; gate_w: [m, E]; w_up: [E, m, h]; w_down: [E, h, m].
    Returns (y [G, m], aux_loss scalar, dropped fp32 scalar — the count
    of routing assignments the capacity factor silently refused;
    round-18 surfaces it instead of letting tokens vanish.  Float so
    the eager tape's vjp sees a normal-cotangent output)."""
    logits = x2d.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    combine, dispatch, dropped = top_k_masks_with_drops(probs, topk,
                                                       capacity)
    aux = aux_fn(probs) if aux_fn is not None else jnp.asarray(0.0)
    cdt = combine.astype(x2d.dtype)
    ddt = dispatch.astype(x2d.dtype)
    expert_in = jnp.einsum("gec,gm->ecm", ddt, x2d)     # token alltoall
    h = jnp.einsum("ecm,emh->ech", expert_in, w_up) + b_up[:, None, :]
    if activation == "gelu":
        h = jax.nn.gelu(h)
    elif activation == "relu":
        h = jax.nn.relu(h)
    elif activation == "swiglu":  # w_up holds 2*h; split
        a, b = jnp.split(h, 2, axis=-1)
        h = jax.nn.silu(a) * b
    eo = jnp.einsum("ech,ehm->ecm", h, w_down) + b_down[:, None, :]
    y = jnp.einsum("gec,ecm->gm", cdt, eo)              # combine alltoall
    return y, aux, lax.stop_gradient(dropped).astype(jnp.float32)


@register("moe_dropless_forward", amp="white")
def _moe_dropless_op(x2d, gate_w, w_up, b_up, w_down, b_down, *,
                     topk: int, aux_fn=None, activation="gelu"):
    """Dropless (capacity = infinity) MoE without dense all-expert
    compute — the MegaBlocks formulation on TPU: routed tokens are
    SORTED by expert id and pushed through grouped GEMMs
    (``lax.ragged_dot``: one MXU pass per expert group, group sizes
    dynamic), then unsorted and combined.  Exactly G*topk token-FFN
    products regardless of routing skew, vs the capacity path's dense
    [G, E, C] dispatch (reference fused_moe's eval path computes all E
    experts per token).

    x2d: [G, m]; returns (y [G, m], aux)."""
    g, m = x2d.shape
    e = gate_w.shape[1]
    logits = x2d.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    aux = aux_fn(probs) if aux_fn is not None else jnp.asarray(0.0)
    top_p, top_ids = jax.lax.top_k(probs, topk)         # [G, k]
    flat_ids = top_ids.reshape(-1)                      # [G*k]
    order = jnp.argsort(flat_ids, stable=True)
    sorted_ids = flat_ids[order]
    token_of = order // topk                            # source token
    xs = x2d[token_of]                                  # [G*k, m] sorted
    group_sizes = jnp.bincount(flat_ids, length=e).astype(jnp.int32)
    h = jax.lax.ragged_dot(xs, w_up.astype(xs.dtype), group_sizes) \
        + b_up.astype(xs.dtype)[sorted_ids]
    if activation == "gelu":
        h = jax.nn.gelu(h)
    elif activation == "relu":
        h = jax.nn.relu(h)
    elif activation == "swiglu":
        a, b = jnp.split(h, 2, axis=-1)
        h = jax.nn.silu(a) * b
    eo = jax.lax.ragged_dot(h, w_down.astype(h.dtype), group_sizes) \
        + b_down.astype(h.dtype)[sorted_ids]
    wgt = top_p.reshape(-1)[order].astype(x2d.dtype)
    y = jnp.zeros_like(x2d).at[token_of].add(eo * wgt[:, None])
    # dropless by construction: the overflow count is structurally zero
    return y, aux, jnp.zeros((), jnp.float32)


class MoELayer(Layer):
    """Drop-in MoE FFN.

    Reference API (moe_layer.py:263) takes d_model + a list of expert
    Layers + gate name; here experts are stacked weights (the layout the
    expert-parallel axis shards), constructed from (d_model, d_hidden,
    num_expert).  ``l_aux`` holds the last aux loss (reference attribute).
    """

    GATES = {"naive": NaiveGate, "gshard": GShardGate, "switch": SwitchGate}

    def __init__(self, d_model: int, d_hidden: int, num_expert: int = 8,
                 gate: str = "gshard", top_k: int = 2,
                 capacity_factor: float = 1.2, activation: str = "gelu",
                 mesh: Optional[Mesh] = None, ep_axis: str = "ep",
                 mp_axis: Optional[str] = None,
                 moe_group=None, recompute_interval: int = 0,
                 dropless: bool = False):
        super().__init__()
        if isinstance(gate, str):
            topk = 1 if gate == "switch" else top_k
            self.gate = self.GATES[gate](d_model, num_expert, topk=topk)
        else:
            self.gate = gate
        self.num_expert = num_expert
        self.capacity_factor = capacity_factor
        self.activation = activation
        self.dropless = dropless
        self.l_aux = None
        self.tokens_dropped = None
        scale = 1.0 / (d_model ** 0.5)
        import numpy as np
        rng = np.random.RandomState(0)
        self.w_up = Parameter(jnp.asarray(
            rng.randn(num_expert, d_model, d_hidden) * scale, jnp.float32))
        self.b_up = Parameter(jnp.zeros((num_expert, d_hidden), jnp.float32))
        self.w_down = Parameter(jnp.asarray(
            rng.randn(num_expert, d_hidden, d_model) * scale, jnp.float32))
        self.b_down = Parameter(jnp.zeros((num_expert, d_model), jnp.float32))
        # expert-parameter flag consumed by ClipGradForMOEByGlobalNorm (the
        # reference marks these via no_sync/is_expert on each expert Layer)
        for p_ in (self.w_up, self.b_up, self.w_down, self.b_down):
            p_.is_expert = True
        if mesh is not None and ep_axis in mesh.axis_names \
                and mesh.shape[ep_axis] > 1:
            # EP×TP composition: experts Shard(0) over ep; the expert FFN
            # hidden dim additionally Megatron-sharded over mp (the
            # reference composes MoELayer inside a TP group the same way)
            mp = (mp_axis if mp_axis and mp_axis in mesh.axis_names
                  and mesh.shape[mp_axis] > 1 else None)
            specs = {
                "w_up": P(ep_axis, None, mp),
                "b_up": P(ep_axis, mp),
                "w_down": P(ep_axis, mp, None),
                "b_down": P(ep_axis, None),
            }
            for name, spec in specs.items():
                p_ = getattr(self, name)
                p_.set_value(jax.device_put(
                    p_._value, NamedSharding(mesh, spec)))
            self.gate.weight.set_value(jax.device_put(
                self.gate.weight._value, NamedSharding(mesh, P())))

    def forward(self, x):
        shape = x.shape
        d = shape[-1]
        x2d = x.reshape([-1, d])
        if self.dropless:
            y, aux, dropped = _moe_dropless_op(
                x2d, self.gate.weight, self.w_up, self.b_up, self.w_down,
                self.b_down, topk=self.gate.topk,
                aux_fn=type(self.gate).aux_loss_fn,
                activation=self.activation)
        else:
            g = x2d.shape[0]
            capacity = self.gate.capacity(g, self.capacity_factor)
            y, aux, dropped = _moe_forward_op(
                x2d, self.gate.weight, self.w_up, self.b_up, self.w_down,
                self.b_down, topk=self.gate.topk, capacity=capacity,
                aux_fn=type(self.gate).aux_loss_fn,
                activation=self.activation)
        self.l_aux = aux
        # round-18: capacity overflow surfaced, never silent — the count
        # of routing assignments refused by the capacity factor this
        # forward (0 in the dropless formulation by construction)
        self.tokens_dropped = dropped
        return y.reshape(shape)
