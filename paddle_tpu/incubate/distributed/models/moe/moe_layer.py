"""MoE layer — expert-parallel mixture of experts.

Analog of the reference's ``MoELayer``
(python/paddle/incubate/distributed/models/moe/moe_layer.py:263) with its
MoEScatter/MoEGather alltoall PyLayers (:99,:149) and global_scatter/
global_gather kernels.

TPU-native design: the whole layer is ONE masked-einsum program (GShard
formulation).  Expert weights are stacked [E, ...] and Shard(0) over the
``ep`` mesh axis; the dispatch einsum  ``gec,gm->ecm``  then forces XLA to
emit exactly the token alltoall the reference hand-writes, fused with the
expert matmuls.  The forward is one registered op, so the eager tape
records a single VJP for the entire mixture.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .....core.tensor import Tensor
from .....nn.layer import Layer, Parameter
from .....ops.registry import register
from .gate import GShardGate, NaiveGate, SwitchGate, top_k_masks


@register("moe_forward", amp="white")
def _moe_forward_op(x2d, gate_w, w_up, b_up, w_down, b_down, *,
                    topk: int, capacity: int, aux_fn=None, activation="gelu"):
    """x2d: [G, m]; gate_w: [m, E]; w_up: [E, m, h]; w_down: [E, h, m].
    Returns (y [G, m], aux_loss scalar)."""
    logits = x2d.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    combine, dispatch = top_k_masks(probs, topk, capacity)
    aux = aux_fn(probs) if aux_fn is not None else jnp.asarray(0.0)
    cdt = combine.astype(x2d.dtype)
    ddt = dispatch.astype(x2d.dtype)
    expert_in = jnp.einsum("gec,gm->ecm", ddt, x2d)     # token alltoall
    h = jnp.einsum("ecm,emh->ech", expert_in, w_up) + b_up[:, None, :]
    if activation == "gelu":
        h = jax.nn.gelu(h)
    elif activation == "relu":
        h = jax.nn.relu(h)
    elif activation == "swiglu":  # w_up holds 2*h; split
        a, b = jnp.split(h, 2, axis=-1)
        h = jax.nn.silu(a) * b
    eo = jnp.einsum("ech,ehm->ecm", h, w_down) + b_down[:, None, :]
    y = jnp.einsum("gec,ecm->gm", cdt, eo)              # combine alltoall
    return y, aux


class MoELayer(Layer):
    """Drop-in MoE FFN.

    Reference API (moe_layer.py:263) takes d_model + a list of expert
    Layers + gate name; here experts are stacked weights (the layout the
    expert-parallel axis shards), constructed from (d_model, d_hidden,
    num_expert).  ``l_aux`` holds the last aux loss (reference attribute).
    """

    GATES = {"naive": NaiveGate, "gshard": GShardGate, "switch": SwitchGate}

    def __init__(self, d_model: int, d_hidden: int, num_expert: int = 8,
                 gate: str = "gshard", top_k: int = 2,
                 capacity_factor: float = 1.2, activation: str = "gelu",
                 mesh: Optional[Mesh] = None, ep_axis: str = "ep",
                 mp_axis: Optional[str] = None,
                 moe_group=None, recompute_interval: int = 0):
        super().__init__()
        if isinstance(gate, str):
            topk = 1 if gate == "switch" else top_k
            self.gate = self.GATES[gate](d_model, num_expert, topk=topk)
        else:
            self.gate = gate
        self.num_expert = num_expert
        self.capacity_factor = capacity_factor
        self.activation = activation
        self.l_aux = None
        scale = 1.0 / (d_model ** 0.5)
        import numpy as np
        rng = np.random.RandomState(0)
        self.w_up = Parameter(jnp.asarray(
            rng.randn(num_expert, d_model, d_hidden) * scale, jnp.float32))
        self.b_up = Parameter(jnp.zeros((num_expert, d_hidden), jnp.float32))
        self.w_down = Parameter(jnp.asarray(
            rng.randn(num_expert, d_hidden, d_model) * scale, jnp.float32))
        self.b_down = Parameter(jnp.zeros((num_expert, d_model), jnp.float32))
        # expert-parameter flag consumed by ClipGradForMOEByGlobalNorm (the
        # reference marks these via no_sync/is_expert on each expert Layer)
        for p_ in (self.w_up, self.b_up, self.w_down, self.b_down):
            p_.is_expert = True
        if mesh is not None and ep_axis in mesh.axis_names \
                and mesh.shape[ep_axis] > 1:
            # EP×TP composition: experts Shard(0) over ep; the expert FFN
            # hidden dim additionally Megatron-sharded over mp (the
            # reference composes MoELayer inside a TP group the same way)
            mp = (mp_axis if mp_axis and mp_axis in mesh.axis_names
                  and mesh.shape[mp_axis] > 1 else None)
            specs = {
                "w_up": P(ep_axis, None, mp),
                "b_up": P(ep_axis, mp),
                "w_down": P(ep_axis, mp, None),
                "b_down": P(ep_axis, None),
            }
            for name, spec in specs.items():
                p_ = getattr(self, name)
                p_.set_value(jax.device_put(
                    p_._value, NamedSharding(mesh, spec)))
            self.gate.weight.set_value(jax.device_put(
                self.gate.weight._value, NamedSharding(mesh, P())))

    def forward(self, x):
        shape = x.shape
        d = shape[-1]
        x2d = x.reshape([-1, d])
        g = x2d.shape[0]
        capacity = self.gate.capacity(g, self.capacity_factor)
        y, aux = _moe_forward_op(
            x2d, self.gate.weight, self.w_up, self.b_up, self.w_down,
            self.b_down, topk=self.gate.topk, capacity=capacity,
            aux_fn=type(self.gate).aux_loss_fn, activation=self.activation)
        self.l_aux = aux
        return y.reshape(shape)
