"""MoE gates.

Analog of the reference's gate zoo
(python/paddle/incubate/distributed/models/moe/gate/{naive,gshard,switch}
_gate.py).  Each gate maps token logits to (combine_weights [G,E,C],
dispatch_mask [G,E,C], aux_loss) in the GShard masked-einsum formulation —
the dispatch XLA partitions into an alltoall over the expert axis, versus
the reference's explicit global_scatter/global_gather CUDA ops
(paddle/fluid/operators/collective/global_scatter_op.cu.cc).

The mask math lives in pure functions (jit/tape friendly); the Layer
classes hold the gate weight Parameter and the per-gate aux-loss choice.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .....nn.layer import Layer, Parameter


def _one_hot(idx, num):
    return jax.nn.one_hot(idx, num, dtype=jnp.float32)


def moe_capacity(num_tokens: int, topk: int, num_expert: int,
                 capacity_factor: float) -> int:
    """THE per-expert slot-count rule (reference naive_gate semantics):
    the single copy shared by the gate zoo and the round-18 EP engine's
    per-(rank, expert) capacity, so the two can never desynchronize."""
    return int(capacity_factor * num_tokens * topk / num_expert + 1)


def load_balance_aux_loss(probs):
    """GShard eq.(4) / Switch: E * sum(frac_top1_tokens * mean_prob)."""
    e = probs.shape[-1]
    top1 = jnp.argmax(probs, axis=-1)
    frac = _one_hot(top1, e).mean(axis=0)
    return e * jnp.sum(frac * probs.mean(axis=0))


def zero_aux_loss(probs):
    return jnp.asarray(0.0, jnp.float32)


def top_k_masks(probs, topk: int, capacity: int):
    """Greedy top-k routing with per-expert capacity.

    probs: [G, E].  Returns (combine [G,E,C], dispatch [G,E,C]); tokens
    beyond an expert's capacity are dropped (reference semantics).
    Callers that need the overflow surfaced use
    ``top_k_masks_with_drops``."""
    combine, dispatch, _ = top_k_masks_with_drops(probs, topk, capacity)
    return combine, dispatch


def top_k_masks_with_drops(probs, topk: int, capacity: int):
    """``top_k_masks`` plus the capacity-overflow count: ``dropped`` is
    the number of (token, expert) routing assignments that exceeded the
    expert's capacity and silently vanished from combine/dispatch — the
    round-18 telemetry contract (a capacity-overflow is a MODEL QUALITY
    event, never a silent one; MoELayer surfaces it as
    ``tokens_dropped`` and the EP bench trace reports the rate)."""
    g, e = probs.shape
    combine = jnp.zeros((g, e, capacity), jnp.float32)
    dispatch = jnp.zeros((g, e, capacity), jnp.float32)
    remaining = probs
    position_in_expert = jnp.zeros((e,), jnp.int32)
    dropped = jnp.zeros((), jnp.int32)
    for _ in range(topk):
        idx = jnp.argmax(remaining, axis=-1)          # [G]
        mask = _one_hot(idx, e)                       # [G, E]
        # token's slot within its expert: running prefix count
        pos = (jnp.cumsum(mask, axis=0) - 1) * mask + \
            position_in_expert[None, :] * mask
        keep = (pos < capacity) & (mask > 0)
        # routed assignments past capacity: mask selected, keep refused
        dropped = dropped + ((mask > 0) & ~keep).sum().astype(jnp.int32)
        w = (probs * mask).sum(-1, keepdims=True)     # [G, 1] gate weight
        oh_pos = _one_hot(jnp.where(keep, pos.astype(jnp.int32), 0), capacity)
        sel = keep.astype(jnp.float32)[..., None] * oh_pos  # [G, E, C]
        combine = combine + w[..., None] * sel
        dispatch = jnp.maximum(dispatch, sel)
        position_in_expert = position_in_expert + mask.sum(0).astype(jnp.int32)
        remaining = remaining * (1.0 - mask)
    return combine, dispatch, dropped


class NaiveGate(Layer):
    """Top-k softmax gate, no aux loss (reference naive_gate.py)."""

    aux_loss_fn = staticmethod(zero_aux_loss)

    def __init__(self, d_model: int, num_expert: int, world_size: int = 1,
                 topk: int = 2):
        super().__init__()
        self.num_expert = num_expert * world_size
        self.topk = topk
        self.weight = Parameter(
            jnp.zeros((d_model, self.num_expert), dtype=jnp.float32))

    def capacity(self, num_tokens: int, capacity_factor: float) -> int:
        return moe_capacity(num_tokens, self.topk, self.num_expert,
                            capacity_factor)


class GShardGate(NaiveGate):
    """Top-2 gate with load-balancing aux loss (reference gshard_gate.py)."""

    aux_loss_fn = staticmethod(load_balance_aux_loss)

    def __init__(self, d_model: int, num_expert: int, world_size: int = 1,
                 topk: int = 2, capacity=(1.2, 2.4), group=None):
        super().__init__(d_model, num_expert, world_size, topk=topk)


class SwitchGate(NaiveGate):
    """Top-1 gate (reference switch_gate.py; Switch Transformer)."""

    aux_loss_fn = staticmethod(load_balance_aux_loss)

    def __init__(self, d_model: int, num_expert: int, world_size: int = 1,
                 topk: int = 1, capacity=(1.2, 2.4), group=None):
        super().__init__(d_model, num_expert, world_size, topk=1)
