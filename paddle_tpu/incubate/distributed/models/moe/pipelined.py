"""Pipelined MoE blocks: pp x ep x mp composition in one program.

The shared harness behind the 8-device dryrun leg and
tests/test_gpt_moe.py::test_moe_pipeline_ep_mp_composition: a stack of
MoE-FFN residual blocks pipelined over ``pp`` (layer-major chunks,
pipeline_apply dataflow) with experts Shard(ep) and expert hidden dims
Shard(mp) left to GSPMD.  Reference analog: MoE transformer blocks as
PipelineLayer segments under expert parallelism
(incubate/distributed/models/moe/moe_layer.py:263 + pp_layers.py).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .gate import top_k_masks
from .moe_layer import _moe_forward_op
from .....parallel import compat as _compat
from .....parallel.pipelining import pipeline_apply

MOE_BLOCK_SPECS = {
    "gate_w": P("pp", None, None),
    "w_up": P("pp", "ep", None, "mp"),
    "b_up": P("pp", "ep", "mp"),
    "w_down": P("pp", "ep", "mp", None),
    "b_down": P("pp", "ep", None),
}


from .....common.jax_compat import axis_size as _axis_size

def init_pipelined_moe_params(mesh: Mesh, num_layers: int, num_expert: int,
                              d_model: int, d_hidden: int,
                              seed: int = 0) -> Dict[str, Any]:
    """Layer-major [L, E, ...] expert stacks placed per MOE_BLOCK_SPECS."""
    rng = np.random.RandomState(seed)
    params = {
        "gate_w": jnp.asarray(
            rng.randn(num_layers, d_model, num_expert).astype(np.float32)),
        "w_up": jnp.asarray(rng.randn(
            num_layers, num_expert, d_model, d_hidden).astype(np.float32)
            * 0.3),
        "b_up": jnp.zeros((num_layers, num_expert, d_hidden), jnp.float32),
        "w_down": jnp.asarray(rng.randn(
            num_layers, num_expert, d_hidden, d_model).astype(np.float32)
            * 0.3),
        "b_down": jnp.zeros((num_layers, num_expert, d_model), jnp.float32),
    }
    return {k: jax.device_put(v, NamedSharding(mesh, MOE_BLOCK_SPECS[k]))
            for k, v in params.items()}


def moe_block(lp: Dict[str, Any], act, topk: int = 2):
    """One residual MoE-FFN block on raw arrays (capacity = full batch,
    i.e. no dropping — the parity-friendly setting)."""
    y, _, _ = _moe_forward_op.raw_fn(
        act, lp["gate_w"], lp["w_up"], lp["b_up"], lp["w_down"],
        lp["b_down"], topk=topk, capacity=act.shape[0], aux_fn=None)
    return act + y


def pipelined_moe_forward(params: Dict[str, Any], x, mesh: Mesh,
                          topk: int = 2):
    """Run [m, mb, d_model] micro-batches through the pipelined MoE
    stack; returns [m, mb, d_model] (valid everywhere — last-stage psum
    broadcast)."""

    def stage_fn(sp, act):
        act, _ = jax.lax.scan(
            lambda h, lp: (moe_block(lp, h, topk=topk), None), act, sp)
        return act

    def body(sp, x):
        outs = pipeline_apply(stage_fn, sp, x, axis="pp",
                              squeeze_stage_dim=False)
        last = (jax.lax.axis_index("pp")
                == _axis_size("pp") - 1).astype(outs.dtype)
        return jax.lax.psum(outs * last, "pp")

    from .....common.jax_compat import set_mesh as _set_mesh, \
        shard_map as _shard_map

    # FULL-manual region (round-9): every mesh axis is named, so the
    # jax-0.4.x SPMD partitioner never sees a partial-manual shard_map
    # (the PartitionId lowering it rejects).  The expert stacks keep
    # their Shard(ep)/Shard(mp) AT-REST placement; the P("pp") in_specs
    # gather them over ep/mp at the region boundary and the block
    # compute runs expert-replicated inside — the parity-friendly
    # setting this harness targets (capacity = full batch, no drops).
    with _set_mesh(mesh):
        return jax.jit(_shard_map(
            body, mesh=mesh, axis_names=set(mesh.axis_names),
            in_specs=(P("pp"), P(None)), out_specs=P(None),
            check_vma=False))(params, x)


def moe_block_ep(lp: Dict[str, Any], act, topk: int = 2,
                 ep_axis: str = "ep"):
    """One residual MoE-FFN block with experts SHARDED over ``ep``
    inside the manual region (round-18's ep>1 variant of the pipelined
    harness): each ep rank holds E_local expert stacks, slices the
    global routing masks to its expert block, computes only its own
    experts' slots, and the residual combine psums the partial outputs
    over ``ep`` — true expert-parallel compute, vs ``moe_block``'s
    gather-at-the-boundary expert-replicated body.  Tokens here are
    replicated over ep (the pipelined harness's layout), so no token
    all-to-all is needed; the dispatch/combine all-to-all engine for
    token-sharded EP lives in parallel/expert.py."""
    e_local = lp["w_up"].shape[0]
    ep = _axis_size(ep_axis)
    e = e_local * ep
    r = jax.lax.axis_index(ep_axis)
    logits = act.astype(jnp.float32) @ lp["gate_w"].astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    combine, dispatch = top_k_masks(probs, topk, act.shape[0])
    off = r * e_local
    cl = jax.lax.dynamic_slice_in_dim(combine, off, e_local, axis=1)
    dl = jax.lax.dynamic_slice_in_dim(dispatch, off, e_local, axis=1)
    cdt = cl.astype(act.dtype)
    ddt = dl.astype(act.dtype)
    expert_in = jnp.einsum("gec,gm->ecm", ddt, act)
    h = jnp.einsum("ecm,emh->ech", expert_in,
                   lp["w_up"].astype(act.dtype)) \
        + lp["b_up"].astype(act.dtype)[:, None, :]
    h = jax.nn.gelu(h)
    eo = jnp.einsum("ech,ehm->ecm", h, lp["w_down"].astype(act.dtype)) \
        + lp["b_down"].astype(act.dtype)[:, None, :]
    y_partial = jnp.einsum("gec,ecm->gm", cdt, eo)
    return act + _compat.psum(y_partial, ep_axis)


def pipelined_moe_forward_ep(params: Dict[str, Any], x, mesh: Mesh,
                             topk: int = 2):
    """The ep>1 variant of ``pipelined_moe_forward``: expert stacks stay
    Shard(ep) INSIDE the manual region (in_specs keep the ep entry on
    the [E] dim; only mp gathers at the boundary) and each pipeline
    stage runs ``moe_block_ep`` — pp x ep composition with ep-sharded
    compute in one program."""

    def stage_fn(sp, act):
        act, _ = jax.lax.scan(
            lambda h, lp: (moe_block_ep(lp, h, topk=topk), None), act, sp)
        return act

    def body(sp, x):
        outs = pipeline_apply(stage_fn, sp, x, axis="pp",
                              squeeze_stage_dim=False)
        last = (jax.lax.axis_index("pp")
                == _axis_size("pp") - 1).astype(outs.dtype)
        return jax.lax.psum(outs * last, "pp")

    from .....common.jax_compat import set_mesh as _set_mesh, \
        shard_map as _shard_map

    in_specs = ({
        "gate_w": P("pp", None, None),
        "w_up": P("pp", "ep", None, None),
        "b_up": P("pp", "ep", None),
        "w_down": P("pp", "ep", None, None),
        "b_down": P("pp", "ep", None),
    }, P(None))
    with _set_mesh(mesh):
        return jax.jit(_shard_map(
            body, mesh=mesh, axis_names=set(mesh.axis_names),
            in_specs=in_specs, out_specs=P(None),
            check_vma=False))(params, x)


def sequential_moe_forward(params: Dict[str, Any], x, topk: int = 2):
    """Unsharded sequential reference for parity checks."""
    num_layers = params["gate_w"].shape[0]
    ref = x
    for i in range(num_layers):
        lp = {k: v[i] for k, v in params.items()}
        ref = jnp.stack([moe_block(lp, ref[j], topk=topk)
                         for j in range(x.shape[0])])
    return ref
