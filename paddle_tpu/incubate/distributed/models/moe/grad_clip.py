"""MoE-aware global-norm gradient clipping.

Analog of the reference's ``ClipGradForMOEByGlobalNorm``
(python/paddle/incubate/distributed/models/moe/grad_clip.py): the global
norm must count each expert parameter exactly once across the
expert-parallel group. In the reference, each EP rank holds a distinct slice of experts, so
the expert-norm² is all-reduced over the moe_group before being combined
with the (replicated) dense-parameter norm². Under the single-controller
DTensor runtime the stacked expert weights are ONE global array (sharded
Shard(0) over the ``ep`` axis), so summing its squared entries already
yields the group-wide expert norm — the allreduce is what jnp.sum over a
sharded array compiles to. The class still performs the expert/dense
split so (a) ``is_expert_param`` filtering semantics match and (b) the two
norms are observable (``last_global_norm``/``last_moe_norm``) as in the
reference.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from .....core.tensor import Tensor
from .....optimizer.clip import GradClipBase


def _is_expert_param_default(p) -> bool:
    return bool(getattr(p, "is_expert", False)
                or getattr(p, "no_sync", False))


class ClipGradForMOEByGlobalNorm(GradClipBase):
    """Global-norm clip with the expert-parameter split.

    ``is_expert_param_func(p)`` selects expert params (default: params
    flagged ``is_expert``/``no_sync`` — the convention MoELayer sets).
    ``moe_group`` is accepted for API parity; group reduction is implied by
    the sharded sum under GSPMD (see module docstring).
    """

    def __init__(self, clip_norm: float,
                 is_expert_param_func: Optional[Callable] = None,
                 moe_group=None, group_name: str = "default_moe_group"):
        self.clip_norm = float(clip_norm)
        self.is_expert_param = is_expert_param_func or _is_expert_param_default
        self.moe_group = moe_group
        self.last_global_norm = None
        self.last_moe_norm = None

    def _sq_sum(self, pairs):
        terms = [jnp.sum(jnp.square((g._value if isinstance(g, Tensor) else g)
                                    .astype(jnp.float32)))
                 for _, g in pairs]
        if not terms:
            return jnp.zeros((), jnp.float32)
        return jnp.sum(jnp.stack(terms))

    def __call__(self, params, grads):
        dense, expert = [], []
        for p, g in zip(params, grads):
            if g is None or not getattr(p, "need_clip", True):
                continue
            (expert if self.is_expert_param(p) else dense).append((p, g))

        moe_sq = self._sq_sum(expert)
        dense_sq = self._sq_sum(dense)
        global_norm = jnp.sqrt(moe_sq + dense_sq)
        self.last_moe_norm = float(jnp.sqrt(moe_sq))
        self.last_global_norm = float(global_norm)

        factor = jnp.where(global_norm > self.clip_norm,
                           self.clip_norm / jnp.maximum(global_norm, 1e-12),
                           1.0)
        out = []
        for p, g in zip(params, grads):
            if g is None:
                out.append(None)
                continue
            v = g._value if isinstance(g, Tensor) else g
            if getattr(p, "need_clip", True):
                out.append(Tensor((v.astype(jnp.float32) * factor)
                                  .astype(v.dtype)))
            else:
                out.append(g if isinstance(g, Tensor) else Tensor(g))
        return out
