"""paddle_tpu.utils (analog of python/paddle/utils): cpp_extension custom-op
loader plus small helpers."""

from . import cpp_extension  # noqa: F401


def try_import(name):
    import importlib

    try:
        return importlib.import_module(name)
    except ImportError:
        return None
