"""C++ custom-op loader over the XLA FFI ABI.

Analog of paddle.utils.cpp_extension (load/setup building PD_BUILD_OP
libraries, python/paddle/utils/cpp_extension/) and the phi C ABI
(paddle/phi/capi): user C++ defines XLA FFI handlers (see
paddle_tpu/csrc/custom_ops.cpp for the pattern); ``load`` compiles the
sources against the jax-shipped ``xla/ffi/api`` headers, registers each
handler as an XLA custom-call target, and returns a module whose functions
dispatch through the framework op registry — so custom ops get AMP/tape
treatment and work under jit.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import types
from typing import Callable, Dict, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops.registry import register as _register_op

_loaded: Dict[str, types.SimpleNamespace] = {}


def _compile(name: str, sources: Sequence[str], build_dir: str,
             extra_cflags: Sequence[str]) -> str:
    os.makedirs(build_dir, exist_ok=True)
    so = os.path.join(build_dir, f"lib{name}.so")
    srcs = [os.path.abspath(s) for s in sources]
    newest_src = max(os.path.getmtime(s) for s in srcs)
    if not os.path.exists(so) or os.path.getmtime(so) < newest_src:
        tmp = f"{so}.tmp.{os.getpid()}"
        cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
               f"-I{jax.ffi.include_dir()}", *extra_cflags, *srcs,
               "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"cpp_extension build failed:\n{e.stderr}") from None
        os.replace(tmp, so)
    return so


def load(name: str,
         sources: Sequence[str],
         functions: Dict[str, Union[str, Tuple[str, Optional[Callable]]]],
         extra_cflags: Sequence[str] = (),
         build_directory: Optional[str] = None,
         platform: str = "cpu",
         verbose: bool = False) -> types.SimpleNamespace:
    """Compile + register custom ops; returns a namespace of callables.

    ``functions`` maps python op name -> C++ handler symbol, or
    ``(symbol, out_spec)`` where ``out_spec(*arrays) -> ShapeDtypeStruct``
    describes the output (default: same shape/dtype as the first input —
    the elementwise convention).
    """
    key = name
    if key in _loaded:
        return _loaded[key]
    build_dir = build_directory or os.path.join(
        os.path.dirname(sources[0]), "build")
    so_path = _compile(name, sources, build_dir, extra_cflags)
    lib = ctypes.CDLL(so_path)

    ns = types.SimpleNamespace(__so_path__=so_path)
    for py_name, spec in functions.items():
        symbol, out_spec = spec if isinstance(spec, tuple) else (spec, None)
        handler = getattr(lib, symbol)
        target = f"{name}.{py_name}"
        jax.ffi.register_ffi_target(target, jax.ffi.pycapsule(handler),
                                    platform=platform)

        def make_raw(target, out_spec):
            def raw(*arrays):
                if out_spec is None:
                    a0 = arrays[0]
                    out = jax.ShapeDtypeStruct(a0.shape, a0.dtype)
                else:
                    out = out_spec(*arrays)
                return jax.ffi.ffi_call(target, out)(*arrays)

            return raw

        raw = make_raw(target, out_spec)
        # first-class framework op: tape/AMP/jit via the normal dispatch
        public = _register_op(f"custom.{target}", nondiff=True)(raw)
        setattr(ns, py_name, public)
        setattr(ns, py_name + "_raw", raw)

    _loaded[key] = ns
    return ns


def builtin_custom_ops():
    """The in-tree demo library (csrc/custom_ops.cpp)."""
    src = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                       "csrc", "custom_ops.cpp")
    return load("paddle_tpu_demo_ops", [src],
                functions={"bias_gelu": "BiasGelu",
                           "relu_squared": "ReluSquared"})
