"""paddle_tpu — a TPU-native deep-learning framework.

Brand-new framework with the capabilities of the PaddlePaddle reference
(surveyed in SURVEY.md), designed TPU-first on JAX/XLA/Pallas/PJRT:

- eager execution with tape autograd (Tensor.backward) where every op is a
  pure-JAX function dispatched through a string-keyed registry;
- a trace-and-compile path (paddle_tpu.jit.to_static) that lowers to
  StableHLO and lets XLA do fusion (the reference needs CINN for this);
- hybrid parallelism (dp / sharding 1-3 / tp / sp-sep / pp / ep) expressed
  as one jax.sharding.Mesh with named axes + GSPMD, with shard_map +
  collectives for schedule-explicit paths (pipeline, MoE, ring attention);
- Pallas kernels for the fused hot ops (flash attention, rms_norm, rope).

Public API mirrors the reference's `paddle.*` surface.
"""

from __future__ import annotations

__version__ = "0.1.0"

# core
from .core.tensor import Tensor, to_tensor
from .core import dtype as _dtype_mod
from .core.dtype import (
    bfloat16, float16, float32, float64, int8, int16, int32, int64,
    uint8, bool_ as bool_dtype, complex64, complex128,
)
from .core.device import (
    CPUPlace, Place, TPUPlace, device_count, get_device, is_compiled_with_tpu,
    set_device,
)

# flags
from .common.flags import get_flags, set_flags

# autograd
from .autograd import no_grad, enable_grad, grad, is_grad_enabled
from .autograd import PyLayer

# ops (importing registers everything + patches Tensor methods)
from . import ops
from .ops import *  # noqa: F401,F403
from .ops.creation import assign, tril_indices, triu_indices  # noqa: F401
from .ops.random import (  # noqa: F401
    bernoulli, binomial, multinomial, normal, poisson, rand, randint, randn,
    randperm, seed, standard_normal, uniform, get_rng_state, set_rng_state,
)
from .ops.registry import dispatch as _dispatch

# subpackages (lazy-ish: imported eagerly for API availability)
from . import nn
from . import optimizer
from . import amp
from . import io
from . import autograd
from . import jit
from . import distributed
from . import vision
from . import metric
from . import hapi
from . import profiler
from . import incubate
from . import inference
from . import framework
from . import static
from . import device
from . import sparse
from . import distribution
from . import quantization
from . import utils
from . import geometric
from . import audio
from . import text
from . import onnx
from . import fft
from . import signal
from . import regularizer
from . import hub
from . import reader
from . import cost_model
from . import strings
from .core.selected_rows import SelectedRows
from .batch import batch


def save(obj, path, **kwargs):
    from .framework.io import save as _save

    return _save(obj, path, **kwargs)


def load(path, **kwargs):
    from .framework.io import load as _load

    return _load(path, **kwargs)


def is_grad_enabled_():
    return is_grad_enabled()


def disable_static():
    return None  # eager is the default and only imperative mode


def enable_static():
    raise NotImplementedError(
        "legacy static graph mode is replaced by paddle_tpu.jit.to_static "
        "(trace -> StableHLO -> XLA); see SURVEY.md §3.4"
    )


def in_dynamic_mode():
    return True


def get_default_dtype():
    return "float32"


_default_dtype = ["float32"]


def set_default_dtype(d):
    _default_dtype[0] = str(d)


# ---- round-5 top-level namespace completion (reference __all__ parity;
# asserted by tests/test_namespace_parity.py) ----
from .ops import compat_ops as _compat_ops  # registers the op long tail
from .ops.compat_ops import (  # noqa: F401
    add_n, block_diag, cartesian_prod, cdist, combinations, multigammaln,
    cumulative_trapezoid, deg2rad, diagonal_scatter, frexp, gammainc, gcd,
    histogram_bin_edges, histogramdd, isin, isneginf, isposinf, isreal,
    lcm, ldexp, masked_scatter, nanquantile, pdist, polar, quantile,
    rad2deg, scatter_nd, sgn, signbit, sinc,
    slice_scatter, tensordot, trapezoid, vander,
)
from .frontend_compat import (  # noqa: F401
    CUDAPinnedPlace, CUDAPlace, LazyGuard, ParamAttr, baddbmm,
    bitwise_invert, cauchy_,
    create_parameter, log_normal_, as_complex, as_real, atleast_1d,
    atleast_2d, atleast_3d, broadcast_shape, broadcast_tensors, check_shape,
    column_stack, complex, crop, cublas, cuda_nvrtc, cuda_runtime, cudnn,
    cufft, curand, cusolver, cusparse, disable_signal_handler, dsplit,
    dstack, equal_all, finfo, get_cuda_rng_state, hsplit, hstack,
    iinfo, index_reduce, is_complex, is_empty, is_floating_point,
    is_integer, is_tensor,
    log_normal, lu_solve, numel, nvjitlink, randint_like, rank, row_stack,
    set_cuda_rng_state, set_grad_enabled, set_printoptions, shape, slice,
    standard_gamma, strided_slice, take, tensor_split, tolist, unflatten,
    view, view_as, vsplit, vstack,
    # round-18 tranche: axis-movement aliases + msort/logdet
    logdet, movedim, msort, swapdims,
    # round-19 tranche: special-pair tail + manipulation bases
    argwhere, fliplr, flipud, float_power, logaddexp2, mvlgamma, narrow,
    ravel, take_along_dim, true_divide, xlogy,
    # round-21 tranche: blas-flavoured adds + the elementwise tail
    addbmm, addmv, addr, divide_no_nan, erfc, fix, fmod, negative,
    positive, vdot,
)

# registry-only ops that the reference exposes at top level


def _registry_export(_name):
    def _fn(*args, **kwargs):
        return _dispatch(_name, *args, **kwargs)

    _fn.__name__ = _name
    _fn.__doc__ = f"Top-level alias of the registered op ``{_name}``."
    return _fn


for _n in ("gammaln", "gammaincc", "i0", "i0e", "i1", "i1e", "polygamma",
           "reduce_as",
           "logit", "logcumsumexp", "kthvalue", "mode", "nanmedian",
           "trace", "diag_embed", "renorm", "multiplex", "index_sample",
           "unique_consecutive", "reverse", "increment", "shard_index",
           "bitwise_left_shift", "bitwise_right_shift",
           # round-14 tranche: nucleus sampling rides the registered op
           "top_p_sampling"):
    if _n not in globals():
        globals()[_n] = _registry_export(_n)

# aliases / class re-exports
from .hapi import Model, summary  # noqa: F401
from .distributed.fleet.meta_parallel import DataParallel  # noqa: F401
mod = remainder  # noqa: F405  (reference: mod == remainder == floor_mod)
floor_mod = remainder  # noqa: F405
bool = bool_dtype  # noqa: A001
import jax.numpy as _jnp

float8_e4m3fn = _jnp.float8_e4m3fn
float8_e5m2 = _jnp.float8_e5m2
dtype = _jnp.dtype


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Measured FLOPs of one forward at ``input_size`` (reference
    paddle.flops): traces the net on zeros and counts 2*M*N*K for every
    dispatched matmul/linear/conv (the dominant terms; elementwise ops
    are excluded, as in the reference counter)."""
    from .frontend_compat import count_flops

    return count_flops(net, input_size, print_detail=print_detail)


# in-place variants (see frontend_compat._inplace_of for semantics)
from .frontend_compat import _install_inplace as _mk_inplace

globals().update(_mk_inplace(globals()))
mod_ = globals()["remainder_"]     # reference: mod_ == remainder_
floor_mod_ = globals()["remainder_"]
from .frontend_compat import (bernoulli_, cast_, fill_, geometric_,  # noqa: F401,E402
                              normal_, zero_)
# round-13 tranche: the remaining sampling fills (uniform_ closes the
# standing exemption) + the diagonal-fill family
from .frontend_compat import (exponential_, fill_diagonal_,  # noqa: F401,E402
                              fill_diagonal_tensor,
                              fill_diagonal_tensor_, uniform_)
del _mk_inplace

# snapshot the framework-shipped op set (custom ops registered by user
# code/tests later are exempt from the YAML schema-completeness check)
from .ops.registry import freeze_builtin_ops as _freeze_builtin_ops

_freeze_builtin_ops()
