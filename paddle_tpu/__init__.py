"""paddle_tpu — a TPU-native deep-learning framework.

Brand-new framework with the capabilities of the PaddlePaddle reference
(surveyed in SURVEY.md), designed TPU-first on JAX/XLA/Pallas/PJRT:

- eager execution with tape autograd (Tensor.backward) where every op is a
  pure-JAX function dispatched through a string-keyed registry;
- a trace-and-compile path (paddle_tpu.jit.to_static) that lowers to
  StableHLO and lets XLA do fusion (the reference needs CINN for this);
- hybrid parallelism (dp / sharding 1-3 / tp / sp-sep / pp / ep) expressed
  as one jax.sharding.Mesh with named axes + GSPMD, with shard_map +
  collectives for schedule-explicit paths (pipeline, MoE, ring attention);
- Pallas kernels for the fused hot ops (flash attention, rms_norm, rope).

Public API mirrors the reference's `paddle.*` surface.
"""

from __future__ import annotations

__version__ = "0.1.0"

# core
from .core.tensor import Tensor, to_tensor
from .core import dtype as _dtype_mod
from .core.dtype import (
    bfloat16, float16, float32, float64, int8, int16, int32, int64,
    uint8, bool_ as bool_dtype, complex64, complex128,
)
from .core.device import (
    CPUPlace, Place, TPUPlace, device_count, get_device, is_compiled_with_tpu,
    set_device,
)

# flags
from .common.flags import get_flags, set_flags

# autograd
from .autograd import no_grad, enable_grad, grad, is_grad_enabled
from .autograd import PyLayer

# ops (importing registers everything + patches Tensor methods)
from . import ops
from .ops import *  # noqa: F401,F403
from .ops.creation import assign, tril_indices, triu_indices  # noqa: F401
from .ops.random import (  # noqa: F401
    bernoulli, binomial, multinomial, normal, poisson, rand, randint, randn,
    randperm, seed, standard_normal, uniform, get_rng_state, set_rng_state,
)
from .ops.registry import dispatch as _dispatch

# subpackages (lazy-ish: imported eagerly for API availability)
from . import nn
from . import optimizer
from . import amp
from . import io
from . import autograd
from . import jit
from . import distributed
from . import vision
from . import metric
from . import hapi
from . import profiler
from . import incubate
from . import inference
from . import framework
from . import static
from . import device
from . import sparse
from . import distribution
from . import quantization
from . import utils
from . import geometric
from . import audio
from . import text
from . import onnx
from . import fft
from . import signal
from . import regularizer
from . import hub
from . import reader
from . import cost_model
from . import strings
from .core.selected_rows import SelectedRows
from .batch import batch


def save(obj, path, **kwargs):
    from .framework.io import save as _save

    return _save(obj, path, **kwargs)


def load(path, **kwargs):
    from .framework.io import load as _load

    return _load(path, **kwargs)


def is_grad_enabled_():
    return is_grad_enabled()


def disable_static():
    return None  # eager is the default and only imperative mode


def enable_static():
    raise NotImplementedError(
        "legacy static graph mode is replaced by paddle_tpu.jit.to_static "
        "(trace -> StableHLO -> XLA); see SURVEY.md §3.4"
    )


def in_dynamic_mode():
    return True


def get_default_dtype():
    return "float32"


_default_dtype = ["float32"]


def set_default_dtype(d):
    _default_dtype[0] = str(d)


# snapshot the framework-shipped op set (custom ops registered by user
# code/tests later are exempt from the YAML schema-completeness check)
from .ops.registry import freeze_builtin_ops as _freeze_builtin_ops

_freeze_builtin_ops()
