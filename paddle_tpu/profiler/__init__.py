"""paddle_tpu.profiler (analog of python/paddle/profiler/profiler.py:358).

TPU-native: host-side RecordEvent spans + jax.profiler (XLA/TPU trace) into
one Perfetto/chrome trace; plus the in-training throughput meter
(reference: python/paddle/profiler/timer.py).
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional

import jax


class ProfilerTarget(Enum):
    CPU = 0
    TPU = 1
    CUSTOM_DEVICE = 2


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


_host_events: List[Dict[str, Any]] = []
_recording = [False]


class RecordEvent:
    """Host event span (analog of paddle/fluid/platform/profiler/event_tracing.h
    RecordEvent)."""

    def __init__(self, name: str, event_type: str = "UserDefined"):
        self.name = name
        self.event_type = event_type
        self._begin = None

    def begin(self):
        self._begin = time.perf_counter_ns()

    def end(self):
        if self._begin is None or not _recording[0]:
            return
        import threading

        _host_events.append({
            "name": self.name, "cat": self.event_type, "ph": "X",
            "ts": self._begin / 1000.0,
            "dur": (time.perf_counter_ns() - self._begin) / 1000.0,
            # full ident: masking could collide two threads into one
            # (pid, tid) sweep lane and corrupt the per-thread self-time
            # subtraction in summarize_events
            "pid": os.getpid(), "tid": threading.get_ident(),
        })

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False):
        self.timer_only = timer_only
        self._jax_trace_dir = None
        self._running = False

    def start(self):
        _recording[0] = True
        _host_events.clear()
        self._running = True
        if not self.timer_only and jax.default_backend() in ("tpu", "axon"):
            self._jax_trace_dir = "/tmp/paddle_tpu_profile"
            try:
                jax.profiler.start_trace(self._jax_trace_dir)
            except Exception:
                self._jax_trace_dir = None

    def stop(self):
        _recording[0] = False
        self._running = False
        if self._jax_trace_dir:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass

    def step(self):
        from ..common import flags as _flags

        if not self._running:
            return
        if (_flags.get_flag("FLAGS_log_memory_stats")
                or _flags.get_flag("FLAGS_enable_record_memory")):
            from .. import device as _device

            _host_events.append({
                "name": "memory_stats", "ph": "C", "dur": 0,
                "ts": time.perf_counter() * 1e6,
                "args": {"allocated": _device.memory_allocated(),
                         "max_allocated": _device.max_memory_allocated()},
            })

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def export_chrome_tracing(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump({"traceEvents": _host_events}, f)

    export = export_chrome_tracing

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms", top_n: int = 30):
        """Aggregated statistics table (the profiler_statistic.py analog:
        python/paddle/profiler/profiler_statistic.py) — per-event-name
        calls / total / avg / max / min and share of the profiled span,
        sorted by total self time."""
        return summarize_events(_host_events, time_unit=time_unit,
                                top_n=top_n)


def summarize_events(events, time_unit="ms", top_n: int = 30) -> str:
    """Build the top-N-by-SELF-time table from chrome-trace-style event
    dicts (ph == 'X'): nested span durations are subtracted from their
    parent (a RecordEvent wrapping ten op spans reports only its own
    overhead), so per-name ratios sum to <= 100% of the profiled wall
    span.  Also works on an EXPORTED trace: ``summarize_chrome_trace``."""
    div = {"s": 1e6, "ms": 1e3, "us": 1.0}[time_unit]
    # interval sweep PER (pid, tid): nesting only holds within one
    # thread — mixing threads would subtract unrelated concurrent spans
    # from each other's self time
    by_thread: Dict[tuple, list] = {}
    for e in events:
        if e.get("ph") == "X":
            by_thread.setdefault((e.get("pid", 0), e.get("tid", 0)),
                                 []).append(e)
    stats: Dict[str, list] = {}
    lo, hi = float("inf"), 0.0
    for spans in by_thread.values():
        spans.sort(key=lambda e: (e["ts"], -e["dur"]))
        # a span starting inside the currently-open span is its child —
        # subtract the child's (inclusive) duration from the parent's
        # self time (direct children only; grandchildren already
        # reduced the child)
        self_time = [e["dur"] for e in spans]
        open_stack: list = []
        for i, e in enumerate(spans):
            ts, dur = e["ts"], e["dur"]
            while open_stack and ts >= spans[open_stack[-1]]["ts"] \
                    + spans[open_stack[-1]]["dur"] - 1e-9:
                open_stack.pop()
            if open_stack:
                self_time[open_stack[-1]] -= dur
            open_stack.append(i)
            lo = min(lo, ts)
            hi = max(hi, ts + dur)
        for i, e in enumerate(spans):
            st = max(self_time[i], 0.0)
            s = stats.setdefault(e["name"], [0, 0.0, 0.0, float("inf")])
            s[0] += 1
            s[1] += st
            s[2] = max(s[2], st)
            s[3] = min(s[3], st)
    wall = max(hi - lo, 1e-9)
    header = (f"{'Name':<36}{'Calls':>8}{'Total(' + time_unit + ')':>14}"
              f"{'Avg(' + time_unit + ')':>12}{'Max(' + time_unit + ')':>12}"
              f"{'Min(' + time_unit + ')':>12}{'Ratio(%)':>10}")
    lines = ["-" * len(header), header, "-" * len(header)]
    rows = sorted(stats.items(), key=lambda kv: -kv[1][1])[:top_n]
    for name, (calls, total, mx, mn) in rows:
        lines.append(f"{name[:35]:<36}{calls:>8}{total / div:>14.3f}"
                     f"{total / calls / div:>12.3f}{mx / div:>12.3f}"
                     f"{mn / div:>12.3f}{100.0 * total / wall:>10.2f}")
    lines.append("-" * len(header))
    return "\n".join(lines)


def summarize_chrome_trace(path: str, time_unit="ms", top_n: int = 30) -> str:
    """Summary table from an exported chrome trace file."""
    with open(path) as f:
        data = json.load(f)
    events = data.get("traceEvents", data if isinstance(data, list) else [])
    return summarize_events(events, time_unit=time_unit, top_n=top_n)


class Timer:
    """Throughput meter (analog of python/paddle/profiler/timer.py)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._start = None
        self._steps = 0
        self._samples = 0

    def begin(self):
        self._start = time.perf_counter()

    def step(self, num_samples=1):
        self._steps += 1
        self._samples += num_samples

    def ips(self):
        if not self._start or self._steps == 0:
            return 0.0
        elapsed = time.perf_counter() - self._start
        return self._samples / elapsed

    def steps_per_sec(self):
        if not self._start or self._steps == 0:
            return 0.0
        return self._steps / (time.perf_counter() - self._start)


def benchmark():
    return Timer()
