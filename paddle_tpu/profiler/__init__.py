"""paddle_tpu.profiler (analog of python/paddle/profiler/profiler.py:358).

TPU-native: host-side RecordEvent spans + jax.profiler (XLA/TPU trace) into
one Perfetto/chrome trace; plus the in-training throughput meter
(reference: python/paddle/profiler/timer.py).
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional

import jax


class ProfilerTarget(Enum):
    CPU = 0
    TPU = 1
    CUSTOM_DEVICE = 2


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


_host_events: List[Dict[str, Any]] = []
_recording = [False]


class RecordEvent:
    """Host event span (analog of paddle/fluid/platform/profiler/event_tracing.h
    RecordEvent)."""

    def __init__(self, name: str, event_type: str = "UserDefined"):
        self.name = name
        self.event_type = event_type
        self._begin = None

    def begin(self):
        self._begin = time.perf_counter_ns()

    def end(self):
        if self._begin is None or not _recording[0]:
            return
        _host_events.append({
            "name": self.name, "cat": self.event_type, "ph": "X",
            "ts": self._begin / 1000.0,
            "dur": (time.perf_counter_ns() - self._begin) / 1000.0,
            "pid": os.getpid(), "tid": 0,
        })

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False):
        self.timer_only = timer_only
        self._jax_trace_dir = None
        self._running = False

    def start(self):
        _recording[0] = True
        _host_events.clear()
        self._running = True
        if not self.timer_only and jax.default_backend() in ("tpu", "axon"):
            self._jax_trace_dir = "/tmp/paddle_tpu_profile"
            try:
                jax.profiler.start_trace(self._jax_trace_dir)
            except Exception:
                self._jax_trace_dir = None

    def stop(self):
        _recording[0] = False
        self._running = False
        if self._jax_trace_dir:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass

    def step(self):
        from ..common import flags as _flags

        if not self._running:
            return
        if (_flags.get_flag("FLAGS_log_memory_stats")
                or _flags.get_flag("FLAGS_enable_record_memory")):
            from .. import device as _device

            _host_events.append({
                "name": "memory_stats", "ph": "C", "dur": 0,
                "ts": time.perf_counter() * 1e6,
                "args": {"allocated": _device.memory_allocated(),
                         "max_allocated": _device.max_memory_allocated()},
            })

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def export_chrome_tracing(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump({"traceEvents": _host_events}, f)

    export = export_chrome_tracing

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        agg: Dict[str, float] = {}
        for e in _host_events:
            agg[e["name"]] = agg.get(e["name"], 0.0) + e["dur"]
        lines = ["name\ttotal_us"]
        for name, dur in sorted(agg.items(), key=lambda kv: -kv[1]):
            lines.append(f"{name}\t{dur:.1f}")
        return "\n".join(lines)


class Timer:
    """Throughput meter (analog of python/paddle/profiler/timer.py)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._start = None
        self._steps = 0
        self._samples = 0

    def begin(self):
        self._start = time.perf_counter()

    def step(self, num_samples=1):
        self._steps += 1
        self._samples += num_samples

    def ips(self):
        if not self._start or self._steps == 0:
            return 0.0
        elapsed = time.perf_counter() - self._start
        return self._samples / elapsed

    def steps_per_sec(self):
        if not self._start or self._steps == 0:
            return 0.0
        return self._steps / (time.perf_counter() - self._start)


def benchmark():
    return Timer()
