// Demo out-of-tree custom ops over the XLA FFI C++ ABI.
//
// Analog of the reference's custom-op path (PD_BUILD_OP,
// paddle/fluid/framework/custom_operator.cc + phi/capi C ABI): a user
// compiles C++ against the framework-provided headers and the op becomes a
// first-class kernel. TPU-native shape: the C++ implements an XLA FFI
// handler; paddle_tpu.utils.cpp_extension compiles+registers it as an XLA
// custom call, so it composes with jit/grad like any other op.
//
// Handlers here are CPU reference kernels (the host side of the ABI); a
// TPU custom op would pair this with a Pallas kernel for the device.

#include <cmath>
#include <cstdint>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

// out = 0.5 * (x + bias) * (1 + tanh(sqrt(2/pi) * (v + 0.044715 v^3)))
static ffi::Error BiasGeluImpl(ffi::Buffer<ffi::F32> x,
                               ffi::Buffer<ffi::F32> bias,
                               ffi::ResultBuffer<ffi::F32> out) {
  const size_t n = x.element_count();
  const size_t nb = bias.element_count();
  if (nb == 0 || n % nb != 0)
    return ffi::Error::InvalidArgument("bias must divide x");
  const float* xp = x.typed_data();
  const float* bp = bias.typed_data();
  float* op = out->typed_data();
  for (size_t i = 0; i < n; ++i) {
    const float v = xp[i] + bp[i % nb];
    const float c = 0.7978845608028654f * (v + 0.044715f * v * v * v);
    op[i] = 0.5f * v * (1.0f + std::tanh(c));
  }
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(BiasGelu, BiasGeluImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::F32>>());

// out = max(x, 0)^2  — second symbol to exercise multi-op libraries
static ffi::Error ReluSquaredImpl(ffi::Buffer<ffi::F32> x,
                                  ffi::ResultBuffer<ffi::F32> out) {
  const size_t n = x.element_count();
  const float* xp = x.typed_data();
  float* op = out->typed_data();
  for (size_t i = 0; i < n; ++i) {
    const float r = xp[i] > 0.0f ? xp[i] : 0.0f;
    op[i] = r * r;
  }
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(ReluSquared, ReluSquaredImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::F32>>());
