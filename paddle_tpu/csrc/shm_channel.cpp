// shm_channel — process-shared ring-buffer byte channel for DataLoader
// worker -> parent batch transfer.
//
// TPU-native analog of the reference's shared-memory loader plumbing:
// paddle/fluid/memory/allocation/mmap_allocator.cc (shared-memory tensor
// transfer between loader worker processes and the trainer) plus the
// bounded blocking queue the readers push through
// (paddle/fluid/operators/reader/blocking_queue.h).  Native code is the
// point here: the consumer blocks in C (ctypes releases the GIL), so a
// waiting trainer thread never serializes Python worker threads, and the
// batch payload crosses the process boundary as two memcpys (worker
// numpy buffer -> ring, ring -> preallocated parent numpy buffer) with
// no pickling of array data and no pipe syscalls per batch.
//
// Layout: [Header | ring bytes].  Single producer, single consumer.
// Messages are 8-byte little-endian length-prefixed; bodies may wrap.
// Robust process-shared mutex: a worker dying mid-send surfaces as
// SHMCH_CLOSED/-EOWNERDEAD to the parent instead of a deadlock.
//
// Build: g++ -O2 -std=c++17 -shared -fPIC -pthread shm_channel.cpp -lrt

#include <cerrno>
#include <new>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Header {
  pthread_mutex_t mu;
  pthread_cond_t not_full;
  pthread_cond_t not_empty;
  uint64_t capacity;  // ring payload bytes
  uint64_t head;      // total bytes consumed (mod capacity = read pos)
  uint64_t tail;      // total bytes produced (mod capacity = write pos)
  uint32_t closed;    // producer hung up
};

struct Handle {
  Header* h;
  uint8_t* data;
  uint64_t map_len;
  int owner;  // created (and therefore unlinks) the segment
  char name[240];
};

timespec deadline_in(long timeout_ms) {
  timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  ts.tv_sec += timeout_ms / 1000;
  ts.tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts.tv_nsec >= 1000000000L) {
    ts.tv_sec += 1;
    ts.tv_nsec -= 1000000000L;
  }
  return ts;
}

// lock with robustness recovery; returns 0 or negative errno
int lock_mu(Header* h) {
  int rc = pthread_mutex_lock(&h->mu);
  if (rc == EOWNERDEAD) {
    // previous owner (a worker) died holding the lock: state is a byte
    // ring, always structurally consistent — recover and mark closed so
    // the consumer drains and stops
    pthread_mutex_consistent(&h->mu);
    h->closed = 1;
    return 0;
  }
  return rc ? -rc : 0;
}

constexpr int SHMCH_OK = 0;
constexpr int SHMCH_TIMEOUT = -1;
constexpr int SHMCH_CLOSED = -2;
constexpr int SHMCH_ERR = -3;

// copy n bytes into the ring at tail (caller holds lock and checked room)
void ring_write(Header* h, uint8_t* data, const uint8_t* src, uint64_t n) {
  uint64_t pos = h->tail % h->capacity;
  uint64_t first = n < h->capacity - pos ? n : h->capacity - pos;
  memcpy(data + pos, src, first);
  if (n > first) memcpy(data, src + first, n - first);
  h->tail += n;
}

void ring_read(Header* h, const uint8_t* data, uint8_t* dst, uint64_t n) {
  uint64_t pos = h->head % h->capacity;
  uint64_t first = n < h->capacity - pos ? n : h->capacity - pos;
  memcpy(dst, data + pos, first);
  if (n > first) memcpy(dst + first, data, n - first);
  h->head += n;
}

// stream n bytes (blocking in chunks as space frees)
int stream_send(Handle* hd, const uint8_t* src, uint64_t n, long timeout_ms) {
  Header* h = hd->h;
  uint64_t sent = 0;
  while (sent < n) {
    if (lock_mu(h) != 0) return SHMCH_ERR;
    timespec dl = deadline_in(timeout_ms);
    while (h->tail - h->head == h->capacity && !h->closed) {
      int rc = pthread_cond_timedwait(&h->not_full, &h->mu, &dl);
      if (rc == ETIMEDOUT) {
        pthread_mutex_unlock(&h->mu);
        return SHMCH_TIMEOUT;
      }
      if (rc == EOWNERDEAD) {
        pthread_mutex_consistent(&h->mu);
        h->closed = 1;
      }
    }
    if (h->closed) {
      pthread_mutex_unlock(&h->mu);
      return SHMCH_CLOSED;
    }
    uint64_t room = h->capacity - (h->tail - h->head);
    uint64_t chunk = n - sent < room ? n - sent : room;
    ring_write(h, hd->data, src + sent, chunk);
    sent += chunk;
    pthread_cond_signal(&h->not_empty);
    pthread_mutex_unlock(&h->mu);
  }
  return SHMCH_OK;
}

int stream_recv(Handle* hd, uint8_t* dst, uint64_t n, long timeout_ms) {
  Header* h = hd->h;
  uint64_t got = 0;
  while (got < n) {
    if (lock_mu(h) != 0) return SHMCH_ERR;
    timespec dl = deadline_in(timeout_ms);
    while (h->tail == h->head && !h->closed) {
      int rc = pthread_cond_timedwait(&h->not_empty, &h->mu, &dl);
      if (rc == ETIMEDOUT) {
        pthread_mutex_unlock(&h->mu);
        return SHMCH_TIMEOUT;
      }
      if (rc == EOWNERDEAD) {
        pthread_mutex_consistent(&h->mu);
        h->closed = 1;
      }
    }
    if (h->tail == h->head && h->closed) {
      // producer hung up and the ring is drained
      pthread_mutex_unlock(&h->mu);
      return SHMCH_CLOSED;
    }
    uint64_t avail = h->tail - h->head;
    uint64_t chunk = n - got < avail ? n - got : avail;
    ring_read(h, hd->data, dst + got, chunk);
    got += chunk;
    pthread_cond_signal(&h->not_full);
    pthread_mutex_unlock(&h->mu);
  }
  return SHMCH_OK;
}

}  // namespace

extern "C" {

void* shmch_create(const char* name, uint64_t capacity) {
  shm_unlink(name);  // stale segment from a crashed run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  uint64_t len = sizeof(Header) + capacity;
  if (ftruncate(fd, (off_t)len) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* p = mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (p == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  Header* h = static_cast<Header*>(p);
  memset(h, 0, sizeof(Header));
  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&h->not_full, &ca);
  pthread_cond_init(&h->not_empty, &ca);
  h->capacity = capacity;
  h->head = 0;
  h->tail = 0;
  h->closed = 0;
  Handle* hd = new Handle();
  hd->h = h;
  hd->data = (uint8_t*)p + sizeof(Header);
  hd->map_len = len;
  hd->owner = 1;
  strncpy(hd->name, name, sizeof(hd->name) - 1);
  return hd;
}

void* shmch_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* p = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                 MAP_SHARED, fd, 0);
  close(fd);
  if (p == MAP_FAILED) return nullptr;
  Handle* hd = new Handle();
  hd->h = (Header*)p;
  hd->data = (uint8_t*)p + sizeof(Header);
  hd->map_len = (uint64_t)st.st_size;
  hd->owner = 0;
  strncpy(hd->name, name, sizeof(hd->name) - 1);
  return hd;
}

// poison the stream: a partially-written frame would desynchronize the
// length-prefixed protocol (the consumer would read body bytes as a
// length) — mark closed so the peer gets SHMCH_CLOSED instead
static void shmch_poison(Handle* hd) {
  if (lock_mu(hd->h) == 0) {
    hd->h->closed = 1;
    pthread_cond_broadcast(&hd->h->not_empty);
    pthread_cond_broadcast(&hd->h->not_full);
    pthread_mutex_unlock(&hd->h->mu);
  }
}

// one framed message: 8-byte LE length, then the body
int shmch_send_msg(void* handle, const uint8_t* buf, uint64_t n,
                   long timeout_ms) {
  Handle* hd = (Handle*)handle;
  uint8_t hdr[8];
  memcpy(hdr, &n, 8);
  uint64_t tail0;
  {
    if (lock_mu(hd->h) != 0) return SHMCH_ERR;
    tail0 = hd->h->tail;
    pthread_mutex_unlock(&hd->h->mu);
  }
  int rc = stream_send(hd, hdr, 8, timeout_ms);
  if (rc == SHMCH_OK) rc = stream_send(hd, buf, n, timeout_ms);
  if (rc != SHMCH_OK && hd->h->tail != tail0) shmch_poison(hd);
  return rc;
}

// phase 1: consume the length prefix (returns >= 0 length, or negative
// status).  phase 2 (shmch_recv_body) reads exactly that many bytes,
// typically straight into a preallocated numpy buffer.
int64_t shmch_recv_len(void* handle, long timeout_ms) {
  Handle* hd = (Handle*)handle;
  uint64_t n = 0;
  int rc = stream_recv(hd, (uint8_t*)&n, 8, timeout_ms);
  if (rc != SHMCH_OK) return rc;
  return (int64_t)n;
}

int shmch_recv_body(void* handle, uint8_t* dst, uint64_t n, long timeout_ms) {
  return stream_recv((Handle*)handle, dst, n, timeout_ms);
}

// producer hangup: consumer drains buffered bytes then sees SHMCH_CLOSED
void shmch_close_write(void* handle) {
  Handle* hd = (Handle*)handle;
  if (lock_mu(hd->h) == 0) {
    hd->h->closed = 1;
    pthread_cond_broadcast(&hd->h->not_empty);
    pthread_cond_broadcast(&hd->h->not_full);
    pthread_mutex_unlock(&hd->h->mu);
  }
}

void shmch_close(void* handle) {
  Handle* hd = (Handle*)handle;
  munmap((void*)hd->h, hd->map_len);
  if (hd->owner) shm_unlink(hd->name);
  delete hd;
}

}  // extern "C"
