// TCP key-value coordination store — C++ native runtime component.
//
// Analog of the reference's TCPStore
// (paddle/phi/core/distributed/store/tcp_store.h:121, socket.cpp): the
// rendezvous/bootstrap KV used for comm-id exchange and barriers. The JAX
// coordination service owns jax.distributed bootstrap; this store is the
// framework-level equivalent surfaced as paddle.distributed.TCPStore —
// master hosts the map, clients SET/GET/ADD/WAIT over a length-prefixed
// binary protocol.
//
// Exposed as a C ABI for ctypes (no pybind11 in this image — see
// paddle_tpu/distributed/store.py for the Python wrapper).
//
// Protocol (all integers little-endian):
//   request:  u8 op | u32 klen | key bytes | u32 vlen | value bytes
//   response: i64 status | u32 plen | payload bytes
//   ops: 1=SET 2=GET 3=ADD(value=i64 delta, payload=i64 new value)
//        4=WAIT(value=u32 timeout_ms) 5=DEL 6=NUM_KEYS
//   status: 0 ok, -1 not found / timeout

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct Store {
  std::mutex mu;
  std::condition_variable cv;
  std::unordered_map<std::string, std::string> kv;
};

bool read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  size_t got = 0;
  while (got < n) {
    ssize_t r = ::recv(fd, p + got, n - got, 0);
    if (r <= 0) return false;
    got += static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  size_t sent = 0;
  while (sent < n) {
    ssize_t r = ::send(fd, p + sent, n - sent, 0);
    if (r <= 0) return false;
    sent += static_cast<size_t>(r);
  }
  return true;
}

bool send_response(int fd, int64_t status, const std::string& payload) {
  uint32_t plen = static_cast<uint32_t>(payload.size());
  std::vector<char> out(sizeof(status) + sizeof(plen) + payload.size());
  std::memcpy(out.data(), &status, sizeof(status));
  std::memcpy(out.data() + sizeof(status), &plen, sizeof(plen));
  std::memcpy(out.data() + sizeof(status) + sizeof(plen), payload.data(),
              payload.size());
  return write_full(fd, out.data(), out.size());
}

struct Server {
  Store store;
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> running{false};
  std::thread accept_thread;
  std::mutex conn_mu;
  std::vector<std::thread> conn_threads;
  std::vector<int> conn_fds;

  void handle_conn(int fd) {
    for (;;) {
      uint8_t op;
      uint32_t klen;
      if (!read_full(fd, &op, 1) || !read_full(fd, &klen, 4)) break;
      std::string key(klen, '\0');
      if (klen && !read_full(fd, key.data(), klen)) break;
      uint32_t vlen;
      if (!read_full(fd, &vlen, 4)) break;
      std::string val(vlen, '\0');
      if (vlen && !read_full(fd, val.data(), vlen)) break;

      bool ok = true;
      switch (op) {
        case 1: {  // SET
          {
            std::lock_guard<std::mutex> g(store.mu);
            store.kv[key] = val;
          }
          store.cv.notify_all();
          ok = send_response(fd, 0, "");
          break;
        }
        case 2: {  // GET
          std::lock_guard<std::mutex> g(store.mu);
          auto it = store.kv.find(key);
          ok = (it == store.kv.end()) ? send_response(fd, -1, "")
                                      : send_response(fd, 0, it->second);
          break;
        }
        case 3: {  // ADD
          int64_t delta = 0;
          if (val.size() == sizeof(delta))
            std::memcpy(&delta, val.data(), sizeof(delta));
          int64_t next = 0;
          {
            std::lock_guard<std::mutex> g(store.mu);
            auto it = store.kv.find(key);
            if (it != store.kv.end() && it->second.size() == sizeof(next))
              std::memcpy(&next, it->second.data(), sizeof(next));
            next += delta;
            std::string stored(sizeof(next), '\0');
            std::memcpy(stored.data(), &next, sizeof(next));
            store.kv[key] = stored;
          }
          store.cv.notify_all();
          std::string payload(sizeof(next), '\0');
          std::memcpy(payload.data(), &next, sizeof(next));
          ok = send_response(fd, 0, payload);
          break;
        }
        case 4: {  // WAIT
          uint32_t timeout_ms = 0;
          if (val.size() == sizeof(timeout_ms))
            std::memcpy(&timeout_ms, val.data(), sizeof(timeout_ms));
          std::unique_lock<std::mutex> g(store.mu);
          bool found = store.cv.wait_for(
              g, std::chrono::milliseconds(timeout_ms),
              [&] { return store.kv.count(key) > 0 || !running.load(); });
          ok = send_response(fd, (found && store.kv.count(key)) ? 0 : -1, "");
          break;
        }
        case 5: {  // DEL
          std::lock_guard<std::mutex> g(store.mu);
          ok = send_response(fd, store.kv.erase(key) ? 0 : -1, "");
          break;
        }
        case 6: {  // NUM_KEYS
          int64_t n;
          {
            std::lock_guard<std::mutex> g(store.mu);
            n = static_cast<int64_t>(store.kv.size());
          }
          std::string payload(sizeof(n), '\0');
          std::memcpy(payload.data(), &n, sizeof(n));
          ok = send_response(fd, 0, payload);
          break;
        }
        default:
          ok = send_response(fd, -2, "");
      }
      if (!ok) break;
    }
    {
      // deregister BEFORE closing: stop() must never shutdown() an fd
      // number the OS has already handed to someone else
      std::lock_guard<std::mutex> g(conn_mu);
      conn_fds.erase(std::remove(conn_fds.begin(), conn_fds.end(), fd),
                     conn_fds.end());
    }
    ::close(fd);
  }

  bool start(int want_port) {
    listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd < 0) return false;
    int one = 1;
    ::setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(want_port));
    if (::bind(listen_fd, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) != 0)
      return false;
    socklen_t alen = sizeof(addr);
    ::getsockname(listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
    port = ntohs(addr.sin_port);
    if (::listen(listen_fd, 128) != 0) return false;
    running = true;
    accept_thread = std::thread([this] {
      while (running.load()) {
        int fd = ::accept(listen_fd, nullptr, nullptr);
        if (fd < 0) break;
        int one2 = 1;
        ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one2, sizeof(one2));
        std::lock_guard<std::mutex> g(conn_mu);
        conn_fds.push_back(fd);
        conn_threads.emplace_back([this, fd] { handle_conn(fd); });
      }
    });
    return true;
  }

  void stop() {
    running = false;
    store.cv.notify_all();
    if (listen_fd >= 0) {
      ::shutdown(listen_fd, SHUT_RDWR);
      ::close(listen_fd);
    }
    if (accept_thread.joinable()) accept_thread.join();
    // unblock recv() in every connection thread, then JOIN them — a
    // detached thread would race the Server free (use-after-free on the
    // store mutex/map at teardown)
    {
      std::lock_guard<std::mutex> g(conn_mu);
      for (int fd : conn_fds) ::shutdown(fd, SHUT_RDWR);
    }
    std::vector<std::thread> threads;
    {
      std::lock_guard<std::mutex> g(conn_mu);
      threads.swap(conn_threads);
    }
    for (auto& t : threads)
      if (t.joinable()) t.join();
  }
};

struct Client {
  int fd = -1;
  std::mutex mu;

  bool request(uint8_t op, const std::string& key, const std::string& val,
               int64_t* status, std::string* payload) {
    std::lock_guard<std::mutex> g(mu);
    uint32_t klen = static_cast<uint32_t>(key.size());
    uint32_t vlen = static_cast<uint32_t>(val.size());
    std::vector<char> out(1 + 4 + key.size() + 4 + val.size());
    size_t off = 0;
    std::memcpy(out.data() + off, &op, 1); off += 1;
    std::memcpy(out.data() + off, &klen, 4); off += 4;
    std::memcpy(out.data() + off, key.data(), klen); off += klen;
    std::memcpy(out.data() + off, &vlen, 4); off += 4;
    std::memcpy(out.data() + off, val.data(), vlen);
    if (!write_full(fd, out.data(), out.size())) return false;
    uint32_t plen;
    if (!read_full(fd, status, 8) || !read_full(fd, &plen, 4)) return false;
    payload->assign(plen, '\0');
    if (plen && !read_full(fd, payload->data(), plen)) return false;
    return true;
  }
};

}  // namespace

extern "C" {

void* ts_server_start(int port) {
  auto* s = new Server();
  if (!s->start(port)) {
    delete s;
    return nullptr;
  }
  return s;
}

int ts_server_port(void* h) { return static_cast<Server*>(h)->port; }

void ts_server_stop(void* h) {
  auto* s = static_cast<Server*>(h);
  s->stop();
  delete s;
}

void* ts_client_connect(const char* host, int port, int timeout_ms) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(fd);
    return nullptr;
  }
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) !=
         0) {
    if (std::chrono::steady_clock::now() > deadline) {
      ::close(fd);
      return nullptr;
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto* c = new Client();
  c->fd = fd;
  return c;
}

// returns payload length, or -1 (not found/timeout), or -2 (io error)
long ts_client_request(void* h, int op, const char* key, const char* val,
                       long vlen, char* out, long outcap) {
  auto* c = static_cast<Client*>(h);
  int64_t status = 0;
  std::string payload;
  if (!c->request(static_cast<uint8_t>(op), key, std::string(val, vlen),
                  &status, &payload))
    return -2;
  if (status != 0) return -1;
  long n = static_cast<long>(payload.size());
  if (out && n <= outcap) std::memcpy(out, payload.data(), n);
  return n;
}

void ts_client_close(void* h) {
  auto* c = static_cast<Client*>(h);
  ::close(c->fd);
  delete c;
}

}  // extern "C"
