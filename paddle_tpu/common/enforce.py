"""Error/enforce library.

Analog of paddle/common/enforce.h (PADDLE_ENFORCE_* macros, EnforceNotMet)
and the phi error-code taxonomy (paddle/phi/core/errors.h): typed
exceptions carrying an error code, plus ``enforce``/``enforce_*`` check
helpers used across the runtime. The types multiply-inherit the closest
Python builtin (ValueError/KeyError/...) so idiomatic ``except ValueError``
call sites keep working.
"""

from __future__ import annotations

import enum
from typing import Any, NoReturn, Optional


class ErrorCode(enum.Enum):
    LEGACY = 0
    INVALID_ARGUMENT = 1
    NOT_FOUND = 2
    OUT_OF_RANGE = 3
    ALREADY_EXISTS = 4
    RESOURCE_EXHAUSTED = 5
    PRECONDITION_NOT_MET = 6
    PERMISSION_DENIED = 7
    EXECUTION_TIMEOUT = 8
    UNIMPLEMENTED = 9
    UNAVAILABLE = 10
    FATAL = 11
    EXTERNAL = 12


class EnforceNotMet(Exception):
    """Base framework error (enforce.h EnforceNotMet)."""

    code = ErrorCode.LEGACY

    def __init__(self, message: str = ""):
        super().__init__(message)
        self.message = message

    def __str__(self):
        return f"[{self.code.name}] {self.message}"


class InvalidArgumentError(EnforceNotMet, ValueError):
    code = ErrorCode.INVALID_ARGUMENT


class NotFoundError(EnforceNotMet, KeyError):
    code = ErrorCode.NOT_FOUND

    def __str__(self):  # KeyError quotes its arg; keep the enforce format
        return f"[{self.code.name}] {self.message}"


class OutOfRangeError(EnforceNotMet, IndexError):
    code = ErrorCode.OUT_OF_RANGE


class AlreadyExistsError(EnforceNotMet):
    code = ErrorCode.ALREADY_EXISTS


class ResourceExhaustedError(EnforceNotMet, MemoryError):
    code = ErrorCode.RESOURCE_EXHAUSTED


class PreconditionNotMetError(EnforceNotMet, RuntimeError):
    code = ErrorCode.PRECONDITION_NOT_MET


class PermissionDeniedError(EnforceNotMet, PermissionError):
    code = ErrorCode.PERMISSION_DENIED


class ExecutionTimeoutError(EnforceNotMet, TimeoutError):
    code = ErrorCode.EXECUTION_TIMEOUT


class UnimplementedError(EnforceNotMet, NotImplementedError):
    code = ErrorCode.UNIMPLEMENTED


class UnavailableError(EnforceNotMet, RuntimeError):
    code = ErrorCode.UNAVAILABLE


def enforce(cond: Any, message: str = "",
            exc: type = PreconditionNotMetError) -> None:
    """PADDLE_ENFORCE: raise ``exc(message)`` when ``cond`` is falsy."""
    if not cond:
        raise exc(message)


def enforce_eq(a, b, message: str = "") -> None:
    if a != b:
        raise InvalidArgumentError(
            f"expected {a!r} == {b!r}. {message}".rstrip())


def enforce_ne(a, b, message: str = "") -> None:
    if a == b:
        raise InvalidArgumentError(
            f"expected {a!r} != {b!r}. {message}".rstrip())


def enforce_gt(a, b, message: str = "") -> None:
    if not a > b:
        raise InvalidArgumentError(
            f"expected {a!r} > {b!r}. {message}".rstrip())


def enforce_ge(a, b, message: str = "") -> None:
    if not a >= b:
        raise InvalidArgumentError(
            f"expected {a!r} >= {b!r}. {message}".rstrip())


def enforce_lt(a, b, message: str = "") -> None:
    if not a < b:
        raise InvalidArgumentError(
            f"expected {a!r} < {b!r}. {message}".rstrip())


def enforce_le(a, b, message: str = "") -> None:
    if not a <= b:
        raise InvalidArgumentError(
            f"expected {a!r} <= {b!r}. {message}".rstrip())


def not_found(message: str) -> NoReturn:
    raise NotFoundError(message)


def unimplemented(message: str) -> NoReturn:
    raise UnimplementedError(message)
