"""Global flag registry.

TPU-native analog of the reference's exported-flag registry
(paddle/common/flags.h:93 ``PD_DEFINE_*`` + ``GetExportedFlagInfoMap``
flags.h:337; 183 definitions in paddle/common/flags.cc). Flags are
settable from the environment (``FLAGS_*``), from Python via
``set_flags``/``get_flags``, and are queried by subsystems at call time.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, Optional, Union

_LOCK = threading.RLock()


@dataclass
class FlagInfo:
    name: str
    default: Any
    doc: str
    type: type
    value: Any


_REGISTRY: Dict[str, FlagInfo] = {}


def _coerce(raw: str, ty: type) -> Any:
    if ty is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    return ty(raw)


def define_flag(name: str, default: Any, doc: str = "") -> None:
    """Register a flag. Environment variable ``name`` overrides the default."""
    ty = type(default)
    value = default
    env = os.environ.get(name)
    if env is not None:
        try:
            value = _coerce(env, ty)
        except (TypeError, ValueError):
            value = default
    with _LOCK:
        _REGISTRY[name] = FlagInfo(name=name, default=default, doc=doc, type=ty, value=value)


def get_flags(flags: Union[str, Iterable[str], None] = None) -> Dict[str, Any]:
    with _LOCK:
        if flags is None:
            return {k: v.value for k, v in _REGISTRY.items()}
        if isinstance(flags, str):
            flags = [flags]
        out = {}
        for name in flags:
            if name not in _REGISTRY:
                raise ValueError(f"unknown flag {name!r}")
            out[name] = _REGISTRY[name].value
        return out


def get_flag(name: str) -> Any:
    with _LOCK:
        return _REGISTRY[name].value


def set_flags(flags: Dict[str, Any]) -> None:
    with _LOCK:
        for name, value in flags.items():
            if name not in _REGISTRY:
                raise ValueError(f"unknown flag {name!r}")
            info = _REGISTRY[name]
            info.value = _coerce(value, info.type) if isinstance(value, str) else info.type(value)


def flag_info_map() -> Dict[str, FlagInfo]:
    with _LOCK:
        return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# Behavior-critical flags mirrored from the reference (paddle/common/flags.cc)
# plus TPU-native additions.
# ---------------------------------------------------------------------------
define_flag("FLAGS_check_nan_inf", False, "Check every op output for NaN/Inf (debug).")
define_flag("FLAGS_check_nan_inf_level", 0, "0: error on nan/inf; >0 only report.")
define_flag("FLAGS_use_autotune", False, "Enable runtime autotuning of kernel variants.")
define_flag("FLAGS_benchmark", False, "Synchronize after every op (benchmark mode).")
define_flag("FLAGS_tpu_eager_compile_cache", True, "Cache per-op compiled executables.")
define_flag("FLAGS_tpu_default_matmul_precision", "default", "default|high|highest")
define_flag("FLAGS_host_trace_level", 1, "Host profiler verbosity level.")
define_flag("FLAGS_enable_async_trace", False, "Enable async dispatch tracing.")
define_flag("FLAGS_tensor_operants_mode", "eager", "eager|static tensor operants mode.")
define_flag("FLAGS_comm_timeout_s", 1800, "Collective timeout (watchdog) in seconds.")
define_flag("FLAGS_allocator_strategy", "auto_growth", "Allocator strategy name (compat).")
define_flag("FLAGS_fraction_of_gpu_memory_to_use", 0.92, "Compat only; XLA manages HBM.")
define_flag("FLAGS_log_memory_stats", False, "Log live/peak memory stats per step.")
define_flag("FLAGS_eager_double_grad", True,
            "Record the create_graph (double-grad) re-derivation on eager "
            "ops. Disable to drop the saved-input captures and restore the "
            "minimal first-order memory profile (grad(create_graph=True) "
            "then falls back to constants).")
