"""Global flag registry.

TPU-native analog of the reference's exported-flag registry
(paddle/common/flags.h:93 ``PD_DEFINE_*`` + ``GetExportedFlagInfoMap``
flags.h:337; 183 definitions in paddle/common/flags.cc). Flags are
settable from the environment (``FLAGS_*``), from Python via
``set_flags``/``get_flags``, and are queried by subsystems at call time.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, Optional, Union

_LOCK = threading.RLock()


@dataclass
class FlagInfo:
    name: str
    default: Any
    doc: str
    type: type
    value: Any
    on_set: Optional[Callable[[Any], None]] = None


_REGISTRY: Dict[str, FlagInfo] = {}


def _coerce(raw: str, ty: type) -> Any:
    if ty is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    return ty(raw)


def define_flag(name: str, default: Any, doc: str = "",
                on_set: Optional[Callable[[Any], None]] = None) -> None:
    """Register a flag. Environment variable ``name`` overrides the default.
    ``on_set`` runs on every set_flags update (and once at definition if the
    environment overrode the default) — used to push a flag into an
    external config (e.g. jax.config)."""
    ty = type(default)
    value = default
    env = os.environ.get(name)
    if env is not None:
        try:
            value = _coerce(env, ty)
        except (TypeError, ValueError):
            value = default
    if on_set is not None and value != default:
        try:
            on_set(value)
        except Exception:
            value = default  # bad env value must not break import
    with _LOCK:
        _REGISTRY[name] = FlagInfo(name=name, default=default, doc=doc,
                                   type=ty, value=value, on_set=on_set)


def get_flags(flags: Union[str, Iterable[str], None] = None) -> Dict[str, Any]:
    with _LOCK:
        if flags is None:
            return {k: v.value for k, v in _REGISTRY.items()}
        if isinstance(flags, str):
            flags = [flags]
        out = {}
        for name in flags:
            if name not in _REGISTRY:
                raise ValueError(f"unknown flag {name!r}")
            out[name] = _REGISTRY[name].value
        return out


def get_flag(name: str) -> Any:
    with _LOCK:
        return _REGISTRY[name].value


_VERSION = 0


def version() -> int:
    """Monotone counter bumped by every set_flags commit — lets hot paths
    cache a flag snapshot and revalidate with one int compare instead of
    per-call lock trips (ops/registry.py fast dispatch)."""
    return _VERSION


def set_flags(flags: Dict[str, Any]) -> None:
    """Atomic batch update: every hook runs (and may reject) BEFORE any
    value commits, so a raised hook leaves the whole registry unchanged and
    external configs rolled back to the committed values. Runs under the
    re-entrant lock, so hook+commit pairs cannot interleave across threads
    (hooks may re-enter flags from the same thread)."""
    with _LOCK:
        pending = []
        for name, value in flags.items():
            if name not in _REGISTRY:
                raise ValueError(f"unknown flag {name!r}")
            info = _REGISTRY[name]
            coerced = _coerce(value, info.type) if isinstance(value, str) \
                else info.type(value)
            pending.append((info, coerced))
        hooked = []
        try:
            for info, coerced in pending:
                if info.on_set is not None:
                    info.on_set(coerced)
                    hooked.append(info)
        except Exception:
            for info in hooked:  # restore external state to committed values
                try:
                    info.on_set(info.value)
                except Exception:
                    pass
            raise
        for info, coerced in pending:
            info.value = coerced
        global _VERSION
        _VERSION += 1


def flag_info_map() -> Dict[str, FlagInfo]:
    with _LOCK:
        return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# Behavior-critical flags mirrored from the reference (paddle/common/flags.cc)
# plus TPU-native additions.
# ---------------------------------------------------------------------------
define_flag("FLAGS_check_nan_inf", False, "Check every op output for NaN/Inf (debug).")
define_flag("FLAGS_check_nan_inf_level", 0, "0: error on nan/inf; >0 only report.")
define_flag("FLAGS_use_autotune", False, "Enable runtime autotuning of kernel variants.")
define_flag("FLAGS_benchmark", False,
            "Synchronize after every op — eager timings then measure device "
            "time, not queue depth (wired: dispatch blocks on outputs).")
define_flag("FLAGS_tpu_eager_compile_cache", True,
            "Alias of FLAGS_eager_executable_cache kept from round 1; both "
            "must be on for the cache (wired: ops/registry).")


def _set_matmul_precision(value):
    import jax

    allowed = ("default", "float32", "bfloat16", "bfloat16_3x",
               "tensorfloat32", "high", "highest")
    if value not in allowed:
        raise ValueError(
            f"FLAGS_tpu_default_matmul_precision={value!r}; expected one "
            f"of {allowed}")
    jax.config.update("jax_default_matmul_precision",
                      None if value == "default" else value)


define_flag("FLAGS_tpu_default_matmul_precision", "default",
            "default|float32|bfloat16_3x|highest — pushed into "
            "jax.config.jax_default_matmul_precision on set (wired).",
            on_set=_set_matmul_precision)
define_flag("FLAGS_host_trace_level", 1, "Host profiler verbosity level.")
define_flag("FLAGS_enable_async_trace", False, "Enable async dispatch tracing.")
define_flag("FLAGS_tensor_operants_mode", "eager", "eager|static tensor operants mode.")
define_flag("FLAGS_comm_timeout_s", 1800, "Collective timeout (watchdog) in seconds.")
define_flag("FLAGS_store_barrier_timeout_s", 0.0,
            "Override for every TCPStore connect/barrier timeout (round-12 "
            "elastic satellite): 0 keeps each call site's default; set "
            "e.g. FLAGS_store_barrier_timeout_s=300 in the env to stretch "
            "the gang-rendezvous windows on throttled-CPU containers. "
            "Waits retry in slices with jittered exponential backoff "
            "(wired: distributed/store.py resolve_store_timeout).")
define_flag("FLAGS_allocator_strategy", "auto_growth", "Allocator strategy name (compat).")
define_flag("FLAGS_fraction_of_gpu_memory_to_use", 0.92, "Compat only; XLA manages HBM.")
define_flag("FLAGS_log_memory_stats", False, "Log live/peak memory stats per step.")
define_flag("FLAGS_eager_executable_cache", True,
            "Cache a jitted executable per eager op call signature (op, "
            "arg structure, static kwargs); the backward executable "
            "rematerializes the op's forward inside the fused vjp. Turns "
            "per-op python retracing into an XLA cache hit (the analog of "
            "the reference's phi kernel cache).")
define_flag("FLAGS_eager_double_grad", True,
            "Record the create_graph (double-grad) re-derivation on eager "
            "ops. Disable to drop the saved-input captures and restore the "
            "minimal first-order memory profile (grad(create_graph=True) "
            "then falls back to constants).")

# -- round-2 breadth: reference flags kept for source compatibility. Wired
# flags are marked; "compat" flags are accepted + readable so ported
# scripts' set_flags calls keep working, with the TPU-native behavior
# documented (XLA owns what the flag tuned on CUDA).
define_flag("FLAGS_comm_abort_on_timeout", False,
            "Watchdog kills the process on a hung collective so the "
            "launcher's elastic restart recovers the job (wired).")
define_flag("FLAGS_nccl_blocking_wait", False,
            "Reference alias of FLAGS_comm_abort_on_timeout (wired).")
define_flag("FLAGS_benchmark_nccl", False,
            "compat: collective timing comes from the profiler timeline.")
define_flag("FLAGS_allreduce_record_one_event", True,
            "compat: XLA schedules collective/compute overlap itself.")
define_flag("FLAGS_dynamic_static_unified_comm", True,
            "compat: one collective path (XLA) serves eager and compiled.")
define_flag("FLAGS_use_cinn", False,
            "compat: fusion compilation is always XLA on TPU.")
define_flag("FLAGS_allow_cinn_ops", "",
            "compat: XLA fusion has no per-op allowlist.")
define_flag("FLAGS_deny_cinn_ops", "",
            "compat: XLA fusion has no per-op denylist.")
define_flag("FLAGS_enable_cinn_accuracy_check", False,
            "compat: use FLAGS_check_nan_inf / tests for accuracy checks.")
define_flag("FLAGS_enable_pir_api", True,
            "compat: the trace->StableHLO path is always on (PIR analog).")
define_flag("FLAGS_enable_pir_in_executor", True,
            "compat: XLA executables are the only executor.")
define_flag("FLAGS_new_executor_use_cuda_graph", False,
            "compat: XLA compiles whole-step programs; no graph capture.")
define_flag("FLAGS_new_executor_serial_run", False,
            "compat: PJRT launches are async by design.")
define_flag("FLAGS_fraction_of_cpu_memory_to_use", 1.0,
            "compat: host allocations are malloc'd, not pooled.")
define_flag("FLAGS_initial_gpu_memory_in_mb", 0,
            "compat: XLA preallocates HBM per XLA_PYTHON_CLIENT_* env.")
define_flag("FLAGS_reallocate_gpu_memory_in_mb", 0, "compat.")
define_flag("FLAGS_gpu_memory_limit_mb", 0, "compat.")
define_flag("FLAGS_eager_delete_tensor_gb", 0.0,
            "compat: XLA/PJRT buffer lifetime is reference-counted.")
define_flag("FLAGS_fast_eager_deletion_mode", True, "compat.")
define_flag("FLAGS_use_pinned_memory", True,
            "compat: H2D staging is owned by PJRT.")
define_flag("FLAGS_init_allocated_mem", False, "compat.")
define_flag("FLAGS_conv_workspace_size_limit", 512,
            "compat: XLA conv algorithm picking replaces cuDNN workspace.")
define_flag("FLAGS_cudnn_deterministic", False,
            "compat: set FLAGS_tpu_deterministic instead.")
define_flag("FLAGS_tpu_deterministic", False,
            "Force deterministic XLA reductions (wired via jax config by "
            "user scripts; surfaced here for parity).")
define_flag("FLAGS_cudnn_exhaustive_search", False,
            "Enables runtime kernel autotune, same switch as "
            "FLAGS_use_autotune (wired: ops/autotune.enabled).")
define_flag("FLAGS_embedding_deterministic", 0, "compat.")
define_flag("FLAGS_max_inplace_grad_add", 0, "compat.")
define_flag("FLAGS_pe_profile_fname", "", "compat profiler filename knob.")
define_flag("FLAGS_enable_async_trace", False,
            "Enable async dispatch tracing (wired: profiler).")
def _reset_low_precision_list(value):
    if value:  # (re-)enabling starts a fresh report, like the reference's
        from ..ops import registry  # per-run op list

        registry._LOW_PRECISION_OPS.clear()


define_flag("FLAGS_low_precision_op_list", 0,
            "Record ops AMP routes to low precision; read the set via "
            "paddle.amp.debugging.low_precision_op_list() (wired).",
            on_set=_reset_low_precision_list)
define_flag("FLAGS_enable_auto_parallel", True,
            "compat: DTensor/GSPMD auto-parallel is always available.")
define_flag("FLAGS_retain_grad_for_all_tensor", False,
            "Keep .grad on non-leaf tensors by default (wired: tape).")
define_flag("FLAGS_print_ir", False,
            "Dump StableHLO of compiled functions (wired: jit).")
define_flag("FLAGS_call_stack_level", 1,
            "Error reports include Python stack (wired: enforce).")

# -- round-2 (second pass) breadth: the next tier of reference flags users
# actually set in training scripts. Same convention: (wired) names the
# consumer; "compat" flags are accepted/readable with the TPU-native story
# documented.
define_flag("FLAGS_search_cache_max_number", 4096,
            "Upper bound on cached eager executables, the reference's "
            "kernel-search cache cap (wired: ops/registry executable "
            "cache; dispatch falls back inline once full).")
define_flag("FLAGS_sort_sum_gradient", False,
            "compat: the tape accumulates gradients in deterministic "
            "reverse-topological order unconditionally.")
define_flag("FLAGS_paddle_num_threads", 1,
            "compat: host-side parallelism belongs to XLA:CPU thread pools.")
define_flag("FLAGS_inner_op_parallelism", 0,
            "compat: intra-op parallelism is scheduled by XLA.")
define_flag("FLAGS_dist_threadpool_size", 0,
            "compat: collective execution threads are PJRT-owned.")
define_flag("FLAGS_initial_cpu_memory_in_mb", 500,
            "compat: host allocations are malloc'd, not pooled.")
define_flag("FLAGS_use_mkldnn", False,
            "compat: CPU fallback kernels compile through XLA:CPU.")
define_flag("FLAGS_conv2d_disable_cudnn", False,
            "compat: convs lower to XLA convolutions on TPU.")
define_flag("FLAGS_use_fast_math", False,
            "compat: matmul precision is per-op (bf16 MXU by default; "
            "request fp32 accumulation via precision= on matmul ops).")
define_flag("FLAGS_gemm_use_half_precision_compute_type", False,
            "compat: MXU accumulates in fp32 regardless.")
define_flag("FLAGS_communicator_max_merge_var_num", 20,
            "compat: PS communicator knob; PS stack is stubs-by-design.")
define_flag("FLAGS_communicator_send_queue_size", 20,
            "compat: PS communicator knob; PS stack is stubs-by-design.")
define_flag("FLAGS_apply_pass_to_program", False,
            "compat: XLA passes replace Program passes.")
define_flag("FLAGS_convert_all_blocks", True,
            "compat: whole-function tracing has no sub-block conversion.")
define_flag("FLAGS_jit_engine_type", "XLA",
            "compat: the only JIT engine is XLA (reference: Executor/PE).")
define_flag("FLAGS_use_shm_cache", False,
            "compat: DataLoader workers ship arrays via pipes, not shm.")
define_flag("FLAGS_dataloader_use_file_descriptor", False,
            "compat: see FLAGS_use_shm_cache.")
define_flag("FLAGS_enable_record_memory", False,
            "Alias of FLAGS_log_memory_stats (wired: profiler reads "
            "either).")
define_flag("FLAGS_get_host_by_name_time", 120,
            "Rendezvous DNS wait budget in seconds (wired: launch/TCPStore "
            "connect retry window).")
define_flag("FLAGS_start_cpu_core_id", 0,
            "compat: no CPU core pinning on TPU hosts.")
define_flag("FLAGS_enable_cublas_tensor_op_math", False,
            "compat: MXU usage is implicit in dtype choice.")
define_flag("FLAGS_cublaslt_exhaustive_search_times", 0,
            "compat: see FLAGS_use_autotune.")
define_flag("FLAGS_cudnn_batchnorm_spatial_persistent", False,
            "compat: batch_norm lowers to XLA-fused normalization.")
define_flag("FLAGS_enable_gpu_memory_usage_log", False,
            "compat: use paddle.device.memory_stats / profiler.")
define_flag("FLAGS_enable_gpu_memory_usage_log_mb", True, "compat.")
define_flag("FLAGS_free_idle_chunk", False,
            "compat: XLA's BFC allocator manages HBM chunks.")
define_flag("FLAGS_free_when_no_cache_hit", False, "compat.")
define_flag("FLAGS_gpu_allocator_retry_time", 2000,
            "compat: allocation retry is PJRT-internal.")
define_flag("FLAGS_enable_dependency_builder_debug_info", False,
            "compat: XLA owns instruction scheduling.")
define_flag("FLAGS_executor_log_deps_every_microseconds", 0, "compat.")
define_flag("FLAGS_check_kernel_launch", False,
            "compat: use FLAGS_check_nan_inf; launches are checked by PJRT.")
define_flag("FLAGS_enable_unused_var_check", False,
            "compat: jax tracing prunes unused values structurally.")
define_flag("FLAGS_prim_all", False,
            "compat: composite-op decomposition is jax-native (every op "
            "is already expressed in primitives).")
define_flag("FLAGS_prim_enable_dynamic", False, "compat.")
define_flag("FLAGS_print_allocator_trace_info", False, "compat.")
define_flag("FLAGS_npu_storage_format", False, "compat.")
define_flag("FLAGS_set_to_1d", True,
            "compat: 0-d vs 1-d scalar semantics follow numpy/jax (0-d).")

# ---- round 3: remaining behavior-critical flags from the reference's
# paddle/common/flags.cc (the GPU/oneDNN/graph-store-only tail is ported
# as documented compat no-ops; wired flags say what consumes them) ----

def deterministic_enabled() -> bool:
    """True when bit-stable math is requested — by the determinism flag
    itself OR by auto-parallel align mode (consumer-side OR instead of a
    hook: a nested set_flags inside a hook would break the atomic-
    rollback guarantee above)."""
    f = get_flags(("FLAGS_tpu_deterministic",
                   "FLAGS_enable_auto_parallel_align_mode"))
    return bool(f["FLAGS_tpu_deterministic"]
                or f["FLAGS_enable_auto_parallel_align_mode"])


define_flag("FLAGS_enable_auto_parallel_align_mode", False,
            "Alignment-debug mode for auto-parallel runs (wired: "
            "deterministic_enabled() ORs it with FLAGS_tpu_deterministic "
            "so dp/mp/pp recompositions are bit-comparable; reference "
            "uses it to align dygraph vs static).")
define_flag("FLAGS_alloc_fill_value", -1,
            "When >= 0, paddle.empty/empty_like fill new buffers with this "
            "value instead of zeros (wired: ops/yaml empty impls) — the "
            "uninitialized-memory bug shaker (reference init_allocated_mem "
            "cousin).")
define_flag("FLAGS_logging_pir_py_code_dir", "",
            "When set, jit.to_static dumps each traced function's "
            "StableHLO text into this directory (wired: jit/__init__.py) — "
            "the analog of dumping PIR python code.")
define_flag("FLAGS_logging_trunc_pir_py_code", False,
            "Truncate prior IR dumps instead of appending (wired with "
            "FLAGS_logging_pir_py_code_dir).")
define_flag("FLAGS_accuracy_check_rtol_fp32", 1e-5,
            "Tolerances for amp.debugging.check_accuracy comparisons "
            "(wired: amp/debugging.py).")
define_flag("FLAGS_accuracy_check_atol_fp32", 1e-6, "See rtol_fp32 (wired).")
define_flag("FLAGS_accuracy_check_rtol_fp16", 1e-3, "See rtol_fp32 (wired).")
define_flag("FLAGS_accuracy_check_atol_fp16", 1e-3, "See rtol_fp32 (wired).")
define_flag("FLAGS_accuracy_check_rtol_bf16", 1e-2, "See rtol_fp32 (wired).")
define_flag("FLAGS_accuracy_check_atol_bf16", 1e-2, "See rtol_fp32 (wired).")
define_flag("FLAGS_pir_debug", False,
            "Print jaxpr of each to_static trace to stderr (wired: "
            "jit/__init__.py).")
define_flag("FLAGS_async_trace_count", 0,
            "compat: host->device dispatch is PJRT-async by default.")
define_flag("FLAGS_prim_check_ops", False,
            "compat: jax primitives are closed under tracing; no "
            "decomposition completeness check needed.")
define_flag("FLAGS_disable_dyshape_in_train", False,
            "compat: jit shapes are static per specialization already.")
define_flag("FLAGS_enable_cse_in_dy2st", True,
            "compat: XLA always runs CSE.")
define_flag("FLAGS_enable_fuse_parallel_matmul_pass", True,
            "compat: XLA fusion subsumes the pass.")
define_flag("FLAGS_enable_fusion_fallback", False,
            "compat: Pallas kernels fall back per-op (incubate.nn).")
define_flag("FLAGS_pir_apply_inplace_pass", True,
            "compat: XLA buffer donation/aliasing replaces inplace passes.")
define_flag("FLAGS_pir_apply_shape_optimization_pass", True, "compat.")
define_flag("FLAGS_enable_pir_with_pt_in_dy2st", False, "compat.")
define_flag("FLAGS_enable_pir_in_executor_trace_run", False, "compat.")
define_flag("FLAGS_logging_pir_py_code_dump_symbolic_dims", False, "compat.")
define_flag("FLAGS_enable_collect_shape", False,
            "compat: shape collection is trace-time in jax.")
define_flag("FLAGS_cudnn_exhaustive_search_times", 0,
            "compat: see FLAGS_use_autotune.")
define_flag("FLAGS_cudnn_cache_saturation_count", 1, "compat.")
define_flag("FLAGS_enable_cudnn_frontend", False, "compat: no cuDNN.")
define_flag("FLAGS_batch_norm_use_miopen", False, "compat: no MIOpen.")
define_flag("FLAGS_run_kp_kernel", False, "compat: no Kunlun XPU here.")
define_flag("FLAGS_trt_ibuilder_cache", False, "compat: no TensorRT.")
define_flag("FLAGS_use_cuda_malloc_async_allocator", False,
            "compat: PJRT owns the allocator.")
define_flag("FLAGS_custom_device_mem_record", False, "compat.")
define_flag("FLAGS_enable_blaslt_global_search", False,
            "compat: see FLAGS_use_autotune.")
define_flag("FLAGS_cublaslt_device_best_config", "", "compat.")
define_flag("FLAGS_tracer_onednn_ops_on", "", "compat: no oneDNN tracer.")
define_flag("FLAGS_tracer_onednn_ops_off", "", "compat.")
define_flag("FLAGS_static_runtime_data_save_path", "", "compat.")
define_flag("FLAGS_use_fast_math", False,
            "compat: use FLAGS_tpu_default_matmul_precision for the "
            "speed/accuracy trade.")
define_flag("FLAGS_gemm_use_half_precision_compute_type", False,
            "compat: MXU accumulates fp32 regardless.")
define_flag("FLAGS_enable_async_trace", False, "compat.")
define_flag("FLAGS_use_mkldnn", False, "compat: no oneDNN.")

# ---- round-4 wired additions (reference paddle/common/flags.cc) ----
define_flag("FLAGS_multi_block_attention_min_partition_size", 512,
            "KV-chunk size for chunked decode attention "
            "(incubate.nn.memory_efficient_attention) — the TPU analog "
            "of the GPU multi-block decode partition size.")
define_flag("FLAGS_einsum_opt", False,
            "einsum contraction-order search: True = exhaustive "
            "('optimal'), False = greedy. The reference flag gates its "
            "einsum intermediate cache; contraction planning is the XLA-"
            "native equivalent knob.")
define_flag("FLAGS_selected_gpus", "",
            "comma-separated accelerator indices visible to this process "
            "(reference: device selection for the trainer); filters "
            "paddle.device accelerator enumeration.")
define_flag("FLAGS_enable_api_kernel_fallback", True,
            "allow a failing Pallas kernel to fall back to the XLA "
            "path (the phi fallback-to-CPU-kernel analog). False makes "
            "kernel errors raise.")
define_flag("FLAGS_sync_nccl_allreduce", True,
            "eager collectives block until the result is ready "
            "(XLA dispatch is async; the wait is block_until_ready, "
            "the NCCL-stream-sync analog).")

# ---- round-9 wired additions: the communication-overlap compiler knobs.
# The overlap engine (parallel/overlap.py) structures programs so
# gathers/reduce-scatters CAN hide under compute; whether they DO is the
# XLA scheduler's call — these flags push the latency-hiding scheduler
# and async-collective-fusion switches to the compiler
# (device.xla_overlap_flags / device.apply_xla_overlap_flags merge them
# into XLA_FLAGS before backend init; tests/test_overlap.py proves the
# plumbing reaches the compiler's option parser).
define_flag("FLAGS_tpu_latency_hiding_scheduler", True,
            "Enable XLA's latency-hiding scheduler "
            "(--xla_tpu_enable_latency_hiding_scheduler): reorders "
            "independent collectives ahead of compute so the overlap "
            "engine's layer-ahead gathers actually overlap (wired: "
            "device.xla_overlap_flags).")
define_flag("FLAGS_tpu_async_collective_fusion", True,
            "Enable async collective fusion "
            "(--xla_tpu_enable_async_collective_fusion): splits "
            "collectives into start/done pairs XLA can schedule compute "
            "between (wired: device.xla_overlap_flags).")
define_flag("FLAGS_tpu_async_all_gather", True,
            "Async all-gather lowering (--xla_enable_async_all_gather) "
            "— the ZeRO-3 prefetch gather rides this (wired: "
            "device.xla_overlap_flags).")
define_flag("FLAGS_tpu_async_collective_permute", True,
            "Async collective-permute lowering "
            "(--xla_enable_async_collective_permute) — the "
            "collective-matmul ppermute ring rides this (wired: "
            "device.xla_overlap_flags).")


# ---- exemption record: reference flags with NO TPU/XLA analog --------
# Every name in paddle/common/flags.cc is either WIRED above (same
# FLAGS_ name, real effect) or EXEMPT here with the reason.  The
# completeness test (tests/test_flags_wiring.py) asserts
# wired + exempt covers the reference list exactly.
_CUDA_LIB_DIRS = ("cublas_dir cudnn_dir cupti_dir curand_dir cusolver_dir "
                  "cusparse_dir cusparselt_dir lapack_dir mkl_dir "
                  "mklml_dir nccl_dir nvidia_package_dir op_dir "
                  "win_cuda_bin_dir").split()
_GPUGRAPH = ("gpugraph_debug_gpu_memory gpugraph_dedup_pull_push_mode "
             "gpugraph_enable_gpu_direct_access "
             "gpugraph_enable_hbm_table_collision_stat "
             "gpugraph_enable_segment_merge_grads "
             "gpugraph_hbm_table_load_factor "
             "gpugraph_load_node_list_into_hbm "
             "gpugraph_merge_grads_segment_size "
             "gpugraph_slot_feasign_max_num "
             "gpugraph_sparse_table_storage_mode gpugraph_storage_mode "
             "graph_embedding_split_infer_mode graph_get_neighbor_id "
             "graph_load_in_parallel graph_metapath_split_opt "
             "graph_neighbor_size_percent "
             "enable_graph_multi_node_sampling "
             "enable_neighbor_list_use_uva multi_node_sample_use_gpu_table "
             "query_dest_rank_by_multi_node enable_auto_detect_gpu_topo "
             "enable_auto_rdma_trans enable_all2all_use_fp16 "
             "enable_tracker_all2all enable_sparse_inner_gather "
             "enable_opt_get_features enable_ins_parser_file "
             "enable_slotpool_wait_release enable_slotrecord_reset_shrink "
             "record_pool_max_size slotpool_thread_num").split()
_CINN = ("cinn_compile_thread_num cinn_input_dynamic_dim_spec_file "
         "cinn_specify_input_dynamic_dim cinn_subgraph_graphviz_dir "
         "enable_cinn_auto_tune enable_cinn_compile_cache "
         "enable_interpretercore_launch_cinn check_infer_symbolic").split()
_CUDA_ALLOC = ("auto_free_cudagraph_allocations_on_launch "
               "auto_growth_chunk_size_in_mb "
               "cuda_malloc_async_pool_memory_throttle_ratio "
               "fraction_of_cuda_pinned_memory_to_use "
               "use_auto_growth_pinned_allocator pinned_memory_as_cpu_backend "
               "sync_after_alloc").split()
_LEGACY_EXEC = ("cache_inference_while_scope eager_delete_scope "
                "local_exe_sub_scope_limit memory_fraction_of_eager_deletion "
                "reader_queue_speed_test_mode save_static_runtime_data "
                "multiple_of_cupti_buffer_size "
                "communicator_is_sgd_optimizer "
                "enable_exit_when_partial_worker "
                "enable_adjust_op_order").split()
_PIR_PRIM = ("cse_max_count ir_inplace_kernel_blacklist "
             "logging_pir_py_code_int_tensor_element_limit "
             "pir_broadcast_tree_limit pir_subgraph_saving_dir "
             "prim_forward_blacklist prim_skip_dynamic "
             "manually_trans_conv_filter").split()

FLAG_EXEMPTIONS: Dict[str, str] = {}
for _n in _CUDA_LIB_DIRS:
    FLAG_EXEMPTIONS[_n] = ("CUDA/BLAS library dlopen search path — no "
                           "dynamic GPU library loading under PJRT/XLA")
for _n in _GPUGRAPH:
    FLAG_EXEMPTIONS[_n] = ("GPU-graph-engine / BoxPS / slot-pool data "
                           "feed — documented scope cut (SURVEY §2.10.2: "
                           "heter PS pipeline)")
for _n in _CINN:
    FLAG_EXEMPTIONS[_n] = ("CINN compiler stack — XLA replaces CINN "
                           "wholesale (SURVEY §2.10.1 L6 decision)")
for _n in _CUDA_ALLOC:
    FLAG_EXEMPTIONS[_n] = ("CUDA allocator / pinned-host pool tuning — "
                           "PJRT owns allocation on TPU; stats surfaced "
                           "via device.memory_stats")
for _n in _LEGACY_EXEC:
    FLAG_EXEMPTIONS[_n] = ("legacy fluid executor scope/communicator "
                           "machinery — no scope tree in the jit "
                           "execution model")
for _n in _PIR_PRIM:
    FLAG_EXEMPTIONS[_n] = ("PIR pass / prim-decomposition internals — "
                           "jaxpr->StableHLO has no analogous pass knob; "
                           "IR dumps are FLAGS_logging_pir_py_code_dir")
FLAG_EXEMPTIONS["fused_multi_transformer_op_use_mbfmha"] = (
    "CUDA mbFMHA kernel selector — Pallas flash is the one attention "
    "kernel family on TPU")
FLAG_EXEMPTIONS["use_xqa_optim"] = (
    "CUDA XQA decode kernel selector — decode attention is "
    "incubate.nn.decode_attention on TPU")
FLAG_EXEMPTIONS["trt_min_group_size"] = "TensorRT subgraph engine — no TRT"
