"""Global flag registry.

TPU-native analog of the reference's exported-flag registry
(paddle/common/flags.h:93 ``PD_DEFINE_*`` + ``GetExportedFlagInfoMap``
flags.h:337; 183 definitions in paddle/common/flags.cc). Flags are
settable from the environment (``FLAGS_*``), from Python via
``set_flags``/``get_flags``, and are queried by subsystems at call time.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, Optional, Union

_LOCK = threading.RLock()


@dataclass
class FlagInfo:
    name: str
    default: Any
    doc: str
    type: type
    value: Any


_REGISTRY: Dict[str, FlagInfo] = {}


def _coerce(raw: str, ty: type) -> Any:
    if ty is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    return ty(raw)


def define_flag(name: str, default: Any, doc: str = "") -> None:
    """Register a flag. Environment variable ``name`` overrides the default."""
    ty = type(default)
    value = default
    env = os.environ.get(name)
    if env is not None:
        try:
            value = _coerce(env, ty)
        except (TypeError, ValueError):
            value = default
    with _LOCK:
        _REGISTRY[name] = FlagInfo(name=name, default=default, doc=doc, type=ty, value=value)


def get_flags(flags: Union[str, Iterable[str], None] = None) -> Dict[str, Any]:
    with _LOCK:
        if flags is None:
            return {k: v.value for k, v in _REGISTRY.items()}
        if isinstance(flags, str):
            flags = [flags]
        out = {}
        for name in flags:
            if name not in _REGISTRY:
                raise ValueError(f"unknown flag {name!r}")
            out[name] = _REGISTRY[name].value
        return out


def get_flag(name: str) -> Any:
    with _LOCK:
        return _REGISTRY[name].value


def set_flags(flags: Dict[str, Any]) -> None:
    with _LOCK:
        for name, value in flags.items():
            if name not in _REGISTRY:
                raise ValueError(f"unknown flag {name!r}")
            info = _REGISTRY[name]
            info.value = _coerce(value, info.type) if isinstance(value, str) else info.type(value)


def flag_info_map() -> Dict[str, FlagInfo]:
    with _LOCK:
        return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# Behavior-critical flags mirrored from the reference (paddle/common/flags.cc)
# plus TPU-native additions.
# ---------------------------------------------------------------------------
define_flag("FLAGS_check_nan_inf", False, "Check every op output for NaN/Inf (debug).")
define_flag("FLAGS_check_nan_inf_level", 0, "0: error on nan/inf; >0 only report.")
define_flag("FLAGS_use_autotune", False, "Enable runtime autotuning of kernel variants.")
define_flag("FLAGS_benchmark", False, "Synchronize after every op (benchmark mode).")
define_flag("FLAGS_tpu_eager_compile_cache", True, "Cache per-op compiled executables.")
define_flag("FLAGS_tpu_default_matmul_precision", "default", "default|high|highest")
define_flag("FLAGS_host_trace_level", 1, "Host profiler verbosity level.")
define_flag("FLAGS_enable_async_trace", False, "Enable async dispatch tracing.")
define_flag("FLAGS_tensor_operants_mode", "eager", "eager|static tensor operants mode.")
define_flag("FLAGS_comm_timeout_s", 1800, "Collective timeout (watchdog) in seconds.")
define_flag("FLAGS_allocator_strategy", "auto_growth", "Allocator strategy name (compat).")
define_flag("FLAGS_fraction_of_gpu_memory_to_use", 0.92, "Compat only; XLA manages HBM.")
define_flag("FLAGS_log_memory_stats", False, "Log live/peak memory stats per step.")
define_flag("FLAGS_eager_executable_cache", True,
            "Cache a jitted executable per eager op call signature (op, "
            "arg structure, static kwargs); the backward executable "
            "rematerializes the op's forward inside the fused vjp. Turns "
            "per-op python retracing into an XLA cache hit (the analog of "
            "the reference's phi kernel cache).")
define_flag("FLAGS_eager_double_grad", True,
            "Record the create_graph (double-grad) re-derivation on eager "
            "ops. Disable to drop the saved-input captures and restore the "
            "minimal first-order memory profile (grad(create_graph=True) "
            "then falls back to constants).")

# -- round-2 breadth: reference flags kept for source compatibility. Wired
# flags are marked; "compat" flags are accepted + readable so ported
# scripts' set_flags calls keep working, with the TPU-native behavior
# documented (XLA owns what the flag tuned on CUDA).
define_flag("FLAGS_comm_abort_on_timeout", False,
            "Watchdog kills the process on a hung collective so the "
            "launcher's elastic restart recovers the job (wired).")
define_flag("FLAGS_nccl_blocking_wait", False,
            "Reference alias of FLAGS_comm_abort_on_timeout (wired).")
define_flag("FLAGS_benchmark_nccl", False,
            "compat: collective timing comes from the profiler timeline.")
define_flag("FLAGS_allreduce_record_one_event", True,
            "compat: XLA schedules collective/compute overlap itself.")
define_flag("FLAGS_dynamic_static_unified_comm", True,
            "compat: one collective path (XLA) serves eager and compiled.")
define_flag("FLAGS_use_cinn", False,
            "compat: fusion compilation is always XLA on TPU.")
define_flag("FLAGS_allow_cinn_ops", "",
            "compat: XLA fusion has no per-op allowlist.")
define_flag("FLAGS_deny_cinn_ops", "",
            "compat: XLA fusion has no per-op denylist.")
define_flag("FLAGS_enable_cinn_accuracy_check", False,
            "compat: use FLAGS_check_nan_inf / tests for accuracy checks.")
define_flag("FLAGS_enable_pir_api", True,
            "compat: the trace->StableHLO path is always on (PIR analog).")
define_flag("FLAGS_enable_pir_in_executor", True,
            "compat: XLA executables are the only executor.")
define_flag("FLAGS_new_executor_use_cuda_graph", False,
            "compat: XLA compiles whole-step programs; no graph capture.")
define_flag("FLAGS_new_executor_serial_run", False,
            "compat: PJRT launches are async by design.")
define_flag("FLAGS_fraction_of_cpu_memory_to_use", 1.0,
            "compat: host allocations are malloc'd, not pooled.")
define_flag("FLAGS_initial_gpu_memory_in_mb", 0,
            "compat: XLA preallocates HBM per XLA_PYTHON_CLIENT_* env.")
define_flag("FLAGS_reallocate_gpu_memory_in_mb", 0, "compat.")
define_flag("FLAGS_gpu_memory_limit_mb", 0, "compat.")
define_flag("FLAGS_eager_delete_tensor_gb", 0.0,
            "compat: XLA/PJRT buffer lifetime is reference-counted.")
define_flag("FLAGS_fast_eager_deletion_mode", True, "compat.")
define_flag("FLAGS_use_pinned_memory", True,
            "compat: H2D staging is owned by PJRT.")
define_flag("FLAGS_init_allocated_mem", False, "compat.")
define_flag("FLAGS_conv_workspace_size_limit", 512,
            "compat: XLA conv algorithm picking replaces cuDNN workspace.")
define_flag("FLAGS_cudnn_deterministic", False,
            "compat: set FLAGS_tpu_deterministic instead.")
define_flag("FLAGS_tpu_deterministic", False,
            "Force deterministic XLA reductions (wired via jax config by "
            "user scripts; surfaced here for parity).")
define_flag("FLAGS_cudnn_exhaustive_search", False,
            "compat: see FLAGS_use_autotune.")
define_flag("FLAGS_embedding_deterministic", 0, "compat.")
define_flag("FLAGS_max_inplace_grad_add", 0, "compat.")
define_flag("FLAGS_pe_profile_fname", "", "compat profiler filename knob.")
define_flag("FLAGS_enable_async_trace", False,
            "Enable async dispatch tracing (wired: profiler).")
define_flag("FLAGS_low_precision_op_list", 0,
            "compat: AMP op lists live in paddle_tpu.amp.")
define_flag("FLAGS_enable_auto_parallel", True,
            "compat: DTensor/GSPMD auto-parallel is always available.")
define_flag("FLAGS_retain_grad_for_all_tensor", False,
            "Keep .grad on non-leaf tensors by default (wired: tape).")
define_flag("FLAGS_print_ir", False,
            "Dump StableHLO of compiled functions (wired: jit).")
define_flag("FLAGS_call_stack_level", 1,
            "Error reports include Python stack (wired: enforce).")
