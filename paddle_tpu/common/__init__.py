from . import flags
from .flags import define_flag, get_flag, get_flags, set_flags

__all__ = ["flags", "define_flag", "get_flag", "get_flags", "set_flags"]
