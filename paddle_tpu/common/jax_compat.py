"""jax cross-version compat shims (round-7).

The toolchain floor moves under this repo: PR-1 aliased
pltpu.TPUCompilerParams/CompilerParams and the ShapeDtypeStruct(vma=)
field inside flash_attention.py; this module is the shared home for the
next such gaps.  ``jax.shard_map`` was promoted out of jax.experimental
after 0.4.x (kwargs renamed: check_rep -> check_vma, manual axes became
``axis_names`` instead of the complementary ``auto`` set), and
``jax.sharding.set_mesh`` did not exist there at all.  On older jax the
hybrid-parallel stack (llama_hybrid, pipeline_parallel, MoE pipelining,
auto_parallel api) failed at attribute lookup; route those calls through
this module.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
              check_vma=None):
    """jax.shard_map where available; the jax.experimental fallback
    otherwise, with check_vma mapped onto check_rep and ``axis_names``
    (manual axes) mapped onto the complementary ``auto`` set."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kw = {}
        if check_vma is not None:
            kw["check_vma"] = check_vma
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kw)
    from jax.experimental.shard_map import shard_map as _sm

    kw = {}
    if check_vma is not None:
        kw["check_rep"] = check_vma
    if axis_names is not None:
        kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def axis_size(axis):
    """Static size of a bound (manual) mesh axis: jax.lax.axis_size on
    new jax; on 0.4.x ``jax.core.axis_frame(name)`` resolves it (that
    version returns the bare int; guard the frame-object form too)."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis)
    import jax.core as _jc

    fr = _jc.axis_frame(axis)
    return fr if isinstance(fr, int) else fr.size


def set_mesh(mesh):
    """Context manager binding ``mesh`` as the ambient mesh.  Newer jax
    ships jax.sharding.set_mesh; on older jax the Mesh object itself is
    the context manager that binds the physical mesh for jit bodies."""
    sm = getattr(jax.sharding, "set_mesh", None)
    if sm is not None:
        return sm(mesh)
    return mesh


# ---------------------------------------------------------------------------
# memory-kind shims (round-10): the HBM memory engine places optimizer /
# activation state in ``pinned_host`` and streams it back per bucket.
# The public surface moved across versions — jax.sharding exposes
# TransferToMemoryKind on newer toolchains, 0.4.x keeps it in
# jax._src.sharding_impls; Device.addressable_memories / memory kinds on
# shardings may be absent entirely on old CPU wheels.  Every helper here
# degrades to "no memory kinds" (None / identity) instead of raising, so
# the offload lattice falls back to device residency with the SAME code
# path (the residency contract stays exercised on CPU).
# ---------------------------------------------------------------------------


def transfer_to_memory_kind(kind):
    """TransferToMemoryKind(kind) where the class exists (public home
    first, 0.4.x private home second); None when the toolchain has no
    memory-kind transfer support — callers must then skip the transfer
    (identity), not crash."""
    if kind is None:
        return None
    cls = getattr(jax.sharding, "TransferToMemoryKind", None)
    if cls is None:
        try:
            from jax._src.sharding_impls import (
                TransferToMemoryKind as cls)
        except ImportError:
            return None
    return cls(kind)


def device_memory_kinds(device=None):
    """Memory kinds addressable by ``device`` (default: first device),
    default kind FIRST.  () when the toolchain/backend exposes no memory
    spaces (very old jax, exotic plugins)."""
    try:
        d = device if device is not None else jax.devices()[0]
        default = d.default_memory().kind
        kinds = [m.kind for m in d.addressable_memories()]
    except Exception:
        return ()
    return tuple([default] + [k for k in kinds if k != default])


def sharding_with_memory_kind(sharding, kind):
    """``sharding.with_memory_kind(kind)``; the original sharding when
    kind is None or the toolchain predates memory-kind shardings."""
    if kind is None:
        return sharding
    fn = getattr(sharding, "with_memory_kind", None)
    if fn is None:
        return sharding
    return fn(kind)


def device_put_memory_kind(x, kind):
    """Transfer ``x`` to memory space ``kind`` (the streaming primitive
    of the offload engine).  Under a trace it uses TransferToMemoryKind
    (the only form jit accepts); on concrete arrays it derives a
    concrete sharding via with_memory_kind (the only form EAGER
    device_put accepts).  Identity when the toolchain has no memory
    kinds or ``kind`` is None — the bucket loop still runs, only the
    residency change is elided."""
    t = transfer_to_memory_kind(kind)
    if t is None:
        return x
    if isinstance(x, jax.core.Tracer):
        return jax.device_put(x, t)
    sh = getattr(x, "sharding", None)
    if sh is None or getattr(sh, "memory_kind", None) == kind:
        return x
    return jax.device_put(x, sharding_with_memory_kind(sh, kind))
