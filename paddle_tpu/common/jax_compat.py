"""jax cross-version compat shims (round-7).

The toolchain floor moves under this repo: PR-1 aliased
pltpu.TPUCompilerParams/CompilerParams and the ShapeDtypeStruct(vma=)
field inside flash_attention.py; this module is the shared home for the
next such gaps.  ``jax.shard_map`` was promoted out of jax.experimental
after 0.4.x (kwargs renamed: check_rep -> check_vma, manual axes became
``axis_names`` instead of the complementary ``auto`` set), and
``jax.sharding.set_mesh`` did not exist there at all.  On older jax the
hybrid-parallel stack (llama_hybrid, pipeline_parallel, MoE pipelining,
auto_parallel api) failed at attribute lookup; route those calls through
this module.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
              check_vma=None):
    """jax.shard_map where available; the jax.experimental fallback
    otherwise, with check_vma mapped onto check_rep and ``axis_names``
    (manual axes) mapped onto the complementary ``auto`` set."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kw = {}
        if check_vma is not None:
            kw["check_vma"] = check_vma
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kw)
    from jax.experimental.shard_map import shard_map as _sm

    kw = {}
    if check_vma is not None:
        kw["check_rep"] = check_vma
    if axis_names is not None:
        kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def axis_size(axis):
    """Static size of a bound (manual) mesh axis: jax.lax.axis_size on
    new jax; on 0.4.x ``jax.core.axis_frame(name)`` resolves it (that
    version returns the bare int; guard the frame-object form too)."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis)
    import jax.core as _jc

    fr = _jc.axis_frame(axis)
    return fr if isinstance(fr, int) else fr.size


def set_mesh(mesh):
    """Context manager binding ``mesh`` as the ambient mesh.  Newer jax
    ships jax.sharding.set_mesh; on older jax the Mesh object itself is
    the context manager that binds the physical mesh for jit bodies."""
    sm = getattr(jax.sharding, "set_mesh", None)
    if sm is not None:
        return sm(mesh)
    return mesh
