"""Reader decorators (``paddle.reader`` analog).

Reference: ``python/paddle/reader/decorator.py`` — composable generators
feeding training loops: map_readers, shuffle, chain, compose, buffered,
firstn, cache, xmap_readers.  These are host-side and backend-agnostic;
the threaded ones mirror the reference's queue-based implementations.
"""

from __future__ import annotations

import itertools
import queue
import random as _random
import threading
from typing import Any, Callable, Iterable

__all__ = [
    "map_readers", "shuffle", "chain", "compose", "buffered", "firstn",
    "cache", "xmap_readers", "multiprocess_reader",
]


def map_readers(func, *readers):
    """Apply ``func`` element-wise over samples zipped from ``readers``."""

    def reader():
        its = [r() for r in readers]
        for args in zip(*its):
            yield func(*args)

    return reader


def shuffle(reader, buf_size):
    """Buffered shuffle: fill a window of ``buf_size`` samples, emit in
    random order (reference decorator.py shuffle)."""

    def shuffled():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return shuffled


def chain(*readers):
    """Concatenate readers back-to-back."""

    def chained():
        for r in readers:
            yield from r()

    return chained


def compose(*readers, **kwargs):
    """Zip readers into tuples per sample; check_alignment asserts equal
    lengths (reference ComposeNotAligned)."""
    check_alignment = kwargs.pop("check_alignment", True)
    if kwargs:
        raise TypeError(f"unexpected kwargs {sorted(kwargs)}")

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def composed():
        its = [r() for r in readers]
        if check_alignment:
            for items in itertools.zip_longest(*its, fillvalue=_SENTINEL):
                if any(i is _SENTINEL for i in items):
                    raise ComposeNotAligned(
                        "readers have different lengths")
                yield sum((make_tuple(i) for i in items), ())
        else:
            for items in zip(*its):
                yield sum((make_tuple(i) for i in items), ())

    return composed


_SENTINEL = object()


class ComposeNotAligned(ValueError):
    pass


def buffered(reader, size):
    """Decouple producer/consumer with a background thread + queue of
    ``size`` (reference decorator.py buffered)."""

    def buffered_reader():
        q: queue.Queue = queue.Queue(maxsize=size)

        def fill():
            try:
                for d in reader():
                    q.put(d)
            finally:
                q.put(_SENTINEL)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _SENTINEL:
                break
            yield e

    return buffered_reader


def firstn(reader, n):
    """Limit to the first ``n`` samples."""

    def firstn_reader():
        yield from itertools.islice(reader(), n)

    return firstn_reader


def cache(reader):
    """Materialize the full reader once; replays from memory."""
    all_data = None

    def cached():
        nonlocal all_data
        if all_data is None:
            all_data = list(reader())
        yield from all_data

    return cached


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader using ``process_num`` worker threads
    (reference decorator.py xmap_readers; threads instead of processes —
    mappers in TPU input pipelines are numpy-bound and release the GIL)."""

    def xreader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)
        errors: list = []
        # set on any worker failure: unblocks the feed thread (which could
        # otherwise sit forever in put() on a full in_q with all its
        # consumers dead) and tells surviving workers to wind down
        failed = threading.Event()

        def _put(q_, item) -> bool:
            while not failed.is_set():
                try:
                    q_.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def feed():
            try:
                for i, d in enumerate(reader()):
                    if not _put(in_q, (i, d)):
                        break
            except BaseException as e:  # noqa: BLE001 — must not deadlock
                errors.append(e)
            finally:
                for _ in range(process_num):
                    if not _put(in_q, _SENTINEL):
                        break

        def work():
            try:
                while not failed.is_set():
                    try:
                        item = in_q.get(timeout=0.1)
                    except queue.Empty:
                        continue
                    if item is _SENTINEL:
                        return
                    i, d = item
                    out_q.put((i, mapper(d)))
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
                failed.set()
            finally:
                # always post the sentinel so the consumer can't hang on a
                # dead worker; its recorded error re-raises below
                out_q.put(_SENTINEL)

        threading.Thread(target=feed, daemon=True).start()
        workers = [threading.Thread(target=work, daemon=True)
                   for _ in range(process_num)]
        for w in workers:
            w.start()

        done = 0
        if order:
            pending = {}
            want = 0
            while done < process_num:
                item = out_q.get()
                if item is _SENTINEL:
                    done += 1
                    continue
                i, d = item
                pending[i] = d
                while want in pending:
                    yield pending.pop(want)
                    want += 1
            for i in sorted(pending):
                yield pending[i]
        else:
            while done < process_num:
                item = out_q.get()
                if item is _SENTINEL:
                    done += 1
                    continue
                yield item[1]
        if errors:
            raise errors[0]

    return xreader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Interleave multiple readers concurrently (thread-backed; the
    reference uses fork+pipe, which is unsafe with a live TPU client)."""

    def mreader():
        q: queue.Queue = queue.Queue(queue_size)

        def run(r):
            try:
                for d in r():
                    q.put(d)
            finally:
                q.put(_SENTINEL)

        for r in readers:
            threading.Thread(target=run, args=(r,), daemon=True).start()
        done = 0
        while done < len(readers):
            e = q.get()
            if e is _SENTINEL:
                done += 1
                continue
            yield e

    return mreader
