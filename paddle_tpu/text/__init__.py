"""paddle_tpu.text — text utilities and datasets.

Analog of python/paddle/text: the ViterbiDecoder layer/functional wrap the
registered viterbi_decode op; datasets mirror the reference surface with a
synthetic backend (the reference downloads corpora — zero-egress builds
generate deterministic token streams with the same shapes instead).
"""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..io import Dataset
from ..nn.layer import Layer
from ..ops.registry import dispatch

__all__ = ["ViterbiDecoder", "viterbi_decode", "Imdb", "UCIHousing",
           "WMT14", "WMT16", "Conll05st", "Imikolov", "Movielens"]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag: bool = True, name=None):
    """(scores, best-tag paths) for a batch of CRF emissions (reference
    python/paddle/text/viterbi_decode.py → viterbi_decode op)."""
    return dispatch("viterbi_decode", potentials, transition_params,
                    lengths, include_bos_eos_tag=include_bos_eos_tag)


class ViterbiDecoder(Layer):
    def __init__(self, transitions, include_bos_eos_tag: bool = True,
                 name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


class _SyntheticTextDataset(Dataset):
    """Deterministic token-id sequences standing in for a downloaded
    corpus (shapes/dtypes match the reference dataset)."""

    def __init__(self, mode: str, size: int, seq_len: int, vocab: int,
                 num_classes: int = 2, seed: int = 0):
        self.mode = mode
        rng = np.random.RandomState(seed + (0 if mode == "train" else 1))
        self._x = rng.randint(1, vocab, size=(size, seq_len)).astype("int64")
        self._y = rng.randint(0, num_classes, size=(size,)).astype("int64")

    def __getitem__(self, idx):
        return self._x[idx], self._y[idx]

    def __len__(self):
        return len(self._x)


class Imdb(_SyntheticTextDataset):
    """Sentiment classification (reference text/datasets/imdb.py)."""

    def __init__(self, mode="train", cutoff=150, size=256, seq_len=128,
                 vocab=5000):
        super().__init__(mode, size, seq_len, vocab, num_classes=2)


class UCIHousing(Dataset):
    """Regression (reference text/datasets/uci_housing.py shape: 13 -> 1)."""

    def __init__(self, mode="train", size=256):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        self._x = rng.rand(size, 13).astype("float32")
        w = np.linspace(0.1, 1.3, 13, dtype="float32")
        self._y = (self._x @ w)[:, None].astype("float32")

    def __getitem__(self, idx):
        return self._x[idx], self._y[idx]

    def __len__(self):
        return len(self._x)


class WMT14(_SyntheticTextDataset):
    """Translation pairs (reference text/datasets/wmt14.py)."""

    def __init__(self, mode="train", dict_size=30000, size=256, seq_len=32):
        super().__init__(mode, size, seq_len, min(dict_size, 30000))
        rng = np.random.RandomState(42)
        self._tgt = rng.randint(1, min(dict_size, 30000),
                                size=(size, seq_len)).astype("int64")

    def __getitem__(self, idx):
        return self._x[idx], self._tgt[idx], self._tgt[idx]


class WMT16(WMT14):
    pass


class Conll05st(Dataset):
    """SRL dataset (reference text/datasets/conll05.py): each item is the
    8-column tuple (word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2,
    mark, labels) over a seq_len window."""

    def __init__(self, mode="train", size=128, seq_len=32, word_vocab=5000,
                 num_labels=67):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        self._cols = [rng.randint(1, word_vocab, (size, seq_len)).astype("int64")
                      for _ in range(6)]
        self._cols.append(rng.randint(0, 2, (size, seq_len)).astype("int64"))
        self._cols.append(rng.randint(0, num_labels,
                                      (size, seq_len)).astype("int64"))

    def __getitem__(self, idx):
        return tuple(c[idx] for c in self._cols)

    def __len__(self):
        return len(self._cols[0])


class Imikolov(Dataset):
    """PTB-style n-gram LM dataset (reference text/datasets/imikolov.py):
    items are (context n-1 grams, next word)."""

    def __init__(self, mode="train", data_type="NGRAM", window_size=5,
                 size=512, vocab=2000):
        assert data_type in ("NGRAM", "SEQ"), \
            f"data type should be NGRAM, SEQ, but it is {data_type}"
        rng = np.random.RandomState(0 if mode == "train" else 1)
        self.data_type = data_type
        self.window_size = window_size
        seq = rng.randint(1, vocab, size + window_size).astype("int64")
        self._ctx = np.stack([seq[i:i + window_size - 1]
                              for i in range(size)])
        self._nxt = seq[window_size - 1:window_size - 1 + size]
        # SEQ mode: whole sentences (reference imikolov.py SEQ yields the
        # full id sequence per line)
        self._seqs = np.stack([seq[i:i + window_size] for i in range(size)])

    def __getitem__(self, idx):
        if self.data_type == "SEQ":
            return self._seqs[idx]
        return self._ctx[idx], self._nxt[idx]

    def __len__(self):
        return len(self._ctx)


class Movielens(Dataset):
    """Rating prediction (reference text/datasets/movielens.py): items are
    (user_id, gender, age, job, movie_id, category, title, rating)."""

    def __init__(self, mode="train", size=256, num_users=6040,
                 num_movies=3952, title_len=8):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = size
        self._user = rng.randint(1, num_users, n).astype("int64")
        self._gender = rng.randint(0, 2, n).astype("int64")
        self._age = rng.randint(0, 7, n).astype("int64")
        self._job = rng.randint(0, 21, n).astype("int64")
        self._movie = rng.randint(1, num_movies, n).astype("int64")
        self._cat = rng.randint(0, 18, (n, 3)).astype("int64")
        self._title = rng.randint(1, 5000, (n, title_len)).astype("int64")
        self._rating = rng.randint(1, 6, n).astype("float32")

    def __getitem__(self, idx):
        return (self._user[idx], self._gender[idx], self._age[idx],
                self._job[idx], self._movie[idx], self._cat[idx],
                self._title[idx], self._rating[idx])

    def __len__(self):
        return len(self._user)
