"""paddle_tpu.text — text utilities and datasets.

Analog of python/paddle/text: the ViterbiDecoder layer/functional wrap the
registered viterbi_decode op; datasets mirror the reference surface with a
synthetic backend (the reference downloads corpora — zero-egress builds
generate deterministic token streams with the same shapes instead).
"""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..io import Dataset
from ..nn.layer import Layer
from ..ops.registry import dispatch

__all__ = ["ViterbiDecoder", "viterbi_decode", "Imdb", "UCIHousing",
           "WMT14", "WMT16"]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag: bool = True, name=None):
    """(scores, best-tag paths) for a batch of CRF emissions (reference
    python/paddle/text/viterbi_decode.py → viterbi_decode op)."""
    return dispatch("viterbi_decode", potentials, transition_params,
                    lengths, include_bos_eos_tag=include_bos_eos_tag)


class ViterbiDecoder(Layer):
    def __init__(self, transitions, include_bos_eos_tag: bool = True,
                 name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


class _SyntheticTextDataset(Dataset):
    """Deterministic token-id sequences standing in for a downloaded
    corpus (shapes/dtypes match the reference dataset)."""

    def __init__(self, mode: str, size: int, seq_len: int, vocab: int,
                 num_classes: int = 2, seed: int = 0):
        self.mode = mode
        rng = np.random.RandomState(seed + (0 if mode == "train" else 1))
        self._x = rng.randint(1, vocab, size=(size, seq_len)).astype("int64")
        self._y = rng.randint(0, num_classes, size=(size,)).astype("int64")

    def __getitem__(self, idx):
        return self._x[idx], self._y[idx]

    def __len__(self):
        return len(self._x)


class Imdb(_SyntheticTextDataset):
    """Sentiment classification (reference text/datasets/imdb.py)."""

    def __init__(self, mode="train", cutoff=150, size=256, seq_len=128,
                 vocab=5000):
        super().__init__(mode, size, seq_len, vocab, num_classes=2)


class UCIHousing(Dataset):
    """Regression (reference text/datasets/uci_housing.py shape: 13 -> 1)."""

    def __init__(self, mode="train", size=256):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        self._x = rng.rand(size, 13).astype("float32")
        w = np.linspace(0.1, 1.3, 13, dtype="float32")
        self._y = (self._x @ w)[:, None].astype("float32")

    def __getitem__(self, idx):
        return self._x[idx], self._y[idx]

    def __len__(self):
        return len(self._x)


class WMT14(_SyntheticTextDataset):
    """Translation pairs (reference text/datasets/wmt14.py)."""

    def __init__(self, mode="train", dict_size=30000, size=256, seq_len=32):
        super().__init__(mode, size, seq_len, min(dict_size, 30000))
        rng = np.random.RandomState(42)
        self._tgt = rng.randint(1, min(dict_size, 30000),
                                size=(size, seq_len)).astype("int64")

    def __getitem__(self, idx):
        return self._x[idx], self._tgt[idx], self._tgt[idx]


class WMT16(WMT14):
    pass
