"""Audio feature layers (analog of python/paddle/audio/features/layers.py:
Spectrogram:45, MelSpectrogram:130, LogMelSpectrogram:237, MFCC:344)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer import Layer
from . import functional as F


class Spectrogram(Layer):
    def __init__(self, n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", dtype: str = "float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.window = F.get_window(window, self.win_length)

    def forward(self, x):
        spec = F.stft_frames(x, self.n_fft, self.hop_length,
                             self.win_length, self.window,
                             center=self.center, pad_mode=self.pad_mode)
        mag = jnp.abs(spec)
        if self.power != 1.0:
            mag = mag ** self.power
        return Tensor(mag.astype(jnp.float32))


class MelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: str = "slaney",
                 dtype: str = "float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                       window, power, center, pad_mode)
        self.fbank = F.compute_fbank_matrix(sr, n_fft, n_mels, f_min,
                                            f_max, htk, norm)

    def forward(self, x):
        s = self.spectrogram(x)._value      # [..., n_bins, frames]
        mel = jnp.einsum("mf,...ft->...mt", self.fbank._value, s)
        return Tensor(mel)


class LogMelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: str = "slaney",
                 ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: Optional[float] = None, dtype: str = "float32"):
        super().__init__()
        self.mel = MelSpectrogram(sr, n_fft, hop_length, win_length, window,
                                  power, center, pad_mode, n_mels, f_min,
                                  f_max, htk, norm)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return F.power_to_db(self.mel(x), self.ref_value, self.amin,
                             self.top_db)


class MFCC(Layer):
    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: str = "slaney",
                 ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: Optional[float] = None, dtype: str = "float32"):
        super().__init__()
        self.logmel = LogMelSpectrogram(sr, n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        n_mels, f_min, f_max, htk, norm,
                                        ref_value, amin, top_db)
        self.dct = F.create_dct(n_mfcc, n_mels)

    def forward(self, x):
        lm = self.logmel(x)._value          # [..., n_mels, frames]
        out = jnp.einsum("mk,...mt->...kt", self.dct._value, lm)
        return Tensor(out)
