"""Audio functional helpers (analog of python/paddle/audio/functional:
window_function.py get_window, functional.py hz_to_mel/mel_to_hz/
mel_frequencies/compute_fbank_matrix/create_dct/power_to_db)."""

from __future__ import annotations

import math
from typing import Optional, Union

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor


def get_window(window: str, win_length: int, fftbins: bool = True):
    """hann/hamming/blackman/bohman/ones (reference window_function.py)."""
    n = win_length
    m = n if fftbins else n - 1
    k = np.arange(n)
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * np.pi * k / m)
    elif window == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * k / m)
    elif window == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * k / m)
             + 0.08 * np.cos(4 * np.pi * k / m))
    elif window == "bohman":
        x = np.abs(2 * k / m - 1.0)
        w = (1 - x) * np.cos(np.pi * x) + np.sin(np.pi * x) / np.pi
    elif window in ("ones", "rectangular", "boxcar"):
        w = np.ones(n)
    else:
        raise ValueError(f"unsupported window {window!r}")
    return Tensor(jnp.asarray(w.astype("float32")))


def hz_to_mel(freq, htk: bool = False):
    f = np.asarray(freq, np.float64)
    if htk:
        out = 2595.0 * np.log10(1.0 + f / 700.0)
    else:  # slaney
        f_min, f_sp = 0.0, 200.0 / 3
        out = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = np.where(f >= min_log_hz,
                       min_log_mel + np.log(np.maximum(f, 1e-10)
                                            / min_log_hz) / logstep, out)
    return out if np.ndim(freq) else float(out)


def mel_to_hz(mel, htk: bool = False):
    m = np.asarray(mel, np.float64)
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        out = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = np.where(m >= min_log_mel,
                       min_log_hz * np.exp(logstep * (m - min_log_mel)),
                       out)
    return out if np.ndim(mel) else float(out)


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0,
                    f_max: float = 11025.0, htk: bool = False):
    mels = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk), n_mels)
    return mel_to_hz(mels, htk)


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max: Optional[float] = None,
                         htk: bool = False, norm: str = "slaney"):
    """Triangular mel filterbank [n_mels, n_fft//2 + 1] (reference
    functional.py compute_fbank_matrix, librosa formulation)."""
    f_max = f_max or sr / 2.0
    n_bins = n_fft // 2 + 1
    fft_freqs = np.linspace(0, sr / 2.0, n_bins)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fft_freqs[None, :]
    weights = np.zeros((n_mels, n_bins))
    for i in range(n_mels):
        lower = -ramps[i] / fdiff[i]
        upper = ramps[i + 2] / fdiff[i + 1]
        weights[i] = np.maximum(0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights *= enorm[:, None]
    return Tensor(jnp.asarray(weights.astype("float32")))


def create_dct(n_mfcc: int, n_mels: int, norm: Optional[str] = "ortho"):
    """DCT-II basis [n_mels, n_mfcc] (reference functional.py create_dct)."""
    n = np.arange(n_mels)
    k = np.arange(n_mfcc)
    basis = np.cos(np.pi / n_mels * (n[:, None] + 0.5) * k[None, :])
    if norm == "ortho":
        basis[:, 0] *= 1.0 / math.sqrt(n_mels)
        basis[:, 1:] *= math.sqrt(2.0 / n_mels)
    else:
        basis *= 2.0
    return Tensor(jnp.asarray(basis.astype("float32")))


def power_to_db(magnitude, ref_value: float = 1.0, amin: float = 1e-10,
                top_db: Optional[float] = 80.0):
    x = magnitude._value if isinstance(magnitude, Tensor) \
        else jnp.asarray(magnitude)
    db = 10.0 * jnp.log10(jnp.maximum(x, amin))
    db = db - 10.0 * jnp.log10(jnp.maximum(ref_value, amin))
    if top_db is not None:
        db = jnp.maximum(db, db.max() - top_db)
    return Tensor(db)


def stft_frames(x, n_fft: int, hop_length: int, win_length: int,
                window, center: bool = True, pad_mode: str = "reflect"):
    """Frame + window + rfft: x [..., T] -> complex [..., n_fft//2+1,
    frames]."""
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    wv = window._value if isinstance(window, Tensor) else jnp.asarray(window)
    if win_length < n_fft:  # center-pad the window to n_fft
        lpad = (n_fft - win_length) // 2
        wv = jnp.pad(wv, (lpad, n_fft - win_length - lpad))
    if center:
        pad = [(0, 0)] * (xv.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        xv = jnp.pad(xv, pad, mode=pad_mode)
    t = xv.shape[-1]
    n_frames = 1 + (t - n_fft) // hop_length
    idx = (jnp.arange(n_frames)[:, None] * hop_length
           + jnp.arange(n_fft)[None, :])
    frames = xv[..., idx] * wv              # [..., frames, n_fft]
    spec = jnp.fft.rfft(frames, axis=-1)    # [..., frames, n_bins]
    return jnp.swapaxes(spec, -1, -2)       # [..., n_bins, frames]
