"""paddle_tpu.audio — audio feature extraction.

Analog of python/paddle/audio (functional/ window+mel+dct helpers,
features/layers.py Spectrogram/MelSpectrogram/LogMelSpectrogram/MFCC).
The STFT is framing + rfft — a batched matmul-and-FFT program XLA maps
well to TPU; layers precompute window/filterbank/DCT matrices as
constants.
"""

from . import functional
from .features import LogMelSpectrogram, MFCC, MelSpectrogram, Spectrogram

__all__ = ["functional", "Spectrogram", "MelSpectrogram",
           "LogMelSpectrogram", "MFCC"]
