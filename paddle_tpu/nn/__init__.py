"""paddle_tpu.nn — neural network layers (analog of paddle.nn)."""

from . import functional
from . import initializer
from .layer import Layer, Parameter
from .common import (
    Linear, Embedding, Dropout, Dropout2D, Flatten, Identity, Pad2D, Upsample,
    PixelShuffle, CosineSimilarity, Bilinear, PReLU,
    ReLU, ReLU6, LeakyReLU, ELU, SELU, CELU, GELU, Silu, Swish, Mish,
    Hardswish, Hardsigmoid, Hardtanh, Hardshrink, Softshrink, Tanhshrink,
    ThresholdedReLU, Softplus, Softsign, Sigmoid, Tanh, LogSigmoid, Softmax,
    LogSoftmax, Maxout, GLU,
    PixelUnshuffle, ChannelShuffle, Unfold, Fold, MaxUnPool2D, Dropout3D,
    AlphaDropout, RReLU, UpsamplingNearest2D, UpsamplingBilinear2D,
)
from .conv import Conv1D, Conv2D, Conv3D, Conv2DTranspose
from .norm import (
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, GroupNorm, InstanceNorm2D,
    LayerNorm, LocalResponseNorm, RMSNorm, SyncBatchNorm,
)
from .pooling import (
    AdaptiveAvgPool2D, AdaptiveMaxPool2D, AvgPool1D, AvgPool2D, MaxPool1D,
    MaxPool2D,
)
from .container import LayerDict, LayerList, ParameterList, Sequential
from .loss import (
    BCELoss, BCEWithLogitsLoss, CrossEntropyLoss, KLDivLoss, L1Loss, MSELoss,
    NLLLoss, SmoothL1Loss,
    MarginRankingLoss, SoftMarginLoss, HingeEmbeddingLoss,
    CosineEmbeddingLoss, TripletMarginLoss, MultiLabelSoftMarginLoss,
    GaussianNLLLoss, PoissonNLLLoss, CTCLoss, RNNTLoss,
)
from .transformer import (
    MultiHeadAttention, Transformer, TransformerDecoder,
    TransformerDecoderLayer, TransformerEncoder, TransformerEncoderLayer,
)
from .rnn import (
    GRU, GRUCell, LSTM, LSTMCell, RNN, RNNCellBase, SimpleRNN, SimpleRNNCell,
)

# paddle compat: nn.initializer.* style access is already available.
ClipGradByNorm = None  # set by optimizer.clip at import
ClipGradByGlobalNorm = None
ClipGradByValue = None


def _late_bind_clip():
    global ClipGradByNorm, ClipGradByGlobalNorm, ClipGradByValue
    from ..optimizer import clip as _clip

    ClipGradByNorm = _clip.ClipGradByNorm
    ClipGradByGlobalNorm = _clip.ClipGradByGlobalNorm
    ClipGradByValue = _clip.ClipGradByValue

from .extra_layers import *  # noqa: F401,F403  (round-5 layer long tail)
