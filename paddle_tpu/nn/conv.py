"""Conv layers (analog of python/paddle/nn/layer/conv.py). Weight layout is
(out_channels, in_channels/groups, *kernel) matching the reference; XLA maps
these onto the MXU via conv_general_dilated."""

from __future__ import annotations

import math

import jax.numpy as jnp

from . import functional as F
from . import initializer as init
from .layer import Layer, Parameter


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, ndim, stride=1,
                 padding=0, dilation=1, groups=1, bias_attr=None,
                 weight_attr=None, data_format="NCHW"):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * ndim
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = tuple(kernel_size)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.data_format = data_format
        w_shape = (out_channels, in_channels // groups, *self.kernel_size)
        w_init = weight_attr if isinstance(weight_attr, init.Initializer) else init.KaimingUniform()
        self.weight = Parameter(w_init(w_shape, jnp.float32))
        if bias_attr is False:
            self._parameters["bias"] = None
        else:
            fan_in = in_channels // groups * int(math.prod(self.kernel_size))
            bound = 1.0 / math.sqrt(fan_in)
            b_init = bias_attr if isinstance(bias_attr, init.Initializer) else init.Uniform(-bound, bound)
            self.bias = Parameter(b_init((out_channels,), jnp.float32))


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, bias_attr, weight_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self._parameters.get("bias"),
                        stride=self.stride, padding=self.padding,
                        dilation=self.dilation, groups=self.groups,
                        data_format=self.data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, bias_attr, weight_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self._parameters.get("bias"),
                        stride=self.stride, padding=self.padding,
                        dilation=self.dilation, groups=self.groups,
                        data_format=self.data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, bias_attr, weight_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self._parameters.get("bias"),
                        stride=self.stride, padding=self.padding,
                        dilation=self.dilation, groups=self.groups,
                        data_format=self.data_format)


class Conv2DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size, kernel_size)
        self.stride = stride
        self.padding = padding
        self.output_padding = output_padding
        self.dilation = dilation
        self.groups = groups
        self.data_format = data_format
        w_shape = (in_channels, out_channels // groups, *kernel_size)
        w_init = weight_attr if isinstance(weight_attr, init.Initializer) else init.KaimingUniform()
        self.weight = Parameter(w_init(w_shape, jnp.float32))
        if bias_attr is False:
            self._parameters["bias"] = None
        else:
            self.bias = Parameter(jnp.zeros((out_channels,), dtype=jnp.float32))

    def forward(self, x):
        return F.conv2d_transpose(x, self.weight, self._parameters.get("bias"),
                                  stride=self.stride, padding=self.padding,
                                  output_padding=self.output_padding,
                                  dilation=self.dilation, groups=self.groups,
                                  data_format=self.data_format)
