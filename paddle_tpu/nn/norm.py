"""Normalization layers (analog of python/paddle/nn/layer/norm.py).
BatchNorm keeps running stats as non-trainable buffers; LayerNorm/RMSNorm
compute in fp32 and cast back (TPU-friendly, matches phi kernel semantics)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from . import functional as F
from .layer import Layer, Parameter


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self._normalized_shape = tuple(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self._parameters["weight"] = None
        else:
            self.weight = Parameter(jnp.ones(self._normalized_shape, dtype=jnp.float32))
        if bias_attr is False:
            self._parameters["bias"] = None
        else:
            self.bias = Parameter(jnp.zeros(self._normalized_shape, dtype=jnp.float32))

    def forward(self, x):
        begin = x.ndim - len(self._normalized_shape)
        return F.layer_norm(x, self._parameters.get("weight"),
                            self._parameters.get("bias"),
                            epsilon=self._epsilon, begin_norm_axis=begin)


class RMSNorm(Layer):
    """TPU-first norm used by Llama-family models (analog of
    paddle.incubate.nn.functional.fused_rms_norm)."""

    def __init__(self, hidden_size, epsilon=1e-6):
        super().__init__()
        self._epsilon = epsilon
        self.weight = Parameter(jnp.ones((hidden_size,), dtype=jnp.float32))

    def forward(self, x):
        from ..incubate.nn import fused as _fused

        return _fused.fused_rms_norm(x, self.weight, epsilon=self._epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is False:
            self._parameters["weight"] = None
        else:
            self.weight = Parameter(jnp.ones((num_features,), dtype=jnp.float32))
        if bias_attr is False:
            self._parameters["bias"] = None
        else:
            self.bias = Parameter(jnp.zeros((num_features,), dtype=jnp.float32))
        self.register_buffer("_mean", Tensor(jnp.zeros((num_features,), dtype=jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones((num_features,), dtype=jnp.float32)))

    def forward(self, x):
        training = self.training and not (self._use_global_stats is True)
        return F.batch_norm(x, self._mean, self._variance,
                            self._parameters.get("weight"),
                            self._parameters.get("bias"),
                            training=training, momentum=self._momentum,
                            epsilon=self._epsilon, data_format=self._data_format)


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, **kw):
        kw.setdefault("data_format", "NCL")
        kw["data_format"] = "NCHW" if kw["data_format"] == "NCL" else "NHWC"
        super().__init__(num_features, **kw)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Under GSPMD data parallelism the batch statistics are computed over the
    global (sharded) batch automatically inside jit; eager single-process
    behavior equals BatchNorm. (Reference: paddle.nn.SyncBatchNorm backed by
    NCCL allreduce of stats.)"""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is False:
            self._parameters["weight"] = None
        else:
            self.weight = Parameter(jnp.ones((num_channels,), dtype=jnp.float32))
        if bias_attr is False:
            self._parameters["bias"] = None
        else:
            self.bias = Parameter(jnp.zeros((num_channels,), dtype=jnp.float32))

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._parameters.get("weight"),
                            self._parameters.get("bias"), epsilon=self._epsilon,
                            data_format=self._data_format)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False:
            self._parameters["weight"] = None
        else:
            self.weight = Parameter(jnp.ones((num_features,), dtype=jnp.float32))
        if bias_attr is False:
            self._parameters["bias"] = None
        else:
            self.bias = Parameter(jnp.zeros((num_features,), dtype=jnp.float32))

    def forward(self, x):
        return F.instance_norm(x, self._parameters.get("weight"),
                               self._parameters.get("bias"), epsilon=self._epsilon)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW"):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k

    def forward(self, x):
        import jax

        v = x._value if hasattr(x, "_value") else x
        sq = jnp.square(v)
        half = self.size // 2
        summed = jnp.zeros_like(sq)
        c = v.shape[1]
        for i in range(-half, half + 1):
            if i < 0:
                summed = summed.at[:, :c + i].add(sq[:, -i:])
            elif i > 0:
                summed = summed.at[:, i:].add(sq[:, :-i])
            else:
                summed = summed + sq
        denom = jnp.power(self.k + self.alpha * summed / self.size, self.beta)
        return Tensor(v / denom)
