"""Common layers: Linear, Embedding, Dropout, activations, padding, Flatten,
Upsample. Analog of python/paddle/nn/layer/common.py + activation.py."""

from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp

from ..core.dtype import convert_dtype
from . import functional as F
from . import initializer as init
from .layer import Layer, Parameter


class Linear(Layer):
    """y = x @ W + b, W shape (in, out) — matches the reference layout
    (python/paddle/nn/layer/common.py Linear)."""

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        w_init = weight_attr if isinstance(weight_attr, init.Initializer) else init.XavierUniform()
        self.weight = Parameter(w_init((in_features, out_features), jnp.float32))
        if bias_attr is False:
            self.bias = None
            self._parameters["bias"] = None
        else:
            b_init = bias_attr if isinstance(bias_attr, init.Initializer) else init.Constant(0.0)
            self.bias = Parameter(b_init((out_features,), jnp.float32))

    def forward(self, x):
        return F.linear(x, self.weight, self._parameters.get("bias"))

    def __repr__(self):
        return f"Linear(in={self.in_features}, out={self.out_features})"


class Embedding(Layer):
    """Analog of paddle.nn.Embedding (phi embedding kernel)."""

    def __init__(self, num_embeddings: int, embedding_dim: int, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        w_init = weight_attr if isinstance(weight_attr, init.Initializer) else init.Normal(0.0, 1.0)
        w = w_init((num_embeddings, embedding_dim), jnp.float32)
        if padding_idx is not None:
            w = w.at[padding_idx].set(0.0)
        self.weight = Parameter(w)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training, mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training, data_format=self.data_format)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        return x.flatten(self.start_axis, self.stop_axis)


def _act_layer(name, fn_name, **defaults):
    def forward(self, x):
        from ..ops.registry import dispatch

        return dispatch(fn_name, x, **{k: getattr(self, k) for k in defaults})

    def __init__(self, **kwargs):
        Layer.__init__(self)
        for k, v in defaults.items():
            setattr(self, k, kwargs.get(k, v))

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


ReLU = _act_layer("ReLU", "relu")
ReLU6 = _act_layer("ReLU6", "relu6")
LeakyReLU = _act_layer("LeakyReLU", "leaky_relu", negative_slope=0.01)
ELU = _act_layer("ELU", "elu", alpha=1.0)
SELU = _act_layer("SELU", "selu")
CELU = _act_layer("CELU", "celu", alpha=1.0)
GELU = _act_layer("GELU", "gelu", approximate=False)
Silu = _act_layer("Silu", "silu")
Swish = _act_layer("Swish", "swish")
Mish = _act_layer("Mish", "mish")
Hardswish = _act_layer("Hardswish", "hardswish")
Hardsigmoid = _act_layer("Hardsigmoid", "hardsigmoid")
Hardtanh = _act_layer("Hardtanh", "hardtanh", min=-1.0, max=1.0)
Hardshrink = _act_layer("Hardshrink", "hardshrink", threshold=0.5)
Softshrink = _act_layer("Softshrink", "softshrink", threshold=0.5)
Tanhshrink = _act_layer("Tanhshrink", "tanhshrink")
ThresholdedReLU = _act_layer("ThresholdedReLU", "thresholded_relu", threshold=1.0, value=0.0)
Softplus = _act_layer("Softplus", "softplus", beta=1.0, threshold=20.0)
Softsign = _act_layer("Softsign", "softsign")
Sigmoid = _act_layer("Sigmoid", "sigmoid")
Tanh = _act_layer("Tanh", "tanh")
LogSigmoid = _act_layer("LogSigmoid", "logsigmoid")
Softmax = _act_layer("Softmax", "softmax", axis=-1)
LogSoftmax = _act_layer("LogSoftmax", "log_softmax", axis=-1)
Maxout = _act_layer("Maxout", "maxout", groups=2, axis=1)
GLU = _act_layer("GLU", "glu", axis=-1)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init_value=0.25, name=None):
        super().__init__()
        self.weight = Parameter(jnp.full((num_parameters,), init_value, dtype=jnp.float32))

    def forward(self, x):
        from ..ops.registry import dispatch

        w = self.weight
        if w.shape[0] != 1:
            shape = [1] * x.ndim
            shape[1] = w.shape[0]
            w = w.reshape(shape)
        return dispatch("prelu", x, w)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW"):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value,
                     data_format=self.data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, data_format="NCHW"):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW"):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, data_format=self.data_format)


class Identity(Layer):
    def forward(self, x):
        return x


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, name=None):
        super().__init__()
        bound = 1.0 / math.sqrt(in1_features)
        self.weight = Parameter(init.Uniform(-bound, bound)(
            (out_features, in1_features, in2_features), jnp.float32))
        self.bias = Parameter(jnp.zeros((1, out_features), dtype=jnp.float32))

    def forward(self, x1, x2):
        from ..ops.registry import dispatch

        out = dispatch("einsum", "bi,oij,bj->bo", x1, self.weight, x2)
        return out + self.bias


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW"):
        super().__init__()
        self.downscale_factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor,
                                 data_format=self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW"):
        super().__init__()
        self.groups = groups
        self.data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups,
                                 data_format=self.data_format)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1):
        super().__init__()
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.unfold(x, self.kernel_sizes, self.strides, self.paddings,
                        self.dilations)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1):
        super().__init__()
        self.output_sizes = output_sizes
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.fold(x, self.output_sizes, self.kernel_sizes, self.strides,
                      self.paddings, self.dilations)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None):
        super().__init__()
        if data_format != "NCHW":
            # the reference unpool kernel is NCHW-only too
            raise ValueError("MaxUnPool2D only supports data_format='NCHW', "
                             f"got {data_format!r}")
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.output_size)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW"):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0):
        super().__init__()
        self.lower = lower
        self.upper = upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)


class UpsamplingNearest2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW"):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, "nearest",
                             data_format=self.data_format)


class UpsamplingBilinear2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW"):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, "bilinear",
                             align_corners=True, data_format=self.data_format)
