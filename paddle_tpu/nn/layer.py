"""Layer base class.

Analog of the reference's paddle.nn.Layer (python/paddle/nn/layer/layers.py):
parameter/sublayer registration, forward pre/post hooks, state_dict,
train/eval mode, ``to`` dtype casts, named traversal.

TPU-first addition: ``functional_state`` / ``functional_call`` expose the
layer as (pytree-of-params, pure function) — the bridge to jax.jit/pjit used
by paddle_tpu.jit.to_static and the distributed engine.
"""

from __future__ import annotations

import copy
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core.dtype import convert_dtype
from . import initializer as init


class Parameter(Tensor):
    """Trainable tensor (analog of paddle Parameter / EagerParamBase)."""

    def __init__(self, value, trainable: bool = True, name: Optional[str] = None):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.is_parameter = True
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True

    @property
    def trainable(self):
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v):
        self.stop_gradient = not v


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype: str = "float32"):
        self._parameters: "OrderedDict[str, Parameter]" = OrderedDict()
        self._sub_layers: "OrderedDict[str, Layer]" = OrderedDict()
        self._buffers: "OrderedDict[str, Tensor]" = OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._forward_post_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._hook_id = 0
        self.training = True
        self._dtype = dtype
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # ------------------------ attribute plumbing -------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        subs = self.__dict__.get("_sub_layers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if subs is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            subs[name] = value
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                del params[name]
            if subs is not None and name in subs:
                del subs[name]
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        if "_parameters" in self.__dict__ and name in self.__dict__["_parameters"]:
            return self.__dict__["_parameters"][name]
        if "_sub_layers" in self.__dict__ and name in self.__dict__["_sub_layers"]:
            return self.__dict__["_sub_layers"][name]
        if "_buffers" in self.__dict__ and name in self.__dict__["_buffers"]:
            return self.__dict__["_buffers"][name]
        raise AttributeError(f"{type(self).__name__!r} has no attribute {name!r}")

    def __delattr__(self, name):
        if name in self._parameters:
            del self._parameters[name]
        elif name in self._sub_layers:
            del self._sub_layers[name]
        elif name in self._buffers:
            del self._buffers[name]
        else:
            object.__delattr__(self, name)

    # ------------------------ registration -------------------------------
    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def create_parameter(self, shape, dtype=None, default_initializer=None,
                         is_bias: bool = False, attr=None) -> Parameter:
        dtype = convert_dtype(dtype or self._dtype)
        if default_initializer is None:
            default_initializer = init.Constant(0.0) if is_bias else init.XavierUniform()
        value = default_initializer(shape, dtype)
        return Parameter(value)

    # ------------------------ traversal -----------------------------------
    def named_parameters(self, prefix: str = "", include_sublayers: bool = True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (prefix + name if not prefix else f"{prefix}.{name}"), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                for n, p in layer.named_parameters(sub_prefix):
                    if id(p) not in seen:
                        seen.add(id(p))
                        yield n, p

    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", persistable_only: bool = False
                      ) -> Iterator[Tuple[str, Tensor]]:
        for name, b in self._buffers.items():
            if b is None:
                continue
            if persistable_only and name in self._non_persistable_buffer_names:
                continue
            yield (f"{prefix}.{name}" if prefix else name), b
        for lname, layer in self._sub_layers.items():
            sub_prefix = f"{prefix}.{lname}" if prefix else lname
            yield from layer.named_buffers(sub_prefix, persistable_only)

    def buffers(self) -> List[Tensor]:
        return [b for _, b in self.named_buffers()]

    def named_sublayers(self, prefix: str = "", include_self: bool = False
                        ) -> Iterator[Tuple[str, "Layer"]]:
        if include_self:
            yield prefix, self
        for lname, layer in self._sub_layers.items():
            sub_prefix = f"{prefix}.{lname}" if prefix else lname
            yield sub_prefix, layer
            yield from layer.named_sublayers(sub_prefix)

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self) -> Iterator["Layer"]:
        yield from self._sub_layers.values()

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        yield from self._sub_layers.items()

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for layer in self.children():
            layer.apply(fn)
        fn(self)
        return self

    # ------------------------ modes ---------------------------------------
    def train(self):
        self.training = True
        for layer in self.children():
            layer.train()
        return self

    def eval(self):
        self.training = False
        for layer in self.children():
            layer.eval()
        return self

    # ------------------------ hooks ----------------------------------------
    class _HookRemove:
        def __init__(self, d, k):
            self._d, self._k = d, k

        def remove(self):
            self._d.pop(self._k, None)

    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return Layer._HookRemove(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return Layer._HookRemove(self._forward_post_hooks, self._hook_id)

    # ------------------------ call -----------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    # ------------------------ state dict ------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True) -> Dict[str, Tensor]:
        out = OrderedDict() if destination is None else destination
        for name, p in self.named_parameters():
            out[structured_name_prefix + name] = p
        # non-persistable buffers are filtered by their OWNING layer's set
        # (a root-level check would miss sublayer registrations)
        for name, b in self.named_buffers(persistable_only=True):
            out[structured_name_prefix + name] = b
        return out

    def set_state_dict(self, state_dict, use_structured_name: bool = True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, target in own.items():
            if name in state_dict:
                src = state_dict[name]
                v = src._value if isinstance(src, Tensor) else jnp.asarray(src)
                target.set_value(v.astype(target.dtype))
            else:
                missing.append(name)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    load_dict = set_state_dict

    # ------------------------ dtype / device ---------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = convert_dtype(dtype)
            for p in self.parameters():
                p.set_value(p._value.astype(dt))
            for b in self.buffers():
                if jnp.issubdtype(b.dtype, jnp.floating):
                    b.set_value(b._value.astype(dt))
        if device is not None:
            from ..core.device import Place

            place = device if isinstance(device, Place) else Place(str(device).split(":")[0])
            for t in list(self.parameters()) + list(self.buffers()):
                t.set_value(jax.device_put(t._value, place.jax_device))
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # ------------------------ functional bridge ------------------------------
    def functional_state(self) -> Dict[str, Any]:
        """Raw-array pytree of all params+buffers keyed by structured name."""
        return {k: v._value for k, v in self.state_dict().items()}

    def load_functional_state(self, state: Dict[str, Any]):
        """Write a functional-state pytree back into the layer's own
        storage.  The compiled train steps DONATE their params/opt-state
        buffers (jit donate_argnums), which deletes the layer's original
        arrays — after a compiled run, call this with the returned params
        before using the layer eagerly (state_dict/save/inference)."""
        sd = self.state_dict()
        for k, t in sd.items():
            if k in state:
                t.set_value(state[k])
        return self

    def functional_call(self, state: Dict[str, Any], *args, **kwargs):
        """Run forward with parameter values substituted from ``state``
        (pure w.r.t. the layer's own storage; the jit bridge)."""
        sd = self.state_dict()
        saved = {k: t._value for k, t in sd.items()}
        try:
            for k, t in sd.items():
                if k in state:
                    t._value = state[k]
            return self(*args, **kwargs)
        finally:
            for k, t in sd.items():
                t._value = saved[k]

    def full_name(self):
        return self._name_scope

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def __repr__(self):
        extra = []
        for name, layer in self._sub_layers.items():
            body = repr(layer).replace("\n", "\n  ")
            extra.append(f"  ({name}): {body}")
        inner = "\n".join(extra)
        if inner:
            return f"{type(self).__name__}(\n{inner}\n)"
        return f"{type(self).__name__}()"
