"""Weight initializers (analog of paddle.nn.initializer /
python/paddle/nn/initializer/*). Initializers are host-side: they produce a
jax array for a given (shape, dtype) using the global generator."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import random as _random


def _fan_in_out(shape):
    shape = tuple(shape)
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    # conv weight (out, in, *k): fan_in = in * k, fan_out = out * k
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(tuple(shape), self.value, dtype=dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        k = _random.default_generator().next_key()
        return jax.random.uniform(k, tuple(shape), dtype=jnp.float32,
                                  minval=self.low, maxval=self.high).astype(dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        k = _random.default_generator().next_key()
        return (self.mean + self.std * jax.random.normal(k, tuple(shape), dtype=jnp.float32)).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        k = _random.default_generator().next_key()
        z = jax.random.truncated_normal(k, self.a, self.b, tuple(shape), dtype=jnp.float32)
        return (self.mean + self.std * z).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        fan_in, fan_out = _fan_in_out(shape)
        limit = self.gain * math.sqrt(6.0 / (fan_in + fan_out))
        k = _random.default_generator().next_key()
        return jax.random.uniform(k, tuple(shape), dtype=jnp.float32,
                                  minval=-limit, maxval=limit).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        fan_in, fan_out = _fan_in_out(shape)
        std = self.gain * math.sqrt(2.0 / (fan_in + fan_out))
        k = _random.default_generator().next_key()
        return (std * jax.random.normal(k, tuple(shape), dtype=jnp.float32)).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, negative_slope=0.0, nonlinearity="leaky_relu", fan_mode="fan_in"):
        self.negative_slope = negative_slope
        self.fan_mode = fan_mode

    def __call__(self, shape, dtype):
        fan_in, fan_out = _fan_in_out(shape)
        fan = fan_in if self.fan_mode == "fan_in" else fan_out
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        limit = gain * math.sqrt(3.0 / fan)
        k = _random.default_generator().next_key()
        return jax.random.uniform(k, tuple(shape), dtype=jnp.float32,
                                  minval=-limit, maxval=limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, negative_slope=0.0, nonlinearity="leaky_relu", fan_mode="fan_in"):
        self.negative_slope = negative_slope
        self.fan_mode = fan_mode

    def __call__(self, shape, dtype):
        fan_in, fan_out = _fan_in_out(shape)
        fan = fan_in if self.fan_mode == "fan_in" else fan_out
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        std = gain / math.sqrt(fan)
        k = _random.default_generator().next_key()
        return (std * jax.random.normal(k, tuple(shape), dtype=jnp.float32)).astype(dtype)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        k = _random.default_generator().next_key()
        return (self.gain * jax.nn.initializers.orthogonal()(k, tuple(shape), jnp.float32)).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        v = jnp.asarray(self.value, dtype=dtype)
        assert tuple(v.shape) == tuple(shape), f"Assign shape {v.shape} != {shape}"
        return v


# paddle-compat aliases
constant = Constant
uniform = Uniform
normal = Normal
