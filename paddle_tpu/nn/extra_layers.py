"""Round-5 nn layer long tail (reference python/paddle/nn/__init__.py
__all__): pooling/pad/norm/loss/conv-transpose layer classes over the
functional surface, plus seq2seq decoding (BiRNN, BeamSearchDecoder,
dynamic_decode) and SpectralNorm."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops.registry import dispatch
from . import functional as F
from .layer import Layer, Parameter

__all__ = [
    "AdaptiveAvgPool1D", "AdaptiveAvgPool3D", "AdaptiveMaxPool1D",
    "AdaptiveMaxPool3D", "AvgPool3D", "MaxPool3D", "MaxUnPool1D",
    "MaxUnPool3D", "LPPool1D", "LPPool2D", "FractionalMaxPool2D",
    "FractionalMaxPool3D", "Pad1D", "Pad3D", "ZeroPad1D", "ZeroPad2D",
    "ZeroPad3D", "InstanceNorm1D", "InstanceNorm3D", "Softmax2D",
    "Unflatten", "PairwiseDistance", "MultiMarginLoss",
    "TripletMarginWithDistanceLoss", "HSigmoidLoss",
    "AdaptiveLogSoftmaxWithLoss", "FeatureAlphaDropout", "Conv1DTranspose",
    "Conv3DTranspose", "SpectralNorm", "BiRNN", "BeamSearchDecoder",
    "dynamic_decode",
]


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


# ------------------------------ pooling -------------------------------------


class _PoolNd(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, **kw):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.kw = kw


class MaxPool3D(_PoolNd):
    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding)


class AvgPool3D(_PoolNd):
    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveMaxPool1D(AdaptiveAvgPool1D):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(output_size)
        self.return_mask = return_mask

    def forward(self, x):
        # return_mask forwards to the functional (which raises loudly
        # for the unsupported index round-trip instead of silently
        # dropping the flag)
        return F.adaptive_max_pool1d(x, self.output_size,
                                     return_mask=self.return_mask)


class AdaptiveAvgPool3D(AdaptiveAvgPool1D):
    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size)


class AdaptiveMaxPool3D(AdaptiveMaxPool1D):
    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size,
                                     return_mask=self.return_mask)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.output_size)


class MaxUnPool3D(MaxUnPool1D):
    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.output_size)


class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self.norm_type = norm_type
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding

    def forward(self, x):
        return F.lp_pool1d(x, self.norm_type, self.kernel_size, self.stride,
                           self.padding)


class LPPool2D(LPPool1D):
    def forward(self, x):
        return dispatch("lp_pool2d", x, self.norm_type, self.kernel_size,
                        self.stride, self.padding)


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.random_u = random_u

    def forward(self, x):
        return dispatch("fractional_max_pool2d", x, self.output_size,
                        random_u=self.random_u)


class FractionalMaxPool3D(FractionalMaxPool2D):
    def forward(self, x):
        return dispatch("fractional_max_pool3d", x, self.output_size,
                        random_u=self.random_u)


# ------------------------------ padding -------------------------------------


class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCL", name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value,
                     data_format=self.data_format)


class Pad3D(Pad1D):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad1D(Pad1D):
    def __init__(self, padding, data_format="NCL", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class ZeroPad2D(Layer):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__()
        self.padding = padding
        self.data_format = data_format

    def forward(self, x):
        return F.zeropad2d(x, self.padding, data_format=self.data_format)


class ZeroPad3D(Pad1D):
    def __init__(self, padding, data_format="NCDHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


# ------------------------------ norm / shape --------------------------------


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False:
            self._parameters["weight"] = None
        else:
            self.weight = Parameter(jnp.ones((num_features,), jnp.float32))
        if bias_attr is False:
            self._parameters["bias"] = None
        else:
            self.bias = Parameter(jnp.zeros((num_features,), jnp.float32))

    def forward(self, x):
        return F.instance_norm(x, self._parameters.get("weight"),
                               self._parameters.get("bias"),
                               epsilon=self._epsilon)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr)


class Softmax2D(Layer):
    """Softmax over the CHANNEL dim of NCHW inputs (reference
    nn.Softmax2D)."""

    def forward(self, x):
        return F.softmax(x, axis=-3)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis = axis
        self.shape = shape

    def forward(self, x):
        import paddle_tpu as _p

        return _p.unflatten(x, self.axis, self.shape)


# ------------------------------ losses --------------------------------------


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p = p
        self.margin = margin
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.multi_margin_loss(input, label, self.p, self.margin,
                                   self.weight, self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.distance_function = distance_function
        self.margin = margin
        self.swap = swap
        self.reduction = reduction

    def forward(self, input, positive, negative):  # noqa: A002
        return F.triplet_margin_with_distance_loss(
            input, positive, negative, self.distance_function, self.margin,
            self.swap, self.reduction)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid loss layer over the registered hsigmoid_loss
    op (reference nn.HSigmoidLoss; SimpleCode tree)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        self.num_classes = num_classes
        rng = np.random.RandomState(0)
        self.weight = Parameter(jnp.asarray(
            rng.randn(num_classes - 1, feature_size).astype(np.float32)
            * 0.01))
        if bias_attr is not False:
            self.bias = Parameter(jnp.zeros((num_classes - 1,),
                                            jnp.float32))
        else:
            self._parameters["bias"] = None

    def forward(self, input, label):  # noqa: A002
        return dispatch("hsigmoid_loss", input, label, self.num_classes,
                        self._parameters["weight"],
                        self._parameters.get("bias"))


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Adaptive softmax layer (reference nn.AdaptiveLogSoftmaxWithLoss):
    head over [shortlist + clusters], projected tails per cluster."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        cutoffs = list(cutoffs)
        self.cutoffs = cutoffs + [n_classes]
        self.n_clusters = len(cutoffs)
        head_size = cutoffs[0] + self.n_clusters
        rng = np.random.RandomState(0)
        self.head_weight = Parameter(jnp.asarray(
            rng.randn(in_features, head_size).astype(np.float32) * 0.02))
        if head_bias:
            self.head_bias = Parameter(jnp.zeros((head_size,), jnp.float32))
        else:
            self._parameters["head_bias"] = None
        self._tails = []
        for i in range(self.n_clusters):
            hsz = max(1, int(in_features // (div_value ** (i + 1))))
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            w1 = Parameter(jnp.asarray(
                rng.randn(in_features, hsz).astype(np.float32) * 0.02))
            w2 = Parameter(jnp.asarray(
                rng.randn(hsz, osz).astype(np.float32) * 0.02))
            self._parameters[f"tail_{i}_proj"] = w1
            self._parameters[f"tail_{i}_out"] = w2
            self._tails.append((f"tail_{i}_proj", f"tail_{i}_out"))

    def forward(self, input, label):  # noqa: A002
        tails = [(self._parameters[a], self._parameters[b])
                 for a, b in self._tails]
        out, loss = F.adaptive_log_softmax_with_loss(
            input, label, self._parameters["head_weight"], tails,
            self.cutoffs, self._parameters.get("head_bias"))
        return out, loss

    def log_prob(self, input):  # noqa: A002
        """Full [N, n_classes] log-probabilities."""
        xf = _val(input).astype(jnp.float32)
        head = xf @ _val(self._parameters["head_weight"])
        if self._parameters.get("head_bias") is not None:
            head = head + _val(self._parameters["head_bias"])
        head_lp = jax.nn.log_softmax(head, axis=-1)
        shortlist = self.cutoffs[0]
        parts = [head_lp[:, :shortlist]]
        for i, (a, b) in enumerate(self._tails):
            tl = (xf @ _val(self._parameters[a])) @ _val(
                self._parameters[b])
            tail_lp = jax.nn.log_softmax(tl, axis=-1)
            parts.append(head_lp[:, shortlist + i:shortlist + i + 1]
                         + tail_lp)
        return Tensor(jnp.concatenate(parts, axis=1))

    def predict(self, input):  # noqa: A002
        return Tensor(jnp.argmax(self.log_prob(input)._value, axis=-1))


class FeatureAlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.feature_alpha_dropout(x, self.p, training=self.training)


# ------------------------------ convs ---------------------------------------


class _ConvTransposeNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, nd,
                 stride=1, padding=0, output_padding=0, dilation=1,
                 groups=1, weight_attr=None, bias_attr=None,
                 data_format=None):
        super().__init__()
        from .initializer import XavierUniform

        ks = (kernel_size,) * nd if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.stride = stride
        self.padding = padding
        self.output_padding = output_padding
        self.dilation = dilation
        self.groups = groups
        w = XavierUniform()((in_channels, out_channels // groups) + ks,
                            jnp.float32)
        self.weight = Parameter(w)
        if bias_attr is not False:
            self.bias = Parameter(jnp.zeros((out_channels,), jnp.float32))
        else:
            self._parameters["bias"] = None


class Conv1DTranspose(_ConvTransposeNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, output_padding, dilation, groups,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv1d_transpose(
            x, self._parameters["weight"], self._parameters.get("bias"),
            stride=self.stride, padding=self.padding,
            output_padding=self.output_padding, groups=self.groups,
            dilation=self.dilation)


class Conv3DTranspose(_ConvTransposeNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, output_padding, dilation, groups,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        return dispatch(
            "conv3d_transpose", x, self._parameters["weight"],
            self._parameters.get("bias"), stride=self.stride,
            padding=self.padding, output_padding=self.output_padding,
            groups=self.groups, dilation=self.dilation)


class SpectralNorm(Layer):
    """Spectral normalization of a weight tensor by power iteration
    (reference nn.SpectralNorm): returns W / sigma_max."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        rng = np.random.RandomState(0)
        self.weight_u = Parameter(jnp.asarray(
            rng.randn(h).astype(np.float32)))
        self.weight_v = Parameter(jnp.asarray(
            rng.randn(w).astype(np.float32)))

    def forward(self, weight):
        wv = _val(weight)
        mat = jnp.moveaxis(wv, self.dim, 0).reshape(wv.shape[self.dim], -1)
        u = _val(self.weight_u)
        v = _val(self.weight_v)
        for _ in range(max(1, self.power_iters)):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + self.eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + self.eps)
        sigma = u @ mat @ v
        self.weight_u._value = u
        self.weight_v._value = v
        return Tensor(wv / (sigma + self.eps))


# ------------------------------ seq2seq decode ------------------------------


class BiRNN(Layer):
    """Bidirectional cell wrapper (reference nn.BiRNN): runs the forward
    and backward cells over the sequence and concatenates outputs."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from .rnn import RNN

        fw = RNN(self.cell_fw, time_major=self.time_major)
        bw = RNN(self.cell_bw, time_major=self.time_major,
                 is_reverse=True)
        s_fw, s_bw = (initial_states if initial_states is not None
                      else (None, None))
        o_fw, st_fw = fw(inputs, s_fw)
        o_bw, st_bw = bw(inputs, s_bw)
        out = Tensor(jnp.concatenate([_val(o_fw), _val(o_bw)], axis=-1))
        return out, (st_fw, st_bw)


class BeamSearchDecoder:
    """Reference nn.BeamSearchDecoder over an RNN cell: step-wise beam
    expansion driven by dynamic_decode."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        if embedding_fn is None:
            raise ValueError(
                "BeamSearchDecoder needs embedding_fn (token ids -> cell "
                "inputs); the decoder cannot guess the cell's input "
                "width")
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def initialize(self, inits, batch_size):
        k = self.beam_size
        tok = jnp.full((batch_size, k), self.start_token, jnp.int32)
        lp = jnp.tile(jnp.asarray([[0.0] + [-1e9] * (k - 1)], jnp.float32),
                      (batch_size, 1))
        fin = jnp.zeros((batch_size, k), bool)
        return tok, lp, fin, inits

    def step(self, tokens, states):
        """One cell step over flattened [B*K] beams -> log-probs."""
        emb = self.embedding_fn(Tensor(tokens))
        out, new_states = self.cell(emb, states)
        logits = self.output_fn(out) if self.output_fn else out
        return jax.nn.log_softmax(_val(logits).astype(jnp.float32),
                                  axis=-1), new_states


def dynamic_decode(decoder, inits=None, max_step_num=32, batch_size=1,
                   **kwargs):
    """Run a BeamSearchDecoder to completion (reference
    paddle.nn.dynamic_decode): returns (token ids [B, K, T], beam
    log-probs [B, K])."""
    tok, lp, fin, states = decoder.initialize(inits, batch_size)
    b, k = tok.shape
    seqs = []
    for _ in range(max_step_num):
        flat_tok = tok.reshape(b * k)
        logp, states = decoder.step(flat_tok, states)
        v = logp.shape[-1]
        logp = logp.reshape(b, k, v)
        # finished beams only extend with end_token at zero cost
        pad = jnp.full((b, k, v), -1e9).at[:, :, decoder.end_token].set(0.0)
        logp = jnp.where(fin[:, :, None], pad, logp)
        total = lp[:, :, None] + logp
        flat = total.reshape(b, k * v)
        lp, idx = jax.lax.top_k(flat, k)
        beam = idx // v
        tok = (idx % v).astype(jnp.int32)
        fin = jnp.take_along_axis(fin, beam, axis=1) | \
            (tok == decoder.end_token)
        seqs = [jnp.take_along_axis(s, beam, axis=1) for s in seqs]
        seqs.append(tok)
        if bool(fin.all()):
            break
    ids = jnp.stack(seqs, axis=-1)
    return Tensor(ids), Tensor(lp)
