"""paddle_tpu.nn.functional — functional nn API.

Analog of python/paddle/nn/functional/*: thin Tensor-level wrappers over the
registered nn ops, plus dropout/attention conveniences that thread RNG
through the global generator.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops.registry import dispatch
from ...ops import random as _random

# re-export op-level entry points (Tensor in/out via dispatch)
from ...ops.nn_ops import (  # noqa: F401
    relu, relu6, leaky_relu, prelu, elu, selu, celu, gelu, silu, swish, mish,
    hardswish, hardsigmoid, hardtanh, hardshrink, softshrink, tanhshrink,
    thresholded_relu, softplus, softsign, maxout, glu, softmax, log_softmax,
    layer_norm, rms_norm, group_norm, instance_norm,
    linear, conv1d, conv2d, conv3d, conv2d_transpose,
    max_pool1d, max_pool2d, avg_pool1d, avg_pool2d,
    adaptive_avg_pool2d, adaptive_max_pool2d,
    embedding, scaled_dot_product_attention,
    softmax_with_cross_entropy, binary_cross_entropy,
    binary_cross_entropy_with_logits, mse_loss, l1_loss, smooth_l1_loss,
    kl_div, nll_loss, cosine_similarity, pixel_shuffle, unfold,
    local_response_norm, max_unpool2d, npair_loss,
    margin_ranking_loss, soft_margin_loss, hinge_embedding_loss,
    cosine_embedding_loss, triplet_margin_loss,
    multi_label_soft_margin_loss, gaussian_nll_loss, poisson_nll_loss,
    square_error_cost, dice_loss, sigmoid_focal_loss,
)
from ...ops.math import sigmoid, tanh  # noqa: F401
from ...ops.manip import pad, one_hot  # noqa: F401
# yaml-schema ops with torch-golden generated tests (ops/yaml/ops.yaml)
from ...ops.generated import (  # noqa: F401
    affine_grid, channel_shuffle, fold, grid_sample, max_pool2d_with_index,
    pixel_unshuffle, temporal_shift,
)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    """Analog of paddle.nn.functional.dropout (phi dropout kernel)."""
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return dispatch("scale", x, scale=1.0 - p)
        return x
    key = _random.default_generator().next_key()
    shape = tuple(x.shape)
    if axis is not None:
        axes = [axis] if isinstance(axis, int) else list(axis)
        shape = tuple(s if i in axes else 1 for i, s in enumerate(shape))
    mask = jax.random.bernoulli(key, 1.0 - p, shape)
    mask = jnp.broadcast_to(mask, tuple(x.shape))
    return dispatch("dropout_impl", x, Tensor(mask), p=p, mode=mode)


def dropout2d(x, p=0.5, training=True, data_format="NCHW"):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",  # noqa: A002
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0):
    """Analog of paddle.nn.functional.cross_entropy
    (phi cross_entropy_with_softmax kernel + python wrapper)."""
    if label_smoothing > 0.0 and not soft_label:
        num_classes = input.shape[axis]
        oh = dispatch("one_hot", label, num_classes=num_classes)
        oh = dispatch("cast", oh, dtype=jnp.float32)
        smooth = oh * (1.0 - label_smoothing) + label_smoothing / num_classes
        return cross_entropy(input, smooth, weight=weight, reduction=reduction,
                             soft_label=True, axis=axis, use_softmax=use_softmax)
    if use_softmax:
        nll = dispatch("softmax_with_cross_entropy", input, label,
                       soft_label=soft_label, ignore_index=ignore_index, axis=axis)
    else:
        logp = dispatch("log", input)
        if soft_label:
            nll = -(label * logp).sum(axis=axis, keepdim=True)
        else:
            return nll_loss(logp, label, weight=weight, ignore_index=ignore_index,
                            reduction=reduction)
    if weight is not None and not soft_label:
        w = dispatch("gather", weight, label, axis=0)
        nll = nll * w.unsqueeze(-1)
    if reduction == "none":
        return nll
    if reduction == "sum":
        return nll.sum()
    if not soft_label:
        lblv = label._value if isinstance(label, Tensor) else label
        valid = (lblv != ignore_index)
        denom = jnp.maximum(valid.sum(), 1).astype(jnp.float32)
        return nll.sum() / Tensor(denom)
    return nll.mean()


def normalize(x, p=2, axis=1, epsilon=1e-12):
    return dispatch("normalize_op", x, p=p, axis=axis, epsilon=epsilon)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW"):
    if not training:
        return dispatch("batch_norm_infer", x, running_mean, running_var,
                        weight, bias, epsilon=epsilon, data_format=data_format)
    out, mean, var = dispatch("batch_norm_train", x, weight, bias,
                              epsilon=epsilon, data_format=data_format)
    # update running stats in-place (host side, matches reference semantics)
    if running_mean is not None:
        running_mean.set_value(momentum * running_mean._value + (1 - momentum) * mean._value)
        running_var.set_value(momentum * running_var._value + (1 - momentum) * var._value)
    return out


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, data_format="NCHW"):
    if size is None:
        h_axis, w_axis = (2, 3) if data_format == "NCHW" else (1, 2)
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else (scale_factor, scale_factor)
        size = (int(x.shape[h_axis] * sf[0]), int(x.shape[w_axis] * sf[1]))
    if mode == "nearest":
        return dispatch("interpolate_nearest", x, size=tuple(size), data_format=data_format)
    if mode in ("bilinear", "linear"):
        return dispatch("interpolate_bilinear", x, size=tuple(size),
                        align_corners=align_corners, data_format=data_format)
    raise NotImplementedError(f"interpolate mode {mode!r}")


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             data_format="NCHW"):
    return interpolate(x, size, scale_factor, mode, align_corners, data_format)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, training=True):
    """Analog of paddle.nn.functional.flash_attention.flash_attention
    (python/paddle/nn/functional/flash_attention.py:195). On TPU this routes
    to the Pallas flash kernel when available, else the XLA softmax path."""
    from ...incubate.nn import attention as _attn

    out = _attn.flash_attention(query, key, value, causal=causal,
                                dropout=dropout if training else 0.0)
    if return_softmax:
        return out, None
    return out


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q=None, max_seqlen_k=None, scale=None,
                        dropout=0.0, causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True):
    """Ragged (varlen) flash attention over a PACKED token stream —
    analog of paddle.nn.functional.flash_attention.flash_attn_unpadded
    (python/paddle/nn/functional/flash_attention.py; GPU kernel
    phi/kernels/gpu/flash_attn_kernel.cu).  query [total_q, h, d] with
    cu_seqlens offsets; the Pallas kernel skips disjoint-segment tiles
    (per-segment block skipping), so no padding FLOPs are spent."""
    from ...ops.registry import dispatch

    out = dispatch("flash_attn_unpadded", query, key, value,
                   cu_seqlens_q, cu_seqlens_k,
                   max_seqlen_q=max_seqlen_q, max_seqlen_k=max_seqlen_k,
                   scale=scale, dropout=dropout if training else 0.0,
                   causal=causal)
    if return_softmax:
        return out, None
    return out


def flashmask_attention(query, key, value, startend_row_indices=None,
                        dropout=0.0, causal=False, window_size=None,
                        return_softmax_lse=False, return_seed_offset=False,
                        fixed_seed_offset=None, rng_name="", training=True):
    """FlashMask column-sparse-mask attention — analog of
    paddle.nn.functional.flashmask_attention (python/paddle/nn/functional/
    flash_attention.py:1098; op paddle/phi/ops/yaml/ops.yaml:1913).

    ``startend_row_indices`` [b, mh, sk, {1,2,4}] int32 encodes per-column
    masked row bands (causal document mask, share-question mask, sliding
    window, global+window...).  Runs the Pallas flash kernel with
    mask-driven block skipping; the 4-bound non-causal class the
    reference leaves NotImplementedError is supported here."""
    from ...ops.registry import dispatch

    out = dispatch("flashmask_attention", query, key, value,
                   startend_row_indices,
                   dropout=dropout if training else 0.0, causal=causal,
                   window_size=window_size)
    extras = []
    if return_softmax_lse:
        extras.append(None)   # lse is a kernel residual, not re-exposed
    if return_seed_offset:
        extras.append(None)
    if extras:
        return (out, *extras)
    return out


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k,
                                max_seqlen_q=None, max_seqlen_k=None,
                                scale=None, dropout=0.0, causal=False,
                                return_softmax=False, fixed_seed_offset=None,
                                rng_name="", varlen_padded=True,
                                training=True):
    """Varlen attention on a GQA-packed qkv tensor [total, h/kvh + 2,
    kvh, d] — analog of paddle.nn.functional.flash_attn_varlen_qkvpacked
    (python/paddle/nn/functional/flash_attention.py:848)."""
    from ...ops.registry import dispatch

    out = dispatch("flash_attn_varlen_qkvpacked", qkv,
                   cu_seqlens_q, cu_seqlens_k,
                   max_seqlen_q=max_seqlen_q, max_seqlen_k=max_seqlen_k,
                   scale=scale, dropout=dropout if training else 0.0,
                   causal=causal, varlen_padded=varlen_padded)
    if return_softmax:
        return out, None
    return out


def scaled_dot_product_attention_(q, k, v, attn_mask=None, dropout_p=0.0,
                                  is_causal=False, training=True):
    mask_t = None
    if dropout_p > 0.0 and training:
        key_ = _random.default_generator().next_key()
        b, sq, h, _ = q.shape
        sk = k.shape[1]
        mask_t = Tensor(jax.random.bernoulli(key_, 1.0 - dropout_p, (b, h, sq, sk)))
    return dispatch("scaled_dot_product_attention", q, k, v, attn_mask=attn_mask,
                    dropout_mask=mask_t, dropout_p=dropout_p, is_causal=is_causal)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW"):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True):
    """SELU-preserving dropout (reference functional/common.py
    alpha_dropout): dropped units take alpha' and an affine (a, b)
    restores zero mean / unit variance."""
    if not training or p == 0.0:
        return x
    alpha_p = -1.7580993408473766  # -scale * alpha of SELU
    a = ((1.0 - p) * (1.0 + p * alpha_p ** 2)) ** -0.5
    b = -a * alpha_p * p
    key = _random.default_generator().next_key()
    keep = Tensor(jax.random.bernoulli(key, 1.0 - p, tuple(x.shape)))
    kept = dispatch("cast", keep, dtype=jnp.float32)
    return (x * kept + (1.0 - kept) * alpha_p) * a + b


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1):
    """Differentiable categorical relaxation (reference functional/
    activation.py gumbel_softmax); ``hard`` uses the straight-through
    one-hot."""
    key = _random.default_generator().next_key()
    u = jax.random.uniform(key, tuple(x.shape), minval=1e-10, maxval=1.0)
    g = Tensor(-jnp.log(-jnp.log(u)))
    y = softmax((x + g) / float(temperature), axis=axis)
    if not hard:
        return y
    idx = dispatch("argmax", y, axis=axis)
    y_hard = dispatch("one_hot", idx, num_classes=x.shape[axis])
    y_hard = dispatch("cast", y_hard, dtype=jnp.float32)
    nd = len(x.shape)
    axis = axis % nd
    if axis != nd - 1:
        # one_hot put the class dim last: move it back to ``axis``
        perm = list(range(nd - 1))
        perm.insert(axis, nd - 1)
        y_hard = dispatch("transpose", y_hard, perm=tuple(perm))
    return y_hard - y.detach() + y


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True):
    """Randomized leaky ReLU (reference functional/activation.py rrelu):
    negative slope ~ U[lower, upper] per element in training, the mean
    slope at inference."""
    if not training:
        return leaky_relu(x, negative_slope=(lower + upper) / 2.0)
    key = _random.default_generator().next_key()
    slope = Tensor(jax.random.uniform(key, tuple(x.shape),
                                      minval=lower, maxval=upper))
    neg = x * slope
    pos_mask = dispatch("cast", x > 0.0, dtype=jnp.float32)
    return x * pos_mask + neg * (1.0 - pos_mask)


def class_center_sample(label, num_classes, num_samples, group=None):
    """PartialFC-style class-center sampling (reference
    functional/common.py class_center_sample): keep all positive classes,
    fill to ``num_samples`` with uniformly sampled negatives; returns
    (remapped_label, sampled_class_center). Host-side sampling (eager)."""
    import numpy as np

    lbl = np.asarray(label._value if isinstance(label, Tensor) else label)
    pos = np.unique(lbl)
    if len(pos) >= num_samples:
        sampled = pos
    else:
        neg_pool = np.setdiff1d(np.arange(num_classes), pos,
                                assume_unique=True)
        seed = int(jax.random.randint(
            _random.default_generator().next_key(), (), 0, 2 ** 31 - 1))
        rng = np.random.default_rng(seed)
        extra = rng.choice(neg_pool, size=num_samples - len(pos),
                           replace=False)
        sampled = np.sort(np.concatenate([pos, extra]))
    remap = {c: i for i, c in enumerate(sampled.tolist())}
    remapped = np.vectorize(lambda c: remap[c])(lbl).astype(lbl.dtype)
    return Tensor(jnp.asarray(remapped)), Tensor(jnp.asarray(sampled))


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC loss over the warpctc op (reference functional/loss.py ctc_loss:
    'mean' divides each example's loss by its label length)."""
    loss = dispatch("warpctc", log_probs, labels, input_lengths,
                    label_lengths, blank=blank, norm_by_times=norm_by_times)
    if reduction == "none":
        return loss
    if reduction == "sum":
        return loss.sum()
    ll = label_lengths._value if isinstance(label_lengths, Tensor) \
        else jnp.asarray(label_lengths)
    return (loss / Tensor(jnp.maximum(ll, 1).astype(jnp.float32))).mean()


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean"):
    """RNN-Transducer loss over the warprnnt op (reference
    functional/loss.py:2070 rnnt_loss; lattice DP in ops/yaml/_impl.py
    warprnnt)."""
    loss = dispatch("warprnnt", input, label, input_lengths,
                    label_lengths, blank=blank,
                    fastemit_lambda=fastemit_lambda)
    if isinstance(loss, (tuple, list)):
        loss = loss[0]
    if reduction == "none":
        return loss
    if reduction == "sum":
        return loss.sum()
    return loss.mean()




# ---- round-5 functional long tail (reference nn/functional __all__) ----
from ...ops.nn_ops import (  # noqa: F401
    adaptive_avg_pool1d, adaptive_avg_pool3d, adaptive_log_softmax_with_loss,
    adaptive_max_pool1d, adaptive_max_pool3d, avg_pool3d, conv1d_transpose,
    log_sigmoid, lp_pool1d, max_pool3d, max_unpool1d, max_unpool3d,
    multi_margin_loss, pairwise_distance,
    triplet_margin_with_distance_loss, zeropad2d,
)
from ...ops.registry import dispatch as _rdispatch


def _op_alias(_name):
    def _fn(*args, **kwargs):
        return _rdispatch(_name, *args, **kwargs)

    _fn.__name__ = _name
    _fn.__doc__ = f"Functional alias of the registered op ``{_name}``."
    return _fn


# registered elsewhere in the op library; exposed here for reference
# name parity (python/paddle/nn/functional/__init__.py)
for _n in ("bilinear", "conv3d_transpose", "flash_attn_qkvpacked",
           "fractional_max_pool2d", "fractional_max_pool3d", "gather_tree",
           "hsigmoid_loss", "label_smooth", "log_loss", "lp_pool2d",
           "margin_cross_entropy", "sequence_mask", "sparse_attention"):
    if _n not in globals():
        globals()[_n] = _op_alias(_n)
del _n


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """Channel-masked alpha dropout (reference nn.functional
    .feature_alpha_dropout): whole channels are dropped to the SELU
    negative saturation with affine correction."""
    if not training or p == 0.0:
        return x
    from ...core.tensor import Tensor as _T

    xv = x._value if isinstance(x, _T) else jnp.asarray(x)
    keep_shape = xv.shape[:2]
    mask = jax.random.bernoulli(_random._key(), 1.0 - p, keep_shape)
    return dispatch("feature_alpha_dropout", x, mask, p=p)


def _inplace_act(name, base):
    def fn(x, *args, **kwargs):
        from ...autograd import is_grad_enabled
        from ...core.tensor import Tensor as _T

        out = base(x, *args, **kwargs)
        if isinstance(x, _T):
            if is_grad_enabled() and not getattr(x, "stop_gradient", True):
                raise RuntimeError(
                    f"{name}: in-place activation on a grad-requiring "
                    f"tensor under an active tape (reference "
                    f"tensor-version error); use {name[:-1]}")
            x._value = (out._value if isinstance(out, _T)
                        else jnp.asarray(out)).astype(x._value.dtype)
            return x
        return out

    fn.__name__ = name
    fn.__doc__ = f"In-place variant of ``{name[:-1]}`` (reference " \
                 f"nn.functional.{name})."
    return fn


relu_ = _inplace_act("relu_", relu)
elu_ = _inplace_act("elu_", elu)
hardtanh_ = _inplace_act("hardtanh_", hardtanh)
leaky_relu_ = _inplace_act("leaky_relu_", leaky_relu)
softmax_ = _inplace_act("softmax_", softmax)
tanh_ = _inplace_act("tanh_", lambda x: dispatch("tanh", x))
thresholded_relu_ = _inplace_act("thresholded_relu_", thresholded_relu)
