"""Recurrent layers: SimpleRNN / LSTM / GRU (+cells, generic RNN wrapper).

Analog of python/paddle/nn/layer/rnn.py (RNNCellBase, SimpleRNNCell:372,
LSTMCell, GRUCell, RNN wrapper, SimpleRNN/LSTM/GRU multi-layer nets backed
by the cudnn_lstm/rnn kernels, paddle/phi/kernels/gpu/rnn_kernel.cu).

TPU-native design: one registered op runs a whole (layer, direction) pass
as a ``lax.scan`` over time — XLA unrolls the gate matmuls onto the MXU and
the eager tape records a single VJP for the entire sequence (scan
transposes to a reverse scan for the backward), instead of per-step
Python recording. Gate order matches the reference (i, f, g, o for LSTM;
r, z, c for GRU), so state dicts port weight-for-weight.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops.registry import register
from . import initializer as init
from .layer import Layer, Parameter


def _cell_step(mode, x_t, h, c, w_ih, w_hh, b_ih, b_hh):
    gates = x_t @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        gates = gates + b_ih + b_hh
    if mode == "LSTM":
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, c_new
    if mode == "GRU":
        # r, z, c gate order (reference GRUCell); the candidate's hidden
        # contribution is gated by r BEFORE adding the input contribution
        xg = x_t @ w_ih.T + (b_ih if b_ih is not None else 0.0)
        hg = h @ w_hh.T + (b_hh if b_hh is not None else 0.0)
        xr, xz, xc = jnp.split(xg, 3, axis=-1)
        hr, hz, hc = jnp.split(hg, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        cand = jnp.tanh(xc + r * hc)
        h_new = z * h + (1.0 - z) * cand
        return h_new, c
    act = jnp.tanh if mode == "RNN_TANH" else jax.nn.relu
    h_new = act(gates)
    return h_new, c


@register("rnn_layer", amp="white")
def _rnn_layer_op(x, h0, c0, w_ih, w_hh, b_ih, b_hh, *, mode="LSTM",
                  reverse=False):
    """One (layer, direction) recurrent pass.

    x [B, T, I] (batch-major), h0/c0 [B, H] -> (out [B, T, H], hT, cT).
    Entire sequence is one lax.scan — the fused-kernel analog of the
    reference's cudnn_lstm path."""
    xt = jnp.swapaxes(x, 0, 1)  # [T, B, I]
    if reverse:
        xt = xt[::-1]

    def step(carry, x_t):
        h, c = carry
        h2, c2 = _cell_step(mode, x_t, h, c, w_ih, w_hh, b_ih, b_hh)
        return (h2, c2), h2

    (hT, cT), outs = jax.lax.scan(step, (h0, c0), xt)
    if reverse:
        outs = outs[::-1]
    return jnp.swapaxes(outs, 0, 1), hT, cT


class RNNCellBase(Layer):
    """Cell base (analog of nn.RNNCellBase): holds the 4 canonical weights."""

    GATE_MULT = {"RNN_TANH": 1, "RNN_RELU": 1, "LSTM": 4, "GRU": 3}

    def __init__(self, input_size: int, hidden_size: int, mode: str):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.mode = mode
        m = self.GATE_MULT[mode]
        std = 1.0 / np.sqrt(hidden_size)
        u = init.Uniform(-std, std)
        self.weight_ih = Parameter(u((m * hidden_size, input_size), jnp.float32))
        self.weight_hh = Parameter(u((m * hidden_size, hidden_size), jnp.float32))
        self.bias_ih = Parameter(u((m * hidden_size,), jnp.float32))
        self.bias_hh = Parameter(u((m * hidden_size,), jnp.float32))

    def get_initial_states(self, batch):
        z = Tensor(jnp.zeros((batch, self.hidden_size), jnp.float32))
        if self.mode == "LSTM":
            return (z, Tensor(jnp.zeros((batch, self.hidden_size), jnp.float32)))
        return z


@register("rnn_cell_step", amp="white")
def _rnn_cell_op(x, h, c, w_ih, w_hh, b_ih, b_hh, *, mode="LSTM"):
    return _cell_step(mode, x, h, c, w_ih, w_hh, b_ih, b_hh)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", **kw):
        super().__init__(input_size, hidden_size,
                         "RNN_TANH" if activation == "tanh" else "RNN_RELU")

    def forward(self, inputs, states=None):
        h = states if states is not None else self.get_initial_states(
            inputs.shape[0])
        h2, _ = _rnn_cell_op(inputs, h, h, self.weight_ih, self.weight_hh,
                             self.bias_ih, self.bias_hh, mode=self.mode)
        return h2, h2


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, **kw):
        super().__init__(input_size, hidden_size, "LSTM")

    def forward(self, inputs, states=None):
        h, c = states if states is not None else self.get_initial_states(
            inputs.shape[0])
        h2, c2 = _rnn_cell_op(inputs, h, c, self.weight_ih, self.weight_hh,
                              self.bias_ih, self.bias_hh, mode="LSTM")
        return h2, (h2, c2)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, **kw):
        super().__init__(input_size, hidden_size, "GRU")

    def forward(self, inputs, states=None):
        h = states if states is not None else self.get_initial_states(
            inputs.shape[0])
        h2, _ = _rnn_cell_op(inputs, h, h, self.weight_ih, self.weight_hh,
                             self.bias_ih, self.bias_hh, mode="GRU")
        return h2, h2


class RNN(Layer):
    """Generic wrapper running a cell over time (analog of paddle.nn.RNN).
    Python-loop semantics — use the fused SimpleRNN/LSTM/GRU nets for the
    compiled scan path."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None):
        x = inputs if not self.time_major else inputs.transpose([1, 0, 2])
        steps = range(x.shape[1])
        if self.is_reverse:
            steps = reversed(list(steps))
        states = initial_states
        outs = []
        for t in steps:
            o, states = self.cell(x[:, t], states)
            outs.append(o)
        if self.is_reverse:
            outs = outs[::-1]
        from ..ops import manip

        out = manip.stack(outs, axis=1)
        if self.time_major:
            out = out.transpose([1, 0, 2])
        return out, states


class _RNNBase(Layer):
    """Multi-layer, optionally bidirectional net over the fused scan op
    (analog of nn.layer.rnn.RNNBase backed by cudnn kernels)."""

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__()
        if direction in ("bidirectional", "bidirect"):
            self.num_directions = 2
        elif direction == "forward":
            self.num_directions = 1
        else:
            raise ValueError(f"unknown direction {direction!r}")
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        m = RNNCellBase.GATE_MULT[mode]
        std = 1.0 / np.sqrt(hidden_size)
        u = init.Uniform(-std, std)
        for layer in range(num_layers):
            for d in range(self.num_directions):
                isz = input_size if layer == 0 \
                    else hidden_size * self.num_directions
                sfx = f"_l{layer}" + ("_reverse" if d else "")
                setattr(self, f"weight_ih{sfx}",
                        Parameter(u((m * hidden_size, isz), jnp.float32)))
                setattr(self, f"weight_hh{sfx}",
                        Parameter(u((m * hidden_size, hidden_size), jnp.float32)))
                setattr(self, f"bias_ih{sfx}",
                        Parameter(u((m * hidden_size,), jnp.float32)))
                setattr(self, f"bias_hh{sfx}",
                        Parameter(u((m * hidden_size,), jnp.float32)))

    def _zeros(self, batch):
        n = self.num_layers * self.num_directions
        return Tensor(jnp.zeros((n, batch, self.hidden_size), jnp.float32))

    def forward(self, inputs, initial_states=None):
        x = inputs.transpose([1, 0, 2]) if self.time_major else inputs
        batch = x.shape[0]
        if self.mode == "LSTM":
            h0, c0 = (initial_states if initial_states is not None
                      else (self._zeros(batch), self._zeros(batch)))
        else:
            h0 = initial_states if initial_states is not None \
                else self._zeros(batch)
            c0 = h0  # unused carry for non-LSTM modes
        h_outs, c_outs = [], []
        cur = x
        for layer in range(self.num_layers):
            dir_outs = []
            for d in range(self.num_directions):
                sfx = f"_l{layer}" + ("_reverse" if d else "")
                idx = layer * self.num_directions + d
                out, hT, cT = _rnn_layer_op(
                    cur, h0[idx], c0[idx],
                    getattr(self, f"weight_ih{sfx}"),
                    getattr(self, f"weight_hh{sfx}"),
                    getattr(self, f"bias_ih{sfx}"),
                    getattr(self, f"bias_hh{sfx}"),
                    mode=self.mode, reverse=bool(d))
                dir_outs.append(out)
                h_outs.append(hT)
                c_outs.append(cT)
            if self.num_directions == 2:
                from ..ops import manip

                cur = manip.concat(dir_outs, axis=-1)
            else:
                cur = dir_outs[0]
            if self.dropout and layer < self.num_layers - 1 and self.training:
                from ..ops.registry import dispatch

                cur = dispatch("dropout", cur, p=self.dropout)
        from ..ops import manip

        out = cur.transpose([1, 0, 2]) if self.time_major else cur
        h_n = manip.stack(h_outs, axis=0)
        if self.mode == "LSTM":
            return out, (h_n, manip.stack(c_outs, axis=0))
        return out, h_n


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 activation="tanh", direction="forward", time_major=False,
                 dropout=0.0, **kw):
        super().__init__("RNN_TANH" if activation == "tanh" else "RNN_RELU",
                         input_size, hidden_size, num_layers, direction,
                         time_major, dropout)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout)
