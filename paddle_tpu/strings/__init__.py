"""paddle_tpu.strings — string tensors and string ops.

Analog of the reference's strings subsystem (phi/kernels/strings/:
strings_lower_upper_kernel.h over pstring arrays with the utf8/unicode
case tables in unicode.h; python surface paddle/incubate's string
tensors).  Strings are HOST data: a StringTensor wraps a numpy object
array (the reference's pstring tensor is likewise CPU-resident; its GPU
kernels just move bytes), and the ops run vectorized numpy — there is
nothing for an MXU to do with codepoints.
"""

from __future__ import annotations

from typing import Iterable, List, Union

import numpy as np


class StringTensor:
    """A tensor of variable-length strings (reference: phi
    StringTensor/pstring)."""

    def __init__(self, data, name: str = ""):
        if isinstance(data, StringTensor):
            arr = data._data.copy()
        else:
            arr = np.asarray(data, dtype=object)
        self._data = arr
        self.name = name

    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    def numpy(self) -> np.ndarray:
        return self._data

    def tolist(self):
        return self._data.tolist()

    def __getitem__(self, idx):
        out = self._data[idx]
        return out if isinstance(out, str) else StringTensor(out)

    def __len__(self):
        return len(self._data)

    def __eq__(self, other):
        o = other._data if isinstance(other, StringTensor) else other
        return np.asarray(self._data == o)

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, data={self._data!r})"


def to_string_tensor(strings: Union[Iterable[str], np.ndarray],
                     name: str = "") -> StringTensor:
    """Reference: paddle.to_tensor on pstring data
    (strings_empty_kernel.cc + fill)."""
    return StringTensor(np.asarray(list(strings) if not
                                   isinstance(strings, np.ndarray)
                                   else strings, dtype=object), name)


def _map(fn, x: StringTensor) -> StringTensor:
    return StringTensor(np.vectorize(fn, otypes=[object])(x._data))


def lower(x: StringTensor, use_utf8_encoding: bool = True) -> StringTensor:
    """Reference strings_lower_upper_kernel.h: ascii fast path vs the
    utf8/unicode case-conversion tables — python's str.lower IS the
    unicode table; the ascii flag restricts to A-Z."""
    if use_utf8_encoding:
        return _map(str.lower, x)
    return _map(lambda s: "".join(
        chr(ord(c) + 32) if "A" <= c <= "Z" else c for c in s), x)


def upper(x: StringTensor, use_utf8_encoding: bool = True) -> StringTensor:
    if use_utf8_encoding:
        return _map(str.upper, x)
    return _map(lambda s: "".join(
        chr(ord(c) - 32) if "a" <= c <= "z" else c for c in s), x)


def length(x: StringTensor) -> np.ndarray:
    """Codepoint lengths (int64)."""
    return np.vectorize(len, otypes=[np.int64])(x._data)


def byte_length(x: StringTensor, encoding: str = "utf-8") -> np.ndarray:
    return np.vectorize(lambda s: len(s.encode(encoding)),
                        otypes=[np.int64])(x._data)


def concat(xs: List[StringTensor], axis: int = 0) -> StringTensor:
    return StringTensor(np.concatenate([x._data for x in xs], axis=axis))


def strip(x: StringTensor) -> StringTensor:
    return _map(str.strip, x)


def join(x: StringTensor, sep: str = "") -> str:
    return sep.join(x._data.reshape(-1).tolist())


__all__ = ["StringTensor", "to_string_tensor", "lower", "upper", "length",
           "byte_length", "concat", "strip", "join"]
