"""paddle_tpu.quantization — QAT / PTQ.

Analog of python/paddle/quantization (QuantConfig config.py, QAT qat.py,
PTQ ptq.py, AbsmaxObserver observers/, FakeQuanterWithAbsMaxObserver
quanters/). The fake-quant math rides the framework's registered ops
(fake_quantize_dequantize_abs_max family) with a straight-through
estimator so QAT trains; PTQ convert() lowers Linear layers onto the real
int8 ``weight_only_linear`` op.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Type

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn import Conv2D, Linear
from ..nn.layer import Layer, Parameter
from ..ops.registry import dispatch, register

__all__ = [
    "AbsmaxObserver", "FakeQuanterWithAbsMaxObserver", "QuanterFactory",
    "SingleLayerConfig", "QuantConfig", "QAT", "PTQ", "QuantedLinear",
    "QuantedConv2D", "Int8Linear", "quanter",
]


@register("fake_quant_ste")
def _fake_quant_ste_op(x, scale, bit_length=8):
    """Fake quantize-dequantize with a straight-through estimator: exact
    rounding forward, identity gradient (the reference's
    FakeQuantAbsMax backward)."""
    bnt = float(2 ** (bit_length - 1) - 1)
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / s * bnt), -bnt, bnt) / bnt * s
    return x + jax.lax.stop_gradient(q - x)


class AbsmaxObserver:
    """Running abs-max range observer (reference observers/abs_max.py)."""

    def __init__(self, quant_bits: int = 8, moving_rate: float = 0.9):
        self.quant_bits = quant_bits
        self.moving_rate = moving_rate
        self._absmax: Optional[float] = None

    def observe(self, x) -> float:
        v = float(jnp.abs(x._value if isinstance(x, Tensor) else x).max())
        if self._absmax is None:
            self._absmax = v
        else:
            m = self.moving_rate
            self._absmax = m * self._absmax + (1 - m) * v
        return self._absmax

    def scale(self) -> float:
        return self._absmax if self._absmax is not None else 1.0


class FakeQuanterWithAbsMaxObserver(Layer):
    """QAT quanter: observe abs-max while training, fake-quantize with STE
    (reference quanters/abs_max.py)."""

    def __init__(self, quant_bits: int = 8, moving_rate: float = 0.9,
                 **kw):
        super().__init__()
        self.observer = AbsmaxObserver(quant_bits, moving_rate)
        self.quant_bits = quant_bits

    def forward(self, x):
        if self.training:
            self.observer.observe(x)
        scale = jnp.asarray(self.observer.scale(), jnp.float32)
        return dispatch("fake_quant_ste", x, Tensor(scale),
                        bit_length=self.quant_bits)


class QuanterFactory:
    """Bind a quanter class + kwargs (reference factory.py)."""

    def __init__(self, cls: Type[Layer], **kwargs):
        self.cls = cls
        self.kwargs = kwargs

    def instance(self) -> Layer:
        return self.cls(**self.kwargs)


def quanter(cls=FakeQuanterWithAbsMaxObserver, **kwargs) -> QuanterFactory:
    return QuanterFactory(cls, **kwargs)


class SingleLayerConfig:
    def __init__(self, activation: Optional[QuanterFactory],
                 weight: Optional[QuanterFactory]):
        self.activation = activation
        self.weight = weight


class QuantConfig:
    """Reference config.py surface: a default (activation, weight) pair
    plus per-layer and per-type overrides."""

    def __init__(self, activation: Optional[QuanterFactory] = None,
                 weight: Optional[QuanterFactory] = None):
        self._default = SingleLayerConfig(activation, weight)
        self._layer_configs: List = []   # (layer_obj, cfg)
        self._type_configs: List = []    # (type, cfg)

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l_ in layers:
            self._layer_configs.append(
                (l_, SingleLayerConfig(activation, weight)))

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = (layer_type if isinstance(layer_type, (list, tuple))
                 else [layer_type])
        for t in types:
            self._type_configs.append((t, SingleLayerConfig(activation,
                                                            weight)))

    def config_for(self, layer) -> SingleLayerConfig:
        for obj, cfg in self._layer_configs:
            if obj is layer:
                return cfg
        for t, cfg in self._type_configs:
            if isinstance(layer, t):
                return cfg
        return self._default


class QuantedLinear(Layer):
    """QAT-wrapped Linear: fake-quant activations and weights, fp math
    (reference nn/quant/qat/QuantedLinear)."""

    def __init__(self, inner: Linear, cfg: SingleLayerConfig):
        super().__init__()
        self.inner = inner
        self.activation_quanter = (cfg.activation.instance()
                                   if cfg.activation else None)
        self.weight_quanter = cfg.weight.instance() if cfg.weight else None

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.inner.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        from ..nn import functional as F

        return F.linear(x, w, self.inner._parameters.get("bias"))


class QuantedConv2D(Layer):
    def __init__(self, inner: Conv2D, cfg: SingleLayerConfig):
        super().__init__()
        self.inner = inner
        self.activation_quanter = (cfg.activation.instance()
                                   if cfg.activation else None)
        self.weight_quanter = cfg.weight.instance() if cfg.weight else None

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.inner.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        from ..nn import functional as F

        return F.conv2d(x, w, self.inner._parameters.get("bias"),
                        stride=self.inner.stride,
                        padding=self.inner.padding,
                        dilation=self.inner.dilation,
                        groups=self.inner.groups)


_QAT_MAPPING = {Linear: QuantedLinear, Conv2D: QuantedConv2D}


def _replace_sublayers(model: Layer, fn):
    for name, sub in list(model._sub_layers.items()):
        new = fn(sub)
        if new is not sub:
            model._sub_layers[name] = new
            setattr(model, name, new)
        else:
            _replace_sublayers(sub, fn)


def _walk(model: Layer, prefix=""):
    for name, sub in model._sub_layers.items():
        path = f"{prefix}{name}"
        yield path, sub
        yield from _walk(sub, path + ".")


class QAT:
    """Quantization-aware training driver (reference qat.py)."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        # per-layer configs are registered by OBJECT; resolve them to
        # sublayer paths on the original model so the deepcopy (the
        # reference also copies unless inplace) still honors them
        path_cfg = {}
        for path, sub in _walk(model):
            for obj, cfg in self.config._layer_configs:
                if obj is sub:
                    path_cfg[path] = cfg
        if not inplace:
            model = copy.deepcopy(model)
        paths = {id(sub): path for path, sub in _walk(model)}

        def convert(layer):
            cls = _QAT_MAPPING.get(type(layer))
            if cls is None:
                return layer
            cfg = path_cfg.get(paths.get(id(layer))) \
                or self.config.config_for(layer)
            if cfg.activation is None and cfg.weight is None:
                return layer
            return cls(layer, cfg)

        _replace_sublayers(model, convert)
        return model


class Int8Linear(Layer):
    """Converted inference layer: int8 weights + per-channel scales via
    the weight_only_linear op (reference's quantized inference path)."""

    def __init__(self, inner: Linear):
        super().__init__()
        qw, scale = dispatch("weight_quantize", inner.weight)
        self.weight = Parameter(qw._value)
        self.weight.stop_gradient = True
        self.weight_scale = Parameter(scale._value)
        self.weight_scale.stop_gradient = True
        self.bias = inner._parameters.get("bias")

    def forward(self, x):
        return dispatch("weight_only_linear", x, self.weight,
                        self.weight_scale, self.bias)


class PTQ:
    """Post-training quantization: calibrate observers, then convert
    Linear layers to int8 (reference ptq.py + quantize.py convert)."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        # PTQ calibration reuses the QAT wrappers in eval mode with the
        # observers forced on (observe() needs training=True semantics)
        model = QAT(self.config).quantize(model, inplace=inplace)
        model.train()
        return model

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        if not inplace:
            model = copy.deepcopy(model)

        def conv(layer):
            if isinstance(layer, QuantedLinear):
                return Int8Linear(layer.inner)
            if isinstance(layer, Linear):
                return Int8Linear(layer)
            return layer

        _replace_sublayers(model, conv)
        model.eval()
        return model


class BaseQuanter(Layer):
    """Abstract quanter contract (reference python/paddle/quantization/
    base_quanter.py): a layer that fake-quantizes activations/weights in
    forward and exposes its quantization parameters."""

    def forward(self, input):  # noqa: A002
        raise NotImplementedError

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        raise NotImplementedError

    def bit_length(self):
        return 8

    def quant_axis(self):
        return -1


class BaseObserver(BaseQuanter):
    """Abstract observer contract (reference base_observer.py): a
    quanter that additionally CALIBRATES — it watches activations during
    PTQ sampling and derives thresholds afterwards."""

    def cal_thresholds(self):
        raise NotImplementedError


__all__ += ["BaseQuanter", "BaseObserver"]
