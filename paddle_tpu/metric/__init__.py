"""paddle_tpu.metric (analog of python/paddle/metric/metrics.py:44)."""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    """Analog of paddle.metric.Accuracy (metrics.py:195)."""

    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label):
        pv = np.asarray(pred._value if isinstance(pred, Tensor) else pred)
        lv = np.asarray(label._value if isinstance(label, Tensor) else label)
        if lv.ndim == pv.ndim and lv.shape[-1] == 1:
            lv = lv[..., 0]
        maxk = max(self.topk)
        topk_idx = np.argsort(-pv, axis=-1)[..., :maxk]
        correct = topk_idx == lv[..., None]
        return correct

    def update(self, correct):
        correct = np.asarray(correct._value if isinstance(correct, Tensor) else correct)
        n = correct.shape[0] if correct.ndim else 1
        for i, k in enumerate(self.topk):
            self.total[i] += float(correct[..., :k].any(axis=-1).sum())
            self.count[i] += int(np.prod(correct.shape[:-1]))
        return self.accumulate()

    def accumulate(self):
        res = [t / c if c else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name=None):
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels)
        pred_pos = (p > 0.5).astype(np.int64).reshape(-1)
        l = l.reshape(-1)
        self.tp += int(((pred_pos == 1) & (l == 1)).sum())
        self.fp += int(((pred_pos == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels)
        pred_pos = (p > 0.5).astype(np.int64).reshape(-1)
        l = l.reshape(-1)
        self.tp += int(((pred_pos == 1) & (l == 1)).sum())
        self.fn += int(((pred_pos == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """Streaming AUC via thresholded confusion bins (reference metrics.py Auc)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        self._name = name or "auc"
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels).reshape(-1)
        if p.ndim == 2:
            p = p[:, -1]
        idx = np.clip((p * self.num_thresholds).astype(np.int64), 0, self.num_thresholds)
        for i, lab in zip(idx, l):
            if lab:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        area = 0.0
        pos = neg = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = pos + self._stat_pos[i]
            new_neg = neg + self._stat_neg[i]
            area += (new_neg - neg) * (pos + new_pos) / 2
            pos, neg = new_pos, new_neg
        return float(area / (tot_pos * tot_neg))

    def name(self):
        return self._name


def accuracy(input, label, k=1):  # noqa: A002
    m = Accuracy(topk=(k,))
    return m.update(Tensor(np.asarray(m.compute(input, label))))
