"""Autoregressive generation with a KV cache for the Llama flagship.

Reference analogs: the fused decode path
(python/paddle/incubate/nn/functional/masked_multihead_attention.py and
fused_multi_transformer.py — one-token-per-step attention against a
preallocated cache) plus the generation loops PaddleNLP layers over it.

TPU-native design: the whole decode is TWO compiled programs —
- prefill: one forward over the prompt that also returns the per-layer
  K/V tensors (written into a [L, B, kvh, max_len, d] cache — head-major,
  the Pallas flash-decoding kernel's layout), and
- a ``lax.scan`` over decode steps: each step embeds one token, runs every
  layer against the cache through the Pallas flash-decoding kernel
  (ops/pallas/decode_attention.py — online softmax, HBM traffic bounded
  by the CURRENT position rather than max_len), appends its K/V via
  ``dynamic_update_slice``, samples (greedy / temperature / top-k /
  top-p) and carries the PRNG key chain.
No per-token python dispatch, no cache reallocation, static shapes
throughout — the XLA-friendly formulation of the reference's CUDA decode
kernels.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["generate"]


def _rms_norm(x, w, eps):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) * lax.rsqrt(var + eps)).astype(x.dtype) * w


def _rotate_half(x):
    a, b = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-b, a], axis=-1)


def _apply_rope(q, k, cos, sin):
    """q: [..., h, d]; cos/sin broadcastable [..., 1, d] (neox style, the
    layout _rope_tables builds)."""
    return (q * cos + _rotate_half(q) * sin,
            k * cos + _rotate_half(k) * sin)


class _Weights:
    """Name-indexed view over functional_state (paddle Linear weights are
    [in, out]: y = x @ W).

    Weight-only int8 support: a weight named ``N`` may ride with a
    sibling ``N._scale`` (per-output-channel fp scales from
    quantize_params_int8).  Accessors dequantize ``int8 -> compute
    dtype`` right at the consumer, so under jit XLA fuses the convert +
    scale into the dot's operand stream and int8 is what leaves HBM —
    the reference's weight_only_linear capability (python/paddle/nn/
    quant/quantized_linear.py) realized as an XLA fusion instead of a
    custom kernel.  Embedding lookups gather int8 ROWS first and
    dequantize after (never materialising the full fp matrix)."""

    def __init__(self, cfg, params):
        self.cfg = cfg
        self.p = params
        self._dt = None
        for k, v in params.items():
            if k.endswith("._scale"):
                continue
            if jnp.issubdtype(v.dtype, jnp.floating):
                self._dt = v.dtype
                break
        if self._dt is None:
            self._dt = jnp.bfloat16

    def _deq(self, name):
        w = self.p[name]
        sc = self.p.get(name + "._scale")
        if sc is None:
            return w
        # per-out-channel (last axis) scales; convert+multiply fuse into
        # the consuming dot — int8 streams from HBM, fp stays in VMEM
        return w.astype(self._dt) * sc.astype(self._dt)[None, :]

    def layer(self, i, name):
        return self._deq(f"model.layers.{i}.{name}")

    def is_moe_layer(self, i) -> bool:
        """A layer is MoE iff the checkpoint carries its stacked expert
        weights (sparse checkpoints may mix dense and MoE layers)."""
        return f"model.layers.{i}.mlp.experts.gate_proj.weight" in self.p

    def expert(self, i, proj, idx):
        """Gather-then-dequant expert slices from the stacked
        ``[E, in, out]`` weight: int8 expert ROWS are gathered by
        ``idx`` (expert ids) FIRST and dequantized after with their
        per-(expert, out-channel) scales, so the full fp bank is never
        materialized — ``_moe_ffn`` passes one expert id at a time,
        bounding live memory to a single dequantized slice."""
        name = f"model.layers.{i}.mlp.experts.{proj}.weight"
        w = self.p[name]
        rows = jnp.take(w, idx, axis=0)              # [T, in, out]
        sc = self.p.get(name + "._scale")
        if sc is None:
            return rows
        return rows.astype(self._dt) * jnp.take(
            sc.astype(self._dt), idx, axis=0)[:, None, :]

    def embed(self, ids):
        """Token embedding lookup: gather rows, then dequantize the
        gathered rows only (per-row scales for the [vocab, hidden]
        matrix)."""
        w = self.p["model.embed_tokens.weight"]
        rows = jnp.take(w, ids, axis=0)
        sc = self.p.get("model.embed_tokens.weight._scale")
        if sc is None:
            return rows
        return rows.astype(self._dt) * jnp.take(
            sc.astype(self._dt), ids, axis=0)[..., None]

    def head(self, x):
        if "lm_head.weight" in self.p:
            w = self.p["lm_head.weight"]
            sc = self.p.get("lm_head.weight._scale")
            if sc is None:
                return x @ w
            return (x @ w.astype(self._dt)) * sc.astype(self._dt)[None, :]
        # tied embeddings: reuse the embedding matrix transposed (the
        # per-row embed scales become per-out-channel head scales)
        w = self.p["model.embed_tokens.weight"]
        sc = self.p.get("model.embed_tokens.weight._scale")
        if sc is None:
            return x @ w.T
        return (x @ w.T.astype(self._dt)) * sc.astype(self._dt)[None, :]

    def __getitem__(self, k):
        return self._deq(k)


def quantize_params_int8(params, keep=("norm", "layernorm", "router")):
    """Weight-only int8 quantization of a functional_state dict:
    2D floating weights become int8 with a per-output-channel
    (symmetric absmax) fp32 ``<name>._scale`` sibling; 1D weights
    (norm gains) and anything matching ``keep`` stay in fp (the MoE
    router is tiny and its logits gate everything — it stays fp like
    the norms).  The embedding matrix is quantized per ROW (its rows
    are gathered, its transpose is the tied head's [hidden, vocab]).
    Stacked ``[E, in, out]`` expert banks quantize per (expert,
    out-channel) — the ``_Weights.expert`` gather-then-dequant view
    reads exactly this layout."""
    out = {}
    for name, w in params.items():
        is_embed = name.endswith("embed_tokens.weight")
        is_expert = ".mlp.experts." in name and w.ndim == 3
        if ((w.ndim != 2 and not is_expert)
                or not jnp.issubdtype(w.dtype, jnp.floating)
                or any(s in name for s in keep)):
            out[name] = w
            continue
        if is_expert:
            absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=1)
            scale = jnp.maximum(absmax, 1e-8) / 127.0    # [E, out]
            den = scale[:, None, :]
        else:
            axis = 1 if is_embed else 0      # reduce over the in-dim
            absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axis)
            scale = jnp.maximum(absmax, 1e-8) / 127.0
            den = scale[:, None] if is_embed else scale[None, :]
        q = jnp.round(w.astype(jnp.float32) / den)
        out[name] = jnp.clip(q, -127, 127).astype(jnp.int8)
        out[name + "._scale"] = scale
    return out


def self_draft_params(cfg, params, num_layers: int):
    """Layer-truncated self-speculative draft: reuse the target's first
    ``num_layers`` decoder layers plus its embeddings / final norm /
    head as the proposer model (no separate distilled checkpoint
    needed — the early layers of the same network are a classic cheap
    drafter).  Returns ``(draft_cfg, draft_params)`` ready for
    ``ContinuousBatchingEngine(draft_params=..., draft_cfg=...)``.

    Weight-only int8 dicts pass through unchanged: the ``._scale``
    siblings of kept layers ride along, so an int8 target drafts with
    int8 weights too (compose with ``quantize_params_int8`` in either
    order)."""
    import dataclasses

    n = int(num_layers)
    if not 0 < n <= cfg.num_hidden_layers:
        raise ValueError(
            f"draft depth {n} outside (0, {cfg.num_hidden_layers}]")
    dcfg = dataclasses.replace(cfg, num_hidden_layers=n)
    dparams = {}
    for k, v in params.items():
        if k.startswith("model.layers."):
            if int(k.split(".")[2]) >= n:
                continue
        dparams[k] = v
    return dcfg, dparams


#: row-block quantum of the serving grouped-matmul launches (segment
#: alignment; serving batches are small, so a fine block keeps padding
#: slack low while staying sublane-aligned)
_MOE_FFN_BLOCK_ROWS = 8


def _moe_ffn(w: _Weights, i, xm):
    """Top-k expert routing for one MoE layer on the ``_Weights`` view
    (round-20 dropless serving): fp32 router logits -> top-k softmax
    weights (normalized over the selected experts, the reference
    ``fused_moe`` semantics) -> token copies argsorted by expert into
    block-aligned ragged segments -> ONE grouped-matmul launch per
    projection (ops/pallas/grouped_matmul) applying each expert's
    ``[in, out]`` slice to its row window, SwiGLU, then a weighted
    scatter back to token order.

    This replaces the round-18 masked-dense expert loop (every token
    through every expert, flops scaling E/k-fold): compute is now the
    ragged T*k rows — the same unified-ragged-step shape the training
    dropless path uses — while the expert bank is still read exactly
    once per call.  int8 banks stay int8 all the way into the kernel:
    the raw stacked ``[E, in, out]`` bank plus its per-(expert,
    out-channel) ``._scale`` ride as the kernel's ``w``/``w_scale``,
    which widens one VMEM block at a time and folds the scale into the
    fp32 accumulator — the gather-then-dequant view moved in-kernel, no
    dequantized slice ever materialized in HBM.  ``xm`` is any
    [..., hidden] batch (the unified step's packed [T, h] rows, a
    decode chunk's [slots, 1, h], prefill's [b, s, h]); routing is per
    token row."""
    from ..ops.pallas.grouped_matmul import (align_rows,
                                             grouped_matmul_raw,
                                             segment_starts)

    cfg = w.cfg
    shape = xm.shape
    x2 = xm.reshape(-1, shape[-1])
    router = w.layer(i, "mlp.router.weight")          # [h, E], fp
    # E comes from the CHECKPOINT (MoE-ness is checkpoint-driven, via
    # is_moe_layer) — a cfg.num_experts desync must be loud, not a
    # silently zeroed expert output
    e = int(router.shape[-1])
    bank_e = int(
        w.p[f"model.layers.{i}.mlp.experts.gate_proj.weight"].shape[0])
    if bank_e != e:
        raise ValueError(
            f"layer {i}: router routes {e} experts but the stacked bank "
            f"holds {bank_e}")
    k = int(cfg.moe_top_k)
    if not 1 <= k <= e:
        raise ValueError(
            f"layer {i}: moe_top_k={k} outside [1, {e}] — set "
            f"LlamaConfig.moe_top_k for this sparse checkpoint")
    logits = x2.astype(jnp.float32) @ router.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_ids = lax.top_k(probs, k)              # [T, k]
    top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)

    # ---- sorted ragged dispatch: copies argsorted by expert tile the
    # block-aligned segment windows the kernel contract wants
    bm = _MOE_FFN_BLOCK_ROWS
    tk = x2.shape[0] * k
    flat_ids = top_ids.reshape(-1).astype(jnp.int32)
    order = jnp.argsort(flat_ids)                     # stable
    token_of = order // k
    sorted_ids = flat_ids[order]
    wsorted = top_p.reshape(-1)[order]
    counts = jnp.bincount(flat_ids, length=e).astype(jnp.int32)
    seg_st = segment_starts(counts, bm)
    run_st = jnp.cumsum(counts) - counts              # unaligned starts
    pos = jnp.arange(tk, dtype=jnp.int32) - run_st[sorted_ids]
    dest = seg_st[sorted_ids] + pos
    rpad = int(align_rows(tk, bm) + e * bm)           # static worst case
    xr = jnp.zeros((rpad, x2.shape[1]), x2.dtype).at[dest].set(
        x2[token_of])

    def bank(proj):
        name = f"model.layers.{i}.mlp.experts.{proj}.weight"
        wq = w.p[name]
        sc = w.p.get(name + "._scale")
        if sc is None:
            return wq.astype(x2.dtype), None
        return wq, sc                                 # int8 + [E, out]

    wids = jnp.arange(e, dtype=jnp.int32)

    def gmm(xin, proj):
        wq, sc = bank(proj)
        return grouped_matmul_raw(xin, wq, seg_st, counts, wids,
                                  block_rows=bm, w_scale=sc)

    gate = gmm(xr, "gate_proj")
    up = gmm(xr, "up_proj")
    eo = gmm(jax.nn.silu(gate) * up, "down_proj")     # [rpad, h]

    # ---- combine: gather each copy's expert output, weighted
    # scatter-add back into token order
    ys = eo[dest]
    y = jnp.zeros_like(x2).at[token_of].add(
        ys * wsorted.astype(x2.dtype)[:, None])
    return y.reshape(shape)


def _ffn(w: _Weights, i, xm):
    """Layer ``i``'s FFN on the ``_Weights`` view: dense SwiGLU, or —
    when the checkpoint carries this layer's stacked expert weights —
    top-k expert routing (``_moe_ffn``).  The ONE implementation the
    prefill/decode ``_block``, the serving decode chunk and the
    unified ragged step all share, so a sparse checkpoint serves
    through every path that serves a dense one."""
    if w.is_moe_layer(i):
        return _moe_ffn(w, i, xm)
    gate = xm @ w.layer(i, "mlp.gate_proj.weight")
    up = xm @ w.layer(i, "mlp.up_proj.weight")
    return (jax.nn.silu(gate) * up) @ w.layer(i, "mlp.down_proj.weight")


def _block(w: _Weights, i, x, cos, sin, mask, k_all=None, v_all=None,
           cache_pos=None):
    """One decoder layer. x [b, s, hdim]; without a cache (prefill) it
    attends x's own K/V causally; with k_all/v_all ([b, kvh, M, d] layer
    cache) and ``cache_pos``, x's K/V are first written at that position,
    then attention runs over the cache through the Pallas flash-decoding
    kernel (HBM traffic bounded by cache_pos+s, not M). Returns
    (y, k_attended, v_attended) — the prompt's K/V ([b, s, kvh, d]) in
    prefill, the updated layer cache in decode."""
    cfg = w.cfg
    b, s, _ = x.shape
    h, kvh, d = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    eps = cfg.rms_norm_eps
    xin = _rms_norm(x, w.layer(i, "input_layernorm.weight"), eps)
    q = (xin @ w.layer(i, "self_attn.q_proj.weight")).reshape(b, s, h, d)
    k = (xin @ w.layer(i, "self_attn.k_proj.weight")).reshape(b, s, kvh, d)
    v = (xin @ w.layer(i, "self_attn.v_proj.weight")).reshape(b, s, kvh, d)
    q, k = _apply_rope(q, k, cos, sin)
    g = h // kvh
    if k_all is None:
        # prefill: attend x's own K/V with the causal mask (one big
        # MXU-friendly batched matmul over [S, S])
        k_all, v_all = k, v
        qg = q.reshape(b, s, kvh, g, d).astype(jnp.float32)
        scores = jnp.einsum("bskgd,bSkd->bskgS", qg,
                            k_all.astype(jnp.float32)) * (d ** -0.5)
        if mask is not None:
            scores = scores + mask[None, :, None, None, :]
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("bskgS,bSkd->bskgd", probs,
                         v_all.astype(jnp.float32))
        ctx = ctx.reshape(b, s, h * d).astype(x.dtype)
    else:
        # write the new K/V at cache_pos ([b, kvh, M, d] cache layout)
        kt = jnp.moveaxis(k, 1, 2).astype(k_all.dtype)   # [b, kvh, s, d]
        vt = jnp.moveaxis(v, 1, 2).astype(v_all.dtype)
        k_all = lax.dynamic_update_slice(k_all, kt, (0, 0, cache_pos, 0))
        v_all = lax.dynamic_update_slice(v_all, vt, (0, 0, cache_pos, 0))
        if s == 1 and mask is None:
            # single-token decode: Pallas flash-decoding kernel (HBM
            # traffic bounded by cache_pos+1, not M)
            from ..ops.pallas.decode_attention import flash_decode_raw

            lens = jnp.broadcast_to(cache_pos + 1, (b,)).astype(jnp.int32)
            ctx = flash_decode_raw(q.reshape(b, h, d), k_all, v_all,
                                   lens, scale=d ** -0.5)
            ctx = ctx.reshape(b, s, h * d).astype(x.dtype)
        else:
            # chunked prefill against an existing cache (s > 1, or an
            # explicit mask): general grouped attention over the cache
            qg = q.reshape(b, s, kvh, g, d).astype(jnp.float32)
            scores = jnp.einsum("bskgd,bkSd->bskgS", qg,
                                k_all.astype(jnp.float32)) * (d ** -0.5)
            if mask is not None:
                scores = scores + mask[None, :, None, None, :]
            probs = jax.nn.softmax(scores, axis=-1)
            ctx = jnp.einsum("bskgS,bkSd->bskgd", probs,
                             v_all.astype(jnp.float32))
            ctx = ctx.reshape(b, s, h * d).astype(x.dtype)
    x = x + ctx @ w.layer(i, "self_attn.o_proj.weight")
    xm = _rms_norm(x, w.layer(i, "post_attention_layernorm.weight"), eps)
    x = x + _ffn(w, i, xm)
    return x, k_all, v_all


def _decode_step(w: _Weights, cos_tab, sin_tab, token, pos, k_cache, v_cache):
    """One-token step. token [b], pos scalar; caches [L, b, kvh, M, d].
    Each layer goes through the same _block as prefill, writing its K/V at
    ``pos`` before attending. Returns (logits [b, V], k_cache, v_cache)."""
    cfg = w.cfg
    x = w.embed(token[:, None])
    cos = lax.dynamic_slice_in_dim(cos_tab, pos, 1)[None, :, None, :]
    sin = lax.dynamic_slice_in_dim(sin_tab, pos, 1)[None, :, None, :]
    cos = cos.astype(x.dtype)
    sin = sin.astype(x.dtype)
    for i in range(cfg.num_hidden_layers):
        x, kl, vl = _block(w, i, x, cos, sin, None, k_cache[i], v_cache[i],
                           pos)
        k_cache = k_cache.at[i].set(kl)
        v_cache = v_cache.at[i].set(vl)
    x = _rms_norm(x, w["model.norm.weight"], cfg.rms_norm_eps)
    return w.head(x[:, 0]), k_cache, v_cache


def _sample(logits, key, do_sample, temperature, top_k, top_p):
    logits = logits.astype(jnp.float32)
    if not do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_l = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_l, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with mass >= top_p
        cutoff_idx = jnp.sum(cum < top_p, axis=-1)
        cutoff = jnp.take_along_axis(sorted_l, cutoff_idx[:, None], axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


@partial(jax.jit, static_argnames=("cfg_id", "max_new_tokens", "do_sample",
                                   "temperature", "top_k", "top_p", "eos_id"))
def _generate_jit(params, ids, key, cfg_id, max_new_tokens,
                  do_sample, temperature, top_k, top_p, eos_id):
    cfg, cos_tab, sin_tab = _CFGS[cfg_id]
    w = _Weights(cfg, params)
    b, S = ids.shape
    M = S + max_new_tokens
    h, kvh, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                 cfg.head_dim)
    L = cfg.num_hidden_layers

    # ---- prefill: full causal forward, capture per-layer K/V ----
    positions = jnp.broadcast_to(jnp.arange(S), (b, S))
    x = w.embed(ids)
    cos = jnp.take(cos_tab, positions, axis=0)[:, :, None, :].astype(x.dtype)
    sin = jnp.take(sin_tab, positions, axis=0)[:, :, None, :].astype(x.dtype)
    causal = jnp.where(jnp.tril(jnp.ones((S, S), bool)), 0.0, -jnp.inf)
    k_cache = jnp.zeros((L, b, kvh, M, d), x.dtype)
    v_cache = jnp.zeros((L, b, kvh, M, d), x.dtype)
    for i in range(L):
        x, k, v = _block(w, i, x, cos, sin, causal)
        k_cache = k_cache.at[i, :, :, :S].set(jnp.moveaxis(k, 1, 2))
        v_cache = v_cache.at[i, :, :, :S].set(jnp.moveaxis(v, 1, 2))
    x = _rms_norm(x, w["model.norm.weight"], cfg.rms_norm_eps)
    last_logits = w.head(x[:, -1])

    key, sub = jax.random.split(key)
    tok = _sample(last_logits, sub, do_sample, temperature, top_k, top_p)
    done = jnp.zeros((b,), bool) | (tok == eos_id)

    # ---- decode scan ----
    def step(carry, _):
        tok, pos, k_cache, v_cache, key, done = carry
        logits, k_cache, v_cache = _decode_step(w, cos_tab, sin_tab, tok,
                                                pos, k_cache, v_cache)
        key, sub = jax.random.split(key)
        nxt = _sample(logits, sub, do_sample, temperature, top_k, top_p)
        nxt = jnp.where(done, eos_id, nxt)
        done = done | (nxt == eos_id)
        return (nxt, pos + 1, k_cache, v_cache, key, done), tok

    carry = (tok, jnp.asarray(S, jnp.int32), k_cache, v_cache, key, done)
    (last, _, _, _, _, _), toks = lax.scan(step, carry, None,
                                           length=max_new_tokens - 1)
    out = jnp.concatenate([jnp.moveaxis(toks, 0, 1), last[:, None]], axis=1)
    return out


@partial(jax.jit, static_argnames=("cfg_id", "max_new_tokens", "num_beams",
                                   "length_penalty", "eos_id"))
def _beam_search_jit(params, ids, cfg_id, max_new_tokens, num_beams,
                     length_penalty, eos_id):
    """Compiled beam search: prefill once per prompt, then a ``lax.scan``
    over decode steps carrying B beams per sequence.  Finished (EOS) beams
    are frozen — their candidate row collapses to a single "emit EOS again
    at +0 logp" entry, so they keep competing on their final score.  The
    analog of the reference's beam-search decode (the legacy
    paddle beam_search op + PaddleNLP's loop), formulated as two XLA
    programs with static shapes."""
    cfg, cos_tab, sin_tab = _CFGS[cfg_id]
    w = _Weights(cfg, params)
    b, S = ids.shape
    B = num_beams
    M = S + max_new_tokens
    h, kvh, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                 cfg.head_dim)
    L = cfg.num_hidden_layers

    # ---- prefill (per prompt, beams share it) ----
    positions = jnp.broadcast_to(jnp.arange(S), (b, S))
    x = w.embed(ids)
    cos = jnp.take(cos_tab, positions, axis=0)[:, :, None, :].astype(x.dtype)
    sin = jnp.take(sin_tab, positions, axis=0)[:, :, None, :].astype(x.dtype)
    causal = jnp.where(jnp.tril(jnp.ones((S, S), bool)), 0.0, -jnp.inf)
    k_cache = jnp.zeros((L, b, kvh, M, d), x.dtype)
    v_cache = jnp.zeros((L, b, kvh, M, d), x.dtype)
    for i in range(L):
        x, k, v = _block(w, i, x, cos, sin, causal)
        k_cache = k_cache.at[i, :, :, :S].set(jnp.moveaxis(k, 1, 2))
        v_cache = v_cache.at[i, :, :, :S].set(jnp.moveaxis(v, 1, 2))
    x = _rms_norm(x, w["model.norm.weight"], cfg.rms_norm_eps)
    logp0 = jax.nn.log_softmax(w.head(x[:, -1]).astype(jnp.float32), axis=-1)
    V = logp0.shape[-1]

    alive_logp, tok = lax.top_k(logp0, B)            # [b, B]
    tok = tok.astype(jnp.int32)
    done = tok == eos_id
    gen_len = jnp.ones((b, B), jnp.int32)
    toks_buf = jnp.zeros((b, B, max_new_tokens), jnp.int32)
    toks_buf = toks_buf.at[:, :, 0].set(tok)
    # beams share the prompt cache: tile to [L, b*B, kvh, M, d]
    k_cache = jnp.repeat(k_cache, B, axis=1)
    v_cache = jnp.repeat(v_cache, B, axis=1)

    def gather_cache(c, parent):
        # c: [L, b*B, kvh, M, d] -> reorder the beam sub-axis by parent
        cv = c.reshape(L, b, B, kvh, M, d)
        idx = parent[None, :, :, None, None, None]
        cv = jnp.take_along_axis(cv, idx, axis=2)
        return cv.reshape(L, b * B, kvh, M, d)

    def step(carry, t):
        alive_logp, tok, toks_buf, gen_len, done, k_cache, v_cache = carry
        pos = S + t
        logits, k_cache, v_cache = _decode_step(
            w, cos_tab, sin_tab, tok.reshape(b * B), pos, k_cache, v_cache)
        lp = jax.nn.log_softmax(logits.astype(jnp.float32),
                                axis=-1).reshape(b, B, V)
        # frozen EOS beams: single continuation (EOS again) at +0 logp
        eos_row = jnp.full((V,), -jnp.inf).at[eos_id if eos_id >= 0 else 0
                                              ].set(0.0)
        lp = jnp.where(done[:, :, None], eos_row[None, None, :], lp)
        cand = alive_logp[:, :, None] + lp           # [b, B, V]
        top, idx = lax.top_k(cand.reshape(b, B * V), B)
        parent = (idx // V).astype(jnp.int32)
        ntok = (idx % V).astype(jnp.int32)
        # reorder all beam state by parent
        toks_buf = jnp.take_along_axis(toks_buf, parent[:, :, None], axis=1)
        gen_len = jnp.take_along_axis(gen_len, parent, axis=1)
        done = jnp.take_along_axis(done, parent, axis=1)
        k_cache = gather_cache(k_cache, parent)
        v_cache = gather_cache(v_cache, parent)
        gen_len = gen_len + jnp.where(done, 0, 1)
        toks_buf = lax.dynamic_update_slice_in_dim(
            toks_buf, ntok[:, :, None], t + 1, axis=2)
        done = done | (ntok == eos_id)
        return (top, ntok, toks_buf, gen_len, done, k_cache, v_cache), None

    carry = (alive_logp, tok, toks_buf, gen_len, done, k_cache, v_cache)
    carry, _ = lax.scan(step, carry, jnp.arange(max_new_tokens - 1))
    alive_logp, _, toks_buf, gen_len, done, _, _ = carry
    # GNMT-free simple normalization: score = logp / len^alpha
    scores = alive_logp / jnp.power(gen_len.astype(jnp.float32),
                                    length_penalty)
    best = jnp.argmax(scores, axis=1)                # [b]
    out = jnp.take_along_axis(toks_buf, best[:, None, None], axis=1)[:, 0]
    best_score = jnp.take_along_axis(scores, best[:, None], axis=1)[:, 0]
    return out, best_score


_CFGS = {}


def register_config(cfg):
    """Key the compiled decode programs + rope tables on the config
    VALUES, so equal configs across model instances (and external
    callers like bench.py driving ``_generate_jit`` with their own
    param dict) share one compilation.  Returns the hashable cfg id."""
    import dataclasses

    cfg_key = tuple(sorted(dataclasses.asdict(cfg).items()))
    if cfg_key not in _CFGS:
        from .llama import _rope_tables

        cos_tab, sin_tab = _rope_tables(cfg.head_dim,
                                        cfg.max_position_embeddings,
                                        cfg.rope_theta)
        _CFGS[cfg_key] = (cfg, cos_tab, sin_tab)
    return cfg_key


def generate(model, input_ids, max_new_tokens: int = 32,
             do_sample: bool = False, temperature: float = 1.0,
             top_k: int = 0, top_p: float = 1.0, seed: int = 0,
             eos_token_id: Optional[int] = None, num_beams: int = 1,
             length_penalty: float = 1.0):
    """Generate continuations for ``input_ids`` ([b, S] int) with a KV
    cache; returns [b, S + max_new_tokens] including the prompt. Greedy by
    default; ``do_sample`` enables temperature / top-k / top-p;
    ``num_beams > 1`` selects compiled beam search (returns each prompt's
    best beam, scored as logp / len**length_penalty). After an EOS is
    produced, a sequence keeps emitting ``eos_token_id``."""
    from ..core.tensor import Tensor

    ids = input_ids._value if isinstance(input_ids, Tensor) \
        else jnp.asarray(input_ids)
    ids = ids.astype(jnp.int32)
    cfg = model.cfg if hasattr(model, "cfg") else model.model.cfg
    max_new_tokens = int(max_new_tokens)
    if max_new_tokens <= 0:
        return Tensor(ids)
    total = ids.shape[1] + max_new_tokens
    if total > cfg.max_position_embeddings:
        raise ValueError(
            f"generate: prompt ({ids.shape[1]}) + max_new_tokens "
            f"({max_new_tokens}) = {total} exceeds max_position_embeddings "
            f"({cfg.max_position_embeddings}); rope phases past the table "
            f"would silently repeat")
    params = {k: v for k, v in model.functional_state().items()}
    cfg_key = register_config(cfg)
    eos = -1 if eos_token_id is None else int(eos_token_id)
    if num_beams > 1:
        if do_sample:
            raise ValueError("beam search is deterministic: num_beams > 1 "
                             "is incompatible with do_sample=True")
        new, _ = _beam_search_jit(params, ids, cfg_key, max_new_tokens,
                                  int(num_beams), float(length_penalty), eos)
    else:
        key = jax.random.PRNGKey(seed)
        new = _generate_jit(params, ids, key, cfg_key, max_new_tokens,
                            bool(do_sample), float(temperature), int(top_k),
                            float(top_p), eos)
    return Tensor(jnp.concatenate([ids, new], axis=1))
