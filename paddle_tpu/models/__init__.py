"""paddle_tpu.models — model families (flagships for the north-star
benchmark configs, BASELINE.md)."""

from .llama import (LLAMA_SHARDING_PLAN, LlamaConfig, LlamaForCausalLM,
                    LlamaModel, apply_llama_sharding, build_train_step,
                    make_batch_shardings)
from .llama_hybrid import (build_hybrid_train_step, hybrid_mesh,
                           init_hybrid_state, shard_hybrid_state,
                           stack_llama_state, unstack_llama_state)
from .gpt_moe import (GPTMoEConfig, GPTMoEForCausalLM, apply_gpt_moe_sharding,
                      build_moe_train_step)
from .generation import generate
from .bert import (BertConfig, BertForMaskedLM,
                   BertForSequenceClassification, BertModel,
                   build_bert_train_step)
from .ppyoloe import (PPYOLOE, PPYOLOEConfig, decode_predictions,
                      ppyoloe_loss)
