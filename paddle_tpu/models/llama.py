"""Llama family — the flagship causal-LM (north-star config 4).

Capability analog of the reference's Llama path: PaddleNLP Llama on top of
paddle.incubate fused ops (fused_rms_norm.py, fused_rotary_position_embedding
.py, swiglu.py — python/paddle/incubate/nn/functional/) + the flash-attention
kernel (paddle/phi/kernels/gpu/flash_attn_kernel.cu, SPMD rule
phi/infermeta/spmd_rules/flash_attention.cc) trained under Fleet hybrid
parallelism.

TPU-first design decisions:
- bf16 compute / fp32 master weights (MXU-native; no GradScaler needed),
- GQA attention through incubate.flash_attention (Pallas kernel on TPU,
  XLA-fused softmax path elsewhere),
- rotary embeddings precomputed once as buffers (no per-step gather),
- one GSPMD sharding PLAN (param-name pattern → PartitionSpec) instead of
  per-layer wrapper classes: FSDP ('sharding') × tensor ('mp') × data
  ('dp') × sequence ('sep') axes on a single mesh; XLA inserts all
  collectives,
- the train step is a single jitted, donated, functional program
  (build_train_step) — the analog of the reference's whole
  dygraph-hybrid-runtime hot loop (§3.3) collapsed into one XLA program.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import nn
from ..core.tensor import Tensor
from ..nn.layer import Layer, Parameter
from ..incubate.nn.fused import fused_rms_norm, fused_rotary_position_embedding, swiglu
from ..incubate.nn.attention import flash_attention


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    max_position_embeddings: int = 8192
    rope_theta: float = 500000.0
    rms_norm_eps: float = 1e-5
    tie_word_embeddings: bool = False
    dtype: str = "float32"  # param dtype; compute casts via amp
    # round-18 sparse-serving surface: a checkpoint whose decoder FFNs
    # are mixtures of experts (stacked ``model.layers.i.mlp.experts.*``
    # weights + a ``mlp.router.weight`` gate per MoE layer).  The layer
    # set is checkpoint-driven (a layer is MoE iff its expert stack is
    # present); these fields size the routing (generation._moe_ffn).
    num_experts: int = 0
    moe_top_k: int = 2

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    def cost_sheet(self):
        """Roofline ``ModelCostSheet`` for this config — the analytic
        per-layer FLOP/byte/collective-element counts the round-20
        partitioning search prices candidates with (lazy delegate so the
        models package never imports the parallel stack eagerly)."""
        from ..parallel.roofline import llama_cost_sheet
        return llama_cost_sheet(self)

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig(vocab_size=128256, hidden_size=4096,
                           intermediate_size=14336, num_hidden_layers=32,
                           num_attention_heads=32, num_key_value_heads=8)

    @staticmethod
    def debug(vocab=256, hidden=64, layers=2, heads=4, kv_heads=2,
              inter=128, max_pos=256) -> "LlamaConfig":
        return LlamaConfig(vocab_size=vocab, hidden_size=hidden,
                           intermediate_size=inter, num_hidden_layers=layers,
                           num_attention_heads=heads, num_key_value_heads=kv_heads,
                           max_position_embeddings=max_pos, rope_theta=10000.0)


class LlamaRMSNorm(Layer):
    def __init__(self, hidden_size: int, eps: float = 1e-5):
        super().__init__()
        self.weight = Parameter(jnp.ones((hidden_size,), dtype=jnp.float32))
        self.eps = eps

    def forward(self, x):
        return fused_rms_norm(x, self.weight, epsilon=self.eps)


def _rope_tables(head_dim: int, max_pos: int, theta: float):
    inv_freq = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim))
    t = np.arange(max_pos, dtype=np.float64)
    freqs = np.outer(t, inv_freq)                       # [max_pos, head_dim/2]
    emb = np.concatenate([freqs, freqs], axis=-1)       # [max_pos, head_dim]
    return (jnp.asarray(np.cos(emb), dtype=jnp.float32),
            jnp.asarray(np.sin(emb), dtype=jnp.float32))


class LlamaAttention(Layer):
    """GQA attention. Layout [b, s, h, d] throughout (flash kernel layout)."""

    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        h, d = cfg.hidden_size, cfg.head_dim
        self.q_proj = nn.Linear(h, cfg.num_attention_heads * d, bias_attr=False)
        self.k_proj = nn.Linear(h, cfg.num_key_value_heads * d, bias_attr=False)
        self.v_proj = nn.Linear(h, cfg.num_key_value_heads * d, bias_attr=False)
        self.o_proj = nn.Linear(cfg.num_attention_heads * d, h, bias_attr=False)

    def forward(self, x, cos, sin, attn_mask=None,
                startend_row_indices=None):
        cfg = self.cfg
        b, s, _ = x.shape
        q = self.q_proj(x).reshape([b, s, cfg.num_attention_heads, cfg.head_dim])
        k = self.k_proj(x).reshape([b, s, cfg.num_key_value_heads, cfg.head_dim])
        v = self.v_proj(x).reshape([b, s, cfg.num_key_value_heads, cfg.head_dim])
        # sin/cos arrive [s, d] (prefix positions) or [b, s, d] (explicit
        # position_ids, pre-gathered by LlamaModel); broadcast over (b,·,h,·)
        lead = 1 if cos.ndim == 2 else b
        cos_b = cos.reshape([lead, s, 1, cfg.head_dim])
        sin_b = sin.reshape([lead, s, 1, cfg.head_dim])
        q, k = fused_rotary_position_embedding(q, k, sin=sin_b, cos=cos_b)
        # GQA goes to the attention entry unexpanded: the Pallas kernel
        # routes q heads to kv groups via index maps (no HBM repeat); the
        # XLA fallback repeats internally.  ``attn_mask`` arrives as int32
        # SEGMENT ids ([b, s], normalized by LlamaModel): 1/0 for padded
        # batches, arbitrary ids for packed sequences — splash-attention
        # semantics on both backends.
        if startend_row_indices is not None:
            if attn_mask is not None:
                # composing band masks with segment ids is ambiguous —
                # encode BOTH constraints into startend_row_indices (a
                # causal document mask expresses packed segments) and
                # pass only that; the reference flash API likewise
                # rejects conflicting mask arguments
                raise ValueError(
                    "pass either attention_mask (segment ids) or "
                    "startend_row_indices (FlashMask bands), not both")
            # FlashMask band masks (causal document / share-question /
            # sliding window — python/paddle/nn/functional/
            # flash_attention.py:1098 semantics) on the flagship path
            from ..ops.registry import dispatch

            out = dispatch("flashmask_attention", q, k, v,
                           startend_row_indices, causal=True)
        elif attn_mask is not None:
            out = flash_attention(q, k, v, causal=True,
                                  q_segment_ids=attn_mask,
                                  kv_segment_ids=attn_mask)
        else:
            out = flash_attention(q, k, v, causal=True)
        return self.o_proj(out.reshape([b, s, -1]))


class LlamaMLP(Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.gate_proj = nn.Linear(cfg.hidden_size, cfg.intermediate_size, bias_attr=False)
        self.up_proj = nn.Linear(cfg.hidden_size, cfg.intermediate_size, bias_attr=False)
        self.down_proj = nn.Linear(cfg.intermediate_size, cfg.hidden_size, bias_attr=False)

    def forward(self, x):
        return self.down_proj(swiglu(self.gate_proj(x), self.up_proj(x)))


def _tag_saveable(t: Tensor, name: str) -> Tensor:
    """checkpoint_name the residual-stream block outputs (the HBM memory
    engine's named saveables — parallel/memory.SAVEABLE_NAMES): the
    ``names``/``offload`` remat policies key on exactly these tags.
    Skipped under an active eager tape — re-wrapping the value would
    sever the Tensor's grad history, and policies only ever see tags
    through the jitted functional path anyway."""
    from ..autograd import is_grad_enabled

    if is_grad_enabled():
        return t
    from ..parallel.memory import tag_saveable

    return Tensor(tag_saveable(t._value, name))


class LlamaDecoderLayer(Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.input_layernorm = LlamaRMSNorm(cfg.hidden_size, cfg.rms_norm_eps)
        self.self_attn = LlamaAttention(cfg)
        self.post_attention_layernorm = LlamaRMSNorm(cfg.hidden_size, cfg.rms_norm_eps)
        self.mlp = LlamaMLP(cfg)

    def forward(self, x, cos, sin, attn_mask=None,
                startend_row_indices=None):
        attn = self.self_attn(self.input_layernorm(x), cos, sin,
                              attn_mask=attn_mask,
                              startend_row_indices=startend_row_indices)
        x = x + _tag_saveable(attn, "decoder_attn_out")
        mlp = self.mlp(self.post_attention_layernorm(x))
        return x + _tag_saveable(mlp, "decoder_mlp_out")


class LlamaModel(Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.remat = False  # set by build_train_step(remat=...)
        self.remat_policy = None  # jax.checkpoint policy (None = full remat)
        # optional NamedSharding pinned onto activations at layer
        # boundaries (set by build_train_step when a mesh is given):
        # without it GSPMD propagates the mp-sharded embed weight into a
        # hidden-sharded activation, then has to fully rematerialize to
        # reach the batch-sharded layout the loss wants (the round-1
        # dryrun's "involuntary full rematerialization" warnings)
        self.act_sharding = None
        self.embed_tokens = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.layers = nn.LayerList([LlamaDecoderLayer(cfg)
                                    for _ in range(cfg.num_hidden_layers)])
        self.norm = LlamaRMSNorm(cfg.hidden_size, cfg.rms_norm_eps)
        cos, sin = _rope_tables(cfg.head_dim, cfg.max_position_embeddings,
                                cfg.rope_theta)
        self.register_buffer("rope_cos", Tensor(cos), persistable=False)
        self.register_buffer("rope_sin", Tensor(sin), persistable=False)

    def forward(self, input_ids, position_ids=None, attention_mask=None,
                startend_row_indices=None):
        from ..autograd import is_grad_enabled

        if startend_row_indices is not None and not isinstance(
                startend_row_indices, Tensor):
            startend_row_indices = Tensor(
                jnp.asarray(startend_row_indices, jnp.int32))

        s = input_ids.shape[-1]
        x = self.embed_tokens(input_ids)
        if position_ids is not None:
            # gather per-token rotary phases: [b, s, head_dim]
            pid = position_ids._value if isinstance(position_ids, Tensor) \
                else jnp.asarray(position_ids)
            cos = Tensor(jnp.take(self._buffers["rope_cos"]._value, pid, axis=0))
            sin = Tensor(jnp.take(self._buffers["rope_sin"]._value, pid, axis=0))
        else:
            cos = Tensor(self._buffers["rope_cos"]._value[:s])
            sin = Tensor(self._buffers["rope_sin"]._value[:s])
        # remat only on the functional (jit) path — tape-eager keeps
        # activations anyway, and jax.checkpoint needs pure callees
        use_remat = self.remat and not is_grad_enabled()

        def _pin(t):
            if self.act_sharding is None:
                return t
            return Tensor(jax.lax.with_sharding_constraint(
                t._value, self.act_sharding))

        if attention_mask is not None and not isinstance(attention_mask,
                                                         Tensor):
            attention_mask = Tensor(jnp.asarray(attention_mask))
        if attention_mask is not None:
            mv = attention_mask._value
            if not (jnp.issubdtype(mv.dtype, jnp.bool_)
                    or jnp.issubdtype(mv.dtype, jnp.integer)):
                # a blind cast would INVERT the additive convention
                # (0 = keep, -1e9 = masked); demand keep-mask/segment ids
                raise TypeError(
                    "LlamaModel.attention_mask expects a bool keep-mask or "
                    f"int segment ids [b, s], got dtype {mv.dtype}; convert "
                    "an additive float mask with (mask == 0) first")
            if (jnp.issubdtype(mv.dtype, jnp.integer)
                    and not isinstance(mv, jax.core.Tracer)
                    and bool(jnp.any(mv < 0))):
                # negative values are the additive-int convention in
                # disguise — reject rather than treat them as segment ids
                raise TypeError(
                    "integer attention_mask values must be >= 0 (segment "
                    "ids; 0 marks padding) — additive masks are not "
                    "accepted")
            attention_mask = Tensor(mv.astype(jnp.int32))
        x = _pin(x)
        for layer in self.layers:
            if use_remat:
                x = _remat_layer_call(layer, x, cos, sin, self.remat_policy,
                                      attention_mask, startend_row_indices)
            else:
                x = layer(x, cos, sin, attn_mask=attention_mask,
                          startend_row_indices=startend_row_indices)
            x = _pin(x)
        return self.norm(x)


def _remat_layer_call(layer: "LlamaDecoderLayer", x: Tensor, cos: Tensor,
                      sin: Tensor, policy=None, attn_mask=None,
                      startend_row_indices=None) -> Tensor:
    """Run one decoder layer under jax.checkpoint: activations inside the
    layer are recomputed in backward (the analog of the reference's
    recompute pass, strategy.recompute / fleet recompute_configs).

    ``policy`` selects what to SAVE instead of recompute (e.g.
    ``jax.checkpoint_policies.dots_with_no_batch_dims_saveable`` keeps
    matmul outputs and recomputes only the cheap elementwise chain — the
    usual FLOPs/HBM trade on TPU where recomputing a matmul is 4x the cost
    of recomputing the silu/norm around it)."""
    from ..autograd import no_grad

    state = {k: (t._value if isinstance(t, Tensor) else t)
             for k, t in layer.state_dict().items()}

    @functools.partial(jax.checkpoint, policy=policy,
                       static_argnums=(4, 6))
    def body(state, xv, cosv, sinv, has_mask, maskv, has_sri, sriv):
        with no_grad():
            out = layer.functional_call(
                state, Tensor(xv), Tensor(cosv), Tensor(sinv),
                attn_mask=Tensor(maskv) if has_mask else None,
                startend_row_indices=Tensor(sriv) if has_sri else None)
        return out._value

    mv = attn_mask._value if attn_mask is not None else jnp.zeros((), bool)
    sv = (startend_row_indices._value if startend_row_indices is not None
          else jnp.zeros((), bool))
    return Tensor(body(state, x._value, cos._value, sin._value,
                       attn_mask is not None, mv,
                       startend_row_indices is not None, sv))


class LlamaForCausalLM(Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.model = LlamaModel(cfg)
        if cfg.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size, bias_attr=False)

    def forward(self, input_ids, position_ids=None, attention_mask=None,
                startend_row_indices=None):
        from ..ops.linalg import matmul

        h = self.model(input_ids, position_ids, attention_mask,
                       startend_row_indices=startend_row_indices)
        if self.cfg.tie_word_embeddings:
            # tape-recorded matmul against the embedding Parameter itself so
            # the head contributes gradients to embed_tokens in eager mode
            return matmul(h, self.model.embed_tokens.weight, transpose_y=True)
        return self.lm_head(h)

    def generate(self, input_ids, **kwargs):
        """KV-cached autoregressive decoding (models/generation.py)."""
        from .generation import generate

        return generate(self, input_ids, **kwargs)


# --------------------------------------------------------------------------
# GSPMD sharding plan (the analog of the reference's per-layer TP wrappers +
# sharded-param init in PaddleNLP; see SURVEY.md §2.7)
# --------------------------------------------------------------------------

# param-name suffix → logical placement (fsdp = ZeRO-3 axis, mp = tensor axis)
LLAMA_SHARDING_PLAN = {
    # vocab sharded over BOTH parallel axes, hidden replicated: the lookup
    # output is then batch-sharded x hidden-replicated — exactly the
    # layer-boundary activation layout — so GSPMD never has to convert a
    # hidden-sharded gather result (the round-1 "involuntary full
    # rematerialization" on the embed path); at-rest memory matches the
    # old P("mp", "sharding") 2-D plan (same total ways)
    "embed_tokens.weight":  P(("mp", "sharding"), None),   # [vocab, hidden]
    "q_proj.weight":        P("sharding", "mp"),   # [hidden, heads*d]
    "k_proj.weight":        P("sharding", "mp"),
    "v_proj.weight":        P("sharding", "mp"),
    "o_proj.weight":        P("mp", "sharding"),   # [heads*d, hidden]
    "gate_proj.weight":     P("sharding", "mp"),
    "up_proj.weight":       P("sharding", "mp"),
    "down_proj.weight":     P("mp", "sharding"),   # [inter, hidden]
    "lm_head.weight":       P("sharding", "mp"),   # [hidden, vocab]
    "input_layernorm.weight": P(None),
    "post_attention_layernorm.weight": P(None),
    "norm.weight":          P(None),
}


def _gold_logit(lv, labels):
    """Label-logit pick as an iota-compare masked reduction, NOT
    ``take_along_axis``: the gather's transpose is a [tokens, vocab]
    scatter-add whose SPMD placement falls back to involuntary full
    rematerialization on hybrid meshes (replicating the logits-grad every
    step), while a select+reduce fuses with the adjacent logsumexp pass
    and shards like any elementwise op.  Exact same values — one nonzero
    per row (the reference reads the label column directly in its fused
    softmax-with-CE kernel, paddle/phi/kernels/gpu/
    c_softmax_with_cross_entropy_kernel.cu)."""
    vocab = lv.shape[-1]
    hit = labels[..., None] == jax.lax.broadcasted_iota(
        jnp.int32, lv.shape, lv.ndim - 1)
    return jnp.where(hit, lv.astype(jnp.float32), 0.0).sum(axis=-1)


def plan_spec_for(name: str, plan: Optional[Dict[str, P]] = None) -> P:
    from ..parallel.specs import REPLICATED

    plan = plan if plan is not None else LLAMA_SHARDING_PLAN
    for suffix, spec in plan.items():
        if name.endswith(suffix):
            return spec
    return REPLICATED


def _filter_spec_to_mesh(spec: P, mesh: Mesh) -> P:
    """Drop axes absent from the mesh (e.g. mp when running pure FSDP).
    Canonical home: ``parallel.specs.filter_spec_to_mesh`` (shared with
    the hybrid path and the Sharding Doctor's extractor)."""
    from ..parallel.specs import filter_spec_to_mesh

    return filter_spec_to_mesh(spec, mesh)


def apply_llama_sharding(model: Layer, mesh: Mesh,
                         plan: Optional[Dict[str, P]] = None,
                         schedule=None) -> None:
    """Place every parameter per the unified partitioning schedule
    (round 19): the declared plan under the shared at-rest
    divisibility-or-replicate rule, read through
    ``PartitionSchedule.spec_for`` — the same derivation
    ``build_train_step`` constrains against and the Sharding Doctor's
    extractor pins."""
    if schedule is None:
        from ..parallel.schedule import PartitionSchedule

        schedule = PartitionSchedule.from_model(model, mesh, plan=plan)
    for name, p in model.named_parameters():
        p.set_value(jax.device_put(
            p._value, schedule.named_sharding(name, tuple(p.shape))))


# --------------------------------------------------------------------------
# The compiled train step
# --------------------------------------------------------------------------

def _accum_fold(accum_steps: int, cap: int = 8) -> int:
    """Largest divisor of ``accum_steps`` not exceeding ``cap`` — the
    number of consecutive bf16 micro-grad adds between fp32 folds (caps
    the bf16 summation depth, so the carry error stays ~cap * 2^-9
    relative per element)."""
    for f in range(min(cap, accum_steps), 0, -1):
        if accum_steps % f == 0:
            return f
    return 1


def llama_decay_mask(model: Layer) -> Dict[str, bool]:
    """Per-parameter AdamW decay mask for the Llama family: norm weights
    and biases are exempt.  Shared by build_train_step and external
    callers (bench.py's fused-optimizer flat state must group params by
    the SAME mask the step applies)."""
    return {n: not ("layernorm" in n or n.endswith("norm.weight")
                    or n.endswith(".bias"))
            for n, _ in model.named_parameters()}


def _ce_loss(lv, labels, attn_mask, batch_sharding, mesh):
    """Streaming CE: lse + label-logit pick, fp32 accumulation over bf16
    logits — never materializes a full fp32 log_softmax copy
    ([tokens, vocab] fp32 is >1GB at bench shapes; the cast and the
    extra read/write were pure HBM burn)."""
    if batch_sharding is not None:
        from ..parallel.specs import lead_batch_spec

        lv = jax.lax.with_sharding_constraint(
            lv, NamedSharding(mesh, lead_batch_spec(batch_sharding.spec)))
    lse = jax.scipy.special.logsumexp(lv.astype(jnp.float32), axis=-1)
    nll = lse - _gold_logit(lv, labels)
    if attn_mask is None:
        return nll.mean()
    w = (attn_mask > 0).astype(jnp.float32)
    return (nll * w).sum() / jnp.maximum(w.sum(), 1.0)


_LAYER_PREFIX = "model.layers."


def _build_overlap_forward(model: LlamaForCausalLM, mesh: Mesh, overlap,
                           data_axes: Tuple[str, ...], compute_dtype,
                           remat: bool, remat_policy, schedule=None):
    """Build the overlap-engine forward: cast params dict -> logits.

    The decoder stack runs inside parallel/overlap.py's FULL-manual
    shard_map region (layer-ahead ZeRO-3 prefetch, bucketed grad RS,
    collective matmul, hierarchical collectives); embedding, final norm,
    LM head and the loss stay in GSPMD-land.  Per-layer params are
    stacked [L, ...] at trace time — a bf16 relayout that fuses with the
    compute-dtype cast already paid every step."""
    from ..parallel.overlap import build_overlap_stack

    cfg = model.cfg
    L = cfg.num_hidden_layers
    shapes: Dict[str, Tuple[int, ...]] = {}
    for name, p in model.named_parameters():
        if name.startswith(_LAYER_PREFIX + "0."):
            shapes[name[len(_LAYER_PREFIX) + 2:]] = tuple(p.shape)

    if schedule is None:
        from ..parallel.schedule import PartitionSchedule

        schedule = PartitionSchedule.from_model(model, mesh)

    def spec_for(suffix):
        # the schedule's pre-filter plan spec: the overlap engine's
        # per-axis pick rule applies its own divisibility per axis
        return schedule.plan_spec_for(suffix)

    stack_fwd = build_overlap_stack(
        cfg, mesh, shapes, spec_for, overlap, batch_axes=data_axes,
        remat=remat, remat_policy=remat_policy,
        compute_dtype=compute_dtype)
    cos_full, sin_full = _rope_tables(cfg.head_dim,
                                      cfg.max_position_embeddings,
                                      cfg.rope_theta)
    axes = tuple(a for a in data_axes
                 if a in mesh.axis_names and mesh.shape[a] > 1)
    batch_entry = axes if len(axes) > 1 else (axes[0] if axes else None)
    from ..incubate.nn.fused import _fused_rms_norm_op

    rms_raw = _fused_rms_norm_op.raw_fn

    def fwd(cast: Dict[str, Any], input_ids, attn_mask=None):
        stacked = {
            sfx: jnp.stack([cast[f"{_LAYER_PREFIX}{i}.{sfx}"]
                            for i in range(L)])
            for sfx in shapes}
        s = input_ids.shape[-1]
        # mode="clip": ids are in-range by construction; the bounds-check
        # pred ops are extra reshard candidates for GSPMD (same rationale
        # as llama_hybrid)
        x = jnp.take(cast["model.embed_tokens.weight"], input_ids, axis=0,
                     mode="clip")
        from ..parallel.specs import activation_spec

        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, activation_spec(batch_entry)))
        cos = cos_full[:s].astype(compute_dtype)
        sin = sin_full[:s].astype(compute_dtype)
        seg = None
        if attn_mask is not None:
            seg = attn_mask.astype(jnp.int32)
        h = stack_fwd(stacked, x, cos, sin, seg)
        h = rms_raw(h, cast["model.norm.weight"],
                    epsilon=cfg.rms_norm_eps)
        if cfg.tie_word_embeddings:
            logits = h @ cast["model.embed_tokens.weight"].T
        else:
            logits = h @ cast["lm_head.weight"]
        return logits

    fwd.stack_fwd = stack_fwd
    return fwd


def build_train_step(model: LlamaForCausalLM, optimizer, mesh: Optional[Mesh] = None,
                     data_axes: Tuple[str, ...] = ("dp", "sharding"),
                     remat: bool = False, remat_policy=None,
                     compute_dtype=jnp.bfloat16, accum_steps: int = 1,
                     accum_dtype=None, overlap=None, memory=None,
                     health=None, schedule=None):
    """Build a single donated, jitted train step:

        step_fn(params, opt_state, step_no, lr, input_ids, labels)
            -> (loss, new_params, new_opt_state)

    - params/opt_state keep their NamedShardings (FSDP/TP at rest),
    - with ``mesh``, the batch and logits are constrained to the data axes
      (pins GSPMD's layout choice for the loss reduction),
    - ``remat=True`` checkpoints each decoder layer (jax.checkpoint) —
      activations recomputed in backward; the analog of the reference's
      recompute pass (strategy.recompute).  ``remat_policy`` (a
      jax.checkpoint_policies entry) selects SELECTIVE remat: e.g.
      ``dots_with_no_batch_dims_saveable`` keeps matmul outputs and only
      recomputes the elementwise chain,
    - forward/backward math in ``compute_dtype`` (bf16 on the MXU),
      optimizer math fp32 (master weights in Adam state,
      optimizer.py multi_precision),
    - ``accum_dtype`` picks the gradient-merge accumulator dtype for the
      unmasked accum path.  None (default) resolves to bf16 when
      compute_dtype is bf16 (the backward already emits bf16 grads; the
      round-5 trace put the fp32 accumulator's read-modify-write at
      ~173 ms/step of HBM traffic) and fp32 otherwise (exact parity for
      fp32 test configs).  bf16 accumulation folds into an fp32 carry
      every _accum_fold(accum_steps) micro-steps, bounding the bf16
      summation depth; loss/grad parity vs the fp32 scheme is gated by
      tests/test_grad_accum_bf16_carry.py at accum=32,
    - ``opt_state`` built by ``optimizer.init_flat_state`` routes the
      update through the fused multi-tensor ``apply_flat`` (one pass
      over flattened param groups); per-param pytree state keeps the
      legacy per-tensor ``apply``,
    - ``overlap`` (an ``parallel.overlap.OverlapConfig``; needs ``mesh``)
      routes the decoder stack through the communication-overlap engine:
      a FULL-manual shard_map region with layer-ahead ZeRO-3 gather
      prefetch, bucketed grad reduce-scatter, ppermute-ring collective
      matmul for the mp projections, and hierarchical ICI/DCN
      collectives on multislice meshes (parallel/overlap.py).  Embedding,
      final norm, LM head and the loss stay in plain GSPMD-land;
      ``overlap=None`` keeps the flat GSPMD program (the fallback every
      overlap lever compares against),
    - ``memory`` (a ``parallel.memory.MemoryConfig``) drives the HBM
      memory engine: its NAMED remat policy (``none | dots | names |
      offload | full`` over the checkpoint_name-tagged decoder
      saveables) replaces the binary ``remat``/``remat_policy`` pair on
      BOTH the GSPMD and overlap paths, and
      ``optimizer_residency='host'`` routes the update through the
      bucket-streamed ``apply_flat_offloaded`` when ``opt_state`` was
      built by ``parallel.memory.init_offloaded_state`` (detection is
      structural, like the flat state),
    - ``health`` (a ``distributed.health.HealthConfig``) fuses the
      round-17 health probe INTO this step: the step additionally takes
      a ``health_gates`` fp32[3] cutoff vector (loss / grad-norm /
      update-ratio; None = all-open) and returns a 4th output — the
      probe dict (loss, global grad-norm, per-bucket nonfinite counts,
      update/param ratio, ok flag) — while GUARDING the update in-step:
      a probe that trips any gate makes params and optimizer state pass
      through untouched (bit-exact skip-and-quarantine; the host
      monitor in distributed/health.py decides the ladder response).
      The probe is reductions only — HEALTH001/002 prove it adds no
      full-tree materialization and no collectives,
    - ``schedule`` (a ``parallel.schedule.PartitionSchedule``) is the
      round-19 unified partitioning schedule this step derives from.
      With a mesh and no explicit schedule, one is built from the
      model's declared plan (``PartitionSchedule.from_model``) — so
      every mesh-sharded step IS schedule-derived.  The schedule
      supplies the at-rest specs, the batch pins and the SHARD-MAJOR
      flat-update wire format (``FlatUpdateLayout``): the fused flat
      optimizer's at-rest -> flat boundary becomes a local relayout
      instead of a per-leaf GSPMD reshard — the cut behind the
      round-19 SHARD001 reshard bill (the flat-update pin itself, the
      2004.13336 tactic SHARD005 demands, is unchanged).
    """
    from ..autograd import no_grad
    from ..parallel import memory as _memory

    if schedule is None and mesh is not None:
        from ..parallel.schedule import PartitionSchedule

        schedule = PartitionSchedule.from_model(model, mesh)
    if memory is not None:
        # the named policy owns the remat decision end to end — a
        # caller mixing memory= with the legacy binary flag would get
        # whichever traced last, so resolve once, here
        remat, remat_policy = memory.resolve_remat()
    decay_mask = llama_decay_mask(model)
    if accum_dtype is None:
        accum_dtype = (jnp.bfloat16 if compute_dtype == jnp.bfloat16
                       else jnp.float32)
    batch_sharding = make_batch_shardings(mesh, data_axes) if mesh is not None \
        else None
    ov_forward = None
    if overlap is not None:
        if mesh is None:
            raise ValueError("overlap=OverlapConfig(...) needs a mesh")
        ov_forward = _build_overlap_forward(model, mesh, overlap,
                                            data_axes, compute_dtype,
                                            remat, remat_policy,
                                            schedule=schedule)

    def loss_fn(params: Dict[str, Any], input_ids, labels, attn_mask=None):
        cast = {k: (v.astype(compute_dtype)
                    if jnp.issubdtype(v.dtype, jnp.floating) else v)
                for k, v in params.items()}
        if ov_forward is not None:
            lv = ov_forward(cast, input_ids, attn_mask)
            return _ce_loss(lv, labels, attn_mask, batch_sharding, mesh)
        # set the remat flag only for the duration of THIS trace: jit
        # traces lazily, so a build-time flag would leak across steps
        # built with different remat settings (and into eager inference)
        saved_remat = model.model.remat
        saved_policy = model.model.remat_policy
        saved_act = model.model.act_sharding
        model.model.remat = remat
        model.model.remat_policy = remat_policy
        if batch_sharding is not None:
            # activations ride the batch axes with hidden replicated
            # (Megatron convention); pinning every layer boundary keeps
            # GSPMD from flip-flopping between weight-induced layouts
            from ..parallel.specs import lead_batch_spec

            model.model.act_sharding = NamedSharding(
                mesh, lead_batch_spec(batch_sharding.spec, 3))
        try:
            with no_grad():  # tape off: jax.grad provides the gradients
                logits = model.functional_call(
                    cast, Tensor(input_ids),
                    attention_mask=None if attn_mask is None
                    else Tensor(attn_mask))
        finally:
            model.model.remat = saved_remat
            model.model.remat_policy = saved_policy
            model.model.act_sharding = saved_act
        return _ce_loss(logits._value, labels, attn_mask, batch_sharding,
                        mesh)

    grad_fn = jax.value_and_grad(loss_fn)

    # flat-buffer layout pin for the fused optimizer paths on a mesh:
    # shards the bandwidth-bound update chain across every device (the
    # 2004.13336 cross-replica weight-update sharding) AND guards the
    # concat→update→slice chain against the GSPMD mis-lowering the
    # round-10 parity tests caught (see Adam.apply_flat).  The schedule
    # additionally derives the SHARD-MAJOR wire format (FlatUpdateLayout)
    # consumed when the opt state was built under it; legacy row-major
    # states keep the plain pin.
    flat_sharding = None
    flat_layout = None
    if mesh is not None:
        flat_layout = schedule.flat_update_layout()
        flat_sharding = NamedSharding(mesh, flat_layout.flat_spec())
        if not flat_layout.axes:
            flat_layout = None      # single-device mesh: nothing to cut

    # NOTE (round-19, measured): an explicit at-rest pin on the merged
    # grad tree before the optimizer boundary was tried and REJECTED —
    # on the flagship accum-4 entry it saves 3 collective-permutes but
    # forces 17 extra all-reduces (the deferred dp grad reduction
    # materializes per leaf instead of folding into the flat chain).
    # The shard-major FlatUpdateLayout alone is the right cut.

    def _health_tail(loss, grads, params, opt_state, new_params,
                     new_opt_state, health_gates):
        """The fused probe + in-step no-op guard (round-17) —
        distributed/health.py owns the contract and the implementation."""
        from ..distributed import health as _health

        return _health.probe_and_guard(loss, grads, params, opt_state,
                                       new_params, new_opt_state,
                                       health_gates, health)

    def apply_update(params, grads, opt_state, lr, step_no):
        # host-offloaded bucketed state (parallel/memory.py) routes the
        # streamed fused AdamW; flat (fused multi-tensor) state the
        # single-pass device-resident one — detection is structural in
        # both cases so legacy per-param state keeps working
        if _memory.state_is_offloaded(opt_state):
            return _memory.apply_flat_offloaded(
                optimizer, params, grads, opt_state, lr, step_no + 1,
                decay_mask=decay_mask, flat_sharding=flat_sharding,
                flat_layout=flat_layout)
        if hasattr(optimizer, "apply_flat") \
                and getattr(optimizer, "state_is_flat", lambda s: False)(
                    opt_state):
            return optimizer.apply_flat(
                params, grads, opt_state, lr, step_no + 1,
                decay_mask=decay_mask, flat_sharding=flat_sharding,
                flat_layout=flat_layout)
        return optimizer.apply(
            params, grads, opt_state, lr, step_no + 1,
            decay_mask=decay_mask)

    def step_fn(params, opt_state, step_no, lr, input_ids, labels,
                attention_mask=None, health_gates=None):
        if batch_sharding is not None:
            input_ids = jax.lax.with_sharding_constraint(input_ids, batch_sharding)
            labels = jax.lax.with_sharding_constraint(labels, batch_sharding)
            if attention_mask is not None:
                attention_mask = jax.lax.with_sharding_constraint(
                    attention_mask, batch_sharding)
        loss, grads = grad_fn(params, input_ids, labels, attention_mask)
        new_params, new_opt_state = apply_update(params, grads, opt_state,
                                                 lr, step_no)
        if health is not None:
            return _health_tail(loss, grads, params, opt_state,
                                new_params, new_opt_state, health_gates)
        return loss, new_params, new_opt_state

    def accum_step_fn(params, opt_state, step_no, lr, input_ids, labels,
                      attention_mask=None, health_gates=None):
        """Gradient accumulation (reference: strategy gradient-merge /
        GradientMergeOptimizer): ids/labels carry a leading [accum_steps]
        micro-batch axis; one fp32 grad buffer is accumulated by a
        lax.scan of fwd+bwd micro-steps, then AdamW runs ONCE — the
        HBM-bound optimizer read-modify-write (4 fp32 tensors the size of
        the model) is amortized over accum_steps of compute."""
        if batch_sharding is not None:
            from ..parallel.specs import microbatched

            micro = NamedSharding(mesh,
                                  microbatched(*tuple(batch_sharding.spec)))
            input_ids = jax.lax.with_sharding_constraint(input_ids, micro)
            labels = jax.lax.with_sharding_constraint(labels, micro)
            if attention_mask is not None:
                attention_mask = jax.lax.with_sharding_constraint(
                    attention_mask, micro)

        # two scan bodies, NOT a fabricated all-ones mask: the mask-free
        # path must keep the unmasked attention kernel and plain-mean CE
        # (the headline bench runs here — a dummy mask would drag the
        # segment-masked kernel variant into every layer)
        def micro_step(acc, xs):
            mids, mlabels = xs
            loss, g = grad_fn(params, mids, mlabels, None)
            acc = jax.tree_util.tree_map(
                lambda a, b: a + b.astype(jnp.float32), acc, g)
            return acc, loss

        def micro_step_masked(carry, xs):
            # token-weighted accumulation: micro-batches with unequal
            # valid-token counts must contribute in proportion to their
            # tokens, or the merged gradient deviates from the true
            # global token-mean (per-micro grad_fn returns the gradient
            # of a per-micro token MEAN, so scale by that micro's count)
            #
            # DESIGN NOTE — accepted fp32 region (Graph Doctor DT003,
            # tracked exemption EX-DT003-masked-grad-accum in
            # paddle_tpu/analysis/exemptions.py): this accumulator stays
            # fp32 on purpose.  The bf16-carry scheme needs a fold point
            # where a bounded number of micro-grads collapse into the
            # fp32 carry; here every micro-grad is pre-scaled by its
            # token count w and the normalization (1/wsum) is only known
            # at the END of the window, so partial sums span the whole
            # window and a bounded-depth bf16 carry has no clean fold.
            # Folding unnormalized w-scaled bf16 sums would compound
            # quantization error by the full accum depth — worse than
            # the fp32 traffic it saves.  The headline bench runs the
            # unmasked path; the dtype audit keeps this decision visible
            # (and the exemption-liveness self-check fails if this
            # branch ever loses the fp32 carry without updating the
            # exemption table).
            acc, wsum = carry
            mids, mlabels, mmask = xs
            loss, g = grad_fn(params, mids, mlabels, mmask)
            # true token count, no clamp: an all-padding micro contributes
            # zero weight (its loss/grads are already zero via loss_fn's
            # own divide guard); clamping HERE would add a phantom token
            # and shrink every real micro's contribution by n/(n+1)
            w = (mmask > 0).sum().astype(jnp.float32)
            acc = jax.tree_util.tree_map(
                lambda a, b: a + w * b.astype(jnp.float32), acc, g)
            return (acc, wsum + w), loss * w

        zero = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        # fold == 1 (accum_steps prime > cap) would be strictly worse
        # than the fp32 accumulator — full fp32 carry traffic PLUS bf16
        # quantization of every micro-grad — so it falls through
        if attention_mask is None and accum_dtype != jnp.float32 \
                and accum_steps > 1 and _accum_fold(accum_steps) > 1:
            # bf16 micro-grad carry (round-7): the accumulator the scan
            # reads-modifies-writes every micro-step is bf16 (half the
            # HBM bytes of the fp32 scheme); an fp32 carry absorbs it
            # every ``fold`` micro-steps so at most ``fold`` bf16 adds
            # compound before a fold (fold <= 8 -> ~fold * 2^-9 relative
            # carry error, gated by tests/test_grad_accum_bf16_carry.py).
            # Traffic per micro-step drops from 2x fp32-bytes to
            # 2x bf16-bytes + (2/fold)x fp32-bytes ≈ 5/8 at fold=8.
            fold = _accum_fold(accum_steps)
            ids_c = input_ids.reshape(
                (accum_steps // fold, fold) + input_ids.shape[1:])
            lab_c = labels.reshape(
                (accum_steps // fold, fold) + labels.shape[1:])

            def micro_lo(acc16, xs):
                mids, mlabels = xs
                loss, g = grad_fn(params, mids, mlabels, None)
                acc16 = jax.tree_util.tree_map(
                    lambda a, b: a + b.astype(accum_dtype), acc16, g)
                return acc16, loss

            def fold_step(acc32, xs):
                zero16 = jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, accum_dtype), params)
                acc16, losses = jax.lax.scan(micro_lo, zero16, xs)
                acc32 = jax.tree_util.tree_map(
                    lambda c, a: c + a.astype(jnp.float32), acc32, acc16)
                return acc32, losses

            acc, losses = jax.lax.scan(fold_step, zero, (ids_c, lab_c))
            grads = jax.tree_util.tree_map(lambda a: a / accum_steps, acc)
            mean_loss = losses.mean()
        elif attention_mask is None:
            acc, losses = jax.lax.scan(micro_step, zero,
                                       (input_ids, labels))
            grads = jax.tree_util.tree_map(lambda a: a / accum_steps, acc)
            mean_loss = losses.mean()
        else:
            # masked accumulation stays fp32: token-weighted partial sums
            # span the full accum window (wsum-scaled), so a bounded-depth
            # bf16 carry has no clean fold point; the headline bench runs
            # the unmasked path
            (acc, wsum), wlosses = jax.lax.scan(
                micro_step_masked, (zero, jnp.zeros((), jnp.float32)),
                (input_ids, labels, attention_mask))
            wsum = jnp.maximum(wsum, 1.0)  # guard only the TOTAL
            grads = jax.tree_util.tree_map(lambda a: a / wsum, acc)
            mean_loss = wlosses.sum() / wsum
        new_params, new_opt_state = apply_update(params, grads, opt_state,
                                                 lr, step_no)
        if health is not None:
            return _health_tail(mean_loss, grads, params, opt_state,
                                new_params, new_opt_state, health_gates)
        return mean_loss, new_params, new_opt_state

    fn = step_fn if accum_steps <= 1 else accum_step_fn
    jit_step = jax.jit(fn, donate_argnums=(0, 1))

    @functools.wraps(jit_step, updated=())  # no __dict__ merge: the
    # wrapper must NOT inherit the pjit's aot methods — the doctor
    # reaches them through __wrapped__
    def step(params, opt_state, step_no, lr, input_ids, labels,
             attention_mask=None, health_gates=None):
        # scalar-signature pinning (Graph Doctor retrace sentinel, RT001):
        # callers alternate python ints/floats (weak-typed avals) with
        # arrays (strong) for step_no/lr, and every flip retraces and
        # recompiles the WHOLE step; normalizing at the entry pins one
        # signature.  Donation is untouched — params/opt_state flow into
        # the jit boundary unchanged (the doctor's donation pass audits
        # the inner entry via __wrapped__).
        step_no = jnp.asarray(step_no, jnp.int32)
        lr = jnp.asarray(lr, jnp.float32)
        kw = {}
        if health is not None:
            from ..distributed import health as _health

            kw["health_gates"] = _health.normalize_gates(health_gates)
        if attention_mask is None:
            return jit_step(params, opt_state, step_no, lr, input_ids,
                            labels, **kw)
        return jit_step(params, opt_state, step_no, lr, input_ids, labels,
                        attention_mask, **kw)

    return step


def make_batch_shardings(mesh: Mesh, data_axes: Tuple[str, ...] = ("dp", "sharding")):
    from ..parallel.specs import batch_partition_spec

    return NamedSharding(mesh, batch_partition_spec(mesh, data_axes))
