"""MoE transformer language model — the ERNIE-MoE-style flagship.

SURVEY §7 milestone 8's second config: a GPT-style causal LM whose FFNs are
mixtures of experts, trained under hybrid dp×ep×mp sharding. Reference
analogs: the MoE stack under
python/paddle/incubate/distributed/models/moe/moe_layer.py (layer, gates,
global scatter/gather) composed into an ERNIE/GPT decoder the way the
reference's fleet MoE examples do; attention/embedding parity with
python/paddle/nn/layer/transformer.py.

TPU-native structure:
- attention is the same Pallas-flash entry the Llama flagship uses
  (incubate.nn.attention), causal, with learned position embeddings;
- each MoE FFN is ONE registered op (moe_forward): the GShard masked-einsum
  formulation whose dispatch/combine einsums XLA lowers to the exact
  alltoall the reference hand-writes — experts Shard(0) over the ``ep``
  mesh axis, expert hidden dim over ``mp`` (EP×TP);
- the train step is a single donated jit: CE loss + capacity-weighted
  aux load-balance loss (gshard), optimizer update inside.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from .. import nn
from ..nn.layer import Layer
from ..incubate.nn.attention import flash_attention
from ..incubate.distributed.models.moe.moe_layer import MoELayer

__all__ = ["GPTMoEConfig", "GPTMoEForCausalLM", "apply_gpt_moe_sharding",
           "build_moe_train_step"]


@dataclass
class GPTMoEConfig:
    vocab_size: int = 32000
    hidden_size: int = 1024
    num_hidden_layers: int = 8
    num_attention_heads: int = 16
    ffn_hidden_size: int = 4096
    num_experts: int = 8
    moe_every: int = 2           # every k-th block gets an MoE FFN
    top_k: int = 2
    gate: str = "gshard"
    capacity_factor: float = 1.2
    max_position_embeddings: int = 2048
    layer_norm_eps: float = 1e-5
    aux_loss_weight: float = 0.01

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def debug(cls):
        return cls(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                   num_attention_heads=4, ffn_hidden_size=64, num_experts=4,
                   moe_every=2, max_position_embeddings=64)


class GPTMoEAttention(Layer):
    """Causal MHA over the flash-attention entry. Layout [b, s, h, d]."""

    def __init__(self, cfg: GPTMoEConfig):
        super().__init__()
        h = cfg.hidden_size
        self.qkv_proj = nn.Linear(h, 3 * h)
        self.out_proj = nn.Linear(h, h)
        self.cfg = cfg

    def forward(self, x):
        b, s, _ = x.shape
        cfg = self.cfg
        qkv = self.qkv_proj(x).reshape(
            [b, s, 3, cfg.num_attention_heads, cfg.head_dim])
        q, k, v = (qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2])
        out = flash_attention(q, k, v, causal=True)
        return self.out_proj(out.reshape([b, s, cfg.hidden_size]))


class GPTMoEBlock(Layer):
    def __init__(self, cfg: GPTMoEConfig, use_moe: bool):
        super().__init__()
        self.ln_1 = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.attn = GPTMoEAttention(cfg)
        self.ln_2 = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.use_moe = use_moe
        if use_moe:
            self.mlp = MoELayer(cfg.hidden_size, cfg.ffn_hidden_size,
                                num_expert=cfg.num_experts, gate=cfg.gate,
                                top_k=cfg.top_k,
                                capacity_factor=cfg.capacity_factor,
                                activation="gelu")
        else:
            self.mlp = nn.Sequential(
                nn.Linear(cfg.hidden_size, cfg.ffn_hidden_size), nn.GELU(),
                nn.Linear(cfg.ffn_hidden_size, cfg.hidden_size))

    def forward(self, x):
        x = x + self.attn(self.ln_1(x))
        x = x + self.mlp(self.ln_2(x))
        return x


class GPTMoEForCausalLM(Layer):
    def __init__(self, cfg: GPTMoEConfig):
        super().__init__()
        self.cfg = cfg
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.wpe = nn.Embedding(cfg.max_position_embeddings, cfg.hidden_size)
        self.blocks = nn.LayerList([
            GPTMoEBlock(cfg, use_moe=((i + 1) % cfg.moe_every == 0))
            for i in range(cfg.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                 bias_attr=False)

    def forward(self, input_ids):
        s = input_ids.shape[-1]
        pos = Tensor(jnp.arange(s, dtype=jnp.int32))
        x = self.wte(input_ids) + self.wpe(pos)
        for blk in self.blocks:
            x = blk(x)
        return self.lm_head(self.ln_f(x))

    def aux_losses(self):
        """Aux load-balance losses of the MoE blocks from the LAST forward
        (tracers inside a trace — combine them there)."""
        out = []
        for blk in self.blocks:
            if blk.use_moe and blk.mlp.l_aux is not None:
                out.append(blk.mlp.l_aux)
        return out


# --------------------------------------------------------------------------
# hybrid dp×ep×mp sharding plan
# --------------------------------------------------------------------------

def _param_specs(name: str) -> P:
    """PartitionSpec per parameter name — the Megatron/GShard hybrid:
    attention+dense-FFN weights mp-column/row-sharded, expert stacks
    placed by the CANONICAL ep rule (parallel.specs.expert_leaf_spec:
    leading [E] on ``ep``, expert hidden over mp — the same vocabulary
    the EP engine and the Sharding Doctor consume), embeddings
    mp-sharded on vocab/hidden, norms replicated."""
    from ..parallel.specs import expert_leaf_spec, is_expert_leaf

    if ".mlp.gate.weight" in name:
        return P()
    if is_expert_leaf(name):
        tails = {".mlp.w_up": P(None, "mp"), ".mlp.b_up": P("mp"),
                 ".mlp.w_down": P("mp", None), ".mlp.b_down": P(None)}
        for marker, tail in tails.items():
            if marker in name:
                return expert_leaf_spec(tail)
        return expert_leaf_spec()
    if ".qkv_proj.weight" in name or ".mlp.0.weight" in name:
        return P(None, "mp")  # column parallel
    if ".qkv_proj.bias" in name or ".mlp.0.bias" in name:
        return P("mp")
    if ".out_proj.weight" in name or ".mlp.2.weight" in name:
        return P("mp", None)  # row parallel
    if name.startswith("wte.") or name.startswith("lm_head."):
        return P(None, "mp") if name.endswith("weight") else P()
    return P()


def apply_gpt_moe_sharding(model: GPTMoEForCausalLM, mesh: Mesh) -> None:
    """Place every parameter per the dp×ep×mp plan (GSPMD propagates the
    activation layouts; the moe_forward einsums then lower to ep-axis
    alltoalls, the qkv/out matmuls to mp-axis collectives)."""
    from ..parallel.specs import filter_spec_to_mesh

    for name, p_ in model.named_parameters():
        spec = filter_spec_to_mesh(_param_specs(name), mesh)
        p_.set_value(jax.device_put(p_._value, NamedSharding(mesh, spec)))


def build_moe_train_step(model: GPTMoEForCausalLM, optimizer,
                         mesh: Optional[Mesh] = None,
                         data_axes: Tuple[str, ...] = ("dp",),
                         compute_dtype=jnp.float32):
    """Donated jitted step: (params, opt_state, step_no, lr, ids, labels)
    -> (loss, aux_loss, new_params, new_opt_state). CE over shifted labels
    plus cfg.aux_loss_weight × mean expert-balance aux loss (the
    reference's l_aux term, moe_layer.py:263)."""
    from ..autograd import no_grad

    cfg = model.cfg
    batch_sharding = None
    if mesh is not None:
        from ..parallel.specs import batch_partition_spec

        spec = batch_partition_spec(mesh, data_axes)
        if tuple(spec) != (None,):
            batch_sharding = NamedSharding(mesh, spec)

    def loss_fn(params, input_ids, labels):
        cast = {k: (v.astype(compute_dtype)
                    if jnp.issubdtype(v.dtype, jnp.floating) else v)
                for k, v in params.items()}
        with no_grad():
            logits = model.functional_call(cast, Tensor(input_ids))
        lv = logits._value.astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(lv, axis=-1)
        ll = jnp.take_along_axis(lv, labels[..., None], axis=-1)[..., 0]
        ce = jnp.mean(lse - ll)
        auxes = [a._value if isinstance(a, Tensor) else a
                 for a in model.aux_losses()]
        aux = (jnp.mean(jnp.stack(auxes)) if auxes
               else jnp.asarray(0.0, jnp.float32))
        return ce + cfg.aux_loss_weight * aux, (ce, aux)

    def step(params, opt_state, step_no, lr, input_ids, labels):
        if batch_sharding is not None:
            input_ids = jax.lax.with_sharding_constraint(
                input_ids, batch_sharding)
            labels = jax.lax.with_sharding_constraint(labels, batch_sharding)
        (_, (ce, aux)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, input_ids, labels)
        new_params, new_opt = optimizer.apply(params, grads, opt_state, lr,
                                              step_no + 1)
        return ce, aux, new_params, new_opt

    return jax.jit(step, donate_argnums=(0, 1))
