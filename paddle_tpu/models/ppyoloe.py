"""PP-YOLOE-style anchor-free detector — the north-star config-3 model
(BASELINE.md #3: "PP-YOLOE detection (conv+attn, dynamic shapes via
fusion→HLO)").

Reference analogs: the detector the reference ecosystem trains with the
ops this framework already registers (yolo_box / multiclass_nms3 /
prior_box live in paddle/phi; the PP-YOLOE model zoo is PaddleDetection).
Framework-side capability: a CSPResNet-lite backbone, PAN-lite neck,
decoupled anchor-free head with DFL regression, varifocal + GIoU + DFL
losses, center-sampling assignment — all static-shape jnp so the whole
train step jits (the "dynamic shapes" of detection are handled the
TPU-first way: fixed-size padded GT tensors with validity masks, and NMS
at the end of the compiled graph via the registered multiclass_nms3 op).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn import BatchNorm2D, Conv2D, Layer, LayerList, Silu

__all__ = ["PPYOLOEConfig", "PPYOLOE", "ppyoloe_loss", "decode_predictions"]


@dataclass(frozen=True)
class PPYOLOEConfig:
    num_classes: int = 80
    widths: Tuple[int, ...] = (32, 64, 128, 256)   # stem + 3 stages
    depths: Tuple[int, ...] = (1, 2, 2)
    strides: Tuple[int, ...] = (8, 16, 32)
    reg_max: int = 8                               # DFL bins
    head_width: int = 64

    @staticmethod
    def debug(num_classes=4):
        return PPYOLOEConfig(num_classes=num_classes,
                             widths=(8, 16, 32, 64), depths=(1, 1, 1),
                             reg_max=4, head_width=16)


class _ConvBNAct(Layer):
    def __init__(self, cin, cout, k=3, stride=1):
        super().__init__()
        self.conv = Conv2D(cin, cout, k, stride=stride, padding=k // 2,
                           bias_attr=False)
        self.bn = BatchNorm2D(cout)
        self.act = Silu()

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class _CSPBlock(Layer):
    """CSP stage: split, residual bottlenecks on one branch, concat."""

    def __init__(self, cin, cout, n):
        super().__init__()
        mid = cout // 2
        self.a = _ConvBNAct(cin, mid, 1)
        self.b = _ConvBNAct(cin, mid, 1)
        self.m = LayerList([_ConvBNAct(mid, mid, 3) for _ in range(n)])
        self.out = _ConvBNAct(2 * mid, cout, 1)

    def forward(self, x):
        a = self.a(x)
        for blk in self.m:
            a = a + blk(a)
        b = self.b(x)
        from ..ops.registry import dispatch

        return self.out(dispatch("concat", [a, b], axis=1))


class _Backbone(Layer):
    def __init__(self, cfg: PPYOLOEConfig):
        super().__init__()
        w = cfg.widths
        # stride-4 stem, then 3 stride-2 stages -> pyramid strides 8/16/32,
        # matching cfg.strides (anchor geometry depends on this)
        self.stem1 = _ConvBNAct(3, w[0], 3, stride=2)
        self.stem2 = _ConvBNAct(w[0], w[0], 3, stride=2)
        self.downs = LayerList()
        self.stages = LayerList()
        for i, n in enumerate(cfg.depths):
            self.downs.append(_ConvBNAct(w[i], w[i + 1], 3, stride=2))
            self.stages.append(_CSPBlock(w[i + 1], w[i + 1], n))

    def forward(self, x):
        x = self.stem2(self.stem1(x))
        feats = []
        for down, stage in zip(self.downs, self.stages):
            x = stage(down(x))
            feats.append(x)
        return feats


class _PANNeck(Layer):
    """Top-down fusion then bottom-up re-aggregation (PAN-lite)."""

    def __init__(self, cfg: PPYOLOEConfig):
        super().__init__()
        w = cfg.widths[1:]
        self.lat = LayerList([_ConvBNAct(c, cfg.head_width, 1) for c in w])
        self.td = LayerList([_ConvBNAct(cfg.head_width, cfg.head_width, 3)
                             for _ in w[:-1]])
        self.bu = LayerList([_ConvBNAct(cfg.head_width, cfg.head_width, 3)
                             for _ in w[:-1]])

    def forward(self, feats):
        from ..nn import functional as F
        from ..ops.registry import dispatch

        p = [lat(f) for lat, f in zip(self.lat, feats)]
        # top-down
        for i in range(len(p) - 2, -1, -1):
            up = F.interpolate(p[i + 1], size=tuple(p[i].shape[2:]),
                               mode="nearest")
            p[i] = self.td[i](p[i] + up)
        # bottom-up: resize to the exact coarser shape so odd feature maps
        # (inputs not divisible by 32) still align with the conv pyramid
        for i in range(1, len(p)):
            down = p[i - 1]
            if tuple(down.shape[2:]) != tuple(p[i].shape[2:]):
                down = F.interpolate(down, size=tuple(p[i].shape[2:]),
                                     mode="nearest")
            p[i] = self.bu[i - 1](p[i] + down)
        return p


class _Head(Layer):
    """Decoupled anchor-free head: cls logits + DFL ltrb distributions."""

    def __init__(self, cfg: PPYOLOEConfig):
        super().__init__()
        self.cfg = cfg
        self.cls_conv = LayerList()
        self.reg_conv = LayerList()
        self.cls_pred = LayerList()
        self.reg_pred = LayerList()
        for _ in cfg.strides:
            self.cls_conv.append(_ConvBNAct(cfg.head_width, cfg.head_width))
            self.reg_conv.append(_ConvBNAct(cfg.head_width, cfg.head_width))
            self.cls_pred.append(Conv2D(cfg.head_width, cfg.num_classes, 1))
            self.reg_pred.append(Conv2D(cfg.head_width,
                                        4 * (cfg.reg_max + 1), 1))

    def forward(self, feats):
        cls_out, reg_out = [], []
        for i, f in enumerate(feats):
            c = self.cls_pred[i](self.cls_conv[i](f))
            r = self.reg_pred[i](self.reg_conv[i](f))
            b = c.shape[0]
            cls_out.append(c.reshape([b, self.cfg.num_classes, -1])
                           .transpose([0, 2, 1]))
            reg_out.append(r.reshape([b, 4 * (self.cfg.reg_max + 1), -1])
                           .transpose([0, 2, 1]))
        from ..ops.registry import dispatch

        return (dispatch("concat", cls_out, axis=1),
                dispatch("concat", reg_out, axis=1))


class PPYOLOE(Layer):
    def __init__(self, cfg: PPYOLOEConfig = None, num_classes: int = None):
        super().__init__()
        cfg = cfg or PPYOLOEConfig()
        if num_classes is not None:
            cfg = PPYOLOEConfig(num_classes=num_classes, widths=cfg.widths,
                                depths=cfg.depths, strides=cfg.strides,
                                reg_max=cfg.reg_max,
                                head_width=cfg.head_width)
        self.cfg = cfg
        self.backbone = _Backbone(cfg)
        self.neck = _PANNeck(cfg)
        self.head = _Head(cfg)

    def forward(self, images):
        """images [b, 3, H, W] -> (cls_logits [b, A, C],
        reg_logits [b, A, 4*(reg_max+1)], anchor_points [A, 2],
        stride_per_anchor [A])."""
        feats = self.neck(self.backbone(images))
        cls_logits, reg_logits = self.head(feats)
        pts, strides = _anchor_points(
            [tuple(f.shape[2:]) for f in feats], self.cfg)
        return cls_logits, reg_logits, Tensor(pts), Tensor(strides)


def _anchor_points(level_shapes: Sequence[Tuple[int, int]],
                   cfg: PPYOLOEConfig):
    pts, strides = [], []
    for (h, w), s in zip(level_shapes, cfg.strides):
        ys, xs = jnp.meshgrid(jnp.arange(h) + 0.5, jnp.arange(w) + 0.5,
                              indexing="ij")
        pts.append(jnp.stack([xs.ravel(), ys.ravel()], -1) * s)
        strides.append(jnp.full((h * w,), float(s)))
    return jnp.concatenate(pts), jnp.concatenate(strides)


def _dfl_expect(reg_logits, reg_max):
    """[..., 4*(reg_max+1)] logits -> expected ltrb distances [..., 4]."""
    shp = reg_logits.shape[:-1]
    p = jax.nn.softmax(
        reg_logits.reshape(shp + (4, reg_max + 1)).astype(jnp.float32), -1)
    return (p * jnp.arange(reg_max + 1, dtype=jnp.float32)).sum(-1)


def _decode_boxes(reg_logits, pts, strides, reg_max):
    d = _dfl_expect(reg_logits, reg_max) * strides[None, :, None]
    x, y = pts[None, :, 0], pts[None, :, 1]
    return jnp.stack([x - d[..., 0], y - d[..., 1],
                      x + d[..., 2], y + d[..., 3]], -1)  # xyxy


def _giou(a, b):
    """a, b [..., 4] xyxy -> GIoU [...]."""
    ix1 = jnp.maximum(a[..., 0], b[..., 0])
    iy1 = jnp.maximum(a[..., 1], b[..., 1])
    ix2 = jnp.minimum(a[..., 2], b[..., 2])
    iy2 = jnp.minimum(a[..., 3], b[..., 3])
    inter = jnp.clip(ix2 - ix1, 0) * jnp.clip(iy2 - iy1, 0)
    area_a = jnp.clip(a[..., 2] - a[..., 0], 0) * jnp.clip(a[..., 3] - a[..., 1], 0)
    area_b = jnp.clip(b[..., 2] - b[..., 0], 0) * jnp.clip(b[..., 3] - b[..., 1], 0)
    union = area_a + area_b - inter
    iou = inter / jnp.maximum(union, 1e-9)
    cx1 = jnp.minimum(a[..., 0], b[..., 0])
    cy1 = jnp.minimum(a[..., 1], b[..., 1])
    cx2 = jnp.maximum(a[..., 2], b[..., 2])
    cy2 = jnp.maximum(a[..., 3], b[..., 3])
    hull = jnp.clip(cx2 - cx1, 0) * jnp.clip(cy2 - cy1, 0)
    return iou - (hull - union) / jnp.maximum(hull, 1e-9)


def _assign(pts, strides, gt_boxes, gt_labels, gt_mask, num_classes):
    """Center-sampling assignment (TAL-lite, fully static shapes):
    an anchor is positive for the closest valid GT whose box contains it
    AND whose center is within 2.5 strides. Returns (cls_target [A, C],
    box_target [A, 4], pos_mask [A]) per image."""
    x, y = pts[:, 0], pts[:, 1]
    inside = ((x[:, None] >= gt_boxes[None, :, 0])
              & (x[:, None] <= gt_boxes[None, :, 2])
              & (y[:, None] >= gt_boxes[None, :, 1])
              & (y[:, None] <= gt_boxes[None, :, 3]))
    cx = (gt_boxes[:, 0] + gt_boxes[:, 2]) / 2
    cy = (gt_boxes[:, 1] + gt_boxes[:, 3]) / 2
    dist = jnp.hypot(x[:, None] - cx[None, :], y[:, None] - cy[None, :])
    near = dist <= 2.5 * strides[:, None]
    cand = inside & near & gt_mask[None, :]
    dist = jnp.where(cand, dist, jnp.inf)
    best = jnp.argmin(dist, axis=1)                  # [A]
    pos = jnp.isfinite(jnp.min(dist, axis=1))
    box_t = gt_boxes[best]
    cls_t = jax.nn.one_hot(gt_labels[best], num_classes) \
        * pos[:, None].astype(jnp.float32)
    return cls_t, box_t, pos


def _varifocal(cls_logits, cls_target, alpha=0.75, gamma=2.0):
    p = jax.nn.sigmoid(cls_logits)
    # IoU-aware targets: weight positives by target score, negatives by
    # alpha * p^gamma (reference ppyoloe varifocal loss)
    weight = jnp.where(cls_target > 0, cls_target,
                       alpha * jnp.power(p, gamma))
    ce = (jnp.maximum(cls_logits, 0) - cls_logits * cls_target
          + jnp.log1p(jnp.exp(-jnp.abs(cls_logits))))
    return (ce * weight).sum()


def ppyoloe_loss(outputs, gt_boxes, gt_labels, gt_mask):
    """Compiled detection loss. gt_* are fixed-size padded tensors:
    gt_boxes [b, M, 4] xyxy, gt_labels [b, M] int, gt_mask [b, M] bool."""
    cls_logits, reg_logits, pts, strides = outputs
    cl = cls_logits._value if isinstance(cls_logits, Tensor) else cls_logits
    rl = reg_logits._value if isinstance(reg_logits, Tensor) else reg_logits
    pv = pts._value if isinstance(pts, Tensor) else pts
    sv = strides._value if isinstance(strides, Tensor) else strides
    num_classes = cl.shape[-1]
    reg_max = rl.shape[-1] // 4 - 1

    assign = jax.vmap(lambda b_, l_, m_: _assign(pv, sv, b_, l_, m_,
                                                 num_classes))
    cls_t, box_t, pos = assign(gt_boxes, gt_labels, gt_mask)

    boxes = _decode_boxes(rl, pv, sv, reg_max)
    giou = _giou(boxes, box_t)
    n_pos = jnp.maximum(pos.sum(), 1.0)
    loss_box = (jnp.where(pos, 1.0 - giou, 0.0)).sum() / n_pos

    # IoU-aware cls target (varifocal): positive weight = detached IoU
    iou_w = jax.lax.stop_gradient(jnp.clip((giou + 1) / 2, 0, 1))
    loss_cls = _varifocal(cl.astype(jnp.float32),
                          cls_t * iou_w[..., None]) / n_pos

    # DFL: distances to the assigned box, per-side cross-entropy on the
    # two neighboring bins
    d_t = jnp.stack([pv[None, :, 0] - box_t[..., 0],
                     pv[None, :, 1] - box_t[..., 1],
                     box_t[..., 2] - pv[None, :, 0],
                     box_t[..., 3] - pv[None, :, 1]], -1) / sv[None, :, None]
    d_t = jnp.clip(d_t, 0, reg_max - 0.01)
    lo = jnp.floor(d_t)
    hi = lo + 1
    w_hi = d_t - lo
    logits = rl.reshape(rl.shape[:-1] + (4, reg_max + 1)).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, -1)
    pick = lambda idx: jnp.take_along_axis(
        logp, idx[..., None].astype(jnp.int32), -1)[..., 0]
    dfl = -(pick(lo) * (1 - w_hi) + pick(hi) * w_hi)
    loss_dfl = (jnp.where(pos[..., None], dfl, 0.0)).sum() / (4 * n_pos)

    total = loss_cls + 2.5 * loss_box + 0.5 * loss_dfl
    return total, {"cls": loss_cls, "box": loss_box, "dfl": loss_dfl}


def decode_predictions(outputs, score_threshold=0.05, nms_threshold=0.6,
                       keep_top_k=100):
    """Inference post-process through the registered multiclass_nms3 op
    (the reference's deploy path: yolo_box + multiclass_nms kernels)."""
    from ..ops.registry import dispatch

    cls_logits, reg_logits, pts, strides = outputs
    cl = cls_logits._value
    rl = reg_logits._value
    reg_max = rl.shape[-1] // 4 - 1
    boxes = _decode_boxes(rl, pts._value, strides._value, reg_max)
    scores = jax.nn.sigmoid(cl.astype(jnp.float32))
    return dispatch("multiclass_nms3", Tensor(boxes),
                    Tensor(jnp.swapaxes(scores, 1, 2)),  # [b, C, A]
                    score_threshold=score_threshold,
                    nms_threshold=nms_threshold, keep_top_k=keep_top_k)
