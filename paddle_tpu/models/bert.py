"""BERT/ERNIE encoder family — the north-star config-2 model
(BASELINE.md #2: "ERNIE-3.0 / BERT-base fine-tune, data-parallel").

Reference analogs: the transformer encoder stack the reference builds its
ERNIE/BERT models from (python/paddle/nn/layer/transformer.py
TransformerEncoder; model zoo lives in PaddleNLP, the capability here is
the framework-side encoder + heads + a compiled DP fine-tune step).

TPU-first: the eager Layer graph is also runnable as one jitted train
step (``build_bert_train_step``) with the batch sharded over the mesh's
data axes — DP via GSPMD, no hand-written allreduce.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn import (Dropout, Embedding, GELU, Layer, LayerList, LayerNorm,
                  Linear, Tanh, TransformerEncoder, TransformerEncoderLayer)

__all__ = ["BertConfig", "BertModel", "BertForSequenceClassification",
           "BertForMaskedLM", "build_bert_train_step"]


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-12

    @staticmethod
    def base():
        return BertConfig()

    @staticmethod
    def debug(vocab=97, hidden=32, layers=2, heads=2, inter=64, max_pos=64):
        return BertConfig(vocab_size=vocab, hidden_size=hidden,
                          num_hidden_layers=layers, num_attention_heads=heads,
                          intermediate_size=inter,
                          max_position_embeddings=max_pos)


class _BertEmbeddings(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.word_embeddings = Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = Embedding(cfg.max_position_embeddings,
                                             cfg.hidden_size)
        self.token_type_embeddings = Embedding(cfg.type_vocab_size,
                                               cfg.hidden_size)
        self.layer_norm = LayerNorm(cfg.hidden_size,
                                    epsilon=cfg.layer_norm_eps)
        self.dropout = Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        b, s = input_ids.shape
        if position_ids is None:
            position_ids = Tensor(jnp.broadcast_to(jnp.arange(s), (b, s)))
        if token_type_ids is None:
            token_type_ids = Tensor(jnp.zeros((b, s), jnp.int32))
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(x))


class BertModel(Layer):
    """Embeddings -> TransformerEncoder -> (sequence_output, pooled)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = _BertEmbeddings(cfg)
        layer = TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_attention_heads, cfg.intermediate_size,
            dropout=cfg.hidden_dropout_prob, activation="gelu",
            normalize_before=False)
        self.encoder = TransformerEncoder(layer, cfg.num_hidden_layers)
        self.pooler = Linear(cfg.hidden_size, cfg.hidden_size)
        self.pooler_act = Tanh()

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        if attention_mask is not None:
            # [b, s] 1/0 -> additive [b, 1, 1, s]
            mv = attention_mask._value if isinstance(attention_mask, Tensor) \
                else jnp.asarray(attention_mask)
            add = jnp.where(mv[:, None, None, :].astype(bool), 0.0,
                            jnp.float32(-1e9))
            attention_mask = Tensor(add)
        seq = self.encoder(x, src_mask=attention_mask)
        pooled = self.pooler_act(self.pooler(seq[:, 0]))
        return seq, pooled


class BertForSequenceClassification(Layer):
    def __init__(self, cfg: BertConfig, num_classes: int = 2):
        super().__init__()
        self.bert = BertModel(cfg)
        self.dropout = Dropout(cfg.hidden_dropout_prob)
        self.classifier = Linear(cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids,
                              attention_mask=attention_mask)
        return self.classifier(self.dropout(pooled))


class BertForMaskedLM(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.bert = BertModel(cfg)
        self.transform = Linear(cfg.hidden_size, cfg.hidden_size)
        self.act = GELU()
        self.norm = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        # decoder tied to word embeddings (BERT convention)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        seq, _ = self.bert(input_ids, token_type_ids,
                           attention_mask=attention_mask)
        h = self.norm(self.act(self.transform(seq)))
        w = self.bert.embeddings.word_embeddings.weight
        return h.matmul(w.t())


def build_bert_train_step(model: BertForSequenceClassification, optimizer,
                          mesh=None, data_axes: Tuple[str, ...] = ("dp",),
                          dropout_seed: int = 0):
    """One donated jitted fine-tune step (config-2 path): batch sharded
    over the mesh's data axes, params replicated (plain DP — GSPMD emits
    the gradient all-reduce the reference's EagerReducer does by hand).

        step_fn(params, opt_state, step_no, lr, input_ids, labels,
                attention_mask=None) -> (loss, new_params, new_opt_state)

    Dropout is live and step-dependent: the framework generator's root
    key is swapped for a TRACED key derived from ``step_no`` during the
    trace, so every compiled step draws fresh masks (a trace-time host
    key would bake ONE mask into the executable)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..autograd import no_grad
    from ..ops import random as _random

    batch_sharding = None
    if mesh is not None:
        from ..parallel.specs import batch_partition_spec

        batch_sharding = NamedSharding(
            mesh, batch_partition_spec(mesh, data_axes))

    def loss_fn(params, input_ids, labels, attention_mask, rng_key):
        gen = _random.default_generator()
        saved = gen._root, gen._counter
        gen._root, gen._counter = rng_key, 0
        try:
            with no_grad():
                mask_t = None if attention_mask is None \
                    else Tensor(attention_mask)
                logits = model.functional_call(params, Tensor(input_ids),
                                               attention_mask=mask_t)
        finally:
            gen._root, gen._counter = saved
        lv = logits._value.astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(lv, axis=-1)
        gold = jnp.take_along_axis(lv, labels[:, None], axis=-1)[:, 0]
        return (lse - gold).mean()

    grad_fn = jax.value_and_grad(loss_fn)

    def step_fn(params, opt_state, step_no, lr, input_ids, labels,
                attention_mask=None):
        if batch_sharding is not None:
            input_ids = jax.lax.with_sharding_constraint(input_ids,
                                                         batch_sharding)
            labels = jax.lax.with_sharding_constraint(labels, batch_sharding)
            if attention_mask is not None:
                attention_mask = jax.lax.with_sharding_constraint(
                    attention_mask, batch_sharding)
        rng = jax.random.fold_in(jax.random.PRNGKey(dropout_seed), step_no)
        loss, grads = grad_fn(params, input_ids, labels, attention_mask, rng)
        new_params, new_state = optimizer.apply(params, grads, opt_state, lr,
                                                step_no + 1)
        return loss, new_params, new_state

    return jax.jit(step_fn, donate_argnums=(0, 1))
