"""The composed hybrid-parallel Llama train step: pp x dp x sharding x sep
x mp in ONE jitted program.

Capability analog of the reference's full Fleet hybrid runtime — one model
trained simultaneously under pipeline parallelism
(fleet/meta_parallel/pipeline_parallel.py:547), data parallelism + sharded
optimizer states, segment/sequence parallelism (segment_parallel.py,
topology.py:503 get_sep_*) and Megatron tensor parallelism (mp_layers.py)
over the 5-axis HybridCommunicateGroup (topology.py:189).

TPU-first composition (no actor runtime, no per-rank branching code):

- ``pp`` and ``sep`` are MANUAL mesh axes inside one
  ``jax.shard_map(..., axis_names={"pp","sep"})`` region: pipeline-stage
  advance is one ``lax.ppermute`` per tick (GPipe dataflow; XLA reverses
  the statically-bounded loop for backward), and sequence parallelism is
  the Ulysses alltoall pair (seq<->heads) or an exact ring schedule around
  flash attention.
- ``dp``/``sharding``/``mp`` stay AUTO (GSPMD): per-layer weights are
  stacked layer-major ([L, ...] leaves, dim 0 sharded over pp) with their
  remaining dims carrying the same FSDP('sharding') x TP('mp') placements
  as the single-program plan (LLAMA_SHARDING_PLAN); XLA inserts the
  Megatron collectives inside each pipeline tick.
- Embedding, final norm, LM head and the streaming fp32 cross-entropy run
  OUTSIDE the manual region in plain GSPMD land; their gradients flow
  through the shard_map boundary (ppermute/alltoall transpose rules), so
  tied/untied embeddings train correctly — no special-cased first/last
  pipeline stage.

The decoder-layer math here is the functional twin of
``models/llama.py`` (LlamaAttention/LlamaMLP/LlamaRMSNorm, which follow
incubate/nn/fused.py) — kept expression-for-expression identical so the
pp=1 GSPMD step and this pipelined step agree to float tolerance
(tests/test_llama_hybrid.py parity).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .llama import (LlamaConfig, LLAMA_SHARDING_PLAN, plan_spec_for,
                    _filter_spec_to_mesh, _gold_logit, _rope_tables)
from ..parallel import compat as _compat
from ..parallel.pipelining import pipeline_apply
from ..parallel.sep import ulysses_attention
from ..parallel.ring_attention import ring_flash_attention

HYBRID_AXES = ("pp", "dp", "sharding", "sep", "mp")

_LAYER_PREFIX = "model.layers."


from ..common.jax_compat import axis_size as _axis_size

def hybrid_mesh(devices, pp=1, dp=1, sharding=1, sep=1, mp=1) -> Mesh:
    """Build the 5-axis hybrid mesh (reference: topology.py:189 order
    pp->dp->sharding->sep->mp, outermost..innermost so mp rides the
    fastest-varying / closest ICI neighbours)."""
    n = pp * dp * sharding * sep * mp
    grid = np.asarray(devices[:n], dtype=object).reshape(pp, dp, sharding,
                                                         sep, mp)
    return Mesh(grid, axis_names=HYBRID_AXES)


# --------------------------------------------------------------------------
# state layout: layer-major stacking
# --------------------------------------------------------------------------

def stack_llama_state(state: Dict[str, Any], num_layers: int
                      ) -> Dict[str, Any]:
    """Collapse per-layer params ``model.layers.{i}.X`` into layer-major
    stacks ``model.layers.X`` with leading dim [L].  Sharding dim 0 over
    ``pp`` then gives pipeline stage s the contiguous layer block
    [s*L/P, (s+1)*L/P) — the reference's segment_parallel layer split
    (fleet/meta_parallel/parallel_layers/pp_layers.py segment methods)."""
    out: Dict[str, Any] = {}
    per_layer: Dict[str, list] = {}
    for k, v in state.items():
        if k.startswith(_LAYER_PREFIX):
            rest = k[len(_LAYER_PREFIX):]
            idx, suffix = rest.split(".", 1)
            per_layer.setdefault(suffix, [None] * num_layers)[int(idx)] = v
        else:
            out[k] = v
    for suffix, vals in per_layer.items():
        assert all(v is not None for v in vals), f"missing layers for {suffix}"
        out[_LAYER_PREFIX + suffix] = jnp.stack(
            [jnp.asarray(v) for v in vals], axis=0)
    return out


def unstack_llama_state(hstate: Dict[str, Any], num_layers: int
                        ) -> Dict[str, Any]:
    """Inverse of stack_llama_state (checkpoint interop / parity tests)."""
    out: Dict[str, Any] = {}
    for k, v in hstate.items():
        if k.startswith(_LAYER_PREFIX) and "." in k[len(_LAYER_PREFIX):] \
                and not k[len(_LAYER_PREFIX):].split(".", 1)[0].isdigit():
            suffix = k[len(_LAYER_PREFIX):]
            for i in range(num_layers):
                out[f"{_LAYER_PREFIX}{i}.{suffix}"] = v[i]
        else:
            out[k] = v
    return out


def hybrid_param_spec(name: str, shape: Tuple[int, ...], mesh: Mesh,
                      plan: Optional[Dict[str, P]] = None) -> P:
    """At-rest PartitionSpec of ONE hybrid-state leaf — the placement
    rule of ``shard_hybrid_state``, exposed as a pure shape-level hook
    so the Sharding Doctor's extractor can read this stack's canonical
    layout without materializing state.  Since round 19 the rule
    itself lives in the schedule layer
    (``parallel.schedule.hybrid_leaf_spec`` — the pp tactic's stacking
    rule, shared with ``PartitionSchedule.hybrid_spec``); this hook
    only binds the llama plan."""
    from ..parallel.schedule import hybrid_leaf_spec

    return hybrid_leaf_spec(name, shape, mesh,
                            lambda n: plan_spec_for(n, plan))


def shard_hybrid_state(hstate: Dict[str, Any], mesh: Mesh,
                       plan: Optional[Dict[str, P]] = None) -> Dict[str, Any]:
    """Place the stacked state on the hybrid mesh per
    ``hybrid_param_spec`` (single copy of the placement rule — the
    extractor reads the same hook)."""
    return {
        name: jax.device_put(
            v, NamedSharding(mesh,
                             hybrid_param_spec(name, tuple(v.shape), mesh,
                                               plan)))
        for name, v in hstate.items()}


def init_hybrid_state(model, mesh: Mesh) -> Dict[str, Any]:
    """model (LlamaForCausalLM) -> stacked+sharded hybrid param dict."""
    return shard_hybrid_state(
        stack_llama_state(model.functional_state(),
                          model.cfg.num_hidden_layers),
        mesh)


# --------------------------------------------------------------------------
# functional decoder layer (expression-identical to models/llama.py)
# --------------------------------------------------------------------------

# raw-array twins of the fused ops (same functions models/llama.py runs
# through dispatch) — shared so the math cannot drift from the pp=1 path
from ..incubate.nn.fused import _fused_rms_norm_op, _rope_rotate_half

_rms_norm_raw = _fused_rms_norm_op.raw_fn


def _rms_norm(x, w, eps):
    return _rms_norm_raw(x, w, epsilon=eps)


_rotate_half = _rope_rotate_half


def _decoder_layer(lp: Dict[str, Any], x, cos, sin, cfg: LlamaConfig,
                   sep_axis: Optional[str], sep_attn: str):
    """One decoder layer on raw arrays inside the manual region.

    x: [mb, s_local, h]; cos/sin: [s_local, head_dim] (this sep-rank's
    position slice); lp: this layer's params keyed by intra-layer suffix.
    """
    nh, nkv, hd = (cfg.num_attention_heads, cfg.num_key_value_heads,
                   cfg.head_dim)
    b, sl, _ = x.shape
    h = _rms_norm(x, lp["input_layernorm.weight"], cfg.rms_norm_eps)
    q = (h @ lp["self_attn.q_proj.weight"]).reshape(b, sl, nh, hd)
    k = (h @ lp["self_attn.k_proj.weight"]).reshape(b, sl, nkv, hd)
    v = (h @ lp["self_attn.v_proj.weight"]).reshape(b, sl, nkv, hd)
    cos_b = cos[None, :, None, :]
    sin_b = sin[None, :, None, :]
    q = q * cos_b + _rotate_half(q) * sin_b
    k = k * cos_b + _rotate_half(k) * sin_b
    if sep_axis is None:
        from ..ops.pallas.flash_attention import flash_attention_raw

        attn = flash_attention_raw(q, k, v, causal=True)
    elif sep_attn == "ring":
        attn = ring_flash_attention(q, k, v, axis=sep_axis, causal=True)
    else:
        attn = ulysses_attention(q, k, v, axis=sep_axis, causal=True)
    attn = attn.astype(x.dtype).reshape(b, sl, nh * hd)
    # residual-stream saveable tags (parallel/memory.SAVEABLE_NAMES):
    # the named remat policies select/offload these on the hybrid path
    # exactly as on the GSPMD and overlap stacks
    from ..parallel.memory import tag_saveable

    x = x + tag_saveable(attn @ lp["self_attn.o_proj.weight"],
                         "decoder_attn_out")
    h2 = _rms_norm(x, lp["post_attention_layernorm.weight"],
                   cfg.rms_norm_eps)
    gate = h2 @ lp["mlp.gate_proj.weight"]
    up = h2 @ lp["mlp.up_proj.weight"]
    return x + tag_saveable((jax.nn.silu(gate) * up)
                            @ lp["mlp.down_proj.weight"],
                            "decoder_mlp_out")


# --------------------------------------------------------------------------
# the composed train step
# --------------------------------------------------------------------------

def build_hybrid_train_step(cfg: LlamaConfig, optimizer, mesh: Mesh,
                            num_microbatches: int = 1,
                            compute_dtype=jnp.bfloat16,
                            remat=False,
                            sep_attn: str = "ulysses",
                            schedule: str = "gpipe",
                            virtual_chunks: int = 1,
                            data_axes: Tuple[str, ...] = ("dp", "sharding"),
                            cpu_bf16: str = "promote",
                            overlap=None, health=None):
    """Build the fully-composed hybrid train step:

        step(params, opt_state, step_no, lr, input_ids, labels)
            -> (loss, new_params, new_opt_state)

    ``params`` is the stacked+sharded dict from ``init_hybrid_state``.
    input_ids/labels: [B, S] with B divisible by num_microbatches (and by
    the data-axes degrees), S by the sep degree.  The mesh must carry all
    of HYBRID_AXES (degree 1 axes are fine — ppermute/alltoall over a
    size-1 axis are no-ops, so the same program serves every composition).

    ``schedule`` selects the pipeline runtime:

    - ``"gpipe"`` (default): differentiable dataflow — jax.grad reverses
      the statically-bounded tick loop; memory holds all m micro
      activations.
    - ``"1F1B"`` / ``"ZBH1"`` / ``"FThenB"``: the schedule-explicit
      executor (parallel/pipelining.pipeline_train_step) with the static
      tables from parallel/schedules.py — backward interleaves with
      forward per the table (1F1B's min(p, m) activation bound; ZBH1's
      dx/dw split filling bubbles), grads computed in-schedule, and the
      embedding/LM-head outside the pipeline get their gradients through
      the executor's x-grad / loss-params channels.

    Round-9: both region bodies are FULL-manual (every mesh axis in
    ``axis_names``) — 'sharding' is handled by the overlap engine's
    explicit ZeRO-3 bucket gathers (per-layer with prefetch on the
    gpipe path; once per step at region entry on the schedule-explicit
    path, whose divergent per-rank branches cannot host per-layer
    collectives) and 'mp' by the TP-manual decoder layer
    (parallel/overlap.decoder_layer_tp, collective-matmul dispatcher
    included).  This retires the jax-0.4.x partial-manual shard_map gap:
    no auto axis of degree > 1 remains inside either region, so the
    PartitionId lowering the 0.4.37 SPMD partitioner rejects is never
    emitted.  ``overlap`` (an overlap.OverlapConfig) tunes the engine;
    None uses the defaults.

    Round-10: ``remat`` also accepts a NAMED policy string (``none |
    dots | names | offload | full``) or a ``parallel.memory.
    MemoryConfig`` — resolved through the HBM memory engine's single
    translation point, so the hybrid stack honors the same
    checkpoint_name-tagged saveable set as the GSPMD/overlap paths.
    """
    from ..parallel import overlap as _ov
    from ..parallel.memory import MemoryConfig as _MemCfg

    remat_policy = None
    if isinstance(remat, _MemCfg):
        remat, remat_policy = remat.resolve_remat()
    elif isinstance(remat, str):
        remat, remat_policy = _MemCfg(remat=remat).resolve_remat()
    pp_axis, sep_axis = "pp", "sep"
    for ax in HYBRID_AXES:
        if ax not in mesh.axis_names:
            raise ValueError(f"hybrid mesh must carry axis {ax!r}")
    fp32_wire = False
    if compute_dtype == jnp.bfloat16 and jax.default_backend() == "cpu":
        # XLA:CPU's AllReducePromotion pass aborts ("Invalid binary
        # instruction opcode copy") cloning any shardy-emitted bf16
        # all-reduce (the reduction region is rooted at a Sharding
        # custom-call CreateBinary can't clone); TPU handles bf16
        # collectives natively.  Two CPU modes:
        # - "promote" (default): whole program fp32 — safe everywhere.
        # - "fp32-wire": COMPUTE stays genuinely bf16; only the
        #   shard_map boundary values and the manual collectives
        #   (parallel/compat.py) ride fp32 wires.  This is the CI mode
        #   that exercises the same bf16 program the TPU runs; it
        #   cannot host auto-axis (mp/sharding) bf16 reductions, which
        #   the partitioner inserts out of our reach.
        if cpu_bf16 == "promote":
            compute_dtype = jnp.float32
        elif cpu_bf16 == "fp32-wire":
            fp32_wire = True
            if mesh.shape["mp"] > 1 or mesh.shape["sharding"] > 1:
                raise NotImplementedError(
                    "cpu_bf16='fp32-wire' supports manual-axis "
                    "compositions (pp/sep, and dp on the schedule-"
                    "explicit path); mp/sharding insert auto bf16 "
                    "reductions that crash XLA:CPU — use "
                    "cpu_bf16='promote' for those meshes")
            if mesh.shape["dp"] > 1 and schedule.lower() == "gpipe":
                # on the gpipe path dp is an AUTO axis: the outer
                # jax.grad makes the partitioner insert a bf16 grad
                # all-reduce over dp — the same crash.  dp is manual
                # (and safe) only on the schedule-explicit path.
                raise NotImplementedError(
                    "cpu_bf16='fp32-wire' with dp>1 needs the "
                    "schedule-explicit path (schedule='1F1B'/'ZBH1'), "
                    "where dp is a manual axis; gpipe's auto-dp grad "
                    "reduction is bf16 and crashes XLA:CPU")
        else:
            raise ValueError(f"unknown cpu_bf16 mode {cpu_bf16!r}")

    def _wire_in(t):
        """bf16 -> fp32 at the shard_map boundary (cpu fp32-wire)."""
        return (t.astype(jnp.float32)
                if fp32_wire and t.dtype == jnp.bfloat16 else t)

    def _wire_body(t):
        """fp32 -> bf16 on entry into the manual region body."""
        return (t.astype(jnp.bfloat16)
                if fp32_wire and t.dtype == jnp.float32 else t)
    L = cfg.num_hidden_layers
    pp = mesh.shape[pp_axis]
    sep = mesh.shape[sep_axis]
    if L % pp:
        raise ValueError(f"{L} layers not divisible by pp={pp}")
    m = num_microbatches

    batch_axes = tuple(a for a in data_axes
                       if a in mesh.axis_names and mesh.shape[a] > 1)
    sep_entry = sep_axis if sep > 1 else None

    # ---- round-9 full-manual machinery (parallel/overlap.py) ----
    oc = overlap if overlap is not None else _ov.OverlapConfig()
    sh_deg = int(mesh.shape["sharding"])
    mp_deg = int(mesh.shape["mp"])
    sh_ax = "sharding" if sh_deg > 1 else None
    mp_ax = "mp" if mp_deg > 1 else None
    hier = oc.resolve_hier(mesh, sh_ax)
    # quantized-DCN codec: only with a resolved hierarchical axis (the
    # quantize-across-DCN placement rule, overlap.py docstring §5)
    codec = oc.codec if hier is not None else None
    shapes = _ov.llama_layer_shapes(cfg)
    layout = _ov.plan_layer_layout(
        shapes, mesh, lambda sfx: _filter_spec_to_mesh(
            plan_spec_for(sfx), mesh))
    suffix_order = sorted(shapes)
    manual_axes = set(HYBRID_AXES)
    if sep > 1:
        def _sep_gqa(q, k, v):
            """With mp-manual head splitting the LOCAL kv-head count can
            drop below the sep degree; repeating kv heads up to the q
            grouping is exact GQA semantics (each q head keeps its own
            kv group) and restores ulysses' head-divisibility."""
            if k.shape[2] % sep:
                rep = q.shape[2] // k.shape[2]
                k = jnp.repeat(k, rep, axis=2)
                v = jnp.repeat(v, rep, axis=2)
            return q, k, v

        if sep_attn == "ring":
            def attn_fn(q, k, v):
                q, k, v = _sep_gqa(q, k, v)
                return ring_flash_attention(q, k, v, axis=sep_axis,
                                            causal=True)
        else:
            def attn_fn(q, k, v):
                q, k, v = _sep_gqa(q, k, v)
                return ulysses_attention(q, k, v, axis=sep_axis,
                                         causal=True)
    else:
        attn_fn = None

    def _split(params):
        stacked = {k[len(_LAYER_PREFIX):]: v for k, v in params.items()
                   if k.startswith(_LAYER_PREFIX)}
        outer = {k: v for k, v in params.items()
                 if not k.startswith(_LAYER_PREFIX)}
        return outer, stacked

    cos_full, sin_full = _rope_tables(cfg.head_dim,
                                      cfg.max_position_embeddings,
                                      cfg.rope_theta)

    from ..common.jax_compat import shard_map as _shard_map

    stacked_in_specs = {
        sfx: _ov.leaf_partition_spec(layout[sfx], lead="pp")
        for sfx in suffix_order}

    _gpipe_cache: Dict[Tuple[str, ...], Any] = {}

    def _gpipe_shmap(batch_axes_used: Tuple[str, ...]):
        """Full-manual GPipe region for one batch-axes choice (the
        micro-batch dim must tile EXACTLY over manual axes, so the axes
        actually used depend on the call's shapes — cached per choice).
        """
        if batch_axes_used in _gpipe_cache:
            return _gpipe_cache[batch_axes_used]
        batch_entry = (batch_axes_used if len(batch_axes_used) > 1 else
                       (batch_axes_used[0] if batch_axes_used else None))
        seq_axes = (sep_axis,) if sep > 1 else ()
        # gather-bucket backward: reduce-scatter folds the 'sharding'
        # sum; the remaining batch-partial axes psum the residue
        gather_psum = tuple(a for a in batch_axes_used
                            if a != "sharding") + seq_axes
        # replicated (non-gathered) leaves are batch-partial over EVERY
        # batch/seq axis
        sync_axes = tuple(batch_axes_used) + seq_axes
        grad_mode = "scatter" if "sharding" in batch_axes_used else "slice"
        itemsize = jnp.dtype(jnp.float32 if fp32_wire
                             else compute_dtype).itemsize
        buckets = _ov.plan_buckets(layout, suffix_order, sh_deg, mp_deg,
                                   oc.bucket_bytes, itemsize)
        in_bucket = {s for b in buckets for s in b}
        sync_sfx = [s for s in suffix_order if s not in in_bucket]
        gather_fns = [_ov.make_bucket_gather(sh_ax, hier, gather_psum,
                                             grad_mode, codec=codec)
                      for _ in buckets]
        sync_fn = _ov.make_grad_sync(sync_axes, hier_axis=sh_ax,
                                     hier=hier, codec=codec)
        # x is replicated over pp (only stage 0 consumes it; the other
        # ranks' cotangents are zero) and over mp (column-parallel
        # backward emits PARTIAL x-cotangents per mp rank)
        x_sync = _ov.make_grad_sync(tuple(
            a for a, d in ((pp_axis, pp), ("mp", mp_deg)) if d > 1))

        def pipeline_body(stacked, x, cos, sin):
            """FULL-manual region over all five axes.  stacked leaves:
            [L/pp, *zero3/tp-local]; x: [m, mb_local, s_local, hidden];
            cos/sin: [s_local, head_dim]."""
            stacked = jax.tree_util.tree_map(_wire_body, stacked)
            x, cos, sin = _wire_body(x), _wire_body(cos), _wire_body(sin)
            x = x_sync(x)

            def layer_fn(lp, act):
                return _ov.decoder_layer_tp(lp, act, cos, sin, cfg,
                                            mp_ax, oc, attn_fn=attn_fn)

            def stage_fn(stage_params, act):
                xs_buckets = [_ov._pack_bucket(stage_params, b)
                              for b in buckets]
                if sync_sfx:
                    xs_sync = _ov._pack_bucket(stage_params, sync_sfx)
                else:
                    Lloc = next(iter(stage_params.values())).shape[0]
                    xs_sync = jnp.zeros((Lloc, 0), x.dtype)
                return _ov.gathered_layer_scan(
                    layer_fn, xs_buckets, xs_sync, act, buckets,
                    sync_sfx, layout, sh_deg, mp_deg, gather_fns,
                    sync_fn, oc, remat=remat,
                    remat_policy=remat_policy)

            outs = pipeline_apply(stage_fn, stacked, x, axis=pp_axis,
                                  squeeze_stage_dim=False)
            # only the last stage holds real outputs; broadcast across
            # pp so every rank returns the valid batch shard
            is_last = (lax.axis_index(pp_axis)
                       == _axis_size(pp_axis) - 1).astype(outs.dtype)
            return _wire_in(_compat.psum(outs * is_last, pp_axis))

        sm = _shard_map(
            pipeline_body, mesh=mesh, axis_names=manual_axes,
            in_specs=(stacked_in_specs,
                      P(None, batch_entry, sep_entry, None),
                      P(sep_entry, None), P(sep_entry, None)),
            out_specs=P(None, batch_entry, sep_entry, None),
            check_vma=False)
        _gpipe_cache[batch_axes_used] = (sm, batch_entry)
        return sm, batch_entry

    def _pick_batch_axes(mb: int) -> Tuple[str, ...]:
        """Largest data_axes prefix whose degree product tiles mb
        exactly (manual in_specs demand exact tiling; 'sharding' drops
        first and falls back to a weights-only axis).  Single copy of
        the rule: parallel.specs.pick_batch_axes."""
        from ..parallel.specs import pick_batch_axes

        return pick_batch_axes(mesh, batch_axes, mb)

    # ---- schedule-explicit runtime (1F1B / ZBH1 / FThenB) ----
    sched = None
    if schedule.lower() == "gpipe":
        if int(virtual_chunks) > 1:
            raise ValueError(
                "virtual_chunks > 1 needs a schedule-explicit runtime "
                "(schedule='VPP'); the gpipe dataflow has no interleaved "
                "placement")
    else:
        if cfg.tie_word_embeddings:
            raise NotImplementedError(
                "schedule-explicit hybrid needs an untied lm_head (the "
                "embedding lives outside the pipeline)")
        # dp composes as a MANUAL axis here: batch dims must not be
        # sharded over AUTO axes inside the executor (its per-rank
        # lax.switch branches diverge across pp rows, and GSPMD-inserted
        # batch collectives inside those branches deadlock the
        # collective rendezvous — XLA:CPU reproduces it
        # deterministically).  Instead the batch is split over dp
        # manually, each dp rank runs the schedule on its shard, and the
        # micro-batch grads are psum'ed over dp AT SCHEDULE END —
        # uniform across ranks, outside the divergent branches (the
        # fused_allreduce_gradients analog,
        # fleet/utils/hybrid_parallel_util.py:249).
        from ..parallel.pipelining import pipeline_train_step
        from ..parallel.schedules import build_schedule

        vch = max(int(virtual_chunks), 1)
        if schedule.upper() == "ZBV" and vch == 1:
            vch = 2              # ZBV's two-chunk zigzag is intrinsic
        if L % (pp * vch):
            raise ValueError(
                f"{L} layers not divisible by pp*virtual_chunks = "
                f"{pp}*{vch}")
        sched = build_schedule(schedule, p=pp, m=m, v=vch)
        # chunk placement (single source of truth: the schedule's
        # stage_of — Megatron-interleaved for VPP, zigzag for ZBV),
        # applied here to layer-BLOCKS instead of per-stage param lists
        from ..parallel.pipelining import device_major_order

        _vpp_order, _vpp_inv = device_major_order(sched)

    dpd = mesh.shape["dp"]
    dp_entry = "dp" if dpd > 1 else None
    chunk_specs = {sfx: _ov.chunk_leaf_spec(layout[sfx])
                   for sfx in suffix_order}

    def pipeline_body_sched(chunked, x, y, cos, sin, head_params):
        """chunked leaves arrive [v, L/(pp*v), *zero3/tp-local] per rank
        (v=1 for 1F1B/ZBH1; VPP device-major chunks otherwise); x
        [m, mb_local, s_local, h] (mb split over manual dp); y
        [m, mb_local, s_local]; head_params = final norm + LM head
        (replicated in-region; grads via the loss-params channel).

        FULL-manual: the sharded chunk leaves are bucket-gathered over
        'sharding' ONCE at region entry (the executor's per-rank
        lax.switch branches cannot host per-layer collectives — the
        per-layer prefetch lives on the gpipe path), mp runs TP-manual
        inside the stages, and the executor's grads are sliced back to
        each rank's shard at region exit (batch does not ride 'sharding'
        here, so every rank computes the identical full gradient)."""
        chunked = jax.tree_util.tree_map(_wire_body, chunked)
        head_params = jax.tree_util.tree_map(_wire_body, head_params)
        x, cos, sin = _wire_body(x), _wire_body(cos), _wire_body(sin)
        chunked_full = _ov.gather_tree_over_sharding(
            chunked, layout, lead_ndim=2, sh=sh_deg, mp=mp_deg,
            axis=sh_ax, hier=hier, bucket_bytes=oc.bucket_bytes,
            codec=codec)

        def layer_step(h, lp):
            return _ov.decoder_layer_tp(lp, h, cos, sin, cfg, mp_ax,
                                        oc, attn_fn=attn_fn), None

        wrapped_step = jax.checkpoint(layer_step, policy=remat_policy) \
            if remat else layer_step

        def stage_fn(chunk, act):
            act, _ = lax.scan(wrapped_step, act, chunk)
            return act

        def loss_fn(lp, act, y_mb):
            h = _rms_norm(act, lp["norm"], cfg.rms_norm_eps)
            logits = h @ lp["head"]
            lse = jax.scipy.special.logsumexp(
                logits.astype(jnp.float32), axis=-1)
            gold = _gold_logit(logits, y_mb)
            # local-token mean / (sep*dp) degree: summed over sep+dp
            # below, this is the GLOBAL token mean (equal shard sizes)
            return (lse - gold).mean() / (sep * dpd)

        loss, sgrads, hgrads, dxs = pipeline_train_step(
            stage_fn, loss_fn, sched, chunked_full, x, y, axis=pp_axis,
            loss_params=head_params, want_x_grad=True)
        reduce_axes = tuple(ax for ax, deg in ((sep_axis, sep),
                                               ("dp", dpd)) if deg > 1)
        if reduce_axes:
            # uniform across ranks, AFTER the divergent schedule — the
            # manual-dp grad allreduce (and the sep grad reduction)
            loss = _compat.psum(loss, reduce_axes)
            sgrads = jax.tree_util.tree_map(
                lambda a: _compat.psum(a, reduce_axes), sgrads)
            hgrads = jax.tree_util.tree_map(
                lambda a: _compat.psum(a, reduce_axes), hgrads)
        # executor grads are w.r.t. the GATHERED chunk; keep this rank's
        # shard (identical full grads across 'sharding' — see docstring)
        sgrads = _ov.slice_tree_own_shard(sgrads, layout, lead_ndim=2,
                                          sh=sh_deg, axis=sh_ax)
        if mp_deg > 1:
            # column-parallel backward leaves the stage-0 input grads
            # PARTIAL per mp rank; stage ranks other than stage 0 hold
            # zeros, so the pp psum both completes and broadcasts them
            dxs = _compat.psum(dxs, "mp")
        if pp > 1:
            dxs = _compat.psum(dxs, pp_axis)
        sgrads = jax.tree_util.tree_map(_wire_in, sgrads)
        hgrads = jax.tree_util.tree_map(_wire_in, hgrads)
        return loss, sgrads, hgrads, _wire_in(dxs)

    shmap_sched = _shard_map(
        pipeline_body_sched, mesh=mesh, axis_names=manual_axes,
        in_specs=(chunk_specs, P(None, dp_entry, sep_entry, None),
                  P(None, dp_entry, sep_entry),
                  P(sep_entry, None), P(sep_entry, None), P()),
        out_specs=(P(), chunk_specs, P(),
                   P(None, dp_entry, sep_entry, None)),
        check_vma=False) if sched is not None else None

    def loss_fn(params, input_ids, labels):
        cast = _cast(params)
        outer, stacked = _split(cast)
        B, S = input_ids.shape
        mb = B // m
        shmap, batch_entry = _gpipe_shmap(_pick_batch_axes(mb))
        ids = input_ids.reshape(m, mb, S)
        # mode="clip": token ids are in-range by construction; the default
        # fill mode's bounds-check pred ops are extra reshard candidates
        # for the SPMD partitioner on hybrid meshes
        x = jnp.take(outer["model.embed_tokens.weight"], ids, axis=0,
                     mode="clip")
        from ..parallel.specs import microbatched

        x = lax.with_sharding_constraint(
            x, NamedSharding(mesh,
                             microbatched(batch_entry, sep_entry, None)))
        cos = cos_full[:S].astype(compute_dtype)
        sin = sin_full[:S].astype(compute_dtype)
        h = shmap(jax.tree_util.tree_map(_wire_in, stacked), _wire_in(x),
                  _wire_in(cos), _wire_in(sin))
        h = _wire_body(h)
        h = _rms_norm(h, outer["model.norm.weight"], cfg.rms_norm_eps)
        if cfg.tie_word_embeddings:
            logits = h @ outer["model.embed_tokens.weight"].T
        else:
            logits = h @ outer["lm_head.weight"]
        logits = lax.with_sharding_constraint(
            logits, NamedSharding(mesh, microbatched(batch_entry)))
        lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32),
                                          axis=-1)
        ylb = labels.reshape(m, mb, S)
        nll = lse - _gold_logit(logits, ylb)
        if batch_entry is not None:
            # pin the per-token nll to the batch layout BEFORE the mean:
            # without it GSPMD mixes the lse/gold operand shardings and
            # falls back to involuntary full rematerialization on the add
            nll = lax.with_sharding_constraint(
                nll, NamedSharding(mesh, microbatched(batch_entry)))
        return nll.mean()

    grad_fn = jax.value_and_grad(loss_fn)

    def _cast(params):
        return {k: (v.astype(compute_dtype)
                    if jnp.issubdtype(v.dtype, jnp.floating) else v)
                for k, v in params.items()}

    def _apply_optimizer(params, grads, opt_state, lr, step_no):
        """Single copy of the decay-mask rule + apply: the gpipe and
        schedule-explicit paths must not drift."""
        names = list(params.keys())  # trace-time only: retrace-safe
        no_decay = {n for n in names
                    if "layernorm" in n or n.endswith("norm.weight")
                    or n.endswith(".bias")}
        return optimizer.apply(
            params, grads, opt_state, lr, step_no + 1,
            decay_mask={n: n not in no_decay for n in names})

    def _finish(loss, grads, params, opt_state, lr, step_no,
                health_gates):
        """Shared optimizer tail of both schedule paths — and, with
        ``health``, the round-17 fused probe + in-step no-op guard
        (same contract as build_train_step: a fired gate passes params
        and optimizer state through bit-identically and the probe
        rides out as a 4th output)."""
        new_params, new_opt_state = _apply_optimizer(params, grads,
                                                     opt_state, lr,
                                                     step_no)
        if health is None:
            return loss, new_params, new_opt_state
        from ..distributed import health as _health

        return _health.probe_and_guard(loss, grads, params, opt_state,
                                       new_params, new_opt_state,
                                       health_gates, health)

    def step_fn(params, opt_state, step_no, lr, input_ids, labels,
                health_gates=None):
        outer_batch = (batch_axes if len(batch_axes) > 1
                       else (batch_axes[0] if batch_axes else None))
        if outer_batch is not None or sep_entry is not None:
            from ..parallel.specs import token_batch_spec

            bs = NamedSharding(mesh, token_batch_spec(outer_batch,
                                                      sep_entry))
            input_ids = lax.with_sharding_constraint(input_ids, bs)
            labels = lax.with_sharding_constraint(labels, bs)
        loss, grads = grad_fn(params, input_ids, labels)
        return _finish(loss, grads, params, opt_state, lr, step_no,
                       health_gates)

    def sched_step_fn(params, opt_state, step_no, lr, input_ids, labels,
                      health_gates=None):
        """Schedule-explicit train step: grads come from the executor's
        in-schedule vjps (stages), loss-params channel (norm + head) and
        x-grad channel (embedding), not from an outer jax.grad."""
        if sep_entry is not None or dp_entry is not None:
            # batch splits over MANUAL dp (and sep); 'sharding' stays a
            # weights-only (FSDP-at-rest) axis on this path
            from ..parallel.specs import token_batch_spec

            bs = NamedSharding(mesh, token_batch_spec(dp_entry,
                                                      sep_entry))
            input_ids = lax.with_sharding_constraint(input_ids, bs)
            labels = lax.with_sharding_constraint(labels, bs)
        cast = _cast(params)
        outer, stacked = _split(cast)
        B, S = input_ids.shape
        mb = B // m
        if mb % dpd:
            raise ValueError(
                f"micro-batch size {mb} not divisible by dp degree {dpd}")
        ids = input_ids.reshape(m, mb, S)
        y = labels.reshape(m, mb, S)

        def embed_fn(w):
            return jnp.take(w, ids, axis=0, mode="clip")

        x, embed_vjp = jax.vjp(embed_fn, outer["model.embed_tokens.weight"])
        from ..parallel.specs import microbatched

        x = lax.with_sharding_constraint(
            x, NamedSharding(mesh,
                             microbatched(dp_entry, sep_entry, None)))
        cos = cos_full[:S].astype(compute_dtype)
        sin = sin_full[:S].astype(compute_dtype)
        nstage = pp * sched.v

        def _to_chunks(a):
            # [L, ...] -> [nstage, L/nstage, ...] in VPP device-major
            # order, so sharding dim 0 over pp yields [v, blk, ...] per
            # rank with chunk j = global stage j*pp + rank
            blk = a.reshape((nstage, a.shape[0] // nstage) + a.shape[1:])
            return blk[jnp.asarray(_vpp_order)] if sched.v > 1 else blk

        chunked = jax.tree_util.tree_map(_to_chunks, stacked)
        head_params = {"norm": cast["model.norm.weight"],
                       "head": cast["lm_head.weight"]}
        loss, sgrads, hgrads, dxs = shmap_sched(
            jax.tree_util.tree_map(_wire_in, chunked), _wire_in(x), y,
            _wire_in(cos), _wire_in(sin),
            jax.tree_util.tree_map(_wire_in, head_params))
        (d_embed,) = embed_vjp(dxs.astype(x.dtype))
        grads = {}
        for suffix, g in sgrads.items():
            # [nstage(dev-major), blk, ...] -> stage order -> [L, ...]
            if sched.v > 1:
                g = g[jnp.asarray(_vpp_inv)]
            grads[_LAYER_PREFIX + suffix] = g.reshape((L,) + g.shape[2:])
        grads["model.norm.weight"] = hgrads["norm"]
        grads["lm_head.weight"] = hgrads["head"]
        grads["model.embed_tokens.weight"] = d_embed.astype(jnp.float32)
        return _finish(loss, grads, params, opt_state, lr, step_no,
                       health_gates)

    jstep = jax.jit(step_fn if sched is None else sched_step_fn,
                    donate_argnums=(0, 1))

    def step(params, opt_state, step_no, lr, input_ids, labels,
             health_gates=None):
        from ..common.jax_compat import set_mesh as _set_mesh

        kw = {}
        if health is not None:
            from ..distributed import health as _health

            kw["health_gates"] = _health.normalize_gates(health_gates)
        with _set_mesh(mesh):
            return jstep(params, opt_state, step_no, lr, input_ids,
                         labels, **kw)

    return step
