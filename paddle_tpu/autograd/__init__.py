"""Autograd public API.

Analog of python/paddle/autograd: ``backward``, ``grad``, ``no_grad``,
``PyLayer`` (paddle/fluid/eager/pylayer), hooks.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from . import tape
from .tape import enable_grad, is_grad_enabled, no_grad


def backward(tensors, grad_tensors=None, retain_graph=False):
    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is not None and not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]
    tape.run_backward(tensors, grad_tensors, retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False):
    """Analog of paddle.grad (partial-graph gradients without touching
    ``.grad`` — the reference's GeneralGrad path, fluid/eager/general_grad.h)."""
    from ..core.tensor import Tensor

    if create_graph:
        raise NotImplementedError(
            "create_graph=True (higher-order eager grad) is not supported; "
            "use the compiled path (paddle_tpu.jit) with jax-level autodiff."
        )
    if not isinstance(outputs, (list, tuple)):
        outputs = [outputs]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    if grad_outputs is not None and not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]
    if retain_graph is None:
        retain_graph = False

    captured = [None] * len(inputs)

    hooks_installed = []
    for i, t in enumerate(inputs):
        node, slot = t._grad_edge()
        if node is None:
            if not allow_unused:
                raise ValueError(f"input {i} has stop_gradient=True")
            continue

        def mk_hook(i, slot, is_leaf):
            if is_leaf:
                def leaf_hook(g):
                    captured[i] = g if captured[i] is None else captured[i] + g
                    return None
                return leaf_hook

            def node_hook(cotangents):
                g = cotangents[slot]
                if g is not None:
                    captured[i] = g if captured[i] is None else captured[i] + g
                return None
            return node_hook

        is_leaf = isinstance(node, tape.AccumulateNode)
        hook = mk_hook(i, slot, is_leaf)
        node.hooks.append(hook)
        hooks_installed.append((node, hook, is_leaf, t))

    try:
        # accumulate_to_leaf=False: capture hooks fire but no tensor's .grad
        # is touched (matches the reference's GeneralGrad partial-graph path)
        tape.run_backward(outputs, grad_outputs, retain_graph=retain_graph,
                          accumulate_to_leaf=False)
    finally:
        for node, hook, _, _ in hooks_installed:
            if hook in node.hooks:
                node.hooks.remove(hook)

    results = []
    for i, g in enumerate(captured):
        if g is None:
            if not allow_unused and inputs[i]._grad_edge()[0] is not None:
                # unreached input: return zeros to match reference behavior
                import jax.numpy as jnp

                g = jnp.zeros(tuple(inputs[i].shape), inputs[i].dtype)
            else:
                results.append(None)
                continue
        results.append(Tensor(g, stop_gradient=True))
    return results


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayer:
    """User-defined autograd function (analog of paddle.autograd.PyLayer,
    paddle/fluid/eager/pylayer/py_layer_node.h).

    class Exp(PyLayer):
        @staticmethod
        def forward(ctx, x):
            y = x.exp()
            ctx.save_for_backward(y)
            return y

        @staticmethod
        def backward(ctx, dy):
            (y,) = ctx.saved_tensor
            return dy * y
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..core.tensor import Tensor

        ctx = PyLayerContext()
        with no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)

        single = not isinstance(outputs, (list, tuple))
        out_list = [outputs] if single else list(outputs)

        diff_inputs = [a for a in args if isinstance(a, Tensor) and a._requires_grad()]
        if tape.is_grad_enabled() and diff_inputs:
            out_tensors = [o for o in out_list if isinstance(o, Tensor)]

            def vjp_fn(cotangents):
                cot_tensors = [Tensor(c) if c is not None else None for c in cotangents]
                with no_grad():
                    grads = cls.backward(ctx, *cot_tensors)
                if not isinstance(grads, (list, tuple)):
                    grads = (grads,)
                vals = []
                gi = 0
                for a in args:
                    if isinstance(a, Tensor) and a._requires_grad():
                        g = grads[gi] if gi < len(grads) else None
                        gi += 1
                        vals.append(g._value if isinstance(g, Tensor) else g)
                return tuple(vals)

            node = tape.record_op(
                f"pylayer_{cls.__name__}",
                [o._value for o in out_tensors],
                vjp_fn,
                diff_inputs,
            )
            for slot, o in enumerate(out_tensors):
                o.stop_gradient = False
                o._set_grad_node(node, slot)

        return out_list[0] if single else tuple(out_list)
