"""Autograd public API.

Analog of python/paddle/autograd: ``backward``, ``grad``, ``no_grad``,
``PyLayer`` (paddle/fluid/eager/pylayer), hooks.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from . import tape
from .tape import enable_grad, is_grad_enabled, no_grad


def backward(tensors, grad_tensors=None, retain_graph=False):
    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is not None and not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]
    tape.run_backward(tensors, grad_tensors, retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False):
    """Analog of paddle.grad (partial-graph gradients without touching
    ``.grad`` — the reference's GeneralGrad path, fluid/eager/general_grad.h)."""
    from ..core.tensor import Tensor

    if not isinstance(outputs, (list, tuple)):
        outputs = [outputs]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    if grad_outputs is not None and not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]
    if retain_graph is None:
        # matching double-grad semantics: creating the grad graph implies
        # keeping the forward graph alive
        retain_graph = create_graph

    captured = [None] * len(inputs)

    hooks_installed = []
    for i, t in enumerate(inputs):
        node, slot = t._grad_edge()
        if node is None:
            if not allow_unused:
                raise ValueError(f"input {i} has stop_gradient=True")
            continue

        def mk_hook(i, slot, is_leaf):
            if is_leaf:
                def leaf_hook(g):
                    captured[i] = g if captured[i] is None else captured[i] + g
                    return None
                return leaf_hook

            def node_hook(cotangents):
                g = cotangents[slot]
                if g is not None:
                    captured[i] = g if captured[i] is None else captured[i] + g
                return None
            return node_hook

        is_leaf = isinstance(node, tape.AccumulateNode)
        hook = mk_hook(i, slot, is_leaf)
        node.hooks.append(hook)
        hooks_installed.append((node, hook, is_leaf, t))

    try:
        # accumulate_to_leaf=False: capture hooks fire but no tensor's .grad
        # is touched (matches the reference's GeneralGrad partial-graph path)
        tape.run_backward(outputs, grad_outputs, retain_graph=retain_graph,
                          accumulate_to_leaf=False, create_graph=create_graph)
    finally:
        for node, hook, _, _ in hooks_installed:
            if hook in node.hooks:
                node.hooks.remove(hook)

    results = []
    for i, g in enumerate(captured):
        if g is None:
            if not allow_unused and inputs[i]._grad_edge()[0] is not None:
                # unreached input: return zeros to match reference behavior
                import jax.numpy as jnp

                g = jnp.zeros(tuple(inputs[i].shape), inputs[i].dtype)
            else:
                results.append(None)
                continue
        if isinstance(g, Tensor):
            # create_graph path: keep the tape-connected Tensor so the result
            # can be differentiated again
            results.append(g)
        else:
            results.append(Tensor(g, stop_gradient=True))
    return results


def jacobian(ys, xs, create_graph=False, batch_axis=None):
    """Dense Jacobian of tensor(s) ``ys`` w.r.t. tensor(s) ``xs``.

    Analog of paddle.autograd.jacobian (python/paddle/autograd/autograd.py);
    eagerly materialized with shape ``ys.shape + x.shape`` per input (the
    reference evaluates lazily row-by-row — same math, same row-seeded vjp).
    """
    import numpy as np

    from ..core.tensor import Tensor
    from ..ops import creation as _creation
    from ..ops import manip as _manip

    if batch_axis is not None:
        raise NotImplementedError("batch_axis is not supported; vmap the "
                                  "functional path instead")
    single_y = not isinstance(ys, (list, tuple))
    single_x = not isinstance(xs, (list, tuple))
    ys_l = [ys] if single_y else list(ys)
    xs_l = [xs] if single_x else list(xs)

    import jax.numpy as jnp

    per_y = []
    for y in ys_l:
        y_shape = tuple(y.shape)
        m = int(np.prod(y_shape)) if y_shape else 1
        cols = [[] for _ in xs_l]
        for j in range(m):
            seed = jnp.zeros((m,), y.dtype).at[j].set(1).reshape(y_shape)
            gs = grad([y], xs_l, grad_outputs=[Tensor(seed, stop_gradient=True)],
                      retain_graph=True, create_graph=create_graph,
                      allow_unused=True)
            for i, g in enumerate(gs):
                if g is None:
                    g = _creation.zeros_like(xs_l[i])
                cols[i].append(g)
        outs = []
        for i, x in enumerate(xs_l):
            j_t = _manip.stack(cols[i], axis=0)  # (m, *x.shape)
            j_t = _manip.reshape(j_t, y_shape + tuple(x.shape))
            outs.append(j_t)
        per_y.append(outs[0] if single_x else tuple(outs))
    return per_y[0] if single_y else tuple(per_y)


def hessian(ys, xs, batch_axis=None):
    """Hessian of a scalar ``ys`` w.r.t. ``xs``: shape ``x.shape + x.shape``
    per input (nested tuple for multiple inputs). Analog of
    paddle.autograd.hessian; exercises the double-grad (create_graph) path."""
    if batch_axis is not None:
        raise NotImplementedError("batch_axis is not supported")
    if tuple(ys.shape) not in ((), (1,)):
        raise ValueError("hessian expects a scalar output")
    single_x = not isinstance(xs, (list, tuple))
    xs_l = [xs] if single_x else list(xs)
    gs = grad([ys], xs_l, create_graph=True, allow_unused=True)
    rows = []
    for i, g in enumerate(gs):
        if g is None:
            # input not connected to ys: its Hessian blocks are zero
            from ..ops import creation as _creation

            g = _creation.zeros_like(xs_l[i])
        row = jacobian(g, xs_l if not single_x else xs_l[0])
        rows.append(row)
    return rows[0] if single_x else tuple(rows)


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self._hooks = None       # (pack, unpack) active at save time
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        hooks = _current_saved_tensors_hooks()
        if hooks is not None:
            pack, _ = hooks
            self._saved = tuple(pack(t) for t in tensors)
            self._hooks = hooks
        else:
            self._saved = tensors

    def _unpacked(self):
        if self._hooks is None:
            return self._saved
        _, unpack = self._hooks
        return tuple(unpack(p) for p in self._saved)

    @property
    def saved_tensor(self):
        return self._unpacked()

    def saved_tensors(self):
        return self._unpacked()


import threading as _threading  # noqa: E402

_hooks_tls = _threading.local()


def _current_saved_tensors_hooks():
    stack = getattr(_hooks_tls, "stack", None)
    return stack[-1] if stack else None


class saved_tensors_hooks:
    """Analog of paddle.autograd.saved_tensors_hooks
    (python/paddle/autograd/saved_tensors_hooks.py): a context manager
    installing a (pack, unpack) pair applied to tensors saved for
    backward — the activation-offload / compression hook point.

    Scope note (deliberate, documented): on this stack the hook pair
    applies to tensors saved through ``PyLayerContext.save_for_backward``
    — pack runs at save time, unpack when ``saved_tensor`` is read in
    backward.  Residuals of REGISTERED ops live inside their ``jax.vjp``
    closures (autograd/tape.py design note) where XLA already manages
    their placement; wrap a region in a PyLayer to route its residuals
    through these hooks."""

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        if not hasattr(_hooks_tls, "stack"):
            _hooks_tls.stack = []
        _hooks_tls.stack.append((self.pack_hook, self.unpack_hook))
        return self

    def __exit__(self, *exc):
        _hooks_tls.stack.pop()
        return False


class PyLayer:
    """User-defined autograd function (analog of paddle.autograd.PyLayer,
    paddle/fluid/eager/pylayer/py_layer_node.h).

    class Exp(PyLayer):
        @staticmethod
        def forward(ctx, x):
            y = x.exp()
            ctx.save_for_backward(y)
            return y

        @staticmethod
        def backward(ctx, dy):
            (y,) = ctx.saved_tensor
            return dy * y
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..core.tensor import Tensor

        ctx = PyLayerContext()
        with no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)

        single = not isinstance(outputs, (list, tuple))
        out_list = [outputs] if single else list(outputs)

        diff_inputs = [a for a in args if isinstance(a, Tensor) and a._requires_grad()]
        if tape.is_grad_enabled() and diff_inputs:
            out_tensors = [o for o in out_list if isinstance(o, Tensor)]

            def vjp_fn(cotangents):
                cot_tensors = [Tensor(c) if c is not None else None for c in cotangents]
                with no_grad():
                    grads = cls.backward(ctx, *cot_tensors)
                if not isinstance(grads, (list, tuple)):
                    grads = (grads,)
                vals = []
                gi = 0
                for a in args:
                    if isinstance(a, Tensor) and a._requires_grad():
                        g = grads[gi] if gi < len(grads) else None
                        gi += 1
                        vals.append(g._value if isinstance(g, Tensor) else g)
                return tuple(vals)

            node = tape.record_op(
                f"pylayer_{cls.__name__}",
                [o._value for o in out_tensors],
                vjp_fn,
                diff_inputs,
            )

            def apply_with_graph(cot_tensors):
                # create_graph: run user backward with recording ON so any
                # framework ops inside it land on the tape. Saved tensors
                # that were intermediates created inside forward (under
                # no_grad) are NOT connected to the inputs, so their
                # second-order contribution is dropped — warn rather than be
                # silently wrong.
                import warnings

                if any(isinstance(s, Tensor) and s._grad_edge(create=False)[0] is None
                       for s in ctx._saved):
                    warnings.warn(
                        f"PyLayer {cls.__name__}: double grad treats saved "
                        "tensors with no tape connection as constants; "
                        "second-order terms through them are dropped. Save "
                        "inputs/outputs (not no_grad intermediates) or "
                        "recompute inside backward for exact higher-order "
                        "gradients.", stacklevel=2)
                grads = cls.backward(ctx, *cot_tensors)
                if not isinstance(grads, (list, tuple)):
                    grads = (grads,)
                out, gi = [], 0
                for a in args:
                    if isinstance(a, Tensor) and a._requires_grad():
                        g = grads[gi] if gi < len(grads) else None
                        gi += 1
                        out.append(g if (g is None or isinstance(g, Tensor))
                                   else Tensor(g))
                return tuple(out)

            node.apply_with_graph = apply_with_graph
            for slot, o in enumerate(out_tensors):
                o.stop_gradient = False
                o._set_grad_node(node, slot)

        return out_list[0] if single else tuple(out_list)
